"""Thread-context classification + cross-thread unlocked mutations.

Every function is tagged with the set of thread contexts it can run
on, derived from the pinned spawn sites rather than guessed:

- ``thread:<Class.method>`` — a ``threading.Thread(target=self.m)``
  spawn anywhere in the tree roots ``m`` in its own context (the op /
  finisher / sender / engine worker threads);
- ``reactor`` — readiness callbacks (``on_readable`` / ``on_writable``
  / ``on_io_error`` on classes in ``msg/``) plus anything handed to
  ``call_soon`` / ``call_later`` (including lambda trampolines);
- ``caller`` — public API surface.  Assigned in a second phase, only
  to public methods no thread root already reaches, so a handler that
  merely *could* be called externally but never is does not pollute
  the context sets.

Contexts propagate through the resolved call graph (self-methods,
annotated parameters, attribute types, constructor callback bindings,
unique-name fallback).  ``cross-thread-unlocked`` then flags every
instance attribute written outside ``__init__`` from two or more
contexts whose write sites share no common held lock.  Entry-held
locks are modelled interprocedurally: a helper only ever called with
a lock held (``_finish_locked`` style) inherits the intersection of
its callers' held sets, fixpointed.
"""
from __future__ import annotations

import ast

from .engine import Finding, FunctionInfo, ProjectIndex, in_scope, rule
from .lockmodel import LockEvent, LockId, lock_events

_DEEP_SCOPE = ("ceph_tpu/msg", "ceph_tpu/exec", "ceph_tpu/recovery",
               "ceph_tpu/net.py", "ceph_tpu/cluster.py",
               "ceph_tpu/ops/pipeline.py")
_REACTOR_CALLBACKS = {"on_readable", "on_writable", "on_io_error"}
_TRAMPOLINES = {"call_soon", "call_later"}
# lifecycle methods where single-threaded setup/teardown writes live
_SETUP_METHODS = {"__init__", "__new__", "__enter__", "start"}


class ContextModel:
    """Shared product of the context analysis (built once per index)."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self.events: dict[str, list[LockEvent]] = {}
        self.functions: dict[str, FunctionInfo] = {}
        for mod in index.modules.values():
            for fi in mod.functions.values():
                self.functions[fi.ref] = fi
                self.events[fi.ref] = lock_events(index, fi)
        self.call_graph = self._build_call_graph()
        self.contexts: dict[str, set[str]] = {
            ref: set() for ref in self.functions}
        self._seed_thread_roots()
        self._seed_reactor_roots()
        self._propagate()
        self._seed_caller_roots()
        self._propagate()
        self.entry_held = self._entry_held_fixpoint()

    # -- call graph ---------------------------------------------------
    def _build_call_graph(self) -> dict[str, set[str]]:
        graph: dict[str, set[str]] = {}
        for ref, evs in self.events.items():
            fi = self.functions[ref]
            targets: set[str] = set()
            for e in evs:
                if e.kind != "call":
                    continue
                for callee in self.index.resolve_call(fi, e.node):
                    targets.add(callee.ref)
            # nested defs (closures, ``on_notify`` style) run on the
            # thread of whoever defined them unless spawned elsewhere
            for ch in ast.walk(fi.node):
                if isinstance(ch, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
                        and ch is not fi.node:
                    nested = f"{ref}.{ch.name}"
                    if nested in self.functions:
                        targets.add(nested)
            graph[ref] = targets
        return graph

    # -- roots --------------------------------------------------------
    def _method_ref(self, fi: FunctionInfo, attr: str) -> str | None:
        ci = self.index.class_of(fi)
        if ci is None:
            return None
        target = self.index.lookup_method(ci, attr)
        return target.ref if target else None

    def _seed_thread_roots(self) -> None:
        for ref, evs in self.events.items():
            fi = self.functions[ref]
            for e in evs:
                if e.kind != "call":
                    continue
                call = e.node
                name = call.func.attr \
                    if isinstance(call.func, ast.Attribute) \
                    else call.func.id \
                    if isinstance(call.func, ast.Name) else None
                if name == "Thread":
                    for kw in call.keywords:
                        if kw.arg == "target" and \
                                isinstance(kw.value, ast.Attribute) and \
                                isinstance(kw.value.value, ast.Name) and \
                                kw.value.value.id == "self":
                            t = self._method_ref(fi, kw.value.attr)
                            if t:
                                qn = self.functions[t].qualname
                                self.contexts[t].add(f"thread:{qn}")
                elif name in _TRAMPOLINES:
                    self._seed_trampoline_args(fi, call)

    def _seed_trampoline_args(self, fi: FunctionInfo,
                              call: ast.Call) -> None:
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, ast.Attribute) and \
                    isinstance(arg.value, ast.Name) and \
                    arg.value.id == "self":
                t = self._method_ref(fi, arg.attr)
                if t:
                    self.contexts[t].add("reactor")
            elif isinstance(arg, ast.Lambda):
                for ch in ast.walk(arg.body):
                    if isinstance(ch, ast.Call):
                        for callee in self.index.resolve_call(fi, ch):
                            self.contexts[callee.ref].add("reactor")

    def _seed_reactor_roots(self) -> None:
        for mod in self.index.iter_modules(("ceph_tpu/msg",
                                            "ceph_tpu/net.py")):
            for fi in mod.functions.values():
                if fi.class_name and fi.name in _REACTOR_CALLBACKS:
                    self.contexts[fi.ref].add("reactor")

    def _seed_caller_roots(self) -> None:
        for ref, fi in self.functions.items():
            if self.contexts[ref]:
                continue
            qn = fi.qualname
            if fi.class_name and qn.startswith(fi.class_name + "."):
                qn = qn[len(fi.class_name) + 1:]
            if fi.name.startswith("_") or "." in qn:
                continue  # private, or a nested def (not API surface)
            self.contexts[ref].add("caller")

    def _propagate(self) -> None:
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for ref, targets in self.call_graph.items():
                src = self.contexts[ref]
                if not src:
                    continue
                for t in targets:
                    before = len(self.contexts[t])
                    self.contexts[t] |= src
                    changed |= len(self.contexts[t]) != before

    # -- entry-held locks --------------------------------------------
    def _entry_held_fixpoint(self) -> dict[str, frozenset[LockId]]:
        """Locks provably held on EVERY call into a function
        (intersection over call sites; roots start empty)."""
        callers_held: dict[str, list[frozenset[LockId]]] = {}
        entry: dict[str, frozenset[LockId]] = {
            ref: frozenset() for ref in self.functions}
        for _ in range(8):
            callers_held = {}
            for ref, evs in self.events.items():
                fi = self.functions[ref]
                base = entry[ref]
                for e in evs:
                    if e.kind != "call":
                        continue
                    held = base | frozenset(e.held)
                    for callee in self.index.resolve_call(fi, e.node):
                        callers_held.setdefault(callee.ref,
                                                []).append(held)
            new_entry = {}
            for ref in self.functions:
                sites = callers_held.get(ref)
                if sites:
                    inter = sites[0]
                    for s in sites[1:]:
                        inter &= s
                    new_entry[ref] = inter
                else:
                    new_entry[ref] = frozenset()
            if new_entry == entry:
                break
            entry = new_entry
        return entry


_MODEL_CACHE: dict[int, ContextModel] = {}


def context_model(index: ProjectIndex) -> ContextModel:
    model = _MODEL_CACHE.get(id(index))
    if model is None:
        model = ContextModel(index)
        _MODEL_CACHE.clear()
        _MODEL_CACHE[id(index)] = model
    return model


@rule("cross-thread-unlocked", severity="warning", scope=_DEEP_SCOPE,
      description="an instance attribute is written from two or more "
                  "thread contexts with no common lock held")
def check_cross_thread(index: ProjectIndex) -> list[Finding]:
    model = context_model(index)
    # (class, attr) -> list of (fn ref, line, contexts, held)
    writes: dict[tuple[str, str],
                 list[tuple[str, int, frozenset[str],
                            frozenset[LockId]]]] = {}
    for ref, evs in model.events.items():
        fi = model.functions[ref]
        if not fi.class_name or fi.name in _SETUP_METHODS:
            continue
        if not in_scope(fi.rel, _DEEP_SCOPE):
            continue
        ctxs = frozenset(model.contexts[ref])
        if not ctxs:
            continue
        base = model.entry_held[ref]
        for e in evs:
            if e.kind != "mutate":
                continue
            held = frozenset(e.held) | base
            writes.setdefault((fi.class_name, e.attr), []).append(
                (ref, e.node.lineno, ctxs, held))
    out: list[Finding] = []
    for (cls, attr), sites in sorted(writes.items()):
        all_ctx: set[str] = set()
        for _, _, ctxs, _ in sites:
            all_ctx |= ctxs
        if len(all_ctx) < 2:
            continue
        common = sites[0][3]
        for _, _, _, held in sites[1:]:
            common = common & held
        if common:
            continue
        ref0, line0 = sites[0][0], sites[0][1]
        fns = sorted({r.split(":")[1] for r, _, _, _ in sites})
        out.append(Finding(
            "cross-thread-unlocked", model.functions[ref0].rel, line0,
            "warning",
            f"{cls}.{attr} written from contexts "
            f"{{{','.join(sorted(all_ctx))}}} with no common lock "
            f"(writers: {', '.join(fns)})"))
    return out
