"""hot-path-copy: payload-sized host copies on the messenger/exec data
path.

ISSUE 20's guard rule: the zero-copy data path works by never
materializing payload bytes between the socket and the device — staging
slices, sideband splices, and device relayouts are the ONLY sanctioned
copies, and each reports itself to ``common/copy_ledger``.  A stray
``bytes(view)`` / ``view.tobytes()`` / ``pickle.dumps(payload)`` in
``msg/`` or ``exec/`` silently reintroduces a per-byte copy the ledger
never sees, so the ratio gate under-counts and the regression ships.

Heuristics, deliberately narrow to keep the signal clean:

- ``pickle.dumps(...)`` flags unconditionally in scope: serializing on
  the data path copies everything it touches, payloads included (the
  sideband exists precisely so payloads skip the pickler);
- ``bytes(x)`` / ``bytearray(x)`` constructor calls and ``x.tobytes()``
  flag only when the operand's terminal identifier carries a payload
  hint (``payload``/``data``/``buf``/``body``/``view``/``seg``/
  ``chunk``/``value``/``piece``/``mv``) — ``bytes(name)``-style id
  materialization never trips it;
- functions whose names mark a control-plane boundary (handshake, auth,
  banner, keepalive, connect) are allowlisted: those frames are
  constant-sized and pre-date the payload path.

Justified survivors (the parser's BufferError fallback — already
ledger-counted — the 16-byte MAC slice, the striper's scatter/gather
assembly) live in ``.ceph_lint_baseline.json`` with their why, like
every other rule's.
"""
from __future__ import annotations

import ast

from .engine import Finding, ProjectIndex, rule

_SCOPE = ("ceph_tpu/msg", "ceph_tpu/exec")

# operand identifiers that look payload-sized
_PAYLOAD_HINTS = ("payload", "data", "buf", "body", "view", "seg",
                  "chunk", "value", "piece", "mv")

# function-name fragments marking allowlisted control-plane boundaries
_BOUNDARY_HINTS = ("handshake", "auth", "banner", "keepalive", "connect",
                   "hello")


def _terminal_ident(node) -> str:
    """Lowered terminal identifier of an expression: ``self.payload[i]``
    -> ``payload``, ``mv.cast('B')`` -> ``mv`` (empty when nameless)."""
    while True:
        if isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Attribute):
            return node.attr.lower()
        elif isinstance(node, ast.Name):
            return node.id.lower()
        else:
            return ""


def _payloadish(node) -> bool:
    ident = _terminal_ident(node)
    return any(h in ident for h in _PAYLOAD_HINTS)


def _is_pickle_dumps(call: ast.Call) -> bool:
    fn = call.func
    return (isinstance(fn, ast.Attribute) and fn.attr == "dumps" and
            isinstance(fn.value, ast.Name) and
            fn.value.id in ("pickle", "cPickle"))


def _copy_site(call: ast.Call) -> str | None:
    """Describe the copy a call performs, or None."""
    fn = call.func
    if _is_pickle_dumps(call):
        return "pickle.dumps"
    if isinstance(fn, ast.Name) and fn.id in ("bytes", "bytearray") \
            and len(call.args) == 1 and not call.keywords \
            and _payloadish(call.args[0]):
        return f"{fn.id}({_terminal_ident(call.args[0])})"
    if isinstance(fn, ast.Attribute) and fn.attr == "tobytes" \
            and _payloadish(fn.value):
        return f"{_terminal_ident(fn.value)}.tobytes()"
    return None


def _own_calls(fn_node):
    """Call nodes in a function's OWN body — nested defs are indexed as
    their own FunctionInfo, so their bodies are skipped here to report
    each site exactly once."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


@rule("hot-path-copy", severity="warning", scope=_SCOPE,
      description="a payload-sized host copy (bytes()/tobytes()/"
                  "pickle.dumps) on the msg/exec data path — the "
                  "zero-copy path's sanctioned copies are staging, "
                  "sideband splice, and device relayout, each counted "
                  "by the copy ledger; anything else silently skews "
                  "bytes_copied_per_byte_served")
def check_hot_path_copy(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    for mod in index.iter_modules(_SCOPE):
        for fi in mod.functions.values():
            low = fi.qualname.lower()
            if any(h in low for h in _BOUNDARY_HINTS):
                continue
            for node in _own_calls(fi.node):
                site = _copy_site(node)
                if site is None:
                    continue
                out.append(Finding(
                    "hot-path-copy", fi.rel, node.lineno, "warning",
                    f"payload copy {site} in {fi.qualname}"))
    return out
