"""Lock-order deadlock detection + callbacks/sends under a held lock.

``lock-order-cycle`` builds the static lock-acquisition graph the way
Ceph's ``lockdep.cc`` does at runtime: an edge A→B means some code
path acquires B while holding A — either lexically (nested ``with``)
or through a call made under A whose transitive may-acquire set
contains B.  A cycle in that graph is a potential ABBA deadlock.
Lock identity is (defining class, attribute), so two *instances* of
the same class taking each other's locks fold onto a self-edge; those
are skipped (the tree has no hand-over-hand instance chains).

``callback-under-lock`` flags the `_watch_lock` class of bug PR 14
fixed by hand: invoking a stored callback / handler / send while
holding a lock, which both extends the critical section by arbitrary
user work and invites re-entrant deadlocks.
"""
from __future__ import annotations

import ast
import re

from .engine import Finding, FunctionInfo, ProjectIndex, rule
from .lockmodel import LockEvent, LockId, lock_events, may_acquire_closure

_DEEP_SCOPE = ("ceph_tpu/msg", "ceph_tpu/exec", "ceph_tpu/recovery",
               "ceph_tpu/net.py", "ceph_tpu/cluster.py",
               "ceph_tpu/ops/pipeline.py")

# call names that hand control to arbitrary stored code or the network
_CALLBACK_NAME = re.compile(
    r"^(cb|_cb|fn|_fn|func|callback|_callback|hook|on_[a-z0-9_]+)$")
_SEND_NAMES = {"send", "sendall", "send_message", "sendto",
               "send_from_reactor"}
# invocations that are lock-internal by design, not external hand-offs
_BENIGN_ATTRS = {"notify", "notify_all", "wait", "wait_for", "acquire",
                 "release", "append", "popleft", "pop", "add", "get",
                 "put", "discard", "remove", "clear", "update",
                 "setdefault", "items", "values", "keys", "extend"}


def _all_events(index: ProjectIndex
                ) -> tuple[dict[str, list[LockEvent]],
                           dict[str, FunctionInfo]]:
    events: dict[str, list[LockEvent]] = {}
    functions: dict[str, FunctionInfo] = {}
    for mod in index.modules.values():
        for fi in mod.functions.values():
            events[fi.ref] = lock_events(index, fi)
            functions[fi.ref] = fi
    return events, functions


def _lock_graph(index: ProjectIndex,
                events: dict[str, list[LockEvent]],
                functions: dict[str, FunctionInfo],
                acq: dict[str, set[LockId]],
                ) -> dict[tuple[LockId, LockId], list[tuple[str, int]]]:
    """edges {(held, acquired): [(witness fn ref, line), ...]}."""
    edges: dict[tuple[LockId, LockId], list[tuple[str, int]]] = {}

    def note(a: LockId, b: LockId, ref: str, line: int) -> None:
        if a == b:
            return
        edges.setdefault((a, b), [])
        if len(edges[(a, b)]) < 3:
            edges[(a, b)].append((ref, line))

    for ref, evs in events.items():
        fi = functions[ref]
        for e in evs:
            if e.kind == "acquire" and e.held:
                for h in e.held:
                    note(h, e.lock, ref, e.node.lineno)
            elif e.kind == "call" and e.held:
                for callee in index.resolve_call(fi, e.node):
                    for lid in acq.get(callee.ref, ()):
                        for h in e.held:
                            note(h, lid, ref, e.node.lineno)
    return edges


def _cycles(edges: dict[tuple[LockId, LockId], list]) -> list[list[LockId]]:
    """Strongly connected components with >1 node (or a self loop —
    already excluded upstream) in the lock graph."""
    adj: dict[LockId, set[LockId]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    # iterative Tarjan
    index_of: dict[LockId, int] = {}
    low: dict[LockId, int] = {}
    on_stack: set[LockId] = set()
    stack: list[LockId] = []
    sccs: list[list[LockId]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index_of:
            continue
        work = [(root, iter(sorted(adj[root])))]
        index_of[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index_of:
                    index_of[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index_of[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index_of[v]:
                comp: list[LockId] = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))
    return sccs


@rule("lock-order-cycle", severity="error",
      description="two locks are acquired in both orders on some "
                  "static path (potential ABBA deadlock)")
def check_lock_order(index: ProjectIndex) -> list[Finding]:
    events, functions = _all_events(index)
    acq = may_acquire_closure(index, events, functions)
    edges = _lock_graph(index, events, functions, acq)
    out: list[Finding] = []
    for comp in _cycles(edges):
        members = set(comp)
        witness_parts: list[str] = []
        anchor: tuple[str, int] | None = None
        for (a, b), sites in sorted(edges.items()):
            if a in members and b in members:
                ref, line = sites[0]
                witness_parts.append(f"{a}->{b} in {ref.split(':')[1]}")
                if anchor is None:
                    anchor = (functions[ref].rel, line)
        rel, line = anchor if anchor else ("ceph_tpu", 1)
        names = " <-> ".join(str(lid) for lid in comp)
        out.append(Finding(
            "lock-order-cycle", rel, line, "error",
            f"lock-order cycle {names} ({'; '.join(witness_parts)})"))
    return out


def _call_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


@rule("callback-under-lock", severity="warning", scope=_DEEP_SCOPE,
      description="a stored callback / handler / network send is "
                  "invoked while holding a lock (re-entrancy and "
                  "critical-section-bloat hazard)")
def check_callback_under_lock(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    for mod in index.iter_modules(_DEEP_SCOPE):
        for fi in mod.functions.values():
            aliases = index.local_aliases(fi)
            for e in lock_events(index, fi):
                if e.kind != "call" or not e.held:
                    continue
                name = _call_name(e.node)
                if name is None or name in _BENIGN_ATTRS:
                    continue
                is_send = name in _SEND_NAMES
                is_cb = _CALLBACK_NAME.match(name) is not None
                # a local name judged by the self-attribute it aliases:
                # ``cb, self.on_closed = self.on_closed, None; cb(...)``
                if isinstance(e.node.func, ast.Name) and not is_cb:
                    aliased = aliases.get(e.node.func.id)
                    is_cb = aliased is not None and \
                        _CALLBACK_NAME.match(aliased) is not None
                if not (is_send or is_cb):
                    continue
                held = ",".join(str(h) for h in sorted(e.held))
                kindtxt = "send" if is_send else "callback"
                out.append(Finding(
                    "callback-under-lock", fi.rel, e.node.lineno,
                    "warning",
                    f"{kindtxt} {name}() invoked in {fi.qualname} "
                    f"while holding {held}"))
    return out
