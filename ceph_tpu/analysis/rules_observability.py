"""instrument-under-lock: observability updates inside hot critical
sections.

ISSUE 18's race-surface rule: both PR 15 fixes were instrument updates
(perf counters, tracer events, wire accounting) performed on reactor /
messenger-worker threads while a lock was held — the exact pattern the
sharded counter cells and batched tracer flushes exist to make
unnecessary.  The rule flags any perf-counter / tracer / wire-accounting
call made while holding a lock inside ``msg/`` code that runs on a
reactor callback or a pinned worker thread: an instrument needs no
caller lock anymore, so holding one around it only re-creates the
contention/race class.

Heuristics, deliberately narrow to keep the signal clean:

- unambiguous instrument method names (``tinc``/``hinc``/``account_*``/
  ``observe_rpc``/``note_queue_depth``/``trace_span``/``trace_instant``)
  flag on the name alone;
- generic names (``inc``/``dec``/``set``/``complete``/``instant``/
  ``flush``) flag only when the receiver chain names an instrument
  object (``...perf.inc``, ``self.acct...``, ``tracer...``), so plain
  ``dict.set``-style calls never trip it.

Justified survivors live in ``.ceph_lint_baseline.json`` like every
other rule's.
"""
from __future__ import annotations

import ast

from .engine import Finding, ProjectIndex, rule
from .lockmodel import lock_events
from .rules_threads import context_model

_SCOPE = ("ceph_tpu/msg",)

# method names that are instruments wherever they appear
_ALWAYS = {"tinc", "hinc", "account_tx", "account_rx", "account_msg",
           "observe_rpc", "note_queue_depth", "trace_span",
           "trace_instant", "mark_event"}

# generic method names: instruments only on an instrument-ish receiver
_GENERIC = {"inc", "dec", "set", "complete", "instant", "flush", "time"}

# receiver-chain fragments that identify an instrument object
_RECEIVER_HINTS = ("perf", "acct", "tracer", "accounting", "counters")


def _receiver_chain(call: ast.Call) -> str:
    """Dotted receiver text of an attribute call (``self.perf.inc`` ->
    ``self.perf``), empty for bare-name calls."""
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return ""
    parts: list[str] = []
    node = fn.value
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Name):
        # default_tracer().complete(...) — the factory name is the hint
        parts.append(node.func.id)
    return ".".join(reversed(parts))


def _instrument_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id if fn.id in _ALWAYS else None
    if not isinstance(fn, ast.Attribute):
        return None
    name = fn.attr
    if name in _ALWAYS:
        return name
    if name in _GENERIC:
        recv = _receiver_chain(call).lower()
        if any(h in recv for h in _RECEIVER_HINTS):
            return name
    return None


@rule("instrument-under-lock", severity="warning", scope=_SCOPE,
      description="a perf-counter / tracer / wire-accounting update "
                  "runs under a held lock on a reactor or msg worker "
                  "path (instruments are lock-free by design — holding "
                  "a lock around one re-creates the PR 15 contention/"
                  "race class)")
def check_instrument_under_lock(index: ProjectIndex) -> list[Finding]:
    model = context_model(index)
    out: list[Finding] = []
    for mod in index.iter_modules(_SCOPE):
        for fi in mod.functions.values():
            ctxs = model.contexts.get(fi.ref, set())
            if "reactor" not in ctxs and \
                    not any(c.startswith("thread:") for c in ctxs):
                continue
            for e in lock_events(index, fi):
                if e.kind != "call" or not e.held:
                    continue
                name = _instrument_name(e.node)
                if name is None:
                    continue
                held = ",".join(str(h) for h in sorted(e.held))
                recv = _receiver_chain(e.node)
                target = f"{recv}.{name}" if recv else name
                out.append(Finding(
                    "instrument-under-lock", fi.rel, e.node.lineno,
                    "warning",
                    f"instrument update {target}() in {fi.qualname} "
                    f"while holding {held}"))
    return out
