"""JAX dispatch-purity: keep the jitted hot path silently fast.

Three hazards, all invisible at runtime until they cost you:

- ``jit-host-sync`` — a host synchronisation (``device_get``,
  ``block_until_ready``, ``.item()`` / ``.tolist()``, ``np.asarray``)
  reachable from inside a jitted function body.  Inside a trace these
  either fail late or silently force a transfer per call.
- ``jit-nonstatic-shape`` — a non-static jit parameter used where a
  shape/length is expected (``jnp.zeros(n)``, ``range(n)``,
  ``.reshape(n, -1)``): every distinct value recompiles.
- ``jit-traced-control-flow`` — a non-static parameter steering
  Python ``if``/``while`` inside a jitted body; works only while the
  caller passes Python scalars, and then recompiles per value.
- ``jit-donated-reuse`` — an argument passed in a ``donate_argnums``
  position is read again after the call without being rebound; the
  buffer was handed to XLA and may alias the output.

Jit detection understands ``@jax.jit``, ``@traced_jit`` (the local
wrapper forwards ``static_argnames``/``donate_argnums`` through), and
``@functools.partial(jax.jit, ...)``.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .engine import Finding, FunctionInfo, ProjectIndex, rule

_JIT_NAMES = {"jit", "traced_jit"}
_HOST_SYNC_ATTRS = {"device_get", "block_until_ready", "item",
                    "tolist", "copy_to_host_async"}
_NP_SYNC_FNS = {"asarray", "array", "float64", "float32"}
_SHAPE_CTORS = {"zeros", "ones", "full", "empty", "arange", "linspace",
                "eye", "tile", "broadcast_to", "reshape", "repeat"}
_MAX_DEPTH = 3


@dataclass
class JitInfo:
    fi: FunctionInfo
    static_names: set[str] = field(default_factory=set)
    static_nums: set[int] = field(default_factory=set)
    donate_nums: set[int] = field(default_factory=set)

    def param_names(self) -> list[str]:
        a = self.fi.node.args
        return [p.arg for p in
                list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]

    def static_params(self) -> set[str]:
        names = self.param_names()
        out = set(self.static_names)
        for i in sorted(self.static_nums):
            if i < len(names):
                out.add(names[i])
        return out


def _str_items(node: ast.expr) -> set[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out: set[str] = set()
        for e in node.elts:
            out |= _str_items(e)
        return out
    return set()


def _int_items(node: ast.expr) -> set[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out: set[int] = set()
        for e in node.elts:
            out |= _int_items(e)
        return out
    return set()


def _is_jit_ref(expr: ast.expr) -> bool:
    if isinstance(expr, ast.Name):
        return expr.id in _JIT_NAMES
    if isinstance(expr, ast.Attribute):
        return expr.attr in _JIT_NAMES
    return False


def _jit_info(fi: FunctionInfo) -> JitInfo | None:
    for deco in fi.node.decorator_list:
        target: ast.Call | None = None
        if _is_jit_ref(deco):
            return JitInfo(fi)
        if isinstance(deco, ast.Call):
            if _is_jit_ref(deco.func):
                target = deco
            elif isinstance(deco.func, (ast.Name, ast.Attribute)):
                # functools.partial(jax.jit, ...)
                fname = deco.func.id if isinstance(deco.func, ast.Name) \
                    else deco.func.attr
                if fname == "partial" and deco.args and \
                        _is_jit_ref(deco.args[0]):
                    target = deco
        if target is None:
            continue
        info = JitInfo(fi)
        for kw in target.keywords:
            if kw.arg == "static_argnames":
                info.static_names |= _str_items(kw.value)
            elif kw.arg == "static_argnums":
                info.static_nums |= _int_items(kw.value)
            elif kw.arg == "donate_argnums":
                info.donate_nums |= _int_items(kw.value)
        return info
    return None


def jitted_functions(index: ProjectIndex) -> list[JitInfo]:
    out = []
    for mod in index.iter_modules(("ceph_tpu",)):
        for fi in mod.functions.values():
            info = _jit_info(fi)
            if info is not None:
                out.append(info)
    return out


def _np_alias(index: ProjectIndex, rel: str) -> set[str]:
    mod = index.modules[rel]
    return {alias for alias, dotted in mod.import_aliases.items()
            if dotted.split(".")[0] == "numpy"}


def _host_sync_sites(index: ProjectIndex,
                     fi: FunctionInfo) -> list[tuple[int, str]]:
    np_names = _np_alias(index, fi.rel)
    sites: list[tuple[int, str]] = []
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _HOST_SYNC_ATTRS:
                sites.append((node.lineno, f.attr))
            elif f.attr in _NP_SYNC_FNS and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in np_names:
                sites.append((node.lineno, f"np.{f.attr}"))
    return sites


@rule("jit-host-sync", severity="error", scope=("ceph_tpu",),
      description="a host synchronisation (device_get / "
                  "block_until_ready / .item() / np.asarray) is "
                  "reachable inside a jitted function")
def check_jit_host_sync(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    for info in jitted_functions(index):
        seen: set[str] = {info.fi.ref}
        frontier = [(info.fi, 0)]
        while frontier:
            fi, depth = frontier.pop()
            for line, what in _host_sync_sites(index, fi):
                via = "" if fi.ref == info.fi.ref else \
                    f" via {fi.qualname}"
                out.append(Finding(
                    "jit-host-sync", info.fi.rel,
                    line if fi.ref == info.fi.ref
                    else info.fi.node.lineno, "error",
                    f"host sync {what}() reachable inside jitted "
                    f"{info.fi.qualname}{via}"))
            if depth >= _MAX_DEPTH:
                continue
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                for callee in index.resolve_call(fi, node):
                    # traced_jit.py is the dispatch boundary itself:
                    # its syncs run at call time, outside the trace
                    if callee.ref not in seen and \
                            callee.rel.startswith("ceph_tpu") and \
                            not callee.rel.endswith("traced_jit.py"):
                        seen.add(callee.ref)
                        frontier.append((callee, depth + 1))
    return out


def _param_names_in(expr: ast.expr, params: set[str]) -> set[str]:
    return {n.id for n in ast.walk(expr)
            if isinstance(n, ast.Name) and n.id in params}


@rule("jit-nonstatic-shape", severity="warning", scope=("ceph_tpu",),
      description="a non-static jit parameter feeds a shape/length "
                  "(jnp.zeros(n), range(n), reshape) — every distinct "
                  "value triggers a silent recompile")
def check_jit_nonstatic_shape(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    for info in jitted_functions(index):
        traced = set(info.param_names()) - info.static_params() - {"self"}
        if not traced:
            continue
        for node in ast.walk(info.fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            fname = f.attr if isinstance(f, ast.Attribute) else \
                f.id if isinstance(f, ast.Name) else None
            if fname == "range" or fname in _SHAPE_CTORS:
                shape_args = node.args[:2] if fname != "reshape" \
                    else node.args
                hits: set[str] = set()
                for a in shape_args:
                    hits |= _param_names_in(a, traced)
                for h in sorted(hits):
                    out.append(Finding(
                        "jit-nonstatic-shape", info.fi.rel,
                        node.lineno, "warning",
                        f"non-static parameter {h!r} used as a "
                        f"shape/length in {fname}() inside jitted "
                        f"{info.fi.qualname}"))
    return out


@rule("jit-traced-control-flow", severity="warning", scope=("ceph_tpu",),
      description="a non-static jit parameter steers Python if/while "
                  "inside a jitted body (works only with Python "
                  "scalars, then recompiles per value)")
def check_jit_traced_control_flow(index: ProjectIndex) -> list[Finding]:
    out: list[Finding] = []
    for info in jitted_functions(index):
        traced = set(info.param_names()) - info.static_params() - {"self"}
        if not traced:
            continue
        for node in ast.walk(info.fi.node):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            for h in sorted(_param_names_in(node.test, traced)):
                out.append(Finding(
                    "jit-traced-control-flow", info.fi.rel,
                    node.lineno, "warning",
                    f"non-static parameter {h!r} steers Python "
                    f"control flow inside jitted {info.fi.qualname}"))
    return out


@rule("jit-donated-reuse", severity="error", scope=("ceph_tpu",),
      description="an argument passed in a donate_argnums position "
                  "is read after the call without being rebound — "
                  "the buffer belongs to XLA now")
def check_jit_donated_reuse(index: ProjectIndex) -> list[Finding]:
    donating = {info.fi.name: info for info in jitted_functions(index)
                if info.donate_nums}
    if not donating:
        return []
    out: list[Finding] = []
    for mod in index.iter_modules(("ceph_tpu",)):
        for fi in mod.functions.values():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                fname = node.func.id \
                    if isinstance(node.func, ast.Name) else \
                    node.func.attr \
                    if isinstance(node.func, ast.Attribute) else None
                info = donating.get(fname or "")
                if info is None:
                    continue
                resolved = index.resolve_call(fi, node)
                if not any(c.ref == info.fi.ref for c in resolved):
                    continue
                out.extend(_donated_reuse_at(fi, node, info))
    return out


def _blocks_of(stmt: ast.stmt) -> list[list[ast.stmt]]:
    out: list[list[ast.stmt]] = []
    for field in ("body", "orelse", "finalbody"):
        blk = getattr(stmt, field, None)
        if blk:
            out.append(blk)
    for h in getattr(stmt, "handlers", []) or []:
        out.append(h.body)
    return out


def _following_stmts(fn_node: ast.AST, call: ast.Call) -> list[ast.stmt]:
    """Statements that execute AFTER the one containing ``call`` —
    its later siblings at every nesting level, so a read in the other
    arm of an if/else does not count.  (Loop back-edges are a known
    hole: a donated call late in a loop body with a reuse early in
    the next iteration is missed.)"""
    out: list[ast.stmt] = []

    def rec(body: list[ast.stmt]) -> str | None:
        """None = call not in this block; else 'open'/'terminated' —
        whether the path containing the call falls through this block
        (``return _f(x, donated=...)`` terminates it: later siblings
        are unreachable on the call's path)."""
        for i, stmt in enumerate(body):
            if not any(n is call for n in ast.walk(stmt)):
                continue
            terminated = isinstance(stmt, (ast.Return, ast.Raise))
            for blk in _blocks_of(stmt):
                r = rec(blk)
                if r is not None:
                    terminated = terminated or r == "terminated"
                    break
            if not terminated:
                rest = body[i + 1:]
                out.extend(rest)
                terminated = any(isinstance(s, (ast.Return, ast.Raise))
                                 for s in rest)
            return "terminated" if terminated else "open"
        return None

    rec(fn_node.body)
    return out


def _donated_reuse_at(fi: FunctionInfo, call: ast.Call,
                      info: JitInfo) -> list[Finding]:
    donated: set[str] = set()
    for i in info.donate_nums:
        if i < len(call.args) and isinstance(call.args[i], ast.Name):
            donated.add(call.args[i].id)
    if not donated:
        return []
    # names the call's result rebinds are fresh again: x = f(x)
    rebound: set[str] = set()
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Assign) and node.value is call:
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        rebound.add(n.id)
    live = donated - rebound
    if not live:
        return []
    out = []
    for stmt in _following_stmts(fi.node, call):
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and node.id in live:
                out.append(Finding(
                    "jit-donated-reuse", fi.rel, node.lineno, "error",
                    f"donated buffer {node.id!r} read after the call "
                    f"to {info.fi.name}() in {fi.qualname}"))
                live.discard(node.id)
    return out
