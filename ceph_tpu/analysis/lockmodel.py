"""Shared lock/acquisition model: who holds what, where.

One lexical walk per function produces the event stream both deep
analyses consume:

- ``acquire`` — a ``with self._lock:`` / ``with _module_lock:`` entry,
  with the locks already held at that point (the lock-ORDER edge);
- ``call``    — any call site, with the locks held around it;
- ``mutate``  — a store to ``self.attr`` (plain, augmented, or through
  a subscript on the attribute), with the locks held around it.

Lock identity is the DEFINING class + attribute name (instances are
not distinguished — a may-analysis over the static acquisition graph,
the ``lockdep.cc`` model), or module path + name for module-level
locks.  Nested function/class definitions are their own functions in
the index; the walker does not leak the enclosing ``with`` into them
(a closure runs later, on whoever calls it).

Known holes, accepted (the baseline covers what leaks through): bare
``.acquire()``/``.release()`` pairs are not tracked (the tree uses
``with`` everywhere), and ``Condition.wait`` dropping the lock while
blocked is not modelled (may-hold stays an over-approximation).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass

from .engine import FunctionInfo, ProjectIndex


@dataclass(frozen=True)
class LockId:
    owner: str                      # defining class name, or module rel
    attr: str
    kind: str                       # Lock / RLock / Condition / ...

    def __str__(self) -> str:
        return f"{self.owner}.{self.attr}"

    def __lt__(self, other: "LockId") -> bool:
        return (self.owner, self.attr) < (other.owner, other.attr)


@dataclass(frozen=True)
class LockEvent:
    kind: str                       # "acquire" | "call" | "mutate"
    node: ast.AST
    held: tuple[LockId, ...]        # locks held AROUND this event
    lock: LockId | None = None      # for acquire
    attr: str | None = None         # for mutate: the self.<attr> stored


def resolve_lock_expr(index: ProjectIndex, fi: FunctionInfo,
                      expr: ast.expr) -> LockId | None:
    """``self._lock`` / module-level ``_lock`` → LockId, else None."""
    mod = index.modules[fi.rel]
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        ci = index.class_of(fi)
        if ci is None:
            return None
        hit = index.lock_attr_owner(ci, expr.attr)
        if hit is None:
            return None
        owner, ctor = hit
        return LockId(owner, expr.attr, ctor)
    if isinstance(expr, ast.Name) and expr.id in mod.module_locks:
        return LockId(fi.rel, expr.id, mod.module_locks[expr.id])
    return None


class _Walker:
    def __init__(self, index: ProjectIndex, fi: FunctionInfo):
        self.index = index
        self.fi = fi
        self.events: list[LockEvent] = []
        self._held: list[LockId] = []

    def walk(self) -> list[LockEvent]:
        for stmt in self.fi.node.body:
            self._visit(stmt)
        return self.events

    def _visit(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return                   # its own function in the index
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: list[LockId] = []
            for item in node.items:
                self._visit(item.context_expr)
                lid = resolve_lock_expr(self.index, self.fi,
                                        item.context_expr)
                if lid is not None:
                    self.events.append(LockEvent(
                        "acquire", item.context_expr,
                        tuple(self._held), lock=lid))
                    self._held.append(lid)
                    acquired.append(lid)
            for stmt in node.body:
                self._visit(stmt)
            for _ in acquired:
                self._held.pop()
            return
        if isinstance(node, ast.Call):
            self.events.append(LockEvent("call", node,
                                         tuple(self._held)))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = self._self_attr_target(t)
                if attr is not None:
                    self.events.append(LockEvent(
                        "mutate", node, tuple(self._held), attr=attr))
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    @staticmethod
    def _self_attr_target(t: ast.expr) -> str | None:
        # self.attr = ... | self.attr[k] = ... | self.attr += ...
        if isinstance(t, (ast.Subscript,)):
            t = t.value
        if isinstance(t, ast.Attribute) and \
                isinstance(t.value, ast.Name) and t.value.id == "self":
            return t.attr
        return None


def lock_events(index: ProjectIndex,
                fi: FunctionInfo) -> list[LockEvent]:
    return _Walker(index, fi).walk()


def may_acquire_closure(index: ProjectIndex,
                        events: dict[str, list[LockEvent]],
                        functions: dict[str, FunctionInfo],
                        max_rounds: int = 6
                        ) -> dict[str, set[LockId]]:
    """Transitive may-acquire per function ref, via resolved calls."""
    acq: dict[str, set[LockId]] = {
        ref: {e.lock for e in evs if e.kind == "acquire"}
        for ref, evs in events.items()}
    call_targets: dict[str, set[str]] = {}
    for ref, evs in events.items():
        targets: set[str] = set()
        for e in evs:
            if e.kind != "call":
                continue
            for callee in index.resolve_call(functions[ref], e.node):
                if callee.ref in events:
                    targets.add(callee.ref)
        call_targets[ref] = targets
    for _ in range(max_rounds):
        changed = False
        for ref, targets in call_targets.items():
            before = len(acq[ref])
            for t in targets:
                acq[ref] |= acq[t]
            changed |= len(acq[ref]) != before
        if not changed:
            break
    return acq
