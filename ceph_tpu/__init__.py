"""ceph_tpu: a TPU-native (JAX/XLA/Pallas) erasure-coding + CRUSH placement
framework with the capabilities of Ceph's ErasureCodePlugin registry and
CRUSH placement engine (reference: /root/reference, v15 octopus dev).

Subpackages:
  gf        GF(2^8) tables + RS matrix algebra (host, exact)
  ops       jit'd device kernels + RSCodec
  plugins   ErasureCodeInterface / plugin registry (jax_rs, xor, lrc, ...)
  crush     bit-exact CRUSH: rjenkins hash, straw2, choose, OSDMap chain
  backend   ECBackend-shaped batching pipeline + in-memory shard store
  parallel  device-mesh sharding of codec batches
  bench     ceph_erasure_code_benchmark-compatible CLI
"""
__version__ = "0.1.0"
