"""ceph_tpu: a TPU-native (JAX/XLA/Pallas) erasure-coding + CRUSH placement
framework with the capabilities of Ceph's ErasureCodePlugin registry and
CRUSH placement engine (reference: /root/reference, v15 octopus dev).

Subpackages:
  gf        GF(2^8)/GF(2^16)/GF(2^32) tables, RS + bitmatrix algebra
  ops       jit'd device kernels (pallas/XLA) + RSCodec
  plugins   ErasureCodeInterface / registry (jax_rs, jerasure, isa, shec,
            lrc, clay, xor + native .so plugins)
  crush     bit-exact CRUSH: rjenkins hash, straw2, do_rule, compiler,
            vmapped bulk mapper
  osdmap    pg->up/acting chain, epochs, incrementals, bulk mapping
  backend   PGBackend abstraction: ECBackend + ReplicatedBackend, stores
            (MemStore/FileStore), wire protocol, message bus
  osd       OSD daemon shell, PrimaryLogPG op engine (snapshots, watch/
            notify, cls), peering statechart, PG log, dmClock
  mon/mgr   monitor + Paxos quorum, heartbeats; balancer, autoscaler,
            prometheus exporter
  client    Objecter, librados facade (Rados/IoCtx), RadosStriper
  cluster   MiniCluster (vstart analog) with durable mode
  tools     crushtool / osdmaptool / rados CLIs
  parallel  device-mesh sharding of codec batches
  bench     ceph_erasure_code_benchmark-compatible CLI
  utils     deterministic schedule explorer (the race-detection axis)

Quick start:
    from ceph_tpu import MiniCluster, Rados
    c = MiniCluster(n_osds=12)
    c.create_ec_pool("data", {"k": "4", "m": "2"})
    io = Rados(c).open_ioctx("data")
    io.write_full("obj", b"hello")
"""
__version__ = "0.1.0"


def __getattr__(name):
    # lazy top-level conveniences (importing the cluster pulls jax;
    # keep `import ceph_tpu` light for tooling)
    if name == "MiniCluster":
        from .cluster import MiniCluster
        return MiniCluster
    if name == "Rados":
        from .client.rados import Rados
        return Rados
    if name == "RadosStriper":
        from .client.striper import RadosStriper
        return RadosStriper
    if name == "ObjectOperation":
        from .osd.osd_ops import ObjectOperation
        return ObjectOperation
    raise AttributeError(name)
