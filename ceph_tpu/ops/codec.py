"""RSCodec: the device-resident Reed-Solomon codec.

Combines host-side matrix algebra (construction + erasure-signature-cached
inversion, mirroring the isa plugin's table cache,
reference: src/erasure-code/isa/ErasureCodeIsaTableCache.h:35-65) with the
jit'd device kernels from rs_kernels.  Shapes are static per (k, m, N);
matrices are traced, so one compilation covers all erasure signatures.
"""
from __future__ import annotations

import collections
import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..common import device_attribution
from ..common.tracer import trace_span
from ..gf import matrix as gfm
from ..gf import ref as gfref
from . import rs_kernels

TECHNIQUES = {
    "reed_sol_van": gfm.rs_vandermonde_jerasure,
    "vandermonde": gfm.rs_vandermonde_isa,
    "cauchy": gfm.cauchy1,
}

# Matches the isa decode-table LRU capacity, "sufficient up to (12,4)"
# (reference: src/erasure-code/isa/ErasureCodeIsaTableCache.h:46-48).
DECODE_CACHE_SIZE = 2516


class _DecodeTables:
    """One signature's cached decode state: the host matrix, the source
    chunk order, and — uploaded lazily, then pinned for the LRU entry's
    lifetime — the device-resident copy.  The device copy is what keeps
    an LRU *hit* from paying a host->device matrix transfer per call."""

    __slots__ = ("D", "src", "dev")

    def __init__(self, D: np.ndarray, src: list[int]):
        self.D = D
        self.src = src
        self.dev: jax.Array | None = None


@functools.partial(jax.jit, static_argnames=("variant",),
                   donate_argnums=(1,))
def _gf_apply_donated(mat, data, variant):
    """Steady-state pipeline apply with the data buffer DONATED: the
    packed input block is dead after the dispatch (the pipeline packs a
    fresh one per batch), so XLA may reuse its pages for scratch/output
    instead of holding both live.  TPU-only — the CPU runtime cannot
    alias them and would warn per call."""
    return rs_kernels.gf_apply(mat, data, variant)


def _donation_supported() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:            # backend init failure -> act like CPU
        return False


@functools.partial(jax.jit, static_argnames=("variant",))
def _gf_scale_accumulate(mat, data, acc, variant):
    """One chained-repair hop's partial-sum update: ``mat @ data XOR acc``
    over GF(2^8) — the survivor scales its local chunk by its decode
    coefficients and folds it into the running sum in a single fused
    dispatch (no intermediate host round-trip)."""
    return jnp.bitwise_xor(rs_kernels.gf_apply(mat, data, variant), acc)


def scale_accumulate_device(mat, data, acc, variant: str = "auto"):
    """Device scale-accumulate for a chain hop: ``mat`` [r, 1] decode
    coefficients, ``data`` [1, N] the hop's local chunk stream, ``acc``
    [r, N] running partial sums (or None on the first hop) -> [r, N] on
    device.  One jitted dispatch either way; the shapes are static per
    (r, N) so chains over a wave share a single compilation."""
    if acc is None:
        return rs_kernels.gf_apply(jnp.asarray(mat), jnp.asarray(data),
                                   variant)
    return _gf_scale_accumulate(jnp.asarray(mat), jnp.asarray(data),
                                jnp.asarray(acc), variant)


def scale_accumulate_host(mat: np.ndarray, data: np.ndarray,
                          acc: np.ndarray | None) -> np.ndarray:
    """Exact host sibling of :func:`scale_accumulate_device` (breaker
    fallback and the no-pipeline path)."""
    out = gfref.apply_matrix_fast(
        np.ascontiguousarray(mat, dtype=np.uint8),
        np.ascontiguousarray(data, dtype=np.uint8))
    if acc is not None:
        np.bitwise_xor(out, acc, out=out)
    return out


@functools.partial(jax.jit, static_argnames=("variant",))
def _gf_inner_product(mat, data, variant):
    """Regenerating-repair inner product: ``mat @ data`` over GF(2^8) in
    one fused dispatch.  ``mat`` is a helper's projection row (1 x alpha)
    or the newcomer's combine matrix (alpha x d); ``data`` is the stored
    chunk's symbol rows (alpha x N) or the stacked helper beta-streams
    (d x N).  Shapes are static per (rows, N), so every helper in a wave
    shares one compilation."""
    return rs_kernels.gf_apply(mat, data, variant)


def gf_inner_product_device(mat, data, variant: str = "auto"):
    """Device GF matrix-vector product for the product-matrix repair legs
    (helper projection and newcomer combine) -> jax.Array [rows, N]."""
    return _gf_inner_product(jnp.asarray(mat), jnp.asarray(data), variant)


def gf_inner_product_host(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Exact host sibling of :func:`gf_inner_product_device` (breaker
    fallback and the no-pipeline path)."""
    return gfref.apply_matrix_fast(
        np.ascontiguousarray(mat, dtype=np.uint8),
        np.ascontiguousarray(data, dtype=np.uint8))


class RSCodec:
    """Systematic RS(k, m) over GF(2^8), poly 0x11D.

    device='jax' runs the jit'd TPU kernels; device='numpy' is the exact CPU
    fallback used for latency-bound single small stripes.
    """

    def __init__(self, k: int, m: int, technique: str = "reed_sol_van",
                 device: str = "jax", variant: str = "auto"):
        if k < 2 or m < 1 or k + m > 256:
            raise ValueError(f"bad RS parameters k={k} m={m}")
        if technique not in TECHNIQUES:
            raise ValueError(f"unknown technique {technique!r}")
        if technique == "vandermonde":
            # ISA-L's geometric-progression matrix is only MDS inside this
            # envelope (reference: src/erasure-code/isa/ErasureCodeIsa.cc:323-364).
            if k > 32 or m > 4 or (m == 4 and k > 21):
                raise ValueError(
                    f"technique 'vandermonde' requires k<=32, m<=4 "
                    f"(m=4 => k<=21); got k={k} m={m}")
        self.k, self.m, self.technique = k, m, technique
        self.device, self.variant = device, variant
        self.parity_mat = TECHNIQUES[technique](k, m)          # [m, k] uint8
        self._parity_dev = None
        self._decode_cache: collections.OrderedDict = collections.OrderedDict()
        self._lock = threading.Lock()
        # host->device table-transfer counters: the pipeline tests assert
        # an LRU hit costs ZERO uploads (the serving/recovery hot paths
        # must never re-upload a decode matrix per call)
        self.parity_uploads = 0
        self.decode_table_uploads = 0
        self._donate = None          # lazily probed: platform supports it?

    # -- encode ------------------------------------------------------------

    def encode(self, data: np.ndarray) -> np.ndarray:
        """data [k, N] (or [B, k, N]) uint8 -> parity [m, N] (or [B, m, N])."""
        data = np.ascontiguousarray(data, dtype=np.uint8)
        if data.ndim == 3:
            b, k, n = data.shape
            out = self.encode(np.swapaxes(data, 0, 1).reshape(k, b * n))
            return np.swapaxes(out.reshape(self.m, b, n), 0, 1)
        with trace_span("codec.encode", k=self.k, m=self.m,
                        n=int(data.shape[1]), device=self.device):
            if self.device == "numpy":
                return gfref.apply_matrix_fast(self.parity_mat, data)
            self._upload_parity()
            # synchronous dispatch: the launch-return -> fetch interval is
            # device occupancy, charged to the caller's owner class (the
            # pipeline path accounts at its own completion boundary).  The
            # mark is taken AFTER the launch returns: a first-call launch
            # runs trace+XLA compile synchronously, and that host-side
            # interval must not inflate device busy time.
            out = rs_kernels.gf_apply(self._parity_dev, data, self.variant)
            t0 = device_attribution.dispatch_mark()
            host = np.asarray(jax.device_get(out))
            device_attribution.record_batch(None, t0, host.nbytes)
            return host

    def encode_with_crc(self, data: np.ndarray):
        """Fused encode + checksum: parity [m, N] uint8 AND the
        crc32c(0, row) of every row of concat(data, parity) as a
        [k + m] uint32 array, ONE jitted dispatch (the checksum pass
        rides the rows the encode just produced instead of a host
        crc loop over fetched shards).  Seed-free crcs: callers chain
        them into ceph's running HashInfo semantics with
        ``ecutil.crc32c_zeros`` (see :meth:`HashInfo.append_crcs`)."""
        data = np.ascontiguousarray(data, dtype=np.uint8)
        with trace_span("codec.encode_with_crc", k=self.k, m=self.m,
                        n=int(data.shape[1]), device=self.device):
            if self.device == "numpy":
                from ..backend import ecutil
                parity = gfref.apply_matrix_fast(self.parity_mat, data)
                crcs = np.array(
                    [ecutil.crc32c(0, bytes(r))
                     for r in np.concatenate([data, parity], axis=0)],
                    dtype=np.uint32)
                return parity, crcs
            self._upload_parity()
            parity, crcs = rs_kernels.gf_encode_with_crc(
                self._parity_dev, data, self.variant)
            t0 = device_attribution.dispatch_mark()
            parity_h = np.asarray(jax.device_get(parity))
            crcs_h = np.asarray(jax.device_get(crcs))
            device_attribution.record_batch(None, t0, parity_h.nbytes)
            return parity_h, crcs_h

    def encode_host(self, data: np.ndarray) -> np.ndarray:
        """Pure-host parity (the exact CPU reference path) REGARDLESS of
        ``self.device`` — the circuit breaker's fallback when the device
        side is failing: data [k, N] uint8 -> parity [m, N]."""
        with trace_span("codec.encode_host", k=self.k, m=self.m,
                        n=int(data.shape[-1])):
            return gfref.apply_matrix_fast(
                self.parity_mat, np.ascontiguousarray(data,
                                                      dtype=np.uint8))

    def decode_host(self, stack: np.ndarray, erasures: list[int],
                    available: list[int]) -> np.ndarray:
        """Pure-host recovery, device never touched: ``stack`` [k', N]
        survivors already in the ``src`` order ``decode_matrix(erasures,
        available)`` returns -> recovered rows [len(erasures), N].  The
        host sibling of :meth:`decode_device` for breaker fallback."""
        entry = self._decode_entry(sorted(int(e) for e in erasures),
                                   available=list(available))
        with trace_span("codec.decode_host", k=self.k, m=self.m,
                        n=int(stack.shape[-1]), erasures=len(erasures)):
            return gfref.apply_matrix_fast(
                entry.D, np.ascontiguousarray(stack, dtype=np.uint8))

    def _upload_parity(self) -> None:
        if self._parity_dev is None:
            with trace_span("codec.table_upload",
                            bytes=int(self.parity_mat.nbytes)):
                self._parity_dev = jnp.asarray(self.parity_mat)
                self.parity_uploads += 1

    def _donation_ok(self) -> bool:
        if self._donate is None:
            self._donate = _donation_supported()
        return self._donate

    def encode_device(self, data: jax.Array,
                      donate: bool = False) -> jax.Array:
        """Device-to-device encode (no host transfer), for pipeline use.
        ``donate=True`` marks ``data`` dead-after-call on platforms that
        support buffer donation (the pipeline's steady-state path)."""
        self._upload_parity()
        if donate and self._donation_ok():
            return _gf_apply_donated(self._parity_dev, data, self.variant)
        return rs_kernels.gf_apply(self._parity_dev, data, self.variant)

    # -- decode ------------------------------------------------------------

    def _decode_entry(self, erasures, available=None) -> _DecodeTables:
        """Signature-LRU lookup/build of the shared decode state."""
        sig = (tuple(sorted(int(e) for e in erasures)),
               None if available is None else tuple(sorted(int(a) for a in available)))
        with self._lock:
            hit = self._decode_cache.get(sig)
            if hit is not None:
                self._decode_cache.move_to_end(sig)
                return hit
        with trace_span("codec.decode_matrix_build", k=self.k, m=self.m,
                        erasures=len(sig[0])):
            D, src = gfm.decode_matrix(self.parity_mat, list(erasures),
                                       available)
        entry = _DecodeTables(D, src)
        with self._lock:
            entry = self._decode_cache.setdefault(sig, entry)
            self._decode_cache.move_to_end(sig)
            if len(self._decode_cache) > DECODE_CACHE_SIZE:
                self._decode_cache.popitem(last=False)
        return entry

    def decode_matrix(self, erasures, available=None):
        """Signature-LRU-cached (decode matrix, source chunk list)."""
        entry = self._decode_entry(erasures, available)
        return entry.D, entry.src

    def decode_matrix_device(self, erasures, available=None):
        """Like :meth:`decode_matrix` but the matrix is the DEVICE-resident
        copy, uploaded once per LRU entry: an LRU hit costs zero
        host->device transfers (the re-upload-per-call bug the pipeline
        tests pin via ``decode_table_uploads``)."""
        entry = self._decode_entry(erasures, available)
        return self._entry_device(entry), entry.src

    def _entry_device(self, entry: _DecodeTables) -> jax.Array:
        """Pin (lazily uploading) an already-fetched entry's device copy —
        one LRU lookup per decode call, not two."""
        if entry.dev is None:
            # upload outside the lock (it can be slow), publish under it:
            # two threads racing a fresh signature upload twice but count
            # once, and the pinned copy is whichever published first
            with trace_span("codec.table_upload", bytes=int(entry.D.nbytes)):
                dev = jnp.asarray(entry.D)
            with self._lock:
                if entry.dev is None:
                    entry.dev = dev
                    self.decode_table_uploads += 1
        return entry.dev

    def decode(self, chunks: dict[int, np.ndarray],
               erasures: list[int]) -> dict[int, np.ndarray]:
        """Recover the erased chunk indices from surviving chunks.

        chunks: {index: [N] uint8} (>= k survivors), erasures: lost indices.
        """
        erasures = sorted(int(e) for e in erasures)
        if not erasures:
            return {}
        entry = self._decode_entry(erasures, available=list(chunks))
        stack = np.stack([np.asarray(chunks[i], dtype=np.uint8)
                          for i in entry.src])
        with trace_span("codec.decode", k=self.k, m=self.m,
                        n=int(stack.shape[1]), erasures=len(erasures),
                        device=self.device):
            if self.device == "numpy":
                rec = gfref.apply_matrix_fast(entry.D, stack)
            else:
                # mark after the launch returns (compile time is host time)
                out = rs_kernels.gf_apply(self._entry_device(entry), stack,
                                          self.variant)
                t0 = device_attribution.dispatch_mark()
                rec = np.asarray(jax.device_get(out))
                device_attribution.record_batch(None, t0, rec.nbytes)
        return {e: rec[i] for i, e in enumerate(erasures)}

    @staticmethod
    def _src_index_map(src: list[int],
                       src_expected: list[int]) -> list[int] | None:
        """Row gather mapping caller order -> decode_matrix order, or None
        when it is the identity over a prefix (precomputed in O(k) — the
        per-element ``src.index(s)`` scan was O(k^2) per batch)."""
        if src == src_expected:
            return None
        pos = {s: i for i, s in enumerate(src)}
        idx = [pos[s] for s in src_expected]
        if idx == list(range(len(idx))):
            return None          # identity after dropping extras: slice, no gather
        return idx

    def decode_batch(self, stack: np.ndarray, src: list[int],
                     erasures: list[int]) -> np.ndarray:
        """Batched decode with one shared erasure signature.

        stack: [B, k, N] survivors in ``src`` order -> [B, len(erasures), N].
        """
        src = [int(s) for s in src]
        entry = self._decode_entry(erasures, available=src)
        idx = self._src_index_map(src, entry.src)
        if idx is not None:
            stack = stack[:, idx, :]
        elif len(entry.src) != stack.shape[1]:
            stack = stack[:, :len(entry.src), :]     # drop extras: a view
        b, k, n = stack.shape
        folded = np.ascontiguousarray(
            np.swapaxes(stack, 0, 1).reshape(k, b * n), dtype=np.uint8)
        with trace_span("codec.decode_batch", k=self.k, m=self.m,
                        batch=int(b), n=int(n), erasures=len(erasures),
                        device=self.device):
            if self.device == "numpy":
                rec = gfref.apply_matrix_fast(entry.D, folded)
            else:
                # mark after the launch returns (compile time is host time)
                out = rs_kernels.gf_apply(self._entry_device(entry), folded,
                                          self.variant)
                t0 = device_attribution.dispatch_mark()
                rec = np.asarray(jax.device_get(out))
                device_attribution.record_batch(None, t0, rec.nbytes)
        return np.swapaxes(rec.reshape(len(erasures), b, n), 0, 1)

    # -- device-resident decode (no host round-trip; pipeline path) --------

    def decode_device(self, stack: jax.Array, erasures: list[int],
                      available: list[int] | None = None,
                      donate: bool = False) -> jax.Array:
        """Device-to-device decode: ``stack`` [k, N] survivors already in
        the sorted-src order ``decode_matrix(erasures, available)``
        returns -> recovered rows [len(erasures), N], still on device.
        No ``device_get`` and no matrix re-upload — the decode matrix
        rides the signature LRU's device copy."""
        erasures = sorted(int(e) for e in erasures)
        D_dev, src = self.decode_matrix_device(erasures, available)
        if int(stack.shape[0]) != len(src):
            raise ValueError(
                f"stack has {stack.shape[0]} rows for {len(src)} sources")
        if donate and self._donation_ok():
            return _gf_apply_donated(D_dev, stack, self.variant)
        return rs_kernels.gf_apply(D_dev, stack, self.variant)

    def decode_batch_device(self, stack: jax.Array, src: list[int],
                            erasures: list[int],
                            donate: bool = False) -> jax.Array:
        """Device-to-device batched decode: ``stack`` [B, k', N] survivors
        in ``src`` order -> [B, len(erasures), N] on device.  The row
        permutation, fold and unfold all run as device ops, so nothing
        touches the host."""
        src = [int(s) for s in src]
        erasures = sorted(int(e) for e in erasures)
        D_dev, src_expected = self.decode_matrix_device(erasures,
                                                        available=src)
        idx = self._src_index_map(src, src_expected)
        if idx is not None:
            stack = jnp.take(stack, jnp.asarray(idx), axis=1)
        elif len(src_expected) != int(stack.shape[1]):
            stack = stack[:, :len(src_expected), :]
        b, k, n = (int(s) for s in stack.shape)
        folded = jnp.swapaxes(stack, 0, 1).reshape(k, b * n)
        if donate and self._donation_ok():
            rec = _gf_apply_donated(D_dev, folded, self.variant)
        else:
            rec = rs_kernels.gf_apply(D_dev, folded, self.variant)
        return jnp.swapaxes(rec.reshape(len(erasures), b, n), 0, 1)
