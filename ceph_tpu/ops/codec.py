"""RSCodec: the device-resident Reed-Solomon codec.

Combines host-side matrix algebra (construction + erasure-signature-cached
inversion, mirroring the isa plugin's table cache,
reference: src/erasure-code/isa/ErasureCodeIsaTableCache.h:35-65) with the
jit'd device kernels from rs_kernels.  Shapes are static per (k, m, N);
matrices are traced, so one compilation covers all erasure signatures.
"""
from __future__ import annotations

import collections
import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..common.tracer import trace_span
from ..gf import matrix as gfm
from ..gf import ref as gfref
from . import rs_kernels

TECHNIQUES = {
    "reed_sol_van": gfm.rs_vandermonde_jerasure,
    "vandermonde": gfm.rs_vandermonde_isa,
    "cauchy": gfm.cauchy1,
}

# Matches the isa decode-table LRU capacity, "sufficient up to (12,4)"
# (reference: src/erasure-code/isa/ErasureCodeIsaTableCache.h:46-48).
DECODE_CACHE_SIZE = 2516


class RSCodec:
    """Systematic RS(k, m) over GF(2^8), poly 0x11D.

    device='jax' runs the jit'd TPU kernels; device='numpy' is the exact CPU
    fallback used for latency-bound single small stripes.
    """

    def __init__(self, k: int, m: int, technique: str = "reed_sol_van",
                 device: str = "jax", variant: str = "auto"):
        if k < 2 or m < 1 or k + m > 256:
            raise ValueError(f"bad RS parameters k={k} m={m}")
        if technique not in TECHNIQUES:
            raise ValueError(f"unknown technique {technique!r}")
        if technique == "vandermonde":
            # ISA-L's geometric-progression matrix is only MDS inside this
            # envelope (reference: src/erasure-code/isa/ErasureCodeIsa.cc:323-364).
            if k > 32 or m > 4 or (m == 4 and k > 21):
                raise ValueError(
                    f"technique 'vandermonde' requires k<=32, m<=4 "
                    f"(m=4 => k<=21); got k={k} m={m}")
        self.k, self.m, self.technique = k, m, technique
        self.device, self.variant = device, variant
        self.parity_mat = TECHNIQUES[technique](k, m)          # [m, k] uint8
        self._parity_dev = None
        self._decode_cache: collections.OrderedDict = collections.OrderedDict()
        self._lock = threading.Lock()

    # -- encode ------------------------------------------------------------

    def encode(self, data: np.ndarray) -> np.ndarray:
        """data [k, N] (or [B, k, N]) uint8 -> parity [m, N] (or [B, m, N])."""
        data = np.ascontiguousarray(data, dtype=np.uint8)
        if data.ndim == 3:
            b, k, n = data.shape
            out = self.encode(np.swapaxes(data, 0, 1).reshape(k, b * n))
            return np.swapaxes(out.reshape(self.m, b, n), 0, 1)
        with trace_span("codec.encode", k=self.k, m=self.m,
                        n=int(data.shape[1]), device=self.device):
            if self.device == "numpy":
                return gfref.apply_matrix_fast(self.parity_mat, data)
            self._upload_parity()
            out = rs_kernels.gf_apply(self._parity_dev, data, self.variant)
            return np.asarray(jax.device_get(out))

    def _upload_parity(self) -> None:
        if self._parity_dev is None:
            with trace_span("codec.table_upload",
                            bytes=int(self.parity_mat.nbytes)):
                self._parity_dev = jnp.asarray(self.parity_mat)

    def encode_device(self, data: jax.Array) -> jax.Array:
        """Device-to-device encode (no host transfer), for pipeline use."""
        self._upload_parity()
        return rs_kernels.gf_apply(self._parity_dev, data, self.variant)

    # -- decode ------------------------------------------------------------

    def decode_matrix(self, erasures, available=None):
        """Signature-LRU-cached (decode matrix, source chunk list)."""
        sig = (tuple(sorted(int(e) for e in erasures)),
               None if available is None else tuple(sorted(int(a) for a in available)))
        with self._lock:
            hit = self._decode_cache.get(sig)
            if hit is not None:
                self._decode_cache.move_to_end(sig)
                return hit
        with trace_span("codec.decode_matrix_build", k=self.k, m=self.m,
                        erasures=len(sig[0])):
            D, src = gfm.decode_matrix(self.parity_mat, list(erasures),
                                       available)
        with self._lock:
            self._decode_cache[sig] = (D, src)
            if len(self._decode_cache) > DECODE_CACHE_SIZE:
                self._decode_cache.popitem(last=False)
        return D, src

    def decode(self, chunks: dict[int, np.ndarray],
               erasures: list[int]) -> dict[int, np.ndarray]:
        """Recover the erased chunk indices from surviving chunks.

        chunks: {index: [N] uint8} (>= k survivors), erasures: lost indices.
        """
        erasures = sorted(int(e) for e in erasures)
        if not erasures:
            return {}
        D, src = self.decode_matrix(erasures, available=list(chunks))
        stack = np.stack([np.asarray(chunks[i], dtype=np.uint8) for i in src])
        with trace_span("codec.decode", k=self.k, m=self.m,
                        n=int(stack.shape[1]), erasures=len(erasures),
                        device=self.device):
            if self.device == "numpy":
                rec = gfref.apply_matrix_fast(D, stack)
            else:
                rec = np.asarray(jax.device_get(
                    rs_kernels.gf_apply(jnp.asarray(D), stack,
                                        self.variant)))
        return {e: rec[i] for i, e in enumerate(erasures)}

    def decode_batch(self, stack: np.ndarray, src: list[int],
                     erasures: list[int]) -> np.ndarray:
        """Batched decode with one shared erasure signature.

        stack: [B, k, N] survivors in ``src`` order -> [B, len(erasures), N].
        """
        src = [int(s) for s in src]
        D, src_expected = self.decode_matrix(erasures, available=src)
        if src != src_expected:
            # decode_matrix always works in sorted-src order; permute the
            # caller's rows to match (and drop extras beyond the k used).
            stack = stack[:, [src.index(s) for s in src_expected], :]
        b, k, n = stack.shape
        folded = np.ascontiguousarray(
            np.swapaxes(stack, 0, 1).reshape(k, b * n), dtype=np.uint8)
        with trace_span("codec.decode_batch", k=self.k, m=self.m,
                        batch=int(b), n=int(n), erasures=len(erasures),
                        device=self.device):
            if self.device == "numpy":
                rec = gfref.apply_matrix_fast(D, folded)
            else:
                rec = np.asarray(jax.device_get(
                    rs_kernels.gf_apply(jnp.asarray(D), folded,
                                        self.variant)))
        return np.swapaxes(rec.reshape(len(erasures), b, n), 0, 1)
