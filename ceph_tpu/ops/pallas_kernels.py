"""Pallas TPU kernel for GF(2^8) matrix application (the RS hot op).

Same math as :mod:`ceph_tpu.ops.rs_kernels` (out = mat @GF data), but the
whole bitslice pipeline — byte->bit-plane unpack, GF(2) matmul on the MXU,
mod-2, bit-plane->byte pack — is fused into ONE kernel over VMEM tiles.

Why it can beat the XLA path: the XLA bitslice graph materialises the
unpacked bit-planes ([8k, N] bf16 = 16x the input bytes) and the f32
accumulator ([8r, N] = 32x the output bytes) in HBM between fusions; this
kernel streams uint8 in and uint8 out, holding the 16x/32x inflation only
in VMEM — HBM traffic drops to the information-theoretic (k+r)/N bytes per
byte, and the op is HBM-bound (SURVEY.md: HBM bandwidth is the usual
bottleneck; pallas_guide.md "fuse what XLA can't").

Bit-plane layout is plane-major (row b*k+j = bit b of chunk j) so the
in-kernel unpack/pack are static concatenates/slices — no sublane
reshuffles for Mosaic to choke on.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..gf.tables import MUL_TABLE

DEFAULT_TILE = 8192   # best sustained stream in the k=8,m=4 sweep on v5e


def expand_bits_plane_major(mat: jax.Array) -> jax.Array:
    """GF(2^8) matrix [r, k] -> GF(2) bit-matrix [8r, 8k], plane-major:

    B[bi*r + i, bj*k + j] = bit bi of (mat[i, j] * 2^bj  in GF(2^8)).
    """
    from .rs_kernels import expand_bits_raw
    r, k = mat.shape
    bits = expand_bits_raw(mat)                   # [r, bi, k, bj]
    return bits.transpose(1, 0, 3, 2).reshape(8 * r, 8 * k)


def _gf_stripes_kernel(bmat_ref, data_ref, out_ref, *, r: int, k: int,
                       groups: int):
    """Vertical-layout fused kernel: the block holds ``groups`` stripe
    slabs of k chunk rows each; all slabs go through ONE int8 MXU matmul
    against a block-diagonal bit-matrix.

    Why this shape wins (measured on v5e, tools/kernel_sweep.py):
    - int8 with int32 accumulation doubles MXU peak vs bf16 (the sums are
      0/1 bits, <= 8k terms, exact either way);
    - the block-diagonal stacking lifts the degenerate [8r, 8k] = [32, 64]
      stationary operand (1/8 of the 128x128 MXU busy at k=8, m=4) to
      [G*8r, G*8k] = [128, 256] — full tiles;
    - tall [G*k, T] uint8 blocks occupy 32 sublanes instead of 8, so the
      VMEM copies and DMAs run at full width.
    """
    d = data_ref[:].astype(jnp.int32)                 # [G*k, T]
    parts = []
    for g in range(groups):
        slab = d[g * k:(g + 1) * k]
        parts.extend(((slab >> b) & 1) for b in range(8))
    bits = jnp.concatenate(parts, axis=0).astype(jnp.int8)   # [G*8k, T]
    acc = jax.lax.dot_general(
        bmat_ref[:], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32) & 1         # [G*8r, T], mod 2
    outs = []
    for g in range(groups):
        base = g * 8 * r
        o = acc[base:base + r]
        for b in range(1, 8):
            o = o | (acc[base + b * r:(base + (b + 1) * r)] << b)
        outs.append(o)
    out_ref[:] = jnp.concatenate(outs, axis=0).astype(jnp.uint8)


@functools.partial(jax.jit,
                   static_argnames=("stripes", "groups", "tile_n",
                                    "interpret"))
def gf_apply_stripes_pallas(mat: jax.Array, data: jax.Array, stripes: int,
                            groups: int = 4, tile_n: int = 8192,
                            interpret: bool = False) -> jax.Array:
    """Batched GF apply over the VERTICAL stripe layout.

    data: [stripes * k, chunk_bytes] uint8 — stripe s occupies rows
    [s*k, (s+1)*k).  Returns [stripes * r, chunk_bytes], stripe s's parity
    at rows [s*r, (s+1)*r).  This is the codec's device-native batch
    layout: stripes arrive one after another from the IO path, so stacking
    them as rows is a no-copy append, and it feeds the MXU full tiles
    (see _gf_stripes_kernel).
    """
    from jax.experimental import pallas as pl

    mat = jnp.asarray(mat, dtype=jnp.uint8)
    data = jnp.asarray(data, dtype=jnp.uint8)
    r, k = mat.shape
    rows, n = data.shape
    assert rows == stripes * k, f"{rows} rows != {stripes} stripes x {k}"
    groups = max(1, min(groups, stripes))
    # pad the stripe count to a group multiple (zero stripes encode to
    # zero parity) and the byte axis to a lane multiple
    s_pad = (-stripes) % groups
    if s_pad:
        data = jnp.pad(data, ((0, s_pad * k), (0, 0)))
    s_total = stripes + s_pad
    n_tiles = max(1, -(-n // tile_n))
    tile = max(128, (-(-n // n_tiles) + 127) // 128 * 128)
    n_pad = n_tiles * tile
    if n_pad != n:
        data = jnp.pad(data, ((0, 0), (0, n_pad - n)))

    bexp = expand_bits_plane_major(mat)                       # [8r, 8k]
    blocks = []
    for g in range(groups):
        row = [jnp.zeros((8 * r, 8 * k), jnp.uint8)] * groups
        row[g] = bexp
        blocks.append(jnp.concatenate(row, axis=1))
    bmat = jnp.concatenate(blocks, axis=0).astype(jnp.int8)   # [G8r, G8k]

    out = pl.pallas_call(
        functools.partial(_gf_stripes_kernel, r=r, k=k, groups=groups),
        out_shape=jax.ShapeDtypeStruct((s_total * r, n_pad), jnp.uint8),
        grid=(s_total // groups, n_tiles),
        in_specs=[
            pl.BlockSpec((groups * 8 * r, groups * 8 * k),
                         lambda i, j: (0, 0)),
            pl.BlockSpec((groups * k, tile), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((groups * r, tile), lambda i, j: (i, j)),
        interpret=interpret,
    )(bmat, data)
    if n_pad != n:
        out = out[:, :n]
    if s_pad:
        out = out[:stripes * r]
    return out


def _gf_kernel(bmat_ref, data_ref, out_ref, *, r: int, k: int):
    d = data_ref[:].astype(jnp.int32)             # [k, T]
    planes = [((d >> b) & 1) for b in range(8)]
    # int8 x int8 -> int32: exact (0/1 values, <= 8k terms) and 2x the
    # bf16 MXU peak on v5e — measured ~1.3x end-to-end (kernel_sweep.py)
    bits = jnp.concatenate(planes, axis=0).astype(jnp.int8)   # [8k, T]
    acc = jax.lax.dot_general(
        bmat_ref[:], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32) & 1     # mod 2
    out = acc[0:r]
    for b in range(1, 8):
        out = out | (acc[b * r:(b + 1) * r] << b)
    out_ref[:] = out.astype(jnp.uint8)


@functools.partial(jax.jit,
                   static_argnames=("tile_n", "interpret"))
def gf_apply_pallas(mat: jax.Array, data: jax.Array,
                    tile_n: int = DEFAULT_TILE,
                    interpret: bool = False) -> jax.Array:
    """out[r, N] = mat @GF data, fused bitslice pipeline in one kernel.

    mat: [r, k] uint8, data: [k, N] uint8.  N is padded to a tile multiple
    internally (zero GF columns contribute zero parity).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    mat = jnp.asarray(mat, dtype=jnp.uint8)
    data = jnp.asarray(data, dtype=jnp.uint8)
    r, k = mat.shape
    _, n = data.shape
    bmat = expand_bits_plane_major(mat).astype(jnp.int8)

    # pick the tile so padding waste stays < 128 columns per tile (a fixed
    # 8k tile would do up to 8x wasted work at N just over a tile boundary):
    # spread N over ceil(N/tile) tiles of the smallest 128-multiple size
    n_tiles = max(1, -(-n // tile_n))
    tile_n = max(128, (-(-n // n_tiles) + 127) // 128 * 128)
    n_pad = n_tiles * tile_n
    if n_pad != n:
        data = jnp.pad(data, ((0, 0), (0, n_pad - n)))
    grid = (n_tiles,)

    out = pl.pallas_call(
        functools.partial(_gf_kernel, r=r, k=k),
        out_shape=jax.ShapeDtypeStruct((r, n_pad), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * r, 8 * k), lambda i: (0, 0)),
            pl.BlockSpec((k, tile_n), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((r, tile_n), lambda i: (0, i)),
        interpret=interpret,
    )(bmat, data)
    return out[:, :n] if n_pad != n else out


def _xor_kernel(w_ref, data_ref, out_ref):
    """Binary-matrix XOR-matmul tile: the shared bit-plane core with the
    bitmatrix as the operand directly — no coefficient expansion (cf.
    _gf_kernel); inflation stays in VMEM."""
    from .rs_kernels import bitplane_xor_matmul
    out_ref[:] = bitplane_xor_matmul(w_ref[:],
                                     data_ref[:].astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret"))
def xor_apply_pallas(W: jax.Array, packets: jax.Array,
                     tile_n: int = 16384,
                     interpret: bool = False) -> jax.Array:
    """Fused packet-layout bitmatrix apply: W [R, K] 0/1, packets [K, P]
    uint8 -> [R, P].  The data path of the bitmatrix techniques and the
    wide-word (w=16/32) codes: bit-plane inflation stays in VMEM.  Row
    counts ride full blocks, so any (R, K) — e.g. liberation's [14, 28]
    or w=32 reed_sol's [64, 128] — lowers without padding games."""
    from jax.experimental import pallas as pl

    W = jnp.asarray(W, dtype=jnp.int8)
    packets = jnp.asarray(packets, dtype=jnp.uint8)
    r, k = W.shape
    kk, p = packets.shape
    assert kk == k
    n_tiles = max(1, -(-p // tile_n))
    tile = max(128, (-(-p // n_tiles) + 127) // 128 * 128)
    p_pad = n_tiles * tile
    if p_pad != p:
        packets = jnp.pad(packets, ((0, 0), (0, p_pad - p)))
    out = pl.pallas_call(
        _xor_kernel,
        out_shape=jax.ShapeDtypeStruct((r, p_pad), jnp.uint8),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((r, k), lambda i: (0, 0)),
            pl.BlockSpec((k, tile), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((r, tile), lambda i: (0, i)),
        interpret=interpret,
    )(W, packets)
    return out[:, :p] if p_pad != p else out
