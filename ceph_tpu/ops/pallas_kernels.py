"""Pallas TPU kernel for GF(2^8) matrix application (the RS hot op).

Same math as :mod:`ceph_tpu.ops.rs_kernels` (out = mat @GF data), but the
whole bitslice pipeline — byte->bit-plane unpack, GF(2) matmul on the MXU,
mod-2, bit-plane->byte pack — is fused into ONE kernel over VMEM tiles.

Why it can beat the XLA path: the XLA bitslice graph materialises the
unpacked bit-planes ([8k, N] bf16 = 16x the input bytes) and the f32
accumulator ([8r, N] = 32x the output bytes) in HBM between fusions; this
kernel streams uint8 in and uint8 out, holding the 16x/32x inflation only
in VMEM — HBM traffic drops to the information-theoretic (k+r)/N bytes per
byte, and the op is HBM-bound (SURVEY.md: HBM bandwidth is the usual
bottleneck; pallas_guide.md "fuse what XLA can't").

Bit-plane layout is plane-major (row b*k+j = bit b of chunk j) so the
in-kernel unpack/pack are static concatenates/slices — no sublane
reshuffles for Mosaic to choke on.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..gf.tables import MUL_TABLE

DEFAULT_TILE = 8192   # best sustained stream in the k=8,m=4 sweep on v5e


def expand_bits_plane_major(mat: jax.Array) -> jax.Array:
    """GF(2^8) matrix [r, k] -> GF(2) bit-matrix [8r, 8k], plane-major:

    B[bi*r + i, bj*k + j] = bit bi of (mat[i, j] * 2^bj  in GF(2^8)).
    """
    from .rs_kernels import expand_bits_raw
    r, k = mat.shape
    bits = expand_bits_raw(mat)                   # [r, bi, k, bj]
    return bits.transpose(1, 0, 3, 2).reshape(8 * r, 8 * k)


def _gf_kernel(bmat_ref, data_ref, out_ref, *, r: int, k: int):
    d = data_ref[:].astype(jnp.int32)             # [k, T]
    planes = [((d >> b) & 1) for b in range(8)]
    bits = jnp.concatenate(planes, axis=0).astype(jnp.bfloat16)  # [8k, T]
    acc = jax.lax.dot_general(
        bmat_ref[:], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)       # [8r, T] exact int sums
    acc = acc.astype(jnp.int32) & 1               # mod 2
    out = acc[0:r]
    for b in range(1, 8):
        out = out | (acc[b * r:(b + 1) * r] << b)
    out_ref[:] = out.astype(jnp.uint8)


@functools.partial(jax.jit,
                   static_argnames=("tile_n", "interpret"))
def gf_apply_pallas(mat: jax.Array, data: jax.Array,
                    tile_n: int = DEFAULT_TILE,
                    interpret: bool = False) -> jax.Array:
    """out[r, N] = mat @GF data, fused bitslice pipeline in one kernel.

    mat: [r, k] uint8, data: [k, N] uint8.  N is padded to a tile multiple
    internally (zero GF columns contribute zero parity).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    mat = jnp.asarray(mat, dtype=jnp.uint8)
    data = jnp.asarray(data, dtype=jnp.uint8)
    r, k = mat.shape
    _, n = data.shape
    bmat = expand_bits_plane_major(mat).astype(jnp.bfloat16)

    # pick the tile so padding waste stays < 128 columns per tile (a fixed
    # 8k tile would do up to 8x wasted work at N just over a tile boundary):
    # spread N over ceil(N/tile) tiles of the smallest 128-multiple size
    n_tiles = max(1, -(-n // tile_n))
    tile_n = max(128, (-(-n // n_tiles) + 127) // 128 * 128)
    n_pad = n_tiles * tile_n
    if n_pad != n:
        data = jnp.pad(data, ((0, 0), (0, n_pad - n)))
    grid = (n_tiles,)

    out = pl.pallas_call(
        functools.partial(_gf_kernel, r=r, k=k),
        out_shape=jax.ShapeDtypeStruct((r, n_pad), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * r, 8 * k), lambda i: (0, 0)),
            pl.BlockSpec((k, tile_n), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((r, tile_n), lambda i: (0, i)),
        interpret=interpret,
    )(bmat, data)
    return out[:, :n] if n_pad != n else out
