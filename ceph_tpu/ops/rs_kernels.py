"""TPU device kernels for GF(2^8) matrix application (RS encode/decode).

The one primitive both encode and decode need is

    out[i, :] = XOR_j  mat[i, j] * data[j, :]     (GF(2^8))

with ``mat`` tiny ([m, k] for encode, [n_lost, k] for decode) and ``data``
huge ([k, N] bytes).  Two TPU-first realisations, both jit'd with the matrix
as a *traced* argument so a single compilation per (r, k, N) shape serves
every coefficient matrix and every erasure signature (the reference instead
caches per-signature CPU decode tables, src/erasure-code/isa/ErasureCodeIsa.cc:227-304):

- ``bitslice``: expand the GF(2^8) matrix to its GF(2) bit-matrix [8r, 8k]
  (each coefficient becomes the 8x8 binary matrix of "multiply by c"), unpack
  data bytes to bit-planes, and compute the GF(2) product as a bf16 matmul on
  the MXU with f32 accumulation (exact: 0/1 values, <=2^8 terms), then mod-2
  and repack.  This turns erasure coding into the MXU's native operation.
- ``lookup``: gather-based VPU path: per-coefficient 256-entry product tables
  (rows of the global 256x256 table) indexed by the data bytes, XOR-reduced
  over j.  Fewer memory blowups, no MXU; wins for small r*k.

Data layout convention everywhere: uint8 arrays [chunks, chunk_bytes]; a
batch of stripes is folded into the byte axis (the matrix is the same for
every stripe, so [k, B*N] == B stripes of [k, N]).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..gf.tables import MUL_TABLE
from .traced_jit import traced_jit

def _mul_dev():
    """The 256x256 GF(2^8) product table as a trace-time constant (64 KiB)."""
    return jnp.asarray(MUL_TABLE)


def expand_bits_raw(mat: jax.Array) -> jax.Array:
    """Traced GF(2^8) matrix [r, k] -> GF(2) bits [r, bi, k, bj] (uint8 0/1):
    bit bi of (mat[i,j] * 2^bj).  Shared by the interleaved (XLA bitslice)
    and plane-major (pallas) layouts, which differ only in the final
    reshape."""
    powers = jnp.asarray([1 << j for j in range(8)], dtype=jnp.uint8)
    # mv[i, j, bj] = mat[i,j] * 2^bj in GF(2^8)
    mv = _mul_dev()[mat.astype(jnp.int32)[:, :, None],
                    powers.astype(jnp.int32)[None, None, :]]
    bi = jnp.arange(8, dtype=jnp.uint8)[None, :, None, None]
    return (mv[:, None, :, :] >> bi) & 1          # [r, bi, k, bj]


def _expand_bits_device(mat: jax.Array) -> jax.Array:
    """Interleaved layout [8r, 8k]: B[8i+bi, 8j+bj]."""
    r, k = mat.shape
    return expand_bits_raw(mat).reshape(8 * r, 8 * k)


def _unpack_bits(data: jax.Array) -> jax.Array:
    """uint8 [k, N] -> bit-planes [8k, N] (row 8j+bj = bit bj of chunk j)."""
    k, n = data.shape
    bj = jnp.arange(8, dtype=jnp.uint8)[None, :, None]
    bits = (data[:, None, :] >> bj) & 1           # [k, 8, N]
    return bits.reshape(8 * k, n)


def _pack_bits(bits: jax.Array) -> jax.Array:
    """int32 bit-planes [8r, N] -> uint8 [r, N]."""
    rr, n = bits.shape
    r = rr // 8
    w = jnp.asarray([1 << i for i in range(8)], dtype=jnp.int32)[None, :, None]
    return (bits.reshape(r, 8, n) * w).sum(axis=1).astype(jnp.uint8)


@traced_jit
def gf_apply_bitslice(mat: jax.Array, data: jax.Array) -> jax.Array:
    """MXU path: out = mat @GF data via GF(2) bf16 matmul."""
    B = _expand_bits_device(mat).astype(jnp.bfloat16)      # [8r, 8k]
    x = _unpack_bits(data).astype(jnp.bfloat16)            # [8k, N]
    acc = jax.lax.dot_general(
        B, x, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                # exact integer sums
    bits = acc.astype(jnp.int32) & 1                       # mod 2
    return _pack_bits(bits)


@traced_jit
def gf_apply_lookup(mat: jax.Array, data: jax.Array) -> jax.Array:
    """VPU path: per-coefficient 256-entry product-table gathers, XOR-reduced."""
    tables = _mul_dev()[mat.astype(jnp.int32)]             # [r, k, 256]

    def one(tab_j, d_j):                                   # [r,256], [N] -> [r,N]
        return jnp.take(tab_j, d_j.astype(jnp.int32), axis=1)

    terms = jax.vmap(one, in_axes=(1, 0))(tables, data)    # [k, r, N]
    return jax.lax.reduce(terms, np.uint8(0), jax.lax.bitwise_xor, [0])


@traced_jit
def xor_reduce(data: jax.Array) -> jax.Array:
    """XOR of all chunk rows: [k, N] -> [1, N] (m=1 / parity-row-of-ones path,
    cf. the isa plugin's region_xor short-circuit, ErasureCodeIsa.cc:119-131)."""
    return jax.lax.reduce(data, np.uint8(0), jax.lax.bitwise_xor, [0])[None, :]


def _runs_on_tpu(data) -> bool:
    """Where will this op execute?  For concrete arrays the committed
    device wins (a CPU-committed array on a TPU host runs on CPU, where
    the Mosaic kernel cannot lower).  Under jit there is no committed
    device to inspect, so the runtime's default device decides — jitting
    over a CPU-committed array on a TPU host is unsupported (pass
    variant='bitslice' explicitly for that)."""
    try:
        devices = getattr(data, "devices", None)
        if callable(devices):
            try:
                devs = devices()
            except Exception:
                # Tracer.devices() raises ConcretizationTypeError: a traced
                # array has no committed device.  This MUST fall through to
                # the runtime check below — treating it as "not TPU" silently
                # routed every jitted caller to the XLA fallback instead of
                # the pallas kernel (observed 3x throughput loss on the
                # tunneled backend).
                devs = None
            if devs:
                return all(d.platform == "tpu" for d in devs)
        return jax.devices()[0].platform == "tpu"
    except Exception:          # backend init failure -> act like CPU
        return False


def gf_apply_stripes(mat, data, stripes: int, variant: str = "auto"):
    """Batched GF apply over the VERTICAL stripe layout: data
    [stripes*k, Nc] -> [stripes*r, Nc] (stripe s = rows [s*k, (s+1)*k)).

    This is the codec's device-native batch layout (stripes stack as rows,
    a no-copy append for the IO path) and the fast path on TPU: tall
    blocks + block-diagonal int8 MXU matmuls (see
    pallas_kernels.gf_apply_stripes_pallas).  Off-TPU it folds back to the
    horizontal layout and reuses the XLA paths.
    """
    mat = jnp.asarray(mat, dtype=jnp.uint8)
    data = jnp.asarray(data, dtype=jnp.uint8)
    r, k = mat.shape
    rows, n = data.shape
    assert rows == stripes * k
    if variant in ("auto", "pallas") and _runs_on_tpu(data) and n >= 1024:
        from .pallas_kernels import gf_apply_stripes_pallas
        return gf_apply_stripes_pallas(mat, data, stripes)
    # fallback: [S*k, N] -> [k, S*N] -> gf_apply -> [S*r, N]
    folded = data.reshape(stripes, k, n).transpose(1, 0, 2).reshape(k, -1)
    out = gf_apply(mat, folded, variant)
    return out.reshape(r, stripes, n).transpose(1, 0, 2).reshape(
        stripes * r, n)


def gf_apply(mat, data, variant: str = "auto"):
    """Apply a GF(2^8) matrix to chunk data on the device.

    mat: [r, k] uint8 (numpy or jax), data: [k, N] uint8 -> [r, N] uint8.
    variant: 'pallas' (fused TPU kernel), 'bitslice' (MXU via XLA),
    'lookup' (VPU), or 'auto'.
    """
    mat = jnp.asarray(mat, dtype=jnp.uint8)
    data = jnp.asarray(data, dtype=jnp.uint8)
    if variant == "auto":
        # Fused pallas pipeline on TPU (measured ~1.1-1.3x the XLA bitslice
        # path at k=8,m=4 — unpacked bit-planes never round-trip HBM);
        # XLA paths elsewhere.  Tiny matrices with short rows stay on the
        # VPU lookup path where the MXU can't amortise its unpack.
        if mat.shape[0] * mat.shape[1] < 8:
            variant = "lookup"
        elif _runs_on_tpu(data) and data.shape[1] >= 1024:
            variant = "pallas"
        else:
            variant = "bitslice"
    if variant == "pallas":
        from .pallas_kernels import gf_apply_pallas
        return gf_apply_pallas(mat, data)
    if variant == "bitslice":
        return gf_apply_bitslice(mat, data)
    if variant == "lookup":
        return gf_apply_lookup(mat, data)
    raise ValueError(f"unknown variant {variant!r}")


def xor_apply(W, packets, variant: str = "auto"):
    """GF(2) XOR-matmul on the MXU: out[r] = XOR over i with W[r,i]==1 of
    packets[i], bytewise.  variant: 'pallas' (fused kernel — honoured
    unconditionally, like gf_apply), 'xla', or 'auto' (pallas on TPU for
    wide rows, XLA elsewhere).

    W: [R, K] 0/1 uint8, packets: [K, P] uint8 -> [R, P] uint8.  The device
    path for bitmatrix codes (liberation/blaum_roth/liber8tion — see
    gf/bitmatrix.py): a byte XOR is 8 independent GF(2) sums, so unpack the
    bit-planes along the column axis, run ONE int8 matmul (exact: 0/1
    values, <= K terms in int32), take mod 2, and repack.
    """
    W = jnp.asarray(W, dtype=jnp.int8)
    packets = jnp.asarray(packets, dtype=jnp.uint8)
    if variant == "pallas" or (variant == "auto" and _runs_on_tpu(packets)
                               and packets.shape[1] >= 1024):
        from .pallas_kernels import xor_apply_pallas
        return xor_apply_pallas(W, packets)
    if variant not in ("auto", "xla"):
        raise ValueError(f"unknown variant {variant!r}")
    return _xor_apply_xla(W, packets)


def bitplane_xor_matmul(W, d):
    """The shared core: uint8 columns -> 8 bit-planes -> ONE int8 matmul
    -> mod 2 -> repacked bytes.  Used by the jitted XLA path AND the
    pallas kernel body (both operate on plain jnp values)."""
    p = d.shape[1]
    planes = jnp.concatenate(
        [(d >> b) & 1 for b in range(8)], axis=1).astype(jnp.int8)
    acc = jax.lax.dot_general(
        W, planes, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32) & 1            # [R, 8P]
    out = acc[:, :p]
    for b in range(1, 8):
        out = out | (acc[:, b * p:(b + 1) * p] << b)
    return out.astype(jnp.uint8)


@traced_jit
def _xor_apply_xla(W, packets):
    return bitplane_xor_matmul(W, packets)


# -- fused crc32c (ISSUE 20 layer c: checksums ride the encode dispatch) -----
#
# crc32c is GF(2)-linear in the data bits once the seed is factored out
# (backend/ecutil.crc32c_zeros), so a row's crc32c(0, row) folds like a
# reduction: start from per-byte crcs (one 256-entry table gather, the
# same shape as the codec's lookup path), then log2(n) fold levels where
# adjacent 2^l-byte blocks combine as  Z_{2^l}(left) ^ right  —  Z_L the
# 32x32 GF(2) matrix advancing a register through L zero bytes.  Rows
# pad with zeros on the LEFT: leading zeros are free for a zero-seeded
# register, so padding changes nothing while keeping every level an
# exact halving (static shapes, one compilation per (r, n)).  The fold
# matrices are trace-time constants (lru-cached per level), and the
# GF(2) matrix application is 32 bit-planes through one integer matmul —
# the same bitslice trick the encode kernel uses, so the fused
# encode+crc dispatch keeps everything on the MXU/VPU with no host loop.

@functools.lru_cache(maxsize=1)
def _crc_t0_dev() -> jax.Array:
    from ..backend import ecutil
    # first call may land inside a jit trace; the cache must hold a
    # CONCRETE array, never that trace's tracer
    with jax.ensure_compile_time_eval():
        return jnp.array(ecutil._CRC_TABLES[0], dtype=jnp.uint32)


@functools.lru_cache(maxsize=64)
def _crc_fold_mat_dev(level: int) -> jax.Array:
    """Bit matrix of Z_{2^level}: M[i, j] = bit j of the image of
    register bit i."""
    from ..backend import ecutil
    op = ecutil.crc32c_zeros_op(1 << level)
    with jax.ensure_compile_time_eval():
        return jnp.array([[(op[i] >> j) & 1 for j in range(32)]
                          for i in range(32)], dtype=jnp.int32)


def _crc_apply_fold(crcs: jax.Array, mat: jax.Array) -> jax.Array:
    """Apply one 32x32 GF(2) fold matrix to a [r, m] uint32 crc array:
    unpack to bit-planes, one integer matmul, mod 2, repack."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = ((crcs[:, :, None] >> shifts[None, None, :]) & 1).astype(
        jnp.int32)                                     # [r, m, 32]
    out_bits = (bits @ mat) & 1                        # [r, m, 32]
    weights = jnp.left_shift(jnp.uint32(1), shifts)
    return jnp.sum(out_bits.astype(jnp.uint32) * weights, axis=-1,
                   dtype=jnp.uint32)


def _crc_rows_body(rows: jax.Array, pad: int) -> jax.Array:
    """Traced body: uint8 [r, n] -> uint32 [r] of crc32c(0, row)."""
    c = _crc_t0_dev()[rows.astype(jnp.int32)]          # per-byte crcs
    r, n = rows.shape
    if pad > n:
        c = jnp.concatenate(
            [jnp.zeros((r, pad - n), dtype=jnp.uint32), c], axis=1)
    level = 0
    while c.shape[1] > 1:
        m = _crc_fold_mat_dev(level)                   # trace-time const
        c = _crc_apply_fold(c[:, 0::2], m) ^ c[:, 1::2]
        level += 1
    return c[:, 0]


@functools.partial(jax.jit, static_argnames=("pad",))
def _crc32c_rows_jit(rows, pad):
    return _crc_rows_body(rows, pad)


def crc32c_rows(rows) -> jax.Array:
    """Device crc32c(seed=0) of each row of a uint8 [r, n] array, in one
    jitted dispatch.  Seed-chained ceph semantics are the caller's host
    combine: ``crc32c(seed, row) == crc32c_zeros(seed, n) ^ crc32c_rows(rows)[i]``."""
    rows = jnp.asarray(rows, dtype=jnp.uint8)
    n = rows.shape[1]
    pad = 1 if n <= 1 else 1 << (n - 1).bit_length()
    return _crc32c_rows_jit(rows, pad)


@functools.partial(jax.jit, static_argnames=("variant", "pad"))
def _gf_encode_with_crc_jit(mat, data, variant, pad):
    parity = gf_apply(mat, data, variant)
    rows = jnp.concatenate([data, parity], axis=0)
    return parity, _crc_rows_body(rows, pad)


def gf_encode_with_crc(mat, data, variant: str = "auto"):
    """The fused encode+checksum dispatch: parity rows AND the
    crc32c(0, ·) of every row of concat(data, parity), one jit call.

    mat: [m, k] uint8, data: [k, N] uint8 -> (parity [m, N] uint8,
    crcs [k + m] uint32).  Bitwise-identical to gf_apply + a host
    crc loop; the checksum pass reuses the device-resident rows the
    encode just produced instead of a second HBM round-trip."""
    mat = jnp.asarray(mat, dtype=jnp.uint8)
    data = jnp.asarray(data, dtype=jnp.uint8)
    n = data.shape[1]
    pad = 1 if n <= 1 else 1 << (n - 1).bit_length()
    return _gf_encode_with_crc_jit(mat, data, variant, pad)
