"""CodecPipeline: depth-limited async device dispatch for codec batches.

The transfer-stall fix the ISSUE-5 tentpole names: every synchronous
``RSCodec.encode``/``decode`` call blocks on ``np.asarray(jax.device_get)``
right after dispatch, so host-side pack/unpack (``np.stack``, transposes,
``ascontiguousarray``) and device compute run SERIALLY.  JAX dispatch is
asynchronous on every backend (a dispatched computation runs in the XLA
runtime while Python continues), so the pipeline keeps up to ``depth``
dispatched batches in flight and defers ``block_until_ready`` to an
explicit completion boundary:

    submit(pack, dispatch, unpack):
        pack()              host: build the folded uint8 block      [overlaps
        dispatch(packed)    device: async kernel launch              previous
        -> PipelineFuture                                            batches'
    completion (oldest-first once depth is exceeded, or flush(),     device
    or an out-of-order ``result()``):                                compute]
        block_until_ready + device_get                 <- the ONLY host sync
        unpack(packed, host) -> future's result

This module IS the completion boundary: ``tests/test_no_host_sync.py``
guards that ``exec/`` and ``recovery/`` never call ``jax.device_get`` /
``block_until_ready`` (or import jax at all) — batch N+1's host prep in
those layers can therefore never accidentally serialise against batch N's
device work.

Steady-state dispatches donate the packed input buffer (dead after
launch; TPU only — see ``codec._gf_apply_donated``), and every stage
lands on the PR-1 tracer (``pipeline.pack``/``dispatch``/``complete``
spans) plus an in-flight-depth perf collection.

Multi-chip: when ``jax_rs_mesh_devices`` names >= 2 devices, encode and
decode dispatches split the coalesced batch across the ``dp`` axis of a
``parallel.mesh`` device mesh (``sharded_batch_encode_step`` — the
parity-only serving variant of the dryrun-validated encode step — and
``sharded_decode_step``), so the serving path rides the same shard_map
machinery the MULTICHIP dryruns validate.
"""
from __future__ import annotations

import collections
import threading
import weakref

import jax
import jax.numpy as jnp

from ..common import default_context
from ..common import device_attribution
from ..common.perf_counters import PerfCountersBuilder
from ..common.tracer import (activate_trace, current_trace,
                             default_tracer, trace_span)
from ..failure.breaker import CircuitBreaker, state_rank
from ..failure.injector import InjectedFault, InjectedOOM

DEPTH_BUCKETS = [0, 1, 2, 4, 8, 16, 32]

_MISSING = object()


class PipelineFuture:
    """Completion handle for one in-flight device batch.

    ``result()``/``exception()`` FORCE completion when the item is still
    in flight (out-of-order completion is legal: forcing item 3 before
    item 1 completes 3 alone; 1 stays dispatched).  Device-side failures
    (anything ``block_until_ready`` or the unpack stage raises) surface
    here, never on the dispatching thread.

    ``timeout`` bounds only the wait for ANOTHER thread to finish the
    item: the forcing path runs the completion itself, and JAX has no
    timed sync — ``block_until_ready`` waits on the device unboundedly.
    """

    __slots__ = ("kind", "meta", "owner", "fallback", "trace",
                 "_pipeline", "_packed", "_dev", "_unpack",
                 "_host_fallback", "_dispatched_at", "_event", "_result",
                 "_error", "_callbacks", "_cb_lock")

    def __init__(self, pipeline: "CodecPipeline", kind: str, meta: dict,
                 owner: str = "client", trace=None):
        self.kind = kind
        self.meta = meta
        # the owner class this batch's device occupancy is charged to
        # (common/device_attribution), resolved on the SUBMITTING thread
        # where the trace context is active
        self.owner = owner
        # the submitter's TraceContext: completion/fallback spans run on
        # whatever thread forces the boundary, and activating this keeps
        # them in the op's trace (critical-path `device`/`retry` phases)
        self.trace = trace
        # True when the sync host codec served this batch (breaker open
        # or a device failure healed by the fallback)
        self.fallback = False
        self._pipeline = weakref.ref(pipeline)
        self._packed = None
        self._dev = None
        self._unpack = None
        self._host_fallback = None
        self._dispatched_at = 0.0
        self._event = threading.Event()
        self._result = None
        self._error: BaseException | None = None
        self._callbacks: list = []
        self._cb_lock = threading.Lock()

    # -- consumer side -----------------------------------------------------

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def value(self):
        """The result, valid once done (for done-callbacks)."""
        return self._result

    @property
    def error(self) -> BaseException | None:
        """The failure, valid once done (for done-callbacks)."""
        return self._error

    def _force(self) -> None:
        if not self._event.is_set():
            pl = self._pipeline()
            if pl is not None:
                pl.complete(self)

    def result(self, timeout: float | None = None):
        self._force()
        if not self._event.wait(timeout):
            raise TimeoutError(f"pipeline item not complete within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result

    def exception(self, timeout: float | None = None):
        self._force()
        if not self._event.wait(timeout):
            raise TimeoutError(f"pipeline item not complete within {timeout}s")
        return self._error

    def add_done_callback(self, fn) -> None:
        """``fn(future)`` on completion; immediate when already done."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    # -- pipeline side -----------------------------------------------------

    def _finish(self, result, error: BaseException | None) -> None:
        with self._cb_lock:
            self._result = result
            self._error = error
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            fn(self)


def _build_perf(name: str):
    return (PerfCountersBuilder(name)
            .add_u64("in_flight", "dispatched device batches not yet "
                                  "completed (the pipeline's depth gauge)")
            .add_u64_counter("submitted", "batches submitted to the pipeline")
            .add_u64_counter("completed", "batches completed (fetch + unpack)")
            .add_u64_counter("errors", "batches that failed in pack, "
                                       "dispatch, device compute, or unpack")
            .add_u64_counter("mesh_dispatches",
                             "batches split across the device mesh's dp "
                             "axis (jax_rs_mesh_devices engaged)")
            .add_u64_counter("host_fallbacks",
                             "batches served by the sync host codec "
                             "because the device breaker was open or "
                             "the device failed with a fallback in hand")
            .add_u64("breaker_state",
                     "circuit breaker state (0 closed, 1 half-open "
                     "probe in flight, 2 open: device path bypassed)")
            .add_histogram("inflight_depth", DEPTH_BUCKETS,
                           "in-flight depth observed at each dispatch")
            .add_time_avg("pack_time", "host pack stage (overlaps in-flight "
                                       "device compute)")
            .add_time_avg("dispatch_time", "async device dispatch stage")
            .add_time_avg("complete_time", "completion boundary: device "
                                           "sync + host unpack")
            .create_perf_counters())


class CodecPipeline:
    """Depth-limited async dispatch queue over the device codec.

    ``depth`` bounds in-flight device batches (0 = synchronous: every
    submit completes before returning — the comparison baseline).  When a
    submit exceeds the bound, the OLDEST item completes first: that is
    the pipeline's backpressure AND its completion boundary on the
    steady-state path.
    """

    def __init__(self, depth: int | None = None,
                 name: str = "codec_pipeline", cct=None,
                 mesh_devices: int | None = None):
        self.cct = cct if cct is not None else default_context()
        conf = self.cct.conf
        self.name = name
        self.depth = int(conf.get("jax_rs_pipeline_depth")
                         if depth is None else depth)
        self.mesh_devices = int(conf.get("jax_rs_mesh_devices")
                                if mesh_devices is None else mesh_devices)
        self.perf = _build_perf(name)
        self.cct.perf.add(self.perf)
        self._lock = threading.Lock()
        self._queue: collections.OrderedDict = collections.OrderedDict()
        # circuit breaker on the device path (failure/breaker.py):
        # pipeline_breaker_threshold consecutive device failures open it
        # and fallback-capable submits run the sync host codec until a
        # half-open probe (after pipeline_breaker_cooldown) re-closes.
        # Threshold 0 disables (no breaker, pre-ISSUE-9 behavior).
        thresh = int(conf.get("pipeline_breaker_threshold"))
        self.breaker = CircuitBreaker(
            f"{name}.breaker", threshold=thresh,
            cooldown=float(conf.get("pipeline_breaker_cooldown"))) \
            if thresh > 0 else None
        # device-plane fault injection (failure/injector.py): when set,
        # dispatch/completion rolls may raise InjectedFault/InjectedOOM
        self.fault_injector = None
        self._mesh = None
        self._mesh_failed = False
        self._enc_steps: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._dec_step = None

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Drain and unhook the perf collection (the repo's discipline:
        a discarded component must not leave frozen gauges behind); the
        breaker leaves the live registry so it stops raising
        DEVICE_DEGRADED."""
        self.flush()
        self.cct.perf.remove(self.perf.name)
        if self.breaker is not None:
            self.breaker.close()

    def reopen(self) -> None:
        """Re-register the perf collection AND the breaker after a close
        (engine restart) — a reopened pipeline's breaker must be visible
        to DEVICE_DEGRADED again."""
        self.cct.perf.add(self.perf)
        if self.breaker is not None:
            self.breaker.reopen()

    # -- fault injection (device plane) ------------------------------------

    def inject_faults(self, injector) -> None:
        """Attach (or, with None, detach) a FaultInjector whose device
        plane rolls dispatch/completion failures and simulated OOM into
        this pipeline — the chaos harness hook."""
        self.fault_injector = injector

    def _roll_device_fault(self, stage: str) -> None:
        inj = self.fault_injector
        if inj is None:
            return
        f = inj.plan.device
        if stage == "dispatch":
            if inj.roll("device", "oom", f.oom_prob, target=self.name):
                raise InjectedOOM("RESOURCE_EXHAUSTED: injected device "
                                  "OOM at dispatch")
            if inj.roll("device", "dispatch_fail", f.dispatch_fail_prob,
                        target=self.name):
                raise InjectedFault("injected device dispatch failure")
        elif inj.roll("device", "completion_fail",
                      f.completion_fail_prob, target=self.name):
            raise InjectedFault("injected device completion failure")

    # -- breaker bookkeeping -----------------------------------------------

    def _device_failure(self) -> None:
        if self.breaker is not None:
            self.breaker.record_failure()
            self.perf.set("breaker_state", state_rank(self.breaker.state))

    def _device_success(self) -> None:
        if self.breaker is not None:
            self.breaker.record_success()
            self.perf.set("breaker_state", 0)

    def _serve_host(self, fut: PipelineFuture, host_fallback,
                    unpack) -> PipelineFuture:
        """Serve one batch entirely on the host codec (breaker open, or
        a device failure with a fallback in hand).  The batch is marked
        degraded in device attribution so `device top` shows how much
        work the chip is NOT doing."""
        fut.fallback = True
        self.perf.inc("host_fallbacks")
        if self.breaker is not None:
            self.breaker.note_fallback()
        try:
            # re-activate the submitter's trace: the fallback is the
            # op's RETRY time (critical-path phase registry), and it may
            # run on a different thread than the submit
            with activate_trace(fut.trace), \
                    trace_span("pipeline.host_fallback", kind=fut.kind,
                               owner=fut.owner), \
                    self.perf.time("complete_time"):
                host = host_fallback(fut._packed)
                result = unpack(fut._packed, host) \
                    if unpack is not None else host
            device_attribution.record_host_fallback(
                fut.owner, getattr(host, "nbytes", 0) or 0)
            self.perf.inc("completed")
            fut._packed = fut._host_fallback = None
            fut._finish(result, None)
        except BaseException as e:              # noqa: BLE001 — the future
            self.perf.inc("errors")             # carries the failure
            fut._packed = fut._host_fallback = None
            fut._finish(None, e)
        return fut

    @property
    def in_flight(self) -> int:
        with self._lock:
            return len(self._queue)

    # -- submission --------------------------------------------------------

    def submit(self, pack, dispatch, unpack, kind: str = "op",
               owner: str | None = None, host_fallback=None,
               **meta) -> PipelineFuture:
        """Run ``pack()`` (host) and ``dispatch(packed)`` (async device
        launch) NOW; defer ``unpack(packed, host_arrays)`` to the
        completion boundary.  Returns the future; errors in any stage
        land on it.  ``owner`` tags the batch's device occupancy
        (client/serving/recovery/scrub/rebalance); when omitted it
        resolves from the active TraceContext's op class.

        ``host_fallback(packed)`` — when provided — is the sync host
        codec's answer to the same batch: it serves the batch when the
        circuit breaker is open (skipping the doomed dispatch entirely)
        and HEALS a batch whose dispatch or device compute fails, so a
        dying device degrades throughput instead of failing ops."""
        fut = PipelineFuture(self, kind, meta,
                             owner=device_attribution.resolve_owner(owner),
                             trace=current_trace())
        self.perf.inc("submitted")
        # pack is host work: its failures are the caller's bug, never
        # breaker evidence — keep it outside the device try
        try:
            with trace_span("pipeline.pack", kind=kind, owner=fut.owner), \
                    self.perf.time("pack_time"):
                packed = pack() if pack is not None else None
            fut._packed = packed
        except BaseException as e:              # noqa: BLE001 — the future
            self.perf.inc("errors")             # carries the failure
            fut._finish(None, e)
            return fut
        if host_fallback is not None and self.breaker is not None \
                and not self.breaker.allow():
            return self._serve_host(fut, host_fallback, unpack)
        try:
            self._roll_device_fault("dispatch")
            with trace_span("pipeline.dispatch", kind=kind,
                            owner=fut.owner), \
                    self.perf.time("dispatch_time"):
                fut._dev = dispatch(packed)
            fut._dispatched_at = device_attribution.dispatch_mark()
            fut._unpack = unpack
            fut._host_fallback = host_fallback
        except BaseException as e:              # noqa: BLE001 — the future
            self._device_failure()              # carries the failure ...
            if host_fallback is not None:       # ... unless the host can
                return self._serve_host(fut, host_fallback, unpack)
            self.perf.inc("errors")
            fut._finish(None, e)
            return fut
        with self._lock:
            self._queue[fut] = True
            depth = len(self._queue)
        self.perf.hinc("inflight_depth", depth)
        self.perf.set("in_flight", depth)
        if self.depth <= 0:
            self.complete(fut)                  # synchronous mode
        else:
            while True:
                with self._lock:
                    if len(self._queue) <= self.depth:
                        break
                    oldest = next(iter(self._queue))
                self.complete(oldest)
        return fut

    # -- completion boundary -----------------------------------------------

    def complete(self, fut: PipelineFuture) -> PipelineFuture:
        """Complete ONE item (possibly out of order): the only place the
        serving/recovery data path waits on the device."""
        with self._lock:
            present = self._queue.pop(fut, _MISSING) is not _MISSING
            self.perf.set("in_flight", len(self._queue))
        if not present:
            # already completed (or another thread is completing it now)
            fut._event.wait()
            return fut
        result, error = None, None
        recorded = device_ok = False
        try:
            with activate_trace(fut.trace), \
                    trace_span("pipeline.complete", kind=fut.kind,
                               owner=fut.owner), \
                    self.perf.time("complete_time"):
                self._roll_device_fault("completion")
                dev = jax.block_until_ready(fut._dev)
                device_ok = True
                self._device_success()
                nbytes = getattr(dev, "nbytes", 0) or 0
                # device occupancy ends at block_until_ready: the
                # device_get transfer (slow over the axon tunnel) and the
                # host-side unpack below are HOST time — charging them
                # would inflate busy_s and the owner's share while the
                # chip sits idle
                device_attribution.record_batch(fut.owner,
                                                fut._dispatched_at, nbytes)
                recorded = True
                host = jax.device_get(dev)
                result = fut._unpack(fut._packed, host) \
                    if fut._unpack is not None else host
        except BaseException as e:              # noqa: BLE001 — device-side
            error = e                           # failures surface on the
            if not recorded:                    # future, not the completer
                # the chip was busy up to the failure either way
                device_attribution.record_batch(fut.owner,
                                                fut._dispatched_at, 0)
            if not device_ok:
                self._device_failure()
                if fut._host_fallback is not None:
                    # a completion-boundary device failure with the host
                    # answer in hand: heal the batch instead of failing it
                    fallback, unpack = fut._host_fallback, fut._unpack
                    fut._dev = fut._unpack = None
                    return self._serve_host(fut, fallback, unpack)
            self.perf.inc("errors")
        self.perf.inc("completed")
        # free buffers promptly
        fut._packed = fut._dev = fut._unpack = fut._host_fallback = None
        fut._finish(result, error)
        # pipeline completion boundary: fold this thread's pending span
        # batch into the tracer ring once per completed item
        default_tracer().flush()
        return fut

    def complete_one(self) -> bool:
        """Complete the oldest in-flight item; False when empty."""
        with self._lock:
            if not self._queue:
                return False
            oldest = next(iter(self._queue))
        self.complete(oldest)
        return True

    def flush(self) -> None:
        """Complete everything in flight (oldest first)."""
        while self.complete_one():
            pass

    # -- device dispatch helpers (single-chip or mesh-sharded) -------------

    def _mesh_ctx(self):
        """The (cached) device mesh when ``jax_rs_mesh_devices`` engages:
        >= 2 devices requested AND present.  A failed probe latches off —
        the serving path must not re-raise per batch."""
        if self.mesh_devices < 2 or self._mesh_failed:
            return None
        if self._mesh is None:
            try:
                if len(jax.devices()) < self.mesh_devices:
                    self._mesh_failed = True
                    return None
                from ..parallel import mesh as meshmod
                self._mesh = meshmod.make_mesh(self.mesh_devices)
            except Exception:
                self._mesh_failed = True
                return None
        return self._mesh

    def dispatch_encode(self, codec, data_shards, chunk_size: int):
        """``data_shards`` [k, S*chunk] host uint8 (logical row order) ->
        device parity [m, S*chunk], dispatched async.  Splits the stripe
        batch over the mesh's dp axis when the mesh engages and the
        shapes divide; single-chip (donating) dispatch otherwise."""
        mesh = self._mesh_ctx()
        if mesh is not None:
            out = self._mesh_encode(codec, data_shards, int(chunk_size),
                                    mesh)
            if out is not None:
                return out
        return codec.encode_device(jnp.asarray(data_shards), donate=True)

    def _mesh_encode(self, codec, data_shards, c: int, mesh):
        k, total = data_shards.shape
        if c <= 0 or total % c:
            return None
        stripes = total // c
        dp, sp = mesh.shape["dp"], mesh.shape["sp"]
        if c % sp:
            return None
        step = self._enc_steps.get(codec)
        if step is None:
            from ..parallel import mesh as meshmod
            step = meshmod.sharded_batch_encode_step(mesh, codec.parity_mat)
            self._enc_steps[codec] = step
        # [k, S*c] -> [S, k, c] (+ zero stripes up to a dp multiple: RS is
        # positionwise-linear, zero stripes encode to zero parity)
        data = jnp.asarray(data_shards).reshape(k, stripes, c)
        data = jnp.swapaxes(data, 0, 1)
        pad = (-stripes) % dp
        if pad:
            data = jnp.pad(data, ((0, pad), (0, 0), (0, 0)))
        parity = step(data)
        self.perf.inc("mesh_dispatches")
        parity = jnp.swapaxes(parity[:stripes], 0, 1)
        return parity.reshape(codec.m, total)

    def host_encode(self, codec, data_shards, chunk_size: int):
        """The sync-host mirror of :meth:`dispatch_encode` — the
        ``host_fallback`` the ecutil pipelined entries hand to submit."""
        return codec.encode_host(data_shards)

    def host_decode(self, codec, stack, erasures, available):
        """The sync-host mirror of :meth:`dispatch_decode`."""
        return codec.decode_host(stack, erasures, available)

    def dispatch_decode(self, codec, stack, erasures, available):
        """``stack`` [k', S*chunk] host uint8 survivors in the sorted-src
        order ``codec.decode_matrix(erasures, available)`` returns ->
        device recovered rows [len(erasures), S*chunk], async.  Mesh
        path: survivors shard over dp, partial GF products psum over ICI
        (``sharded_decode_step``)."""
        mesh = self._mesh_ctx()
        if mesh is not None:
            out = self._mesh_decode(codec, stack, erasures, available, mesh)
            if out is not None:
                return out
        return codec.decode_device(jnp.asarray(stack), erasures,
                                   available, donate=True)

    def _mesh_decode(self, codec, stack, erasures, available, mesh):
        # the DEVICE-resident matrix from the signature LRU: an LRU hit
        # must cost zero host->device transfers on the mesh path too
        # (the step's jnp.asarray is a no-op on a device array)
        D, src = codec.decode_matrix_device(erasures, available)
        kk, total = stack.shape
        if kk != len(src):
            return None
        sp = mesh.shape["sp"]
        pad = (-total) % sp
        if self._dec_step is None:
            from ..parallel import mesh as meshmod
            self._dec_step = meshmod.sharded_decode_step(mesh)
        chunks = jnp.asarray(stack)
        if pad:
            chunks = jnp.pad(chunks, ((0, 0), (0, pad)))
        out = self._dec_step(D, chunks)
        self.perf.inc("mesh_dispatches")
        return out[:, :total] if pad else out
