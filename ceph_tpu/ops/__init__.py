from .traced_jit import traced_jit
from .rs_kernels import gf_apply, gf_apply_bitslice, gf_apply_lookup, xor_reduce
from .codec import RSCodec, TECHNIQUES
from .pipeline import CodecPipeline, PipelineFuture

__all__ = ["traced_jit",
           "gf_apply", "gf_apply_bitslice", "gf_apply_lookup", "xor_reduce",
           "RSCodec", "TECHNIQUES", "CodecPipeline", "PipelineFuture"]
