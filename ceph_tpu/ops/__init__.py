from .rs_kernels import gf_apply, gf_apply_bitslice, gf_apply_lookup, xor_reduce
from .codec import RSCodec, TECHNIQUES

__all__ = ["gf_apply", "gf_apply_bitslice", "gf_apply_lookup", "xor_reduce",
           "RSCodec", "TECHNIQUES"]
