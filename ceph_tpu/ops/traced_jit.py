"""traced_jit: a jax.jit wrapper that accounts for every compilation.

The plain ``@jax.jit`` hides the costs that dominate TPU cold paths: jaxpr
tracing, XLA compilation, and the first dispatch (which waits out transfer
+ execution).  ``traced_jit`` keeps its own (function, shape/dtype) key
cache built through the AOT API — ``lower()`` / ``compile()`` — so each
stage is timed separately, then:

- emits ``jit.trace`` / ``jit.compile`` / ``jit.first_dispatch`` spans on
  the default tracer,
- records the per-key breakdown in the process-wide registry behind the
  ``jit dump`` admin command,
- bumps the ``jit`` PerfCounters collection (compilations, cache_hits,
  per-stage time averages).

Calls with traced arguments (the wrapper used inside an enclosing jit,
e.g. the bench chain or shard_map) inline through the underlying jitted
function untouched — telemetry covers real dispatches only.  If the AOT
path is unsupported for a signature, the wrapper falls back to the plain
jit cache and books the whole first call as compile time.
"""
from __future__ import annotations

import functools
import threading
import time

import jax

from ..common import device_attribution as _attr
from ..common import roofline as _roofline
from ..common import tracer as _tracer


def _record_cost_analysis(label: str, key, compiled, args) -> tuple:
    """Fold the executable's XLA cost model (FLOPs, bytes accessed) into
    the device-attribution ledger — `device top` then shows each kernel's
    modeled cost next to the measured per-class occupancy — and register
    the per-call cost with the roofline ledger (common/roofline.py),
    which joins it against the measured dispatch seconds recorded below.
    Best-effort: not every backend/executable implements cost_analysis;
    the roofline entry then falls back to summed input-operand bytes.
    Returns the ``(flops, bytes, input_bytes)`` tuple the wrapper caches
    per key and re-sends with every steady-state dispatch."""
    flops = bytes_accessed = 0.0
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):        # older jax returns [dict]
            ca = ca[0] if ca else {}
        if ca:
            flops = float(ca.get("flops", 0.0))
            bytes_accessed = float(ca.get("bytes accessed", 0.0))
            _attr.record_executable(label, flops, bytes_accessed)
    except Exception:                            # noqa: BLE001 — telemetry
        pass
    input_bytes = sum(int(getattr(a, "nbytes", 0) or 0) for a in args)
    cost = (flops, bytes_accessed, input_bytes)
    try:
        _roofline.record_compile(label, key, flops, bytes_accessed,
                                 input_bytes=input_bytes)
    except Exception:                            # noqa: BLE001 — telemetry
        pass
    return cost


def _shape_key(args) -> tuple:
    parts = []
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is not None:
            parts.append((tuple(shape), str(getattr(a, "dtype", ""))))
        else:
            parts.append(repr(a))
    return tuple(parts)


def traced_jit(fn=None, *, name: str | None = None, **jit_kwargs):
    """Drop-in for ``jax.jit`` with compile/dispatch telemetry."""
    if fn is None:
        return lambda f: traced_jit(f, name=name, **jit_kwargs)

    jfn = jax.jit(fn, **jit_kwargs)
    label = name or getattr(fn, "__name__", repr(fn))
    compiled_cache: dict[tuple, object] = {}
    cost_cache: dict[tuple, tuple] = {}      # key -> (flops, bytes, in_b)
    lock = threading.Lock()

    def _timed_dispatch(compiled, key, args):
        """Steady-state dispatch, wall-timed for the roofline ledger (a
        lower bound of device time on async backends — roofline.py's
        honesty note; the first dispatch of every key is sync-timed)."""
        _tracer.record_cache_hit(label, key)
        t0 = time.perf_counter()
        out = compiled(*args)
        _roofline.record_call(label, key, time.perf_counter() - t0,
                              cost=cost_cache.get(key))
        return out

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if kwargs or any(isinstance(a, jax.core.Tracer) for a in args):
            # inlining under an outer trace (or kwargs the AOT signature
            # can't key): no real dispatch happens here
            return jfn(*args, **kwargs)
        key = _shape_key(args)
        compiled = compiled_cache.get(key)
        if compiled is not None:
            return _timed_dispatch(compiled, key, args)
        with lock:
            compiled = compiled_cache.get(key)
            if compiled is not None:
                return _timed_dispatch(compiled, key, args)
            tr = _tracer.default_tracer()
            try:
                with tr.span("jit.trace", fn=label) as sp_t:
                    lowered = jfn.lower(*args)
                with tr.span("jit.compile", fn=label) as sp_c:
                    compiled = lowered.compile()
                cost_cache[key] = _record_cost_analysis(
                    label, key, compiled, args)
                with tr.span("jit.first_dispatch", fn=label) as sp_d:
                    out = compiled(*args)
                    jax.block_until_ready(out)
                compiled_cache[key] = compiled
                _tracer.record_compilation(label, key, sp_t.dur, sp_c.dur,
                                           sp_d.dur)
                _roofline.record_call(label, key, sp_d.dur, synced=True,
                                      cost=cost_cache.get(key))
            except Exception:
                # AOT unsupported for this signature: the jit cache still
                # compiles exactly once per key; book the whole first
                # call as compile time
                t0 = time.perf_counter()
                out = jfn(*args)
                jax.block_until_ready(out)
                dur = time.perf_counter() - t0
                compiled_cache[key] = jfn
                _tracer.record_compilation(label, key, 0.0, dur, 0.0)
                cost_cache[key] = _record_cost_analysis(
                    label, key, None, args)
                _roofline.record_call(label, key, dur, synced=True,
                                      cost=cost_cache.get(key))
            return out

    wrapper.__wrapped_jit__ = jfn
    wrapper.__traced_label__ = label
    return wrapper
