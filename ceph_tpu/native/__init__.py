"""ctypes bindings for the native runtime (native/).

The reference's plugin host is C++ loading plugin .so files via dlopen
(reference: src/erasure-code/ErasureCodePlugin.cc:126-184); here the native
registry (native/src/registry.cc) implements that exact contract and Python
binds it with ctypes (no pybind11 in this environment).  The batch queue
(native/src/batch_queue.cc) is the host side of the TPU sidecar boundary:
C++ producer threads coalesce stripes, a registered Python callback runs
the batched JAX dispatch.
"""
from __future__ import annotations

import ctypes as C
import os
import subprocess
import threading

import numpy as np

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
BUILD_DIR = os.path.join(NATIVE_DIR, "build")

_build_lock = threading.Lock()
_built = False
_registry_lib = None


def build(force: bool = False) -> str:
    """Run `make -C native`; returns the build dir.

    make itself is the staleness check (cheap no-op when up to date), so a
    stale pre-existing build/ never masks newer kernels — it runs once per
    process, unconditionally."""
    global _built
    with _build_lock:
        if force or not _built:
            subprocess.run(["make", "-C", NATIVE_DIR],
                           check=True, capture_output=True)
            _built = True
    return BUILD_DIR


def registry_lib() -> C.CDLL:
    """The process-wide handle to libec_registry.so (builds on demand).

    Shared by every ctypes consumer (NativeRegistry, the gf8 SIMD fast
    path, the native crc32c) so the library is built and dlopened once."""
    global _registry_lib
    with _build_lock:
        if _registry_lib is not None:
            return _registry_lib
    build()
    lib = C.CDLL(os.path.join(BUILD_DIR, "libec_registry.so"))
    lib.ec_simd_level.restype = C.c_int
    lib.ec_crc32c.restype = C.c_uint32
    lib.ec_crc32c.argtypes = [C.c_uint32, C.c_void_p, C.c_size_t]
    lib.ec_apply_matrix.restype = C.c_int
    lib.ec_apply_matrix.argtypes = [
        C.c_void_p, C.c_int, C.c_int, C.c_void_p, C.c_void_p, C.c_size_t]
    with _build_lock:
        _registry_lib = lib
    return _registry_lib


class _CodecOps(C.Structure):
    _fields_ = [
        ("create", C.c_void_p),
        ("destroy", C.c_void_p),
        ("get_data_chunk_count", C.c_void_p),
        ("get_chunk_count", C.c_void_p),
        ("get_chunk_size", C.c_void_p),
        ("encode", C.c_void_p),
        ("decode", C.c_void_p),
        ("minimum_to_decode", C.c_void_p),
    ]


_CREATE = C.CFUNCTYPE(C.c_void_p, C.POINTER(C.c_char_p),
                      C.POINTER(C.c_char_p), C.c_int, C.c_char_p, C.c_int)
_DESTROY = C.CFUNCTYPE(None, C.c_void_p)
_GETINT = C.CFUNCTYPE(C.c_int, C.c_void_p)
_CHUNKSZ = C.CFUNCTYPE(C.c_uint, C.c_void_p, C.c_uint)
_ENCODE = C.CFUNCTYPE(C.c_int, C.c_void_p, C.POINTER(C.c_ubyte),
                      C.POINTER(C.c_ubyte), C.c_size_t)
_DECODE = C.CFUNCTYPE(C.c_int, C.c_void_p, C.POINTER(C.c_void_p), C.c_size_t,
                      C.POINTER(C.c_int), C.c_int)
_MINIMUM = C.CFUNCTYPE(C.c_int, C.c_void_p, C.POINTER(C.c_int), C.c_int,
                       C.POINTER(C.c_int), C.c_int, C.POINTER(C.c_int),
                       C.c_int)


class NativeRegistry:
    """Binding for libec_registry.so (the dlopen plugin host)."""

    _instance = None

    def __init__(self):
        self.lib = registry_lib()
        self.lib.ec_registry_load.argtypes = [C.c_char_p, C.c_char_p,
                                              C.c_char_p, C.c_int]
        self.lib.ec_registry_get.restype = C.POINTER(_CodecOps)
        self.lib.ec_registry_get.argtypes = [C.c_char_p]
        self.lib.ec_registry_count.restype = C.c_int
        self.lib.ec_registry_preload.argtypes = [C.c_char_p, C.c_char_p,
                                                 C.c_char_p, C.c_int]

    @classmethod
    def instance(cls) -> "NativeRegistry":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def load(self, name: str, directory: str | None = None) -> None:
        err = C.create_string_buffer(512)
        rc = self.lib.ec_registry_load(
            name.encode(), (directory or BUILD_DIR).encode(), err, 512)
        if rc != 0:
            raise IOError(rc, err.value.decode() or f"load {name} failed")

    def preload(self, names_csv: str, directory: str | None = None) -> None:
        err = C.create_string_buffer(512)
        rc = self.lib.ec_registry_preload(
            names_csv.encode(), (directory or BUILD_DIR).encode(), err, 512)
        if rc != 0:
            raise IOError(rc, err.value.decode() or "preload failed")

    def count(self) -> int:
        return self.lib.ec_registry_count()

    def factory(self, name: str, profile: dict[str, str],
                directory: str | None = None) -> "NativeCodec":
        """registry.factory (ErasureCodePlugin.cc:92-120): load on demand,
        instantiate with the profile."""
        ops = self.lib.ec_registry_get(name.encode())
        if not ops:
            self.load(name, directory)
            ops = self.lib.ec_registry_get(name.encode())
        if not ops:
            raise IOError(f"plugin {name} not registered after load")
        return NativeCodec(ops.contents, profile)


class NativeCodec:
    """One codec instance behind the C vtable."""

    def __init__(self, ops: _CodecOps, profile: dict[str, str]):
        self._ops = ops
        self._create = _CREATE(ops.create)
        self._destroy = _DESTROY(ops.destroy)
        self._k_fn = _GETINT(ops.get_data_chunk_count)
        self._n_fn = _GETINT(ops.get_chunk_count)
        self._chunk_size = _CHUNKSZ(ops.get_chunk_size)
        self._encode = _ENCODE(ops.encode)
        self._decode = _DECODE(ops.decode)
        self._minimum = _MINIMUM(ops.minimum_to_decode)

        keys = (C.c_char_p * len(profile))(
            *[k.encode() for k in profile])
        vals = (C.c_char_p * len(profile))(
            *[str(v).encode() for v in profile.values()])
        err = C.create_string_buffer(256)
        self._h = self._create(keys, vals, len(profile), err, 256)
        if not self._h:
            raise ValueError(err.value.decode() or "codec init failed")

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._destroy(h)
            self._h = None

    @property
    def k(self) -> int:
        return self._k_fn(self._h)

    @property
    def n(self) -> int:
        return self._n_fn(self._h)

    def get_chunk_size(self, object_size: int) -> int:
        return self._chunk_size(self._h, object_size)

    def encode(self, data: np.ndarray) -> np.ndarray:
        """data [k, chunk] uint8 -> parity [m, chunk]."""
        data = np.ascontiguousarray(data, dtype=np.uint8)
        k, chunk = data.shape
        assert k == self.k, f"expected {self.k} data chunks"
        parity = np.zeros((self.n - k, chunk), dtype=np.uint8)
        rc = self._encode(
            self._h, data.ctypes.data_as(C.POINTER(C.c_ubyte)),
            parity.ctypes.data_as(C.POINTER(C.c_ubyte)), chunk)
        if rc != 0:
            raise IOError(rc, "encode failed")
        return parity

    def decode(self, chunks: dict[int, np.ndarray],
               erasures: list[int], chunk_size: int) -> dict[int, np.ndarray]:
        """chunks: available chunk id -> [chunk] uint8; returns the
        reconstructed chunks for `erasures`."""
        n = self.n
        bufs: list[np.ndarray | None] = [None] * n
        ptrs = (C.c_void_p * n)()
        for i, arr in chunks.items():
            arr = np.ascontiguousarray(arr, dtype=np.uint8)
            assert arr.nbytes == chunk_size
            bufs[i] = arr
            ptrs[i] = arr.ctypes.data
        out = {}
        for e in erasures:
            buf = np.zeros(chunk_size, dtype=np.uint8)
            bufs[e] = buf
            ptrs[e] = buf.ctypes.data
            out[e] = buf
        er = (C.c_int * len(erasures))(*erasures)
        rc = self._decode(self._h, ptrs, chunk_size, er, len(erasures))
        if rc != 0:
            raise IOError(rc, "decode failed")
        return out

    def minimum_to_decode(self, erasures: list[int],
                          available: list[int]) -> list[int]:
        er = (C.c_int * len(erasures))(*erasures)
        av = (C.c_int * len(available))(*available)
        out = (C.c_int * self.k)()
        got = self._minimum(self._h, er, len(erasures), av, len(available),
                            out, self.k)
        if got < 0:
            raise IOError(got, "cannot decode")
        return list(out[:got])


_BATCH_FN = C.CFUNCTYPE(C.c_int, C.c_void_p, C.POINTER(C.c_ubyte),
                        C.POINTER(C.c_ubyte), C.c_size_t, C.c_size_t)
_DONE_FN = C.CFUNCTYPE(None, C.c_void_p, C.c_int)


class BatchQueue:
    """Binding for the stripe-batching dispatch queue (batch_queue.cc).

    ``fn(data, n_stripes, chunk) -> parity`` is the batched encode —
    typically the JAX device dispatch over ``[n_stripes, k, chunk]``.
    """

    def __init__(self, k: int, m: int, chunk_size: int, fn,
                 max_batch: int = 256):
        build()
        self.lib = C.CDLL(os.path.join(BUILD_DIR, "libec_batch.so"))
        self.lib.ec_batch_queue_create.restype = C.c_void_p
        self.lib.ec_batch_queue_create.argtypes = [
            C.c_int, C.c_int, C.c_size_t, C.c_size_t, _BATCH_FN, C.c_void_p]
        self.lib.ec_batch_queue_submit.argtypes = [
            C.c_void_p, C.POINTER(C.c_ubyte), C.POINTER(C.c_ubyte),
            _DONE_FN, C.c_void_p]
        self.lib.ec_batch_queue_flush.argtypes = [C.c_void_p]
        self.lib.ec_batch_queue_destroy.argtypes = [C.c_void_p]
        self.lib.ec_batch_queue_batches.restype = C.c_size_t
        self.lib.ec_batch_queue_batches.argtypes = [C.c_void_p]
        self.lib.ec_batch_queue_stripes.restype = C.c_size_t
        self.lib.ec_batch_queue_stripes.argtypes = [C.c_void_p]

        self.k, self.m, self.chunk = k, m, chunk_size
        self._fn = fn
        self._err: list[BaseException] = []

        def trampoline(_ctx, data_p, parity_p, n_stripes, chunk):
            try:
                data = np.ctypeslib.as_array(
                    data_p, shape=(n_stripes, k, chunk))
                parity = fn(data, n_stripes, chunk)
                parity = np.ascontiguousarray(parity, dtype=np.uint8) \
                    .reshape(n_stripes, m, chunk)
                C.memmove(parity_p, parity.ctypes.data, parity.nbytes)
                return 0
            except BaseException as e:      # noqa: BLE001 - crosses C ABI
                self._err.append(e)
                return -1
        self._trampoline = _BATCH_FN(trampoline)   # keep a reference!
        self._done_keep: dict[int, object] = {}
        self._retired: list[int] = []
        self._q = self.lib.ec_batch_queue_create(
            k, m, chunk_size, max_batch, self._trampoline, None)

    def _reap(self) -> None:
        """Free retired per-stripe callbacks.  Only called when the worker
        is provably outside them (after flush's idle barrier / after
        destroy joins) — freeing a CFUNCTYPE thunk from inside its own
        invocation is a use-after-free."""
        while self._retired:
            self._done_keep.pop(self._retired.pop(), None)

    def submit(self, data: np.ndarray, on_done=None) -> np.ndarray:
        """Queue one stripe [k, chunk]; returns the parity buffer that will
        be filled once the batch containing this stripe dispatches."""
        data = np.ascontiguousarray(data, dtype=np.uint8)
        parity = np.zeros((self.m, self.chunk), dtype=np.uint8)
        key = id(parity)

        def done(_ctx, rc):
            # do NOT free the entry here: this very callback's thunk lives
            # in it; mark it for _reap at the next safe point
            self._retired.append(key)
            if on_done is not None:
                on_done(rc)
        cb = _DONE_FN(done)
        # keep data/parity/callback alive until the batch completes
        self._done_keep[key] = (data, parity, cb)
        rc = self.lib.ec_batch_queue_submit(
            self._q, data.ctypes.data_as(C.POINTER(C.c_ubyte)),
            parity.ctypes.data_as(C.POINTER(C.c_ubyte)), cb, None)
        if rc != 0:
            # the stripe never entered the queue: its done callback will
            # never fire, so retire the keep-alive entry now
            self._done_keep.pop(key, None)
            raise IOError("queue stopped")
        return parity

    def flush(self) -> None:
        self.lib.ec_batch_queue_flush(self._q)
        self._reap()                 # idle barrier passed: thunks are quiet
        if self._err:
            errs, self._err = self._err, []
            if len(errs) == 1:
                raise errs[0]
            raise BaseExceptionGroup("batch dispatch failures", errs)

    @property
    def batches(self) -> int:
        return self.lib.ec_batch_queue_batches(self._q)

    @property
    def stripes(self) -> int:
        return self.lib.ec_batch_queue_stripes(self._q)

    def close(self) -> None:
        if getattr(self, "_q", None):
            self.lib.ec_batch_queue_destroy(self._q)   # joins the worker
            self._q = None
            self._reap()

    def __del__(self):
        self.close()


__all__ = ["build", "registry_lib", "NativeRegistry", "NativeCodec",
           "BatchQueue", "BUILD_DIR", "NATIVE_DIR"]
