"""OSDMap value types: pg_t, pool model, stable-mod placement seeds.

Python analogs of the reference types driving the PG->OSD mapping chain
(reference: src/osd/osd_types.{h,cc}, src/include/rados.h):

- ``ceph_stable_mod`` (src/include/rados.h:86-92): the split-aware modulus
  that keeps PG placement stable while pg_num grows between powers of two.
- ``pg_pool_t`` (src/osd/osd_types.h): pool type (replicated/erasure), size,
  pg_num/pgp_num and their masks (calc_pg_masks), crush rule, flags; the
  placement seed ``raw_pg_to_pps`` (src/osd/osd_types.cc:1640-1656) hashes
  the stable-mod'd ps with the pool id (FLAG_HASHPSPOOL) so pools don't
  overlap.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..crush.hash import crush_hash32_2

# pool types (src/osd/osd_types.h pg_pool_t::TYPE_*)
POOL_TYPE_REPLICATED = 1
POOL_TYPE_ERASURE = 3

# pg_pool_t flags (subset)
FLAG_HASHPSPOOL = 1 << 0

# osd state flags (src/include/rados.h CEPH_OSD_*)
OSD_EXISTS = 1
OSD_UP = 2
OSD_AUTOOUT = 4
OSD_NEW = 8

OSD_IN_WEIGHT = 0x10000          # CEPH_OSD_IN
MAX_PRIMARY_AFFINITY = 0x10000   # CEPH_OSD_MAX_PRIMARY_AFFINITY
DEFAULT_PRIMARY_AFFINITY = 0x10000


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """Stable modulus (src/include/rados.h:86-92): bins in [0,b) where b need
    not be a power of two; entries above b fold into the lower half-range so
    growing b splits one bin at a time."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


def pg_mask(num: int) -> int:
    """calc_pg_masks: containing power-of-two minus 1 (b=12 -> 15)."""
    if num <= 1:
        return 0
    return (1 << (num - 1).bit_length()) - 1


@dataclass(frozen=True)
class PG:
    """pg_t: (pool id, placement seed)."""
    pool: int
    ps: int

    def __str__(self) -> str:
        return f"{self.pool}.{self.ps:x}"


@dataclass
class Pool:
    """pg_pool_t (mapping-relevant subset)."""
    pool_id: int
    type: int = POOL_TYPE_REPLICATED
    size: int = 3
    min_size: int = 2
    pg_num: int = 32
    pgp_num: int = 0                # 0 => same as pg_num
    crush_rule: int = 0
    flags: int = FLAG_HASHPSPOOL
    erasure_code_profile: str = ""
    name: str = ""
    params: dict = field(default_factory=dict)
    # pool snapshots (pg_pool_t::snap_seq / snaps / removed_snaps,
    # src/osd/osd_types.h): snap_seq is the newest issued snap id,
    # snaps maps live snap ids -> names, removed_snaps awaits snaptrim
    snap_seq: int = 0
    snaps: dict = field(default_factory=dict)          # snapid -> name
    removed_snaps: set = field(default_factory=set)

    def __post_init__(self):
        if not self.pgp_num:
            self.pgp_num = self.pg_num

    @property
    def pg_num_mask(self) -> int:
        return pg_mask(self.pg_num)

    @property
    def pgp_num_mask(self) -> int:
        return pg_mask(self.pgp_num)

    def can_shift_osds(self) -> bool:
        """Replicated pools shift over holes; EC pools are positional
        (src/osd/osd_types.h can_shift_osds; ecbackend.rst:100-105)."""
        return self.type == POOL_TYPE_REPLICATED

    def raw_pg_to_pg(self, pg: PG) -> PG:
        """Fold a full-precision ps into [0, pg_num)."""
        return PG(pg.pool, ceph_stable_mod(pg.ps, self.pg_num,
                                           self.pg_num_mask))

    def raw_pg_to_pps(self, pg: PG) -> int:
        """Placement seed (src/osd/osd_types.cc:1640-1656)."""
        if self.flags & FLAG_HASHPSPOOL:
            return crush_hash32_2(
                ceph_stable_mod(pg.ps, self.pgp_num, self.pgp_num_mask),
                pg.pool & 0xFFFFFFFF)
        return ceph_stable_mod(pg.ps, self.pgp_num,
                               self.pgp_num_mask) + pg.pool
