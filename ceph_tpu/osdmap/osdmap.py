"""OSDMap: cluster map + the PG->OSD mapping chain (scalar oracle).

Mirrors the reference mapping chain exactly (reference: src/osd/OSDMap.cc):
``_pg_to_raw_osds`` (:2359-2377) -> ``_apply_upmap`` (:2389-2433) ->
``_raw_to_up_osds`` (:2436-2459, EC pools keep positional holes) ->
``_apply_primary_affinity`` (:2461-2514) -> pg_temp/primary_temp
(:2516-2546), composed in ``_pg_to_up_acting_osds`` (:2591).  Epochs advance
via ``Incremental`` deltas like the reference's OSDMap::Incremental.

This scalar implementation is the oracle for the vectorized bulk mapper in
``bulk.py`` (the OSDMapMapping analog).
"""
from __future__ import annotations

import copy
from dataclasses import dataclass, field

from ..crush.hash import crush_hash32_2
from ..crush.map import CRUSH_ITEM_NONE, CrushMap
from ..crush.mapper import crush_do_rule
from .types import (DEFAULT_PRIMARY_AFFINITY, MAX_PRIMARY_AFFINITY,
                    OSD_EXISTS, OSD_IN_WEIGHT, OSD_UP, PG, Pool)


class OSDMap:
    def __init__(self, max_osd: int = 0, crush: CrushMap | None = None):
        self.epoch = 1
        self.max_osd = 0
        self.osd_state: list[int] = []
        self.osd_weight: list[int] = []          # 16.16 reweight (IN=0x10000)
        self.osd_primary_affinity: list[int] | None = None
        self.crush = crush if crush is not None else CrushMap()
        self.pools: dict[int, Pool] = {}
        self.pool_name: dict[int, str] = {}
        self.pg_upmap: dict[PG, list[int]] = {}
        self.pg_upmap_items: dict[PG, list[tuple[int, int]]] = {}
        self.pg_temp: dict[PG, list[int]] = {}
        self.primary_temp: dict[PG, int] = {}
        if max_osd:
            self.set_max_osd(max_osd)

    # -- osd state ----------------------------------------------------------

    def set_max_osd(self, n: int) -> None:
        while self.max_osd < n:
            self.osd_state.append(0)
            self.osd_weight.append(0)
            if self.osd_primary_affinity is not None:
                self.osd_primary_affinity.append(DEFAULT_PRIMARY_AFFINITY)
            self.max_osd += 1
        del self.osd_state[n:]
        del self.osd_weight[n:]
        if self.osd_primary_affinity is not None:
            del self.osd_primary_affinity[n:]
        self.max_osd = n

    def exists(self, o: int) -> bool:
        return 0 <= o < self.max_osd and bool(self.osd_state[o] & OSD_EXISTS)

    def is_up(self, o: int) -> bool:
        return self.exists(o) and bool(self.osd_state[o] & OSD_UP)

    def is_down(self, o: int) -> bool:
        return not self.is_up(o)

    def is_in(self, o: int) -> bool:
        return self.exists(o) and self.osd_weight[o] > 0

    def is_out(self, o: int) -> bool:
        return not self.is_in(o)

    def create_osd(self, o: int, up: bool = True,
                   weight: int = OSD_IN_WEIGHT) -> None:
        if o >= self.max_osd:
            self.set_max_osd(o + 1)
        self.osd_state[o] = OSD_EXISTS | (OSD_UP if up else 0)
        self.osd_weight[o] = weight

    def set_primary_affinity(self, o: int, aff: int) -> None:
        if self.osd_primary_affinity is None:
            self.osd_primary_affinity = (
                [DEFAULT_PRIMARY_AFFINITY] * self.max_osd)
        self.osd_primary_affinity[o] = aff

    def add_pool(self, pool: Pool, name: str = "") -> None:
        self.pools[pool.pool_id] = pool
        if name:
            pool.name = name
        self.pool_name[pool.pool_id] = pool.name

    def find_rule(self, crush_rule: int, type: int, size: int) -> int:
        """CrushWrapper::find_rule — modern maps have rule id == ruleset, so
        existence is the check."""
        return crush_rule if crush_rule in self.crush.rules else -1

    # -- mapping chain (scalar; OSDMap.cc:2359-2653) ------------------------

    def _pg_to_raw_osds(self, pool: Pool, pg: PG) -> tuple[list[int], int]:
        pps = pool.raw_pg_to_pps(pg)
        size = pool.size
        ruleno = self.find_rule(pool.crush_rule, pool.type, size)
        osds: list[int] = []
        if ruleno >= 0:
            ca = self.crush.choose_args.get(
                pg.pool, self.crush.choose_args.get(-1))
            osds = crush_do_rule(self.crush, ruleno, pps, size,
                                 self.osd_weight, ca)
        self._remove_nonexistent_osds(pool, osds)
        return osds, pps

    def _remove_nonexistent_osds(self, pool: Pool, osds: list[int]) -> None:
        if pool.can_shift_osds():
            # NONE fails exists() too and is dropped (OSDMap.cc:2330-2350)
            osds[:] = [o for o in osds if self.exists(o)]
        else:
            for i, o in enumerate(osds):
                if o != CRUSH_ITEM_NONE and not self.exists(o):
                    osds[i] = CRUSH_ITEM_NONE

    @staticmethod
    def _pick_primary(osds: list[int]) -> int:
        for o in osds:
            if o != CRUSH_ITEM_NONE:
                return o
        return -1

    def _apply_upmap(self, pool: Pool, raw_pg: PG, raw: list[int]) -> None:
        pg = pool.raw_pg_to_pg(raw_pg)
        p = self.pg_upmap.get(pg)
        if p is not None:
            for o in p:
                if (o != CRUSH_ITEM_NONE and 0 <= o < self.max_osd and
                        self.osd_weight[o] == 0):
                    # rejected: the reference returns here, skipping
                    # pg_upmap_items as well (OSDMap.cc:2396-2400)
                    return
            raw[:] = list(p)
        q = self.pg_upmap_items.get(pg)
        if q is not None:
            for frm, to in q:
                exists_ = False
                pos = -1
                for i, o in enumerate(raw):
                    if o == to:
                        exists_ = True
                        break
                    if (o == frm and pos < 0 and
                            not (to != CRUSH_ITEM_NONE and
                                 0 <= to < self.max_osd and
                                 self.osd_weight[to] == 0)):
                        pos = i
                if not exists_ and pos >= 0:
                    raw[pos] = to

    def _raw_to_up_osds(self, pool: Pool, raw: list[int]) -> list[int]:
        if pool.can_shift_osds():
            return [o for o in raw if self.exists(o) and not self.is_down(o)]
        return [CRUSH_ITEM_NONE if (not self.exists(o) or self.is_down(o))
                else o for o in raw]

    def _apply_primary_affinity(self, seed: int, pool: Pool,
                                osds: list[int], primary: int) -> int:
        aff = self.osd_primary_affinity
        if aff is None:
            return primary
        if not any(o != CRUSH_ITEM_NONE and
                   aff[o] != DEFAULT_PRIMARY_AFFINITY for o in osds):
            return primary
        pos = -1
        for i, o in enumerate(osds):
            if o == CRUSH_ITEM_NONE:
                continue
            a = aff[o]
            if (a < MAX_PRIMARY_AFFINITY and
                    (crush_hash32_2(seed & 0xFFFFFFFF, o) >> 16) >= a):
                if pos < 0:
                    pos = i
            else:
                pos = i
                break
        if pos < 0:
            return primary
        primary = osds[pos]
        if pool.can_shift_osds() and pos > 0:
            for i in range(pos, 0, -1):
                osds[i] = osds[i - 1]
            osds[0] = primary
        return primary

    def _get_temp_osds(self, pool: Pool, pg: PG) -> tuple[list[int], int]:
        pg = pool.raw_pg_to_pg(pg)
        temp: list[int] = []
        p = self.pg_temp.get(pg)
        if p is not None:
            for o in p:
                if not self.exists(o) or self.is_down(o):
                    if not pool.can_shift_osds():
                        temp.append(CRUSH_ITEM_NONE)
                else:
                    temp.append(o)
        temp_primary = self.primary_temp.get(pg, -1)
        if temp_primary == -1:
            for o in temp:
                if o != CRUSH_ITEM_NONE:
                    temp_primary = o
                    break
        return temp, temp_primary

    def pg_to_raw_osds(self, pg: PG) -> tuple[list[int], int]:
        pool = self.pools.get(pg.pool)
        if pool is None:
            return [], -1
        raw, _ = self._pg_to_raw_osds(pool, pg)
        return raw, self._pick_primary(raw)

    def pg_to_raw_up(self, pg: PG) -> tuple[list[int], int]:
        pool = self.pools.get(pg.pool)
        if pool is None:
            return [], -1
        raw, pps = self._pg_to_raw_osds(pool, pg)
        self._apply_upmap(pool, pg, raw)
        up = self._raw_to_up_osds(pool, raw)
        primary = self._pick_primary(raw)
        primary = self._apply_primary_affinity(pps, pool, up, primary)
        return up, primary

    def pg_to_up_acting_osds(self, pg: PG):
        """Returns (up, up_primary, acting, acting_primary)
        (OSDMap.cc:2591-2653)."""
        pool = self.pools.get(pg.pool)
        if pool is None or pg.ps >= pool.pg_num:
            return [], -1, [], -1
        acting, acting_primary = self._get_temp_osds(pool, pg)
        raw, pps = self._pg_to_raw_osds(pool, pg)
        self._apply_upmap(pool, pg, raw)
        up = self._raw_to_up_osds(pool, raw)
        up_primary = self._pick_primary(up)
        up_primary = self._apply_primary_affinity(pps, pool, up, up_primary)
        if not acting:
            acting = list(up)
            if acting_primary == -1:
                acting_primary = up_primary
        return up, up_primary, acting, acting_primary

    def clone(self) -> "OSDMap":
        return copy.deepcopy(self)

    # -- (de)serialisation (the reference encodes maps as binary blobs;
    #    this framework uses JSON-able dicts, cf. osdmaptool --dump json) --

    def to_dict(self) -> dict:
        def pgs(d):
            return {f"{pg.pool}.{pg.ps}": v for pg, v in d.items()}
        return {
            "epoch": self.epoch,
            "max_osd": self.max_osd,
            "osd_state": list(self.osd_state),
            "osd_weight": list(self.osd_weight),
            "osd_primary_affinity": (
                None if self.osd_primary_affinity is None
                else list(self.osd_primary_affinity)),
            "crush": self.crush.to_dict(),
            "pools": {str(pid): {
                "pool_id": p.pool_id, "type": p.type, "size": p.size,
                "min_size": p.min_size, "pg_num": p.pg_num,
                "pgp_num": p.pgp_num, "crush_rule": p.crush_rule,
                "flags": p.flags, "name": p.name,
                "erasure_code_profile": p.erasure_code_profile,
            } for pid, p in self.pools.items()},
            "pg_upmap": pgs(self.pg_upmap),
            "pg_upmap_items": pgs(self.pg_upmap_items),
            "pg_temp": pgs(self.pg_temp),
            "primary_temp": pgs(self.primary_temp),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "OSDMap":
        def unpgs(m, conv=lambda v: v):
            out = {}
            for key, v in m.items():
                pool_s, ps_s = key.split(".")
                out[PG(int(pool_s), int(ps_s))] = conv(v)
            return out
        m = cls(crush=CrushMap.from_dict(d["crush"]))
        m.epoch = d.get("epoch", 1)
        m.set_max_osd(d["max_osd"])
        m.osd_state = list(d["osd_state"])
        m.osd_weight = list(d["osd_weight"])
        pa = d.get("osd_primary_affinity")
        m.osd_primary_affinity = None if pa is None else list(pa)
        for pid_s, pd in d.get("pools", {}).items():
            m.add_pool(Pool(**pd))
        m.pg_upmap = unpgs(d.get("pg_upmap", {}), list)
        m.pg_upmap_items = unpgs(
            d.get("pg_upmap_items", {}),
            lambda v: [tuple(x) for x in v])
        m.pg_temp = unpgs(d.get("pg_temp", {}), list)
        m.primary_temp = unpgs(d.get("primary_temp", {}), int)
        return m


@dataclass
class Incremental:
    """OSDMap delta (reference: OSDMap::Incremental, src/osd/OSDMap.h).
    ``new_state`` entries XOR into osd_state (the reference's convention for
    up/down and exists flips)."""
    epoch: int = 0
    new_max_osd: int = -1
    new_pools: dict[int, Pool] = field(default_factory=dict)
    old_pools: list[int] = field(default_factory=list)
    new_state: dict[int, int] = field(default_factory=dict)     # XOR flags
    new_weight: dict[int, int] = field(default_factory=dict)
    new_primary_affinity: dict[int, int] = field(default_factory=dict)
    new_pg_temp: dict[PG, list[int]] = field(default_factory=dict)
    new_primary_temp: dict[PG, int] = field(default_factory=dict)
    new_pg_upmap: dict[PG, list[int]] = field(default_factory=dict)
    old_pg_upmap: list[PG] = field(default_factory=list)
    new_pg_upmap_items: dict[PG, list[tuple[int, int]]] = (
        field(default_factory=dict))
    old_pg_upmap_items: list[PG] = field(default_factory=list)
    new_crush: CrushMap | None = None


def apply_incremental(m: OSDMap, inc: Incremental) -> OSDMap:
    """Apply a delta, producing the next epoch (OSDMap::apply_incremental)."""
    n = m.clone()
    if inc.epoch and inc.epoch != m.epoch + 1:
        raise ValueError(f"incremental epoch {inc.epoch} != {m.epoch + 1}")
    n.epoch = m.epoch + 1
    if inc.new_crush is not None:
        n.crush = inc.new_crush
    if inc.new_max_osd >= 0:
        n.set_max_osd(inc.new_max_osd)
    for pid, pool in inc.new_pools.items():
        n.pools[pid] = pool
        n.pool_name[pid] = pool.name
    for pid in inc.old_pools:
        n.pools.pop(pid, None)
        n.pool_name.pop(pid, None)
    for o, st in inc.new_state.items():
        n.osd_state[o] ^= st
    for o, w in inc.new_weight.items():
        n.osd_weight[o] = w
    for o, a in inc.new_primary_affinity.items():
        n.set_primary_affinity(o, a)
    for pg, osds in inc.new_pg_temp.items():
        if osds:
            n.pg_temp[pg] = list(osds)
        else:
            n.pg_temp.pop(pg, None)
    for pg, o in inc.new_primary_temp.items():
        if o >= 0:
            n.primary_temp[pg] = o
        else:
            n.primary_temp.pop(pg, None)
    for pg, osds in inc.new_pg_upmap.items():
        n.pg_upmap[pg] = list(osds)
    for pg in inc.old_pg_upmap:
        n.pg_upmap.pop(pg, None)
    for pg, items in inc.new_pg_upmap_items.items():
        n.pg_upmap_items[pg] = list(items)
    for pg in inc.old_pg_upmap_items:
        n.pg_upmap_items.pop(pg, None)
    return n
