"""Object-name hashing: ceph_str_hash_rjenkins.

Bit-exact mirror of the reference's string hash (reference:
src/common/ceph_hash.cc:21-78 — Robert Jenkins' evahash over 12-byte
blocks), the function librados uses to place an object name into a pool's
PG space (object_locator -> pg via ceph_str_hash + ceph_stable_mod).
"""
from __future__ import annotations

from ..crush.hash import _mix     # same Jenkins mix as crush_hash32_*

M = 0xFFFFFFFF


def ceph_str_hash_rjenkins(data: bytes | str) -> int:
    if isinstance(data, str):
        data = data.encode()
    length = len(data)
    a = b = 0x9E3779B9
    c = 0
    i = 0
    rem = length
    while rem >= 12:
        k = data[i:i + 12]
        a = (a + int.from_bytes(k[0:4], "little")) & M
        b = (b + int.from_bytes(k[4:8], "little")) & M
        c = (c + int.from_bytes(k[8:12], "little")) & M
        a, b, c = _mix(a, b, c)
        i += 12
        rem -= 12
    c = (c + length) & M
    k = data[i:]
    # the last 11 bytes; first byte of c is reserved for the length
    if rem >= 11: c = (c + (k[10] << 24)) & M
    if rem >= 10: c = (c + (k[9] << 16)) & M
    if rem >= 9:  c = (c + (k[8] << 8)) & M
    if rem >= 8:  b = (b + (k[7] << 24)) & M
    if rem >= 7:  b = (b + (k[6] << 16)) & M
    if rem >= 6:  b = (b + (k[5] << 8)) & M
    if rem >= 5:  b = (b + k[4]) & M
    if rem >= 4:  a = (a + (k[3] << 24)) & M
    if rem >= 3:  a = (a + (k[2] << 16)) & M
    if rem >= 2:  a = (a + (k[1] << 8)) & M
    if rem >= 1:  a = (a + k[0]) & M
    a, b, c = _mix(a, b, c)
    return c
