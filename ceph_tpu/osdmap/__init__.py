"""OSDMap layer: cluster map model + PG->OSD mapping chain.

Scalar oracle chain (osdmap.py, mirrors src/osd/OSDMap.cc:2359-2653) and
the bulk vmapped mapper (bulk.py, the OSDMapMapping analog)."""
from .types import (PG, Pool, POOL_TYPE_ERASURE, POOL_TYPE_REPLICATED,
                    FLAG_HASHPSPOOL, OSD_EXISTS, OSD_UP, OSD_IN_WEIGHT,
                    MAX_PRIMARY_AFFINITY, DEFAULT_PRIMARY_AFFINITY,
                    ceph_stable_mod, pg_mask)
from .osdmap import OSDMap, Incremental, apply_incremental
from .bulk import BulkPGMapper, PoolMapping

__all__ = [
    "PG", "Pool", "POOL_TYPE_ERASURE", "POOL_TYPE_REPLICATED",
    "FLAG_HASHPSPOOL", "OSD_EXISTS", "OSD_UP", "OSD_IN_WEIGHT",
    "MAX_PRIMARY_AFFINITY", "DEFAULT_PRIMARY_AFFINITY",
    "ceph_stable_mod", "pg_mask",
    "OSDMap", "Incremental", "apply_incremental",
    "BulkPGMapper", "PoolMapping",
]
