"""Bulk PG->OSD mapping: whole-pool placement as one vmapped JAX dispatch.

The TPU-native analog of the reference's thread-pool full-cluster mapper
(reference: src/osd/OSDMapMapping.{h,cc} — ``ParallelPGMapper`` splits the
PG range over worker threads, ``OSDMapMapping::update()`` iterates every PG
of every pool, OSDMapMapping.cc:45-53).  Here the whole pool maps in one
jitted ``BulkMapper.map_rule`` call (vmap over placement seeds) and the
post-CRUSH chain (exists/up filtering, primary affinity) runs vectorized in
numpy; the sparse per-PG overrides (pg_upmap, pg_upmap_items, pg_temp,
primary_temp) are re-resolved through the scalar oracle, exactly because
they are dict-sized, not PG-count-sized.

Output rows are fixed-width ``[pg_num, size]`` int64 with CRUSH_ITEM_NONE
padding (replicated pools shift-left over holes like the reference, then
pad; EC pools keep positional holes).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..crush.hash import crush_hash32_2_np
from ..crush.jax_mapper import BulkMapper
from ..crush.map import CRUSH_ITEM_NONE
from .osdmap import OSDMap
from .types import (DEFAULT_PRIMARY_AFFINITY, FLAG_HASHPSPOOL,
                    MAX_PRIMARY_AFFINITY, PG, Pool)

NONE = CRUSH_ITEM_NONE


def stable_mod_np(x: np.ndarray, b: int, bmask: int) -> np.ndarray:
    lo = x & bmask
    return np.where(lo < b, lo, x & (bmask >> 1))


@dataclass
class PoolMapping:
    pool_id: int
    up: np.ndarray              # [pg_num, width] int64, NONE-padded
    up_primary: np.ndarray      # [pg_num] int64
    acting: np.ndarray
    acting_primary: np.ndarray
    pps: np.ndarray             # [pg_num] uint32 placement seeds


class BulkPGMapper:
    """Maps every PG of a pool (or the whole cluster) in bulk."""

    def __init__(self, osdmap: OSDMap):
        self.m = osdmap
        self.bulk = BulkMapper(osdmap.crush)
        # device-independent state vectors
        n = osdmap.max_osd
        self._exists = np.zeros(n, dtype=bool)
        self._up = np.zeros(n, dtype=bool)
        for o in range(n):
            self._exists[o] = osdmap.exists(o)
            self._up[o] = osdmap.is_up(o)
        aff = osdmap.osd_primary_affinity
        self._aff = (None if aff is None
                     else np.asarray(aff, dtype=np.int64))

    # -- pps ---------------------------------------------------------------

    def pool_pps(self, pool: Pool) -> np.ndarray:
        ps = np.arange(pool.pg_num, dtype=np.uint32)
        folded = stable_mod_np(ps, pool.pgp_num, pool.pgp_num_mask)
        if pool.flags & FLAG_HASHPSPOOL:
            return crush_hash32_2_np(
                folded, np.uint32(pool.pool_id & 0xFFFFFFFF))
        return (folded + np.uint32(pool.pool_id)).astype(np.uint32)

    # -- vector post-chain --------------------------------------------------

    def _shift_left(self, arr: np.ndarray, valid: np.ndarray) -> np.ndarray:
        """Stable-compact valid entries to the front, NONE-pad the tail."""
        order = np.argsort(~valid, axis=1, kind="stable")
        out = np.take_along_axis(arr, order, axis=1)
        ok = np.take_along_axis(valid, order, axis=1)
        return np.where(ok, out, NONE)

    def _pick_primary(self, arr: np.ndarray) -> np.ndarray:
        valid = arr != NONE
        anyv = valid.any(axis=1)
        pos = valid.argmax(axis=1)
        prim = arr[np.arange(arr.shape[0]), pos]
        return np.where(anyv, prim, -1)

    def _apply_primary_affinity(self, pps: np.ndarray, pool: Pool,
                                up: np.ndarray, primary: np.ndarray):
        """Vectorized OSDMap::_apply_primary_affinity (OSDMap.cc:2461-2514):
        reject osd as primary when (hash(seed, osd) >> 16) >= affinity;
        fall back to the first valid entry when all reject."""
        if self._aff is None:
            return up, primary
        valid = up != NONE
        osd = np.clip(up, 0, self.m.max_osd - 1).astype(np.int64)
        a = np.where(valid, self._aff[osd], DEFAULT_PRIMARY_AFFINITY)
        nondefault = (valid & (a != DEFAULT_PRIMARY_AFFINITY)).any(axis=1)
        h = crush_hash32_2_np(pps[:, None].astype(np.uint32),
                              up.astype(np.uint32))
        reject = valid & (a < MAX_PRIMARY_AFFINITY) & ((h >> 16) >= a)
        accept = valid & ~reject
        n, width = up.shape
        rows = np.arange(n)
        pos_acc = np.where(accept.any(axis=1), accept.argmax(axis=1), -1)
        pos_val = np.where(valid.any(axis=1), valid.argmax(axis=1), -1)
        pos = np.where(pos_acc >= 0, pos_acc, pos_val)
        new_prim = np.where(pos >= 0, up[rows, np.maximum(pos, 0)], primary)
        new_prim = np.where(nondefault, new_prim, primary)
        if pool.can_shift_osds():
            # rotate the accepted primary to the front of rows that changed
            p = np.where(nondefault & (pos > 0), pos, 0)[:, None]
            idx = np.arange(width)[None, :]
            src = np.where(idx == 0, p, np.where(idx <= p, idx - 1, idx))
            up = np.take_along_axis(up, src, axis=1)
        return up, new_prim

    # -- public -------------------------------------------------------------

    def map_pool(self, pool_id: int) -> PoolMapping:
        m = self.m
        pool = m.pools[pool_id]
        size = pool.size
        pps = self.pool_pps(pool)
        ruleno = m.find_rule(pool.crush_rule, pool.type, size)

        # per-pool choose_args, falling back to the compat set (-1) the
        # way _pg_to_raw_osds does (OSDMap.cc choose_args_index)
        ca = m.crush.choose_args.get(pool_id, m.crush.choose_args.get(-1))
        use_scalar = ruleno < 0
        if not use_scalar:
            try:
                out, placed = self.bulk.map_rule(
                    ruleno, pps, reweights=m.osd_weight, result_max=size,
                    choose_args=ca)
            except ValueError:
                use_scalar = True
        if use_scalar:
            out = np.full((pool.pg_num, size), NONE, dtype=np.int64)
            for i in range(pool.pg_num):
                row, _ = m._pg_to_raw_osds(pool, PG(pool_id, i))
                out[i, :len(row)] = row
            placed = None
        raw = np.asarray(out, dtype=np.int64)
        if raw.shape[1] < size:
            pad = np.full((raw.shape[0], size - raw.shape[1]), NONE,
                          dtype=np.int64)
            raw = np.concatenate([raw, pad], axis=1)
        if placed is not None:
            # firstn rows are only valid up to their placed count
            width = raw.shape[1]
            tail = np.arange(width)[None, :] >= np.asarray(placed)[:, None]
            if not pool.can_shift_osds():
                tail = np.zeros_like(tail)          # indep keeps holes
            raw = np.where(tail, NONE, raw)

        # _remove_nonexistent_osds
        inb = (raw >= 0) & (raw < m.max_osd)
        exists = inb & self._exists[np.clip(raw, 0, m.max_osd - 1)]
        if pool.can_shift_osds():
            raw = self._shift_left(raw, exists)
        else:
            raw = np.where((raw != NONE) & ~exists, NONE, raw)

        # _raw_to_up_osds (down -> hole)
        inb = (raw >= 0) & (raw < m.max_osd)
        upok = inb & self._up[np.clip(raw, 0, m.max_osd - 1)]
        if pool.can_shift_osds():
            up = self._shift_left(raw, upok)
        else:
            up = np.where((raw != NONE) & ~upok, NONE, raw)

        up_primary = self._pick_primary(up)
        up, up_primary = self._apply_primary_affinity(
            pps, pool, up, up_primary)

        acting = up.copy()
        acting_primary = up_primary.copy()

        # sparse overrides through the scalar oracle
        override = set()
        for d in (m.pg_upmap, m.pg_upmap_items, m.pg_temp, m.primary_temp):
            for pg in d:
                if pg.pool == pool_id and pg.ps < pool.pg_num:
                    override.add(pg.ps)
        for ps in override:
            u, upr, act, actpr = m.pg_to_up_acting_osds(PG(pool_id, ps))
            row = np.full(size, NONE, dtype=np.int64)
            row[:len(u)] = u
            up[ps] = row
            up_primary[ps] = upr
            row = np.full(size, NONE, dtype=np.int64)
            row[:len(act)] = act
            acting[ps] = row
            acting_primary[ps] = actpr

        return PoolMapping(pool_id=pool_id, up=up, up_primary=up_primary,
                           acting=acting, acting_primary=acting_primary,
                           pps=pps)

    def map_cluster(self) -> dict[int, PoolMapping]:
        return {pid: self.map_pool(pid) for pid in sorted(self.m.pools)}
