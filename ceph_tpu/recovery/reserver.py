"""AsyncReserver: prioritized, preemptible in-flight reservation grants.

Analog of the reference's ``AsyncReserver`` (reference:
src/common/AsyncReserver.h — the template every OSD instantiates twice,
as ``local_reserver`` and ``remote_reserver``, to gate background
recovery/backfill admission).  Semantics mirrored:

- ``request_reservation(item, on_grant, prio, on_preempt)`` queues the
  item FIFO within its priority; ``do_queues`` grants the
  highest-priority waiter whenever fewer than ``max_allowed``
  reservations are in flight (AsyncReserver.h ``do_queues``).
- a queued request with priority strictly ABOVE an in-flight holder's
  preempts the lowest-priority preemptible holder: the holder's
  ``on_preempt`` fires (it must stop its work and usually re-request),
  and the grant goes to the higher-priority waiter
  (AsyncReserver.h ``preempt_by`` semantics).
- holders registered WITHOUT ``on_preempt`` are not preemptible — the
  reference only preempts requests that supplied a preemption context.
- ``cancel_reservation`` releases a grant or withdraws a queued request
  (idempotent here: late cancels after a preemption are inert) and
  immediately re-runs the queues.
- ``set_max`` / ``update_priority`` re-evaluate grants live, the
  ``osd_max_backfills`` runtime-update path.

Callbacks fire synchronously from ``do_queues`` (the framework's
deterministic single-thread design stands in for the reference's
Finisher thread); re-entrant requests/cancels from inside a callback are
legal — the dispatch loop re-runs until the queues are stable.

The queues are bounded by construction: one entry per requesting item
(a PG / a stalled-op batch), and duplicates of a queued or granted item
are rejected — depth can never exceed the number of distinct PGs.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field


@dataclass
class _Reservation:
    item: object
    prio: int
    on_grant: object
    on_preempt: object = None
    seq: int = 0                 # FIFO age, ties preemption victims


@dataclass
class ReserverStats:
    """Lifetime accounting (the perf-counter surface the scheduler sums)."""
    grants: int = 0
    preemptions: int = 0
    cancels: int = 0
    peak_in_flight: int = 0
    peak_queued: int = 0


class AsyncReserver:
    """Prioritized FIFO reservation queues with bounded in-flight grants."""

    def __init__(self, name: str = "reserver", max_allowed: int = 1,
                 min_priority: int = 0):
        self.name = name
        self.max_allowed = max(0, int(max_allowed))
        self.min_priority = int(min_priority)
        # prio -> FIFO list of _Reservation (bounded: one per distinct item)
        self._queues: dict[int, list[_Reservation]] = {}
        self._queued: dict[object, _Reservation] = {}
        self._granted: dict[object, _Reservation] = {}
        self._seq = itertools.count()
        self.stats = ReserverStats()
        # re-entrancy: callbacks may request/cancel; the outer loop re-runs
        self._stepping = False
        self._dirty = False

    # -- public surface (AsyncReserver.h names) ----------------------------

    def request_reservation(self, item, on_grant, prio: int = 0,
                            on_preempt=None) -> None:
        if item in self._queued or item in self._granted:
            raise ValueError(f"{self.name}: duplicate reservation for "
                             f"{item!r}")
        res = _Reservation(item=item, prio=int(prio), on_grant=on_grant,
                           on_preempt=on_preempt, seq=next(self._seq))
        self._queues.setdefault(res.prio, []).append(res)
        self._queued[item] = res
        self.stats.peak_queued = max(self.stats.peak_queued,
                                     len(self._queued))
        self.do_queues()

    def update_priority(self, item, prio: int) -> None:
        """Re-rank a QUEUED request (a granted one keeps its slot — the
        reference requeues only waiting requests too)."""
        res = self._queued.get(item)
        if res is None or res.prio == prio:
            return
        self._queues[res.prio].remove(res)
        res.prio = int(prio)
        self._queues.setdefault(res.prio, []).append(res)
        self.do_queues()

    def cancel_reservation(self, item) -> bool:
        """Release a grant or withdraw a queued request; True if the item
        was known.  Idempotent: cancelling after a preemption already
        removed the grant is a no-op."""
        res = self._queued.pop(item, None)
        if res is not None:
            self._queues[res.prio].remove(res)
        else:
            res = self._granted.pop(item, None)
        if res is None:
            return False
        self.stats.cancels += 1
        self.do_queues()
        return True

    def set_max(self, max_allowed: int) -> None:
        self.max_allowed = max(0, int(max_allowed))
        self.do_queues()

    def has_reservation(self, item) -> bool:
        return item in self._granted

    def queue_depth(self) -> int:
        return len(self._queued)

    def in_flight(self) -> int:
        return len(self._granted)

    def dump(self) -> dict:
        return {
            "name": self.name,
            "max_allowed": self.max_allowed,
            "min_priority": self.min_priority,
            "queues": {prio: [repr(r.item) for r in q]
                       for prio, q in sorted(self._queues.items())
                       if q},
            "in_progress": {repr(r.item): r.prio
                            for r in self._granted.values()},
            "stats": vars(self.stats).copy(),
        }

    # -- the grant/preempt engine ------------------------------------------

    def do_queues(self) -> None:
        """Grant/preempt until stable.  Re-entrant calls (from grant or
        preempt callbacks) just mark the loop dirty; the outermost call
        keeps stepping until a full pass changes nothing."""
        if self._stepping:
            self._dirty = True
            return
        self._stepping = True
        try:
            while True:
                self._dirty = False
                fired = self._step()
                if not fired and not self._dirty:
                    break
        finally:
            self._stepping = False

    def _head_prio(self) -> int | None:
        best = None
        for prio, q in self._queues.items():
            if q and prio >= self.min_priority and \
                    (best is None or prio > best):
                best = prio
        return best

    def _step(self) -> bool:
        """One batch of state transitions; callbacks fire only after the
        structures are fully consistent (a grant callback observing the
        reserver must see itself granted)."""
        to_preempt: list[_Reservation] = []
        to_grant: list[_Reservation] = []
        while True:
            prio = self._head_prio()
            if prio is None:
                break
            if len(self._granted) < self.max_allowed:
                res = self._queues[prio].pop(0)
                del self._queued[res.item]
                self._granted[res.item] = res
                to_grant.append(res)
                continue
            # full: preempt the lowest-priority PREEMPTIBLE holder, but
            # only for a strictly higher-priority waiter (preempt_by)
            victims = [r for r in self._granted.values()
                       if r.on_preempt is not None]
            if not victims:
                break
            victim = min(victims, key=lambda r: (r.prio, -r.seq))
            if victim.prio >= prio:
                break
            del self._granted[victim.item]
            to_preempt.append(victim)
            res = self._queues[prio].pop(0)
            del self._queued[res.item]
            self._granted[res.item] = res
            to_grant.append(res)
        self.stats.peak_in_flight = max(self.stats.peak_in_flight,
                                        len(self._granted))
        for res in to_preempt:
            self.stats.preemptions += 1
            res.on_preempt()
        for res in to_grant:
            self.stats.grants += 1
            res.on_grant()
        return bool(to_preempt or to_grant)
