"""Regenerating-code repair planning: d helper inner products per loss.

Chained partial-sum repair (``recovery/chain.py``) already moved the
decode into the network, but any code that repairs by DECODE still moves
>= k chunks of independent data — the information floor.  Regenerating
codes change the floor itself: a product-matrix MSR/MBR plugin
(``plugins/plugin_pm_regen.py``, arXiv:1412.3022) rebuilds a lost chunk
from ``d`` helpers that each ship one beta-byte inner product
``psi_f . stored_chunk`` instead of a whole chunk — total repair wire
d*beta, which is ~1.0x the lost bytes at the MBR point and d/alpha at
MSR, both below the k-chunk floor.

This module is the planning half (the regen sibling of
``plan_chains``): capability probing so non-regenerating codes are
untouched, CRUSH-distance helper costing via the plugin's
``minimum_to_repair``, and plan assembly.  The data path lives in the
OSD shard handlers (``backend.pg_backend.OSDShard``): one
:class:`~ceph_tpu.backend.messages.ECRegenRead` primes the newcomer
with the combine matrix, d more carry each helper's projection row, and
:class:`~ceph_tpu.backend.messages.ECRegenHelper` ships the
beta-streams helper -> newcomer directly, so the coordinator sees
control traffic only.

Verification-first (the PR 12 rule): every leg validates against the
replicated plan hinfo (local copy present, version match, length,
chunk crc; the newcomer re-checks the COMBINED chunk's crc), and ANY
mismatch — sub-chunk misalignment, helper death, version skew — aborts
the tid to the coordinator, which falls back to the centralized
verified wave path.  :class:`RegenRepair` duck-types
:class:`~ceph_tpu.recovery.chain.ChainRepair`'s coordinator surface, so
completion, abort, shard-down and version-skew re-drive all ride the
existing chain machinery.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..backend.ecutil import HINFO_KEY
from ..backend.messages import ECRegenRead
from ..common.tracer import trace_span
from .chain import source_costs

__all__ = ["RegenRepair", "plan_regens"]


@dataclass
class RegenRepair:
    """Coordinator-side record of one in-flight regenerating repair.

    Same surface as :class:`~ceph_tpu.recovery.chain.ChainRepair`
    (``pending_pushes``/``failed``/``oids``/``on_each``/``at_version``/
    ``hop_shards``), registered in ``backend._recovery_chains`` +
    ``backend._wave_pushes`` so applied/abort/shard-down/version-skew
    handling is shared; ``kind`` splits the perf counters."""
    tid: int
    oids: dict[str, set[int]]                 # oid -> {lost chunk}
    on_each: object                           # callback(oid, ok)
    at_version: dict[str, int] = field(default_factory=dict)
    lengths: dict[str, int] = field(default_factory=dict)  # STORED bytes
    rows: list[int] = field(default_factory=list)          # [lost chunk]
    hop_shards: tuple[int, ...] = ()          # helper shards + newcomer
    pending_pushes: dict[str, set[int]] = field(default_factory=dict)
    failed: set[str] = field(default_factory=set)
    use_device: bool = False
    kind: str = "regen"


def plan_regens(backend, batch: dict[str, set[int]], on_each
                ) -> dict[str, set[int]]:
    """Plan regenerating repairs for a recovery batch.

    Returns the LEFTOVER oids the regen path cannot serve — callers run
    those through chains / centralized waves.  Leftover reasons: option
    disabled, plugin not regenerating, more than one lost chunk (the
    product-matrix repair protocol is single-erasure; multi-loss decodes
    centrally), fewer than d current helpers, a down target, an oid
    already owned by another wave/op, or missing plan metadata."""
    conf = backend.cct.conf
    ec = backend.ec_impl
    probe = getattr(ec, "supports_regenerating_repair", None)
    if (not conf.get("osd_recovery_regen_enable")
            or probe is None or not probe()):
        return dict(batch)
    leftovers: dict[str, set[int]] = {}
    groups: dict[int, dict[str, set[int]]] = {}
    for oid, missing in batch.items():
        if len(missing) != 1:
            leftovers[oid] = set(missing)
        elif oid in backend._wave_pushes or oid in backend.recovery_ops:
            leftovers[oid] = set(missing)
        else:
            groups.setdefault(next(iter(missing)), {})[oid] = set(missing)
    for lost, group in sorted(groups.items()):
        leftovers.update(_plan_group(backend, lost, group, on_each))
    return leftovers


def _plan_group(backend, lost: int, group: dict[str, set[int]], on_each
                ) -> dict[str, set[int]]:
    """Plan ONE regenerating repair for a lost-chunk group; returns the
    oids it could not take."""
    ec = backend.ec_impl
    d = int(ec.d)
    alpha = int(ec.get_sub_chunk_count())
    cur = backend.current_shards()
    up = backend.up_shards()
    acting = backend.acting
    locations = getattr(backend, "osd_locations", None)
    target = acting[lost]
    if target not in up:
        return group                     # a dead newcomer fails pre-flight
    avail = {c for c, s in enumerate(acting) if s in cur and c != lost}
    if len(avail) < d:
        return group
    try:
        helpers = list(ec.minimum_to_repair(
            lost, d, source_costs(avail, [target], acting, locations)))
    except IOError:
        return group
    try:
        proj = ec.repair_projection(lost).tobytes()
        combine = ec.repair_combine(lost, helpers).tobytes()
    except (IOError, ValueError):
        return group
    with trace_span("recovery.regen", owner="recovery",
                    objects=len(group), helpers=d):
        return _launch(backend, lost, group, on_each, helpers, proj,
                       combine, alpha)


def _launch(backend, lost: int, group, on_each, helpers: list[int],
            proj: bytes, combine: bytes, alpha: int
            ) -> dict[str, set[int]]:
    from .chain import _plan_attrs
    acting = backend.acting
    target = acting[lost]
    leftovers: dict[str, set[int]] = {}
    oids: list[str] = []
    lengths: list[int] = []
    versions: list[int] = []
    attrs: dict[str, dict] = {}
    at_version: dict[str, int] = {}
    for oid in sorted(group):
        hinfo = backend._read_hinfo(oid)
        length = hinfo.get_total_chunk_size()
        if not length or length % alpha:
            leftovers[oid] = group[oid]  # absent/empty or misaligned
            continue
        src_attrs = _plan_attrs(backend, oid, helpers)
        if src_attrs is None:
            leftovers[oid] = group[oid]
            continue
        attrs[oid] = {x: v for x, v in src_attrs.items() if x != HINFO_KEY}
        attrs[oid][HINFO_KEY] = hinfo.to_dict()
        at_version[oid] = backend.pg_log.last_version_of(oid)
        oids.append(oid)
        lengths.append(int(length))
        versions.append(int(hinfo.version))
    if not oids:
        return leftovers
    router = getattr(backend.ec_impl, "use_device", None)
    use_device = bool(router(sum(lengths))) if router is not None else False
    backend.next_tid += 1
    tid = backend.next_tid
    repair = RegenRepair(tid=tid,
                         oids={o: set(group[o]) for o in oids},
                         on_each=on_each, at_version=at_version,
                         lengths=dict(zip(oids, lengths)),
                         rows=[lost],
                         hop_shards=tuple(acting[c] for c in helpers)
                         + (target,),
                         use_device=use_device)
    for oid in oids:
        repair.pending_pushes[oid] = {target}
        backend._wave_pushes[oid] = repair
    backend._recovery_chains[tid] = repair
    # prime the newcomer FIRST so helper streams land on a known tid
    # (arrival order across senders is still not guaranteed — the shard
    # keeps a bounded orphan stash for early streams)
    backend.bus.send(target, ECRegenRead(
        from_shard=backend.whoami, tid=tid, coordinator=backend.whoami,
        target=target, chunk=lost, sub_count=alpha, combine=combine,
        helpers=list(helpers), oids=oids, lengths=lengths,
        versions=versions, attrs=attrs, use_device=use_device))
    for h in helpers:
        backend.bus.send(acting[h], ECRegenRead(
            from_shard=backend.whoami, tid=tid,
            coordinator=backend.whoami, target=target, chunk=h,
            sub_count=alpha, proj=proj, oids=oids, lengths=lengths,
            versions=versions, attrs=attrs, use_device=use_device))
    return leftovers
