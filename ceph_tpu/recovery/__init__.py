"""Background repair orchestration: reservations, priorities, waves.

``reserver``  — :class:`AsyncReserver`, the prioritized/preemptible
                reservation gate (common/AsyncReserver.h analog);
``scheduler`` — :class:`RecoveryScheduler` + :class:`PGRecoveryJob`,
                the per-OSD recovery admission machine with batch-fused
                waves (ecutil.decode_shards_many) and token-bucket
                byte-rate pacing.
"""
from .reserver import AsyncReserver, ReserverStats
from .scheduler import (OSD_BACKFILL_PRIORITY_BASE,
                        OSD_RECOVERY_INACTIVE_PRIORITY_BASE,
                        OSD_RECOVERY_PRIORITY_BASE,
                        OSD_RECOVERY_PRIORITY_FORCED,
                        OSD_RECOVERY_PRIORITY_MAX,
                        JobState, PGRecoveryJob, RecoveryScheduler,
                        live_schedulers)

__all__ = [
    "AsyncReserver", "ReserverStats", "RecoveryScheduler",
    "PGRecoveryJob", "JobState", "live_schedulers",
    "OSD_RECOVERY_PRIORITY_BASE", "OSD_BACKFILL_PRIORITY_BASE",
    "OSD_RECOVERY_INACTIVE_PRIORITY_BASE", "OSD_RECOVERY_PRIORITY_MAX",
    "OSD_RECOVERY_PRIORITY_FORCED",
]
