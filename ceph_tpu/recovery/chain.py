"""Chained streaming repair: partial-sum pipelines over survivor OSDs.

Centralized EC repair hauls k whole chunks to the coordinating primary
(k+|missing| chunk transfers per object).  For linear codes the decode is
a sum the NETWORK can compute instead: plan a chain of survivor shards,
have each hop GF-scale its local chunk by its decode coefficient and XOR
it into a running partial sum, and forward only that accumulator to the
next hop (the RapidRAID / partial-parallel-repair pipelining idea, cf.
arXiv:1207.6744).  The last hop holds the finished chunks and pushes
them straight to the repair targets — the coordinator sees control
traffic only.

Total cluster wire stays >= k transfers (information floor: k chunks'
worth of independent data must move), but the COORDINATOR ingress drops
from ~k chunks per object to ~zero and the repaired-bytes-per-wire-byte
ratio approaches 1 for single-erasure repair, which is what unclogs a
recovering primary.

This module is the planning half: CRUSH-distance source costing, hop
ordering, and wave-batch plan assembly.  The data path lives in the OSD
shard handlers (``backend.pg_backend.OSDShard``); the coordinator-side
bookkeeping record :class:`ChainRepair` duck-types ``_RecoveryWave``'s
surface (``pending_pushes`` / ``failed`` / ``oids`` / ``on_each`` /
``at_version``) so the existing wave completion and shard-down paths
drive chains unchanged.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..backend import ecutil
from ..backend.ecutil import HINFO_KEY
from ..backend.memstore import GObject
from ..backend.messages import ECPartialSum
from ..common.tracer import trace_span

__all__ = ["ChainRepair", "crush_distance", "source_costs", "order_hops",
           "plan_chains"]

# CRUSH-distance buckets (MiniCluster's map: host = osd // osds_per_host).
# The absolute values only matter relative to each other: same-OSD beats
# same-host beats cross-host, and cross-host is lossy enough to outweigh
# a same-host pair (1 + 1 < 3).
SAME_OSD = 0
SAME_HOST = 1
CROSS_HOST = 3


def crush_distance(a: int, b: int, locations=None) -> int:
    """Topology distance between two OSD ids.  ``locations`` maps osd ->
    host bucket; without a map every remote OSD is equidistant."""
    if a == b:
        return SAME_OSD
    if locations is None:
        return SAME_HOST
    ha, hb = locations.get(a), locations.get(b)
    return SAME_HOST if ha is not None and ha == hb else CROSS_HOST


def source_costs(sources, targets, acting, locations=None) -> dict[int, int]:
    """chunk id -> min CRUSH distance from its shard to any repair target
    (the cost map ``minimum_to_decode_with_cost`` ranks by)."""
    return {c: min(crush_distance(acting[c], t, locations) for t in targets)
            for c in sources}


def order_hops(sources, targets, acting, locations=None) -> list[int]:
    """Chain order over source chunks: farthest-from-target first so the
    final (and only target-facing) hop is the nearest survivor — the
    expensive cross-host legs carry one accumulator each, and the short
    last leg fans out the finished chunks.  Ties break on chunk id for
    determinism."""
    return sorted(sources,
                  key=lambda c: (-min(crush_distance(acting[c], t, locations)
                                      for t in targets), c))


@dataclass
class ChainRepair:
    """Coordinator-side record of one in-flight partial-sum chain.

    Duck-types the ``_RecoveryWave`` surface the push-completion and
    shard-down machinery in ``ECBackend``/``PGBackend`` already drives:
    ``pending_pushes``/``failed`` feed ``_finish_wave_oid``, ``oids`` +
    ``on_each`` feed ``_wave_fallback_one``, and registration in
    ``backend._wave_pushes`` routes dead-target handling for free."""
    tid: int
    oids: dict[str, set[int]]                 # oid -> missing chunks
    on_each: object                           # callback(oid, ok)
    at_version: dict[str, int] = field(default_factory=dict)  # pg_log version
    lengths: dict[str, int] = field(default_factory=dict)     # chunk bytes
    rows: list[int] = field(default_factory=list)             # erased chunks
    hop_shards: tuple[int, ...] = ()          # chain legs, in order
    pending_pushes: dict[str, set[int]] = field(default_factory=dict)
    failed: set[str] = field(default_factory=set)
    use_device: bool = False


def plan_chains(backend, batch: dict[str, set[int]], on_each) -> dict[str, set[int]]:
    """Plan partial-sum chains for a recovery wave's batch.

    Groups ``batch`` (oid -> missing chunks) by missing-signature, plans
    one chain per group, registers :class:`ChainRepair` records on the
    backend and launches the first leg.  Returns the LEFTOVER oids the
    chain path cannot serve — callers run those through the centralized
    wave/per-object machinery.  Leftover reasons: option disabled, no
    linear whole-chunk repair form (sub-chunked/clay), chain longer than
    ``osd_recovery_chain_max_len``, a down target, version skew, an oid
    already owned by another wave/op, or missing plan metadata."""
    conf = backend.cct.conf
    if not conf.get("osd_recovery_chain_enable"):
        return dict(batch)
    max_len = int(conf.get("osd_recovery_chain_max_len"))
    leftovers: dict[str, set[int]] = {}
    groups: dict[frozenset, dict[str, set[int]]] = {}
    for oid, missing in batch.items():
        if oid in backend._wave_pushes or oid in backend.recovery_ops:
            # the push slot / op slot is per-oid (one repair owner at a
            # time) — the per-object path knows how to chain behind it
            leftovers[oid] = set(missing)
        else:
            groups.setdefault(frozenset(missing), {})[oid] = set(missing)
    for sig, group in sorted(groups.items(), key=lambda kv: sorted(kv[0])):
        leftovers.update(_plan_group(backend, sig, group, on_each, max_len))
    return leftovers


def _plan_group(backend, sig: frozenset, group: dict[str, set[int]],
                on_each, max_len: int) -> dict[str, set[int]]:
    """Plan ONE chain for a missing-signature group; returns the oids it
    could not take."""
    k = backend.ec_impl.get_data_chunk_count()
    cur = backend.current_shards()
    up = backend.up_shards()
    acting = backend.acting
    locations = getattr(backend, "osd_locations", None)
    if any(acting[c] not in up for c in sig):
        return group                     # a dead target fails pre-flight
    avail = {c for c, s in enumerate(acting) if s in cur and c not in sig}
    if len(avail) < k:
        return group
    try:
        srcs = backend.ec_impl.minimum_to_decode_with_cost(
            set(sig), source_costs(avail, [acting[c] for c in sig],
                                   acting, locations))
    except IOError:
        return group
    ps = backend.ec_impl.partial_sum_coefficients(set(sig), sorted(srcs))
    if ps is None:
        return group                     # no linear whole-chunk form
    coeffs, rows = ps
    if not coeffs or len(coeffs) > max_len:
        return group
    targets = [acting[r] for r in rows]
    hop_chunks = order_hops(coeffs, targets, acting, locations)
    with trace_span("recovery.chain", owner="recovery", objects=len(group),
                    hops=len(hop_chunks)):
        return _launch(backend, group, on_each, rows, targets,
                       hop_chunks, coeffs)


def _launch(backend, group, on_each, rows, targets, hop_chunks, coeffs
            ) -> dict[str, set[int]]:
    acting = backend.acting
    leftovers: dict[str, set[int]] = {}
    oids: list[str] = []
    lengths: list[int] = []
    versions: list[int] = []
    attrs: dict[str, dict] = {}
    at_version: dict[str, int] = {}
    for oid in sorted(group):
        hinfo = backend._read_hinfo(oid)
        length = hinfo.get_total_chunk_size()
        if not length:
            leftovers[oid] = group[oid]  # absent/empty: nothing to chain
            continue
        src_attrs = _plan_attrs(backend, oid, hop_chunks)
        if src_attrs is None:
            leftovers[oid] = group[oid]
            continue
        attrs[oid] = {x: v for x, v in src_attrs.items() if x != HINFO_KEY}
        attrs[oid][HINFO_KEY] = hinfo.to_dict()
        at_version[oid] = backend.pg_log.last_version_of(oid)
        oids.append(oid)
        lengths.append(int(length))
        versions.append(int(hinfo.version))
    if not oids:
        return leftovers
    use_device = ecutil._device_codec(
        backend.ec_impl, sum(lengths)) is not None
    backend.next_tid += 1
    tid = backend.next_tid
    chain = ChainRepair(tid=tid,
                        oids={o: set(group[o]) for o in oids},
                        on_each=on_each, at_version=at_version,
                        lengths=dict(zip(oids, lengths)),
                        rows=list(rows),
                        hop_shards=tuple(acting[c] for c in hop_chunks),
                        use_device=use_device)
    for oid in oids:
        chain.pending_pushes[oid] = set(targets)
        backend._wave_pushes[oid] = chain
    backend._recovery_chains[tid] = chain
    msg = ECPartialSum(from_shard=backend.whoami, tid=tid,
                       coordinator=backend.whoami, oids=oids,
                       lengths=lengths, versions=versions,
                       rows=list(rows), targets=list(targets),
                       hops=[(acting[c], c, tuple(coeffs[c]))
                             for c in hop_chunks],
                       attrs=attrs, acc=None, use_device=use_device)
    backend.bus.send(chain.hop_shards[0], msg)
    return leftovers


def _plan_attrs(backend, oid: str, hop_chunks) -> dict | None:
    """Replicated attrs from the first chain source holding a current
    copy (every hop is current by construction; mirrors the authority
    order ``_read_hinfo`` uses)."""
    from ..backend.pg_backend import shard_store
    for c in hop_chunks:
        s = backend.acting[c]
        if s not in backend.bus.handlers:
            continue
        try:
            return shard_store(backend.bus, s).getattrs(GObject(oid, s))
        except (FileNotFoundError, KeyError):
            continue
    return None
