"""RecoveryScheduler: reservation-gated, prioritized, batch-fused repair.

The orchestration layer between damage DETECTION (peering, shard
revival, scrub) and the repair machinery in ``backend/pg_backend.py``.
Analog of the reference's background-recovery admission stack
(reference: src/common/AsyncReserver.h instantiated as the OSD's
``local_reserver``/``remote_reserver``, OSDService::queue_for_recovery +
the ``osd_max_backfills`` / ``osd_recovery_max_active`` /
``osd_recovery_sleep`` option family), with the TPU twist the ROADMAP
demands: each wave's missing objects are reconstructed through ONE
batched device dispatch (``ecutil.decode_shards_many``) instead of one
``decode`` per object.

Flow per degraded PG (a :class:`PGRecoveryJob`):

1. **local reservation** on the primary OSD's
   :class:`~ceph_tpu.recovery.reserver.AsyncReserver` at a Ceph-style
   priority (table below);
2. per target shard, a **remote reservation** on the target OSD's
   remote reserver (sequential, like the reference's
   RemoteBackfillReserved chain);
3. the shard repair starts with the job as its *driver*: the repair
   planner hands the missing-object list back instead of recovering
   inline, and the job paces it in **waves** — at most
   ``osd_recovery_max_active`` objects each, queued on the primary
   daemon's dmClock queue in the ``background_recovery`` class (client
   ops win under load), byte-budgeted by a token bucket
   (``osd_recovery_max_bytes_per_sec``) with ``osd_recovery_sleep``
   of virtual time between waves;
4. completion releases the reservations; preemption by a
   higher-priority PG (or a map change via the peering statechart)
   aborts the current repair cleanly and requeues the job.

Priority table (reference: PeeringState::get_recovery_priority):

======================================  =====
``OSD_RECOVERY_PRIORITY_FORCED``          255
``OSD_RECOVERY_PRIORITY_MAX``             253
``OSD_RECOVERY_INACTIVE_PRIORITY_BASE``   220   (+ degraded depth)
``OSD_RECOVERY_PRIORITY_BASE``            180   (+ pool prio + depth)
``OSD_BACKFILL_PRIORITY_BASE``            140   (+ pool prio + depth)
======================================  =====

Pool ``recovery_priority`` (a pool param) is clamped to [-10, 10] like
the reference; degraded depth is the number of stale/down shards in the
acting set, so deeper damage sorts first within a band.
"""
from __future__ import annotations

import weakref
from dataclasses import dataclass, field
from enum import Enum

from .reserver import AsyncReserver
from ..common.tracer import trace_span
from ..osd.mclock import BG_RECOVERY
from ..osd.pg_log import OP_DELETE

OSD_RECOVERY_PRIORITY_FORCED = 255
OSD_RECOVERY_PRIORITY_MAX = 253
OSD_RECOVERY_INACTIVE_PRIORITY_BASE = 220
OSD_RECOVERY_PRIORITY_BASE = 180
OSD_BACKFILL_PRIORITY_BASE = 140

# live schedulers, for the prometheus reserver-gauge export and the
# stats digest (the osd_daemon.live_daemons weakref pattern)
_SCHEDULERS: "weakref.WeakSet[RecoveryScheduler]" = weakref.WeakSet()


def live_schedulers() -> list["RecoveryScheduler"]:
    return list(_SCHEDULERS)


class JobState(Enum):
    QUEUED = "queued"            # waiting for the local reservation
    RUNNING = "running"          # local held; repairing target by target
    COMPLETE = "complete"
    CANCELLED = "cancelled"


class _TokenBucket:
    """Post-paid byte budget: a wave always runs, the NEXT wave waits out
    whatever debt it left (guaranteed progress under any cap — the
    pacing role ``osd_recovery_sleep`` + the recovery throttles play in
    the reference).  Burst capacity is one second of rate."""

    def __init__(self, rate: float):
        self.rate = float(rate)
        self.tokens = 0.0
        self.last: float | None = None

    def consume(self, amount: float, now: float) -> float:
        """Spend ``amount`` at ``now``; returns seconds until the debt
        clears (0.0 when within budget or uncapped)."""
        if self.rate <= 0:
            return 0.0
        if self.last is None:
            self.last = now
            self.tokens = self.rate          # full burst on first use
        self.tokens = min(self.rate,
                          self.tokens + (now - self.last) * self.rate)
        self.last = now
        self.tokens -= amount
        return max(0.0, -self.tokens / self.rate)


@dataclass
class PGRecoveryJob:
    """One degraded PG's trip through the scheduler.

    A job repairs its targets in BATCHES: all remote reservations for
    the batch acquire in ascending-OSD order (globally ordered
    hold-and-wait — two jobs can never deadlock on each other's remote
    slots), then every shard repair of the batch runs CONCURRENTLY.
    Concurrency within the batch is load-bearing, not an optimization:
    one shard's missing objects may only become recoverable once the
    OTHER stale shards of the same PG catch up (current_shards() must
    grow past k), exactly like the inline path's parallel repairs."""
    key: str                     # backend.instance_name (unique per PG)
    backend: object
    pgid: object
    daemon: object
    pool_params: dict
    targets: list[int]           # shards waiting for the NEXT batch
    priority: int
    backfill: frozenset = frozenset()   # targets known to need backfill
    state: JobState = JobState.QUEUED
    batch: list = field(default_factory=list)    # shards repairing now
    remote_pending: list = field(default_factory=list)  # ascending OSDs
    remote_waiting: int | None = None   # request queued, not yet granted
    remote_held: set = field(default_factory=set)
    repairs_open: int = 0        # batch repairs not yet complete
    rops: dict = field(default_factory=dict)     # shard -> ShardRepairOp
    stalled: list = field(default_factory=list)  # parked RecoveryOps
    open_ops: int = 0            # re-driven stalled ops still in flight
    not_before: float = 0.0      # wave pacing horizon (daemon clock)
    # bumped on preemption/cancel AND batch restarts so every wave /
    # repair / remote-reservation callback of the old incarnation turns
    # inert (the role osdmap epochs play for sub-ops)
    gen: int = 0
    # the LOCAL reservation's own generation: its grant/preempt closures
    # are registered once per request, so this bumps ONLY when the local
    # reservation is re-requested (preempt/cancel) — a batch restart
    # bumping `gen` must not stale the still-live local callbacks, or a
    # later preemption of the slot would be silently ignored
    local_gen: int = 0
    cancelled: bool = False

    # -- driver interface (ShardRepairOp.driver) ---------------------------

    def offer_work(self, backend, rop, items) -> None:
        """The repair planner computed the missing set: pace it in waves
        instead of recovering inline (pg_backend.handle_pg_log_info /
        handle_pg_scan_reply hand off here when a driver is attached)."""
        self.rops[rop.shard] = rop
        rop.deferred = list(items)
        self.scheduler._queue_wave(self, rop)

    scheduler: object = None     # backref, set at creation


class RecoveryScheduler:
    """Per-OSD local/remote reservers + the PG job state machine."""

    def __init__(self, cct=None, name: str = "recovery"):
        from ..common import PerfCountersBuilder, default_context
        from ..ops.pipeline import CodecPipeline
        self.cct = cct if cct is not None else default_context()
        self.name = name
        self._local: dict[int, AsyncReserver] = {}
        self._remote: dict[int, AsyncReserver] = {}
        self._buckets: dict[int, _TokenBucket] = {}
        self.jobs: dict[str, PGRecoveryJob] = {}
        # one device pipeline shared by every attached PG backend: wave
        # reconstructs dispatch async through it, so a wave's later
        # signature groups pack on the host while earlier groups' device
        # decodes are still in flight (depth 0 turns it off)
        depth = int(self.cct.conf.get("jax_rs_pipeline_depth"))
        self.pipeline = CodecPipeline(depth=depth, cct=self.cct,
                                      name=f"recovery.{name}.pipeline") \
            if depth > 0 else None
        self.perf = (
            PerfCountersBuilder(f"recovery.{name}")
            .add_u64_counter("jobs_scheduled",
                             "PG recovery jobs entering the scheduler")
            .add_u64_counter("jobs_completed",
                             "PG recovery jobs run to completion")
            .add_u64_counter("preemptions",
                             "jobs preempted by higher-priority PGs")
            .add_u64_counter("map_cancels",
                             "jobs cancelled by map changes (re-peering)")
            .add_u64_counter("waves", "recovery waves dispatched")
            .add_u64_counter("wave_objects",
                             "objects dispatched inside waves")
            .add_u64_counter("stalled_requeued",
                             "parked recoveries re-entered via the "
                             "scheduler instead of bypassing it")
            .add_u64("jobs_queued", "jobs waiting for a local reservation")
            .add_u64("jobs_active", "jobs holding a local reservation")
            .create_perf_counters())
        self.cct.perf.add(self.perf)
        # osd_max_backfills is live-tunable (0 pauses background repair):
        # existing reservers must re-bound on a conf set, not just ones
        # created later.  Weakref so a discarded scheduler's observer
        # (the ConfigProxy keeps observers forever) goes inert.
        ref = weakref.ref(self)

        def _on_max_backfills(_name, value, _ref=ref):
            sched = _ref()
            if sched is None:
                return
            for table in (sched._local, sched._remote):
                for r in table.values():
                    r.set_max(int(value))
        self.cct.conf.add_observer("osd_max_backfills", _on_max_backfills)
        # optional cluster log (common/clusterlog.py): job start/finish
        # lines land where an operator reads them (`ceph -w`)
        self.clog = None
        _SCHEDULERS.add(self)

    def close(self) -> None:
        """Unhook from the Context and the live registry (a shut-down
        cluster must stop exporting reserver gauges)."""
        self.cct.perf.remove(self.perf.name)
        if self.pipeline is not None:
            self.pipeline.close()
        _SCHEDULERS.discard(self)
        self.jobs.clear()

    def inject_device_faults(self, injector) -> None:
        """Route the device-plane fault injection (failure/) through the
        scheduler's shared wave pipeline — the chaos harness hook."""
        if self.pipeline is not None:
            self.pipeline.inject_faults(injector)

    # -- conf --------------------------------------------------------------

    def _conf(self, key: str):
        return self.cct.conf.get(key)

    # -- reservers (the OSD's local_reserver / remote_reserver pair) -------

    def local_reserver(self, osd: int) -> AsyncReserver:
        r = self._local.get(osd)
        if r is None:
            r = self._local[osd] = AsyncReserver(
                f"{self.name}.local.osd.{osd}",
                max_allowed=int(self._conf("osd_max_backfills")))
        return r

    def remote_reserver(self, osd: int) -> AsyncReserver:
        r = self._remote.get(osd)
        if r is None:
            r = self._remote[osd] = AsyncReserver(
                f"{self.name}.remote.osd.{osd}",
                max_allowed=int(self._conf("osd_max_backfills")))
        return r

    def _bucket(self, osd: int) -> _TokenBucket:
        b = self._buckets.get(osd)
        rate = float(self._conf("osd_recovery_max_bytes_per_sec"))
        if b is None:
            b = self._buckets[osd] = _TokenBucket(rate)
        b.rate = rate                       # live-tunable
        return b

    # -- attachment (MiniCluster.enable_recovery_scheduler) ----------------

    def attach_backend(self, backend, pgid, daemon,
                       pool_params: dict | None = None) -> None:
        """Wire a PG backend: revival/stall/peering repair paths then
        route through this scheduler instead of firing inline, and wave
        reconstructs ride the scheduler's shared device pipeline."""
        backend.recovery_scheduler = self
        backend.recovery_pipeline = self.pipeline
        backend._recovery_ctx = {"pgid": pgid, "daemon": daemon,
                                 "pool_params": dict(pool_params or {})}
        # chained streaming repair runs its scale-accumulate on SURVIVOR
        # shards, not the primary: hand every shard handler the same
        # shared pipeline so hop dispatches get the breaker / host
        # fallback / device attribution the wave decodes already have
        for handler in backend.bus.handlers.values():
            getattr(handler, "local_shard",
                    handler).recovery_pipeline = self.pipeline

    # -- priorities --------------------------------------------------------

    def pg_priority(self, backend, pool_params: dict | None = None,
                    backfill: frozenset = frozenset(),
                    forced: bool = False) -> int:
        if forced:
            return OSD_RECOVERY_PRIORITY_FORCED
        params = pool_params or {}
        pool_prio = max(-10, min(10, int(params.get("recovery_priority",
                                                    0) or 0)))
        acting = set(backend.acting)
        depth = len(acting & (backend.stale | backend.bus.down))
        if not backend.is_active():
            # inactive PG: writes are blocked — escalate past every
            # ordinary recovery (the reference's inactive base)
            base = OSD_RECOVERY_INACTIVE_PRIORITY_BASE + depth
        elif backfill:
            base = OSD_BACKFILL_PRIORITY_BASE + pool_prio + depth
        else:
            base = OSD_RECOVERY_PRIORITY_BASE + pool_prio + depth
        return max(1, min(OSD_RECOVERY_PRIORITY_MAX, base))

    # -- entry points ------------------------------------------------------

    def schedule_backend(self, backend, targets=None,
                         backfill=frozenset(),
                         forced: bool = False,
                         stalled=None) -> PGRecoveryJob:
        """Queue (or merge into) the PG's recovery job.  ``targets``
        defaults to the backend's stale-but-up shards; an existing live
        job absorbs new targets instead of double-reserving."""
        ctx = getattr(backend, "_recovery_ctx", None)
        if ctx is None:
            raise ValueError(f"backend {backend.instance_name} is not "
                             f"attached to scheduler {self.name}")
        key = backend.instance_name
        want = list(targets) if targets is not None else \
            sorted(backend.stale & backend.up_shards())
        job = self.jobs.get(key)
        if job is not None and not job.cancelled and \
                job.state in (JobState.QUEUED, JobState.RUNNING):
            added = False
            for s in want:
                if s not in job.batch and s not in job.targets:
                    job.targets.append(s)
                    added = True
            # merged targets carry their backfill classification along,
            # or later priority recomputations band them wrongly
            job.backfill = frozenset(job.backfill | set(backfill))
            prio = self.pg_priority(backend, job.pool_params,
                                    job.backfill, forced)
            if prio > job.priority:
                job.priority = prio
                if job.state is JobState.QUEUED:
                    self.local_reserver(backend.whoami).update_priority(
                        job.key, prio)
                elif job.remote_waiting is not None:
                    # escalation must reach the queued REMOTE request
                    # too, or a forced job keeps waiting at its old rank
                    self.remote_reserver(
                        job.remote_waiting).update_priority(
                        (job.key, job.remote_waiting), prio)
            if added and job.state is JobState.RUNNING:
                if not job.batch and not job.remote_pending:
                    self._start_batch(job)
                else:
                    # a batch is in flight but the NEW target may be the
                    # very shard whose catch-up the batch's recoveries
                    # are waiting on (current_shards() below k): restart
                    # with the union — all of a PG's stale shards must
                    # repair together or none can finish
                    self._restart_batch(job)
            return job
        job = PGRecoveryJob(
            key=key, backend=backend, pgid=ctx["pgid"],
            daemon=ctx["daemon"], pool_params=ctx["pool_params"],
            targets=list(want), backfill=frozenset(backfill),
            priority=self.pg_priority(backend, ctx["pool_params"],
                                      frozenset(backfill), forced))
        job.scheduler = self
        # stalled ops must board BEFORE the reservation request: the
        # grant can fire synchronously and run the job to completion —
        # ops attached to an already-completed (popped) job are stranded
        job.stalled = list(stalled or [])
        self.jobs[key] = job
        self.perf.inc("jobs_scheduled")
        if self.clog is not None:
            self.clog.info(
                f"recovery queued for pg {job.pgid} "
                f"(targets {sorted(job.targets)}, prio {job.priority})",
                channel="recovery")
        self._update_gauges()
        self._request_local(job)
        return job

    def _request_local(self, job: PGRecoveryJob) -> None:
        lgen = job.local_gen
        self.local_reserver(job.backend.whoami).request_reservation(
            job.key,
            on_grant=lambda: self._local_granted(job, lgen),
            prio=job.priority,
            on_preempt=lambda: self._preempted_local(job, lgen))

    def requeue_stalled(self, backend, rops) -> PGRecoveryJob | None:
        """Parked RecoveryOps re-enter reservation-gated: they ride the
        PG's job (merged with any pending shard repairs) instead of
        bypassing the scheduler on shard revival."""
        rops = [r for r in rops if r is not None]
        if not rops:
            return None
        self.perf.inc("stalled_requeued", len(rops))
        job = self.jobs.get(backend.instance_name)
        if job is not None and not job.cancelled and \
                job.state in (JobState.QUEUED, JobState.RUNNING):
            # board before the merge: _start_batch may run _maybe_complete
            # and an empty stalled list would let the job finish under us
            job.stalled.extend(rops)
            self.schedule_backend(backend)
            if job.state is JobState.RUNNING:
                self._drive_stalled(job)
                self._maybe_complete(job)
            return job
        return self.schedule_backend(backend, stalled=rops)

    def cancel_pg(self, backend, reason: str = "map change") -> bool:
        """Map change / re-peering: abort the PG's job cleanly.  The
        current shard repair fails (the shard stays stale), reservations
        release, still-parked ops go back to the backend's stall list —
        the re-activation that follows schedules a fresh job."""
        job = self.jobs.pop(backend.instance_name, None)
        if job is None or job.state in (JobState.COMPLETE,
                                        JobState.CANCELLED):
            return False
        job.cancelled = True
        job.gen += 1
        job.local_gen += 1
        job.state = JobState.CANCELLED
        self.perf.inc("map_cancels")
        self._release_all(job)
        self._abort_batch(job)
        backend._stalled_recoveries.extend(job.stalled)
        job.stalled = []
        self._update_gauges()
        return True

    # -- job state machine -------------------------------------------------

    def _local_granted(self, job: PGRecoveryJob, lgen: int) -> None:
        if job.local_gen != lgen or job.cancelled:
            return
        job.state = JobState.RUNNING
        self._update_gauges()
        self._drive_stalled(job)
        self._start_batch(job)

    def _start_batch(self, job: PGRecoveryJob) -> None:
        """Take every queued target as ONE batch and acquire its remote
        reservations in ascending-OSD order before any repair starts
        ('local+remote reservations before any push')."""
        if job.cancelled or job.batch or job.remote_pending:
            return
        seen: set[int] = set()
        batch: list[int] = []
        for shard in job.targets:
            if shard not in seen and shard not in job.backend.bus.down:
                seen.add(shard)
                batch.append(shard)
        job.targets = []
        if not batch:
            self._maybe_complete(job)
            return
        job.batch = batch
        job.remote_pending = sorted(s for s in batch
                                    if s != job.backend.whoami)
        self._acquire_next_remote(job)

    def _acquire_next_remote(self, job: PGRecoveryJob) -> None:
        if job.cancelled:
            return
        if not job.remote_pending:
            self._run_batch(job)
            return
        shard = job.remote_pending.pop(0)
        job.remote_waiting = shard
        gen = job.gen
        self.remote_reserver(shard).request_reservation(
            (job.key, shard),
            on_grant=lambda: self._remote_granted(job, shard, gen),
            prio=job.priority,
            on_preempt=lambda: self._preempted(job, gen))

    def _remote_granted(self, job: PGRecoveryJob, shard: int,
                        gen: int) -> None:
        if job.gen != gen or job.cancelled:
            # grant raced a preemption/cancel of this incarnation: give
            # the slot straight back, or it would be held forever
            self.remote_reserver(shard).cancel_reservation((job.key,
                                                            shard))
            return
        job.remote_waiting = None
        job.remote_held.add(shard)
        self._acquire_next_remote(job)

    def _run_batch(self, job: PGRecoveryJob) -> None:
        """Every reservation held: start ALL the batch's shard repairs
        (concurrently — one shard's objects may only be recoverable once
        the others catch up; see the class docstring)."""
        gen = job.gen
        b = job.backend
        job.repairs_open = 0
        for shard in list(job.batch):
            if shard in b.bus.down:
                job.batch.remove(shard)
                continue
            job.repairs_open += 1
            # the backend dedupes repairs by shard (an existing one just
            # chains our on_complete), so every increment above has a
            # matching completion callback
            job.rops[shard] = b.start_shard_repair(
                shard,
                on_complete=lambda rop, _s=shard:
                    self._on_repair_done(job, _s, gen),
                driver=job)
        if job.repairs_open == 0:
            self._finish_batch(job)

    def _on_repair_done(self, job: PGRecoveryJob, shard: int,
                        gen: int) -> None:
        if job.gen != gen or job.cancelled:
            return
        if shard in job.batch:
            job.batch.remove(shard)
        job.rops.pop(shard, None)
        job.repairs_open = max(0, job.repairs_open - 1)
        if job.repairs_open == 0:
            self._finish_batch(job)

    def _restart_batch(self, job: PGRecoveryJob) -> None:
        """Fold the in-flight batch back into the target queue and start
        over with the union.  Remote slots release and re-acquire in
        ascending order, preserving the deadlock-freedom invariant;
        aborted repairs fail cleanly (their shards stay stale and rejoin
        the new batch), completed pushes are kept by the stores."""
        job.gen += 1                # in-flight wave/repair callbacks go inert
        self._abort_batch(job)
        self._release_remotes(job)
        job.targets = job.batch + job.targets
        job.batch, job.remote_pending = [], []
        job.repairs_open = 0
        job.open_ops = 0            # ungated in-flight ops drain on their own
        self._start_batch(job)

    def _finish_batch(self, job: PGRecoveryJob) -> None:
        job.batch = []
        self._release_remotes(job)
        self._drive_stalled(job)
        if job.targets:                 # revivals that arrived mid-batch
            self._start_batch(job)
        else:
            self._maybe_complete(job)

    def _maybe_complete(self, job: PGRecoveryJob) -> None:
        if job.cancelled or job.targets or job.batch or \
                job.remote_pending or job.stalled or job.open_ops:
            return
        job.state = JobState.COMPLETE
        self.jobs.pop(job.key, None)
        self.local_reserver(job.backend.whoami).cancel_reservation(job.key)
        self.perf.inc("jobs_completed")
        if self.clog is not None:
            self.clog.info(f"recovery of pg {job.pgid} complete",
                           channel="recovery")
        self._update_gauges()

    def _preempted(self, job: PGRecoveryJob, gen: int) -> None:
        """A REMOTE reservation we hold (or wait on) was preempted."""
        if job.gen != gen or job.cancelled:
            return
        self._do_preempt(job)

    def _preempted_local(self, job: PGRecoveryJob, lgen: int) -> None:
        """The LOCAL reservation was preempted (guarded by its own
        generation: batch restarts bump `gen` but leave the local
        grant's closures live)."""
        if job.local_gen != lgen or job.cancelled:
            return
        self._do_preempt(job)

    def _do_preempt(self, job: PGRecoveryJob) -> None:
        """A higher-priority PG took a reservation: stop cleanly — the
        batch's shard repairs fail (their shards stay stale, nothing
        half-applied), in-flight object pushes drain harmlessly — and
        requeue at a freshly computed priority."""
        job.gen += 1
        job.local_gen += 1
        self.perf.inc("preemptions")
        self._release_all(job)
        self._abort_batch(job)
        job.targets = job.batch + job.targets   # remote_pending ⊆ batch
        job.batch, job.remote_pending = [], []
        job.repairs_open = 0
        job.open_ops = 0            # ungated in-flight ops drain on their own
        job.state = JobState.QUEUED
        job.priority = self.pg_priority(job.backend, job.pool_params,
                                        job.backfill)
        self._update_gauges()
        self._request_local(job)

    def _release_all(self, job: PGRecoveryJob) -> None:
        self.local_reserver(job.backend.whoami).cancel_reservation(job.key)
        self._release_remotes(job)

    def _release_remotes(self, job: PGRecoveryJob) -> None:
        """ONE copy of remote-slot release, shared by batch finish,
        batch restart, preemption, and cancel."""
        for shard in sorted(job.remote_held):
            self.remote_reserver(shard).cancel_reservation((job.key,
                                                            shard))
        job.remote_held.clear()
        if job.remote_waiting is not None:
            # a request still queued (no grant yet) must be withdrawn too
            self.remote_reserver(job.remote_waiting).cancel_reservation(
                (job.key, job.remote_waiting))
            job.remote_waiting = None

    def _abort_batch(self, job: PGRecoveryJob) -> None:
        """Fail the batch's shard repairs NOW and deregister them: a
        restarted (or freshly granted) batch must start FRESH repairs —
        leaving a doomed op in ``shard_repairs`` would make the restart
        silently join it and complete with the shard still stale.
        Callbacks of in-flight recover/delete sub-ops go inert once the
        op leaves RECOVERING (the on_shard_down discipline)."""
        b = job.backend
        for shard, rop in sorted(job.rops.items()):
            rop.deferred = []
            rop.failed = True
            if b.shard_repairs.get(shard) is rop:
                b._repair_write_tids = {
                    tid: v for tid, v in b._repair_write_tids.items()
                    if v[0] is not rop}
                rop.pending.clear()
                b._finish_shard_repair(rop)
        job.rops.clear()

    # -- stalled-op re-drive (reservation-gated) ---------------------------

    def _drive_stalled(self, job: PGRecoveryJob) -> None:
        rops, job.stalled = job.stalled, []
        b = job.backend
        for rop in rops:
            gen = job.gen
            prev = rop.on_complete

            def chained(rec, _prev=prev, _job=job, _gen=gen):
                if _prev:
                    _prev(rec)
                self._stalled_op_done(_job, _gen)
            rop.on_complete = chained
            job.open_ops += 1
            try:
                b.continue_recovery_op(rop)
            except IOError:
                # still too few survivors: back to the parked list,
                # reservation budget released for this op
                rop.on_complete = prev
                job.open_ops -= 1
                b._stalled_recoveries.append(rop)

    def _stalled_op_done(self, job: PGRecoveryJob, gen: int) -> None:
        if job.gen != gen or job.cancelled:
            return
        job.open_ops = max(0, job.open_ops - 1)
        self._maybe_complete(job)

    # -- wave pacing (the driver's engine) ---------------------------------

    def _queue_wave(self, job: PGRecoveryJob, rop) -> None:
        """The next wave rides the primary daemon's dmClock queue in the
        background_recovery class: client ops win under load."""
        gen = job.gen
        job.daemon.queue_background(
            job.pgid, lambda: self._run_wave(job, rop, gen),
            op_class=BG_RECOVERY)

    def _run_wave(self, job: PGRecoveryJob, rop, gen: int) -> None:
        if job.gen != gen or job.cancelled or not rop.deferred:
            return
        daemon, b = job.daemon, job.backend
        now = daemon._now()
        if job.not_before > now:
            # 'sleeping' in the cooperative model is consuming virtual
            # time: the byte-budget debt + osd_recovery_sleep
            daemon.advance_clock(job.not_before - now)
        n = max(1, int(self._conf("osd_recovery_max_active")))
        items = rop.deferred[:n]
        del rop.deferred[:n]
        est = 0
        for oid, op in items:
            if op != OP_DELETE:
                try:
                    est += b.object_size(oid)
                except Exception:
                    pass
        wait = self._bucket(daemon.whoami).consume(est, daemon._now())
        job.not_before = daemon._now() + wait + \
            float(self._conf("osd_recovery_sleep"))
        self.perf.inc("waves")
        self.perf.inc("wave_objects", len(items))
        # phase="dispatch": the wave span's SELF time is host-side wave
        # orchestration (the sub-reads and fused decode under it carry
        # their own wire/device phases) — explicit so the critical-path
        # registry guard sees a declaration at the call site too
        with trace_span("recovery.wave", owner="recovery",
                        phase="dispatch",
                        pg=repr(job.pgid), objects=len(items)):
            b.repair_wave(rop, items,
                          on_done=lambda: self._wave_done(job, rop, gen))

    def _wave_done(self, job: PGRecoveryJob, rop, gen: int) -> None:
        if job.gen != gen or job.cancelled:
            return
        if rop.deferred:
            self._queue_wave(job, rop)
        # else: the repair's own completion path (catch-up delta +
        # _finish_shard_repair) fires on_complete -> _on_repair_done

    # -- observability -----------------------------------------------------

    def _update_gauges(self) -> None:
        queued, active = self.job_counts()
        self.perf.set("jobs_queued", queued)
        self.perf.set("jobs_active", active)

    def job_counts(self) -> tuple[int, int]:
        """(queued, active) — the PG_RECOVERY_STALLED check's input."""
        return (sum(1 for j in self.jobs.values()
                    if j.state is JobState.QUEUED),
                sum(1 for j in self.jobs.values()
                    if j.state is JobState.RUNNING))

    def reserver_gauges(self) -> list[tuple[str, int, int, int]]:
        """(kind, osd, queue_depth, in_flight) rows — the prometheus
        ``ceph_tpu_recovery_reserver_*`` surface."""
        rows = []
        for kind, table in (("local", self._local),
                            ("remote", self._remote)):
            for osd, r in sorted(table.items()):
                rows.append((kind, osd, r.queue_depth(), r.in_flight()))
        return rows

    def summary(self) -> dict:
        """The ``ceph -s`` recovery block: queued/active PG jobs +
        reservation occupancy."""
        queued, active = self.job_counts()
        res = {"queued": 0, "granted": 0}
        for _kind, _osd, depth, granted in self.reserver_gauges():
            res["queued"] += depth
            res["granted"] += granted
        return {"queued_pgs": queued, "active_pgs": active,
                "reservations": res}

    def dump(self) -> dict:
        return {
            "jobs": {k: {"state": j.state.value, "priority": j.priority,
                         "targets": list(j.targets),
                         "batch": list(j.batch),
                         "remote_held": sorted(j.remote_held),
                         "stalled": len(j.stalled),
                         "open_ops": j.open_ops}
                     for k, j in sorted(self.jobs.items())},
            "local": {o: r.dump() for o, r in sorted(self._local.items())},
            "remote": {o: r.dump()
                       for o, r in sorted(self._remote.items())},
        }
