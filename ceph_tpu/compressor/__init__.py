"""Compression plugin registry (the EC registry's sibling pattern).

Mirror of the reference's compressor layer (reference:
src/compressor/Compressor.h — abstract ``compress/decompress`` :91-95,
``create(cct, type)`` factory :97-98, algorithm name/type mapping :76-77;
plugins under src/compressor/{zlib,snappy,zstd,lz4} loaded through the same
dlopen registry pattern as erasure-code plugins).  Algorithms available in
this environment: zlib (stdlib), zstd (zstandard), lzma/bz2 (stdlib extras);
snappy and lz4 are registered as unavailable and fail factory() with the
same error shape as an unloadable plugin.
"""
from __future__ import annotations

import abc
import bz2 as _bz2
import lzma as _lzma
import threading
import zlib as _zlib


class Compressor(abc.ABC):
    """(Compressor.h:33-95)."""

    name: str = ""

    @abc.abstractmethod
    def compress(self, data: bytes) -> bytes: ...

    @abc.abstractmethod
    def decompress(self, data: bytes) -> bytes: ...


class ZlibCompressor(Compressor):
    name = "zlib"

    def __init__(self, level: int = 5):
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return _zlib.compress(bytes(data), self.level)

    def decompress(self, data: bytes) -> bytes:
        return _zlib.decompress(bytes(data))


class ZstdCompressor(Compressor):
    name = "zstd"

    def __init__(self, level: int = 1):
        import zstandard
        self._c = zstandard.ZstdCompressor(level=level)
        self._d = zstandard.ZstdDecompressor()

    def compress(self, data: bytes) -> bytes:
        return self._c.compress(bytes(data))

    def decompress(self, data: bytes) -> bytes:
        return self._d.decompress(bytes(data))


class LzmaCompressor(Compressor):
    name = "lzma"

    def compress(self, data: bytes) -> bytes:
        return _lzma.compress(bytes(data))

    def decompress(self, data: bytes) -> bytes:
        return _lzma.decompress(bytes(data))


class Bz2Compressor(Compressor):
    name = "bz2"

    def compress(self, data: bytes) -> bytes:
        return _bz2.compress(bytes(data))

    def decompress(self, data: bytes) -> bytes:
        return _bz2.decompress(bytes(data))


class CompressorRegistry:
    """Name -> factory map (the dlopen registry's shape, in-process)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        self._factories = {
            "zlib": ZlibCompressor,
            "lzma": LzmaCompressor,
            "bz2": Bz2Compressor,
        }
        # the reference also ships snappy and lz4; algorithms whose library
        # is missing surface as load failures, never as ImportError
        self._unavailable = {"snappy", "lz4"}
        try:
            import zstandard  # noqa: F401
            self._factories["zstd"] = ZstdCompressor
        except ImportError:
            self._unavailable.add("zstd")

    @classmethod
    def instance(cls) -> "CompressorRegistry":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def supported(self) -> list[str]:
        return sorted(self._factories)

    def create(self, type: str, **kwargs) -> Compressor:
        """Compressor::create (Compressor.h:97)."""
        if type in self._unavailable:
            raise FileNotFoundError(
                f"load dlopen(libceph_{type}): library not available "
                f"(-ENOENT)")
        factory = self._factories.get(type)
        if factory is None:
            raise ValueError(f"unknown compression algorithm {type!r}")
        return factory(**kwargs)

    def register(self, name: str, factory) -> None:
        self._factories[name] = factory
        self._unavailable.discard(name)


def create(type: str, **kwargs) -> Compressor:
    return CompressorRegistry.instance().create(type, **kwargs)


__all__ = ["Compressor", "CompressorRegistry", "create", "ZlibCompressor",
           "ZstdCompressor", "LzmaCompressor", "Bz2Compressor"]
