"""FaultyStore: ObjectStore wrapper injecting EIO / torn writes / slow reads.

The store plane of the fault injector (reference territory: filestore's
EIO injection and ``bluestore_debug_inject_read_err``).  Wraps ANY store
flavour (MemStore / FileStore / BlueStoreLite / a Collection view) and
delegates everything except the two paths it faults:

- :meth:`read` — injected EIO (``errno.EIO``) or an injected slow-read
  stall of ``slow_read_ms``;
- :meth:`queue_transaction` — injected EIO before anything applies, or a
  TORN write: a strict PREFIX of the transaction's ops applies and the
  call still fails, the crash-consistency shape WAL replay and scrub
  must catch.

The wrapper is transparent to identity-insensitive callers (attribute
delegation via ``__getattr__``); ``unwrap(store)`` recovers the inner
store for teardown paths that need the real object.
"""
from __future__ import annotations

import errno
import time


class FaultyStore:
    """Injecting proxy around an ObjectStore."""

    def __init__(self, store, injector, target: str = ""):
        # avoid __getattr__ recursion: set via object.__setattr__ names
        self._store = store
        self._inj = injector
        self._target = target

    # -- faulted paths -----------------------------------------------------

    def read(self, obj, offset: int = 0, length=None):
        f = self._inj.plan.store
        if self._inj.roll("store", "eio_read", f.eio_read_prob,
                          target=self._target or str(obj)):
            e = IOError(f"injected EIO reading {obj}")
            e.errno = errno.EIO
            raise e
        if self._inj.roll("store", "slow_read", f.slow_read_prob,
                          target=self._target or str(obj),
                          ms=f.slow_read_ms):
            time.sleep(f.slow_read_ms / 1000.0)
        return self._store.read(obj, offset, length)

    def queue_transaction(self, t):
        f = self._inj.plan.store
        if self._inj.roll("store", "eio_write", f.eio_write_prob,
                          target=self._target):
            e = IOError("injected EIO on transaction")
            e.errno = errno.EIO
            raise e
        if len(t.ops) > 1 and self._inj.roll(
                "store", "torn_write", f.torn_write_prob,
                target=self._target, ops=len(t.ops)):
            torn = type(t)()
            torn.ops = list(t.ops[:len(t.ops) // 2])
            self._store.queue_transaction(torn)
            e = IOError(f"injected torn write ({len(torn.ops)}/"
                        f"{len(t.ops)} ops applied)")
            e.errno = errno.EIO
            raise e
        return self._store.queue_transaction(t)

    # -- delegation --------------------------------------------------------

    def __getattr__(self, name):
        return getattr(self._store, name)

    def __repr__(self) -> str:
        return f"FaultyStore({self._store!r})"


def unwrap(store):
    """The real store behind any FaultyStore layers."""
    while isinstance(store, FaultyStore):
        store = store._store
    return store
