"""Transport-plane fault policy for the TCP messenger (``net.py``).

The socket half of ``ms inject socket failures``: the server attaches a
:class:`TransportFaultHooks` to every AUTHENTICATED connection (the
cephx handshake is never faulted — a reconnecting client must always be
able to get back in), and the channel/read loops consult it:

- :meth:`on_send` decides per outbound message: deliver, delay-then-
  deliver, TRUNCATE (a partial frame hits the wire, then the connection
  closes — the peer sees a cut-off frame exactly like a mid-frame RST),
  or RESET (abrupt close);
- :meth:`on_recv` decides per inbound request: deliver, BLACKHOLE (the
  request is swallowed and no reply is ever sent — the client's per-RPC
  deadline is what heals this), or RESET.

Decisions come from the shared :class:`~ceph_tpu.failure.injector.
FaultInjector` streams, so a campaign's transport events land in the
same seeded event log as every other plane.
"""
from __future__ import annotations

import time

SEND_OK = "ok"
SEND_TRUNCATE = "truncate"
SEND_RESET = "reset"

RECV_DELIVER = "deliver"
RECV_BLACKHOLE = "blackhole"
RECV_RESET = "reset"


class TransportFaultHooks:
    """Per-server transport fault policy over one injector."""

    def __init__(self, injector, sleep=time.sleep):
        self.inj = injector
        self._sleep = sleep

    def on_send(self, msg_type: str, nbytes: int, target: str) -> str:
        f = self.inj.plan.transport
        if self.inj.roll("transport", "delay", f.delay_prob,
                         target=target, msg=msg_type, ms=f.delay_ms):
            self._sleep(f.delay_ms / 1000.0)
        if self.inj.roll("transport", "truncate", f.truncate_prob,
                         target=target, msg=msg_type, bytes=nbytes):
            return SEND_TRUNCATE
        if self.inj.roll("transport", "reset", f.reset_prob,
                         target=target, msg=msg_type):
            return SEND_RESET
        return SEND_OK

    def on_recv(self, msg_type: str, target: str) -> str:
        f = self.inj.plan.transport
        if self.inj.roll("transport", "blackhole", f.blackhole_prob,
                         target=target, msg=msg_type):
            return RECV_BLACKHOLE
        if self.inj.roll("transport", "recv_reset", f.reset_prob,
                         target=target, msg=msg_type):
            return RECV_RESET
        return RECV_DELIVER
