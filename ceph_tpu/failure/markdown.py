"""Mark-down limiter: flap damping for OSD up/down transitions.

The reference's ``osd_markdown_log`` machinery (src/osd/OSD.cc
``handle_osd_map`` counts recent mark-downs against
``osd_max_markdown_count``/``osd_max_markdown_period`` and refuses to
rejoin): an OSD marked down too many times inside a sliding window is
FLAPPING — repeatedly bouncing between up and down churns peering,
client resends and recovery reservations far harder than staying down
would.  Once damped, boot attempts are refused until an operator clears
the record (``ceph osd clear-markdown`` analog), and the
``OSD_FLAPPING`` health check reports it.

Time is caller-provided (the monitor's virtual ``now``), so damping
timelines are deterministic in tests and the chaos harness.
"""
from __future__ import annotations

from collections import deque


class MarkDownLimiter:
    """Sliding-window mark-down counter + damped set."""

    def __init__(self, count: int = 5, window: float = 600.0):
        self.count = max(1, int(count))
        self.window = float(window)
        # osd -> recent mark-down stamps (bounded: only the newest
        # ``count`` matter for the threshold)
        self._marks: dict[int, deque] = {}
        self._damped: set[int] = set()

    def _prune(self, osd: int, now: float) -> deque:
        q = self._marks.setdefault(osd, deque(maxlen=self.count))
        while q and now - q[0] > self.window:
            q.popleft()
        return q

    def record_down(self, osd: int, now: float) -> bool:
        """One mark-down at ``now``.  Returns True when this mark tripped
        the damping threshold (the caller logs the transition)."""
        q = self._prune(osd, now)
        q.append(now)
        if len(q) >= self.count and osd not in self._damped:
            self._damped.add(osd)
            return True
        return False

    def allow_up(self, osd: int) -> bool:
        """May this OSD be marked up?  False while damped — the flapping
        OSD stays down until :meth:`clear`."""
        return osd not in self._damped

    def clear(self, osd: int) -> bool:
        """Operator clear: forget the history, allow boots again."""
        self._marks.pop(osd, None)
        was = osd in self._damped
        self._damped.discard(osd)
        return was

    @property
    def damped(self) -> set[int]:
        return set(self._damped)

    def dump(self) -> dict[int, dict]:
        return {osd: {"marks": len(q), "damped": osd in self._damped}
                for osd, q in sorted(self._marks.items()) if q
                or osd in self._damped}
