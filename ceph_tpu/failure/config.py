"""Unified fault-plane configuration: one schema, one seed.

The fault surface grew up piecemeal — the in-process bus had its own
``FaultConfig`` (seed + reorder/dup/drop), the TCP path had nothing, and
the device pipeline's failures were whatever a test monkeypatched in.
This module is the single schema the whole injection surface reads
(reference: the knob set ``qa/tasks/ceph_manager.py``'s Thrasher drives —
``ms inject socket failures``, ``ms inject delay``, filestore EIO
injection, ``bluestore_debug_inject_read_err``): a :class:`FaultPlan`
carries one campaign seed and one sub-config per plane:

- **bus** (:class:`FaultConfig`, unchanged shape — the in-process
  messenger): cross-sender reorder, duplicate delivery, silent drops;
- **transport** (:class:`TransportFaults`, the TCP messenger in
  ``net.py``): connection resets, black-holed requests, truncated
  frames, send/recv delays;
- **store** (:class:`StoreFaults`, any ObjectStore behind
  :class:`~ceph_tpu.failure.store.FaultyStore`): EIO on read/write,
  torn writes, slow-read latency;
- **device** (:class:`DeviceFaults`, the codec pipeline): injected
  dispatch/completion failures and simulated OOM.

Everything here is a plain dataclass of probabilities — stdlib only, no
runtime state.  The runtime half (seeded decision streams, the injected-
event log, clusterlog/perf stamping) lives in
:class:`~ceph_tpu.failure.injector.FaultInjector`.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace


@dataclass
class FaultConfig:
    """Message-level fault injection for the in-process bus (the
    messenger half of the Thrasher: the reference's ``ms inject socket
    failures`` / delivery randomization, qa/tasks/ceph_manager.py).
    Faithful to messenger semantics:

    - per-SENDER ordering is always preserved (TCP/ProtocolV2 guarantees
      in-order delivery per connection; in-process FIFO is load-bearing
      for rollback ordering too) — ``reorder`` randomizes scheduling
      ACROSS senders at each destination, which also models arbitrary
      cross-connection delay;
    - ``dup_prob`` redelivers a message immediately after the first
      delivery (connection reset + resend: the reference dedups resent
      ops by reqid; our shards dedup sub-writes by at_version);
    - ``drop_prob`` silently discards (a reset with no resend — only for
      tests that exercise stall handling; the TCP path now RESENDS with
      reqid dedup, so thrash campaigns should leave this 0).

    Historically defined in ``backend/messages.py``; it now lives here as
    the bus plane of the unified :class:`FaultPlan` (``messages.py``
    re-exports it, and ``MessageBus.inject_faults`` accepts either).
    """
    seed: int = 0
    reorder: bool = False
    dup_prob: float = 0.0
    drop_prob: float = 0.0


@dataclass
class TransportFaults:
    """TCP-plane faults applied by the server's channel hooks
    (``ms inject socket failures`` territory).  All probabilities are
    per-message decisions on the post-auth path — the cephx handshake is
    never faulted, so a reconnecting client always gets back in."""
    reset_prob: float = 0.0        # abrupt connection close mid-stream
    blackhole_prob: float = 0.0    # request swallowed: no reply ever
    truncate_prob: float = 0.0     # partial frame on the wire, then reset
    delay_prob: float = 0.0        # per-message send stall ...
    delay_ms: float = 0.0          # ... of this many milliseconds


@dataclass
class StoreFaults:
    """ObjectStore-plane faults (filestore EIO / bluestore debug read
    error injection territory)."""
    eio_read_prob: float = 0.0     # read raises EIO
    eio_write_prob: float = 0.0    # queue_transaction raises EIO, no apply
    torn_write_prob: float = 0.0   # a PREFIX of the transaction applies
    slow_read_prob: float = 0.0    # read stalls ...
    slow_read_ms: float = 0.0      # ... this long


@dataclass
class DeviceFaults:
    """Device-plane faults injected into the codec pipeline: the r04
    "errored" / r05 "silent CPU fallback" bench history as reproducible
    inputs instead of production surprises."""
    dispatch_fail_prob: float = 0.0     # async launch raises
    completion_fail_prob: float = 0.0   # block_until_ready raises
    oom_prob: float = 0.0               # RESOURCE_EXHAUSTED at dispatch


@dataclass
class FaultPlan:
    """One campaign: one seed, every plane.  Hand it to
    ``MiniCluster.inject_faults`` (which builds the
    :class:`~ceph_tpu.failure.injector.FaultInjector` and fans the plan
    out to bus/store/device) and ``ClusterServer.inject_faults`` (the
    transport plane)."""
    seed: int = 0
    bus: FaultConfig = field(default_factory=FaultConfig)
    transport: TransportFaults = field(default_factory=TransportFaults)
    store: StoreFaults = field(default_factory=StoreFaults)
    device: DeviceFaults = field(default_factory=DeviceFaults)

    def bus_config(self) -> FaultConfig:
        """The bus plane with the CAMPAIGN seed (one seed drives every
        plane; a bus sub-config carrying its own nonzero seed keeps it —
        the escape hatch for reproducing a legacy per-bus test)."""
        if self.bus.seed:
            return self.bus
        return replace(self.bus, seed=self.seed)
