"""FaultInjector: deterministic, seedable fault decisions + event log.

The runtime half of the :mod:`~ceph_tpu.failure.config` schema.  Every
plane (transport/store/device/bus) consults ONE injector, and every
injected event is:

- appended to a bounded in-memory event log (``events``), whose
  order-sensitive digest (``event_digest``) is the reproducibility
  receipt — two campaigns with the same seed and the same workload must
  produce the same digest;
- counted in a ``faults.<name>`` perf collection (per-plane counters),
  so injected failure shows up next to every other perf surface;
- stamped into the clusterlog (DBG channel ``faults``) when one is
  wired, so ``ceph -w`` shows the chaos interleaved with its effects.

Determinism: one ``random.Random`` stream per (plane, kind), seeded from
``f"{seed}:{plane}:{kind}"`` (str seeding is stable across processes).
Decision streams are independent per kind, so adding a new fault kind to
a campaign never perturbs the decisions of existing kinds — the property
that keeps soak repros stable as the fault surface grows.
"""
from __future__ import annotations

import hashlib
import random
import threading

from .config import FaultPlan

MAX_EVENTS = 100_000      # a soak that injects more has lost the plot

PLANES = ("transport", "store", "device", "bus")


class InjectedFault(RuntimeError):
    """An injected failure (device dispatch/completion, store EIO...).
    Distinct type so self-healing tests can tell injected failures from
    real bugs in the machinery under test."""


class InjectedOOM(InjectedFault):
    """Simulated device OOM (the XLA RESOURCE_EXHAUSTED shape)."""


class FaultInjector:
    """Seeded decision streams over a :class:`FaultPlan` + the event log."""

    def __init__(self, plan: FaultPlan | None = None, clusterlog=None,
                 cct=None, name: str = "faults"):
        self.plan = plan if plan is not None else FaultPlan()
        self.clusterlog = clusterlog
        self.name = name
        self._lock = threading.Lock()
        self._rngs: dict[tuple[str, str], random.Random] = {}
        self.events: list[dict] = []
        self._seq = 0
        self.perf = None
        if cct is not None:
            from ..common.perf_counters import PerfCountersBuilder
            b = PerfCountersBuilder(f"faults.{name}")
            b.add_u64_counter("injected", "fault events injected across "
                                          "all planes")
            for plane in PLANES:
                b.add_u64_counter(f"{plane}_events",
                                  f"fault events injected on the {plane} "
                                  f"plane")
            self.perf = b.create_perf_counters()
            cct.perf.add(self.perf)
            self._cct = cct

    def close(self) -> None:
        """Unhook the perf collection (discarded injectors must not
        leave frozen counters behind)."""
        if self.perf is not None:
            self._cct.perf.remove(self.perf.name)
            self.perf = None

    # -- decisions ---------------------------------------------------------

    def _rng(self, plane: str, kind: str) -> random.Random:
        key = (plane, kind)
        rng = self._rngs.get(key)
        if rng is None:
            rng = self._rngs[key] = random.Random(
                f"{self.plan.seed}:{plane}:{kind}")
        return rng

    def roll(self, plane: str, kind: str, prob: float,
             target=None, **detail) -> bool:
        """One seeded decision: True (and the event is recorded) with
        probability ``prob``.  A zero/absent probability consumes NOTHING
        from the stream, so disabled kinds never shift enabled ones."""
        if prob <= 0.0:
            return False
        with self._lock:
            hit = self._rng(plane, kind).random() < prob
        if hit:
            self.record(plane, kind, target, **detail)
        return hit

    # -- the event log -----------------------------------------------------

    def record(self, plane: str, kind: str, target=None, **detail) -> dict:
        """Stamp one injected event (log + perf + clusterlog).  Called by
        :meth:`roll` on a hit, and directly by planes that decide with
        their own RNG (the bus's legacy FaultConfig stream)."""
        with self._lock:
            self._seq += 1
            event = {"seq": self._seq, "plane": plane, "kind": kind,
                     "target": "" if target is None else str(target)}
            if detail:
                event["detail"] = detail
            if len(self.events) < MAX_EVENTS:
                self.events.append(event)
        if self.perf is not None:
            self.perf.inc("injected")
            if plane in PLANES:
                self.perf.inc(f"{plane}_events")
        if self.clusterlog is not None:
            self.clusterlog.debug(
                f"fault injected: {plane}/{kind}"
                + (f" @ {event['target']}" if event["target"] else ""),
                channel="faults")
        return event

    # -- reproducibility ----------------------------------------------------

    def event_digest(self) -> str:
        """Order-sensitive digest over (plane, kind, target) — the
        determinism receipt.  Wall-clock detail is deliberately excluded:
        two same-seed runs differ in timing, never in decisions."""
        h = hashlib.sha256()
        with self._lock:
            for e in self.events:
                h.update(f"{e['plane']}/{e['kind']}/{e['target']}\n"
                         .encode())
        return h.hexdigest()

    def summary(self) -> dict:
        """{plane: {kind: count}} + total, for campaign reports."""
        out: dict = {}
        with self._lock:
            for e in self.events:
                out.setdefault(e["plane"], {}).setdefault(e["kind"], 0)
                out[e["plane"]][e["kind"]] += 1
            total = len(self.events)
        return {"total": total, "planes": out}
