"""ceph_tpu.failure: seeded fault injection + the self-healing machinery.

Two halves (ISSUE 9):

- **Injection** — one :class:`FaultPlan` (one schema, one seed) spanning
  the in-process bus, the TCP transport, the object stores and the
  device pipeline, executed by a :class:`FaultInjector` whose every
  event is logged, perf-counted, clusterlog-stamped, and digested for
  same-seed reproducibility.

- **Self-healing** — the machinery those faults exercise:
  :class:`ExponentialBackoff` (full-jitter, bounded) behind the TCP
  client's reconnect/resend, :class:`CircuitBreaker` behind the codec
  pipeline's host-fallback (``DEVICE_DEGRADED``), and
  :class:`MarkDownLimiter` behind the monitor's flap damping
  (``OSD_FLAPPING``).

``tools/chaos_run.py`` drives both halves as one seeded campaign against
a real TCP MiniCluster.
"""
from .backoff import ExponentialBackoff, RetriesExhausted
from .breaker import (CLOSED, HALF_OPEN, OPEN, CircuitBreaker,
                      live_breakers, state_rank)
from .config import (DeviceFaults, FaultConfig, FaultPlan, StoreFaults,
                     TransportFaults)
from .injector import FaultInjector, InjectedFault, InjectedOOM
from .markdown import MarkDownLimiter
from .store import FaultyStore, unwrap
from .transport import TransportFaultHooks

__all__ = [
    "CLOSED", "HALF_OPEN", "OPEN",
    "CircuitBreaker", "DeviceFaults", "ExponentialBackoff", "FaultConfig",
    "FaultInjector", "FaultPlan", "FaultyStore", "InjectedFault",
    "InjectedOOM", "MarkDownLimiter", "RetriesExhausted", "StoreFaults",
    "TransportFaultHooks", "TransportFaults", "live_breakers",
    "state_rank", "unwrap",
]
