"""Circuit breaker: N consecutive failures -> open -> half-open probes.

The device-path guard the codec pipeline wires in: when the device side
fails ``threshold`` times IN A ROW, the breaker opens and fallback-capable
submitters stop dialing the device (sync host-codec fallback instead of
hammering a wedged backend — the r04 "errored" bench mode as a handled
state).  After ``cooldown`` seconds the next fallback-capable submit is
let through as a HALF-OPEN probe: success re-closes, failure re-opens for
another cooldown.  Any device success (probe or not) re-closes and zeroes
the consecutive count.

Breakers self-register in a process-wide weak set (the
``live_daemons``/``live_engines`` pattern) so the ``DEVICE_DEGRADED``
health check (``mgr/health.py``) can report every non-closed breaker
without the cluster layer threading references around.
"""
from __future__ import annotations

import threading
import time
import weakref

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_RANK = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

_BREAKERS: "weakref.WeakSet[CircuitBreaker]" = weakref.WeakSet()


def live_breakers() -> list["CircuitBreaker"]:
    return sorted(_BREAKERS, key=lambda b: b.name)


def state_rank(state: str) -> int:
    """Numeric severity for gauges: closed=0, half_open=1, open=2."""
    return _STATE_RANK[state]


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probing.

    ``threshold`` consecutive failures open it; ``cooldown`` seconds
    later :meth:`allow` admits ONE probe (half-open); the probe's
    outcome closes or re-opens.  ``clock`` is injectable so tests drive
    the cooldown deterministically.  ``on_transition(breaker, old, new)``
    fires outside the lock on every state change.
    """

    def __init__(self, name: str, threshold: int = 3,
                 cooldown: float = 5.0, clock=time.monotonic,
                 on_transition=None):
        self.name = name
        self.threshold = max(1, int(threshold))
        self.cooldown = float(cooldown)
        self._clock = clock
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self.opens = 0          # cumulative open transitions
        self.probes = 0         # half-open probes admitted
        self.fallbacks = 0      # host-fallback batches served while open
        _BREAKERS.add(self)

    # -- state -------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._consecutive

    def _transition(self, new: str) -> None:
        # caller holds the lock; returns with it held
        old, self._state = self._state, new
        if old != new and self.on_transition is not None:
            cb, args = self.on_transition, (self, old, new)
            self._lock.release()
            try:
                cb(*args)
            finally:
                self._lock.acquire()

    # -- the gate ----------------------------------------------------------

    def allow(self) -> bool:
        """May this submission use the device path?  CLOSED: yes.
        OPEN: no — unless the cooldown elapsed, in which case this call
        CLAIMS the half-open probe slot (True) and subsequent calls get
        False until the probe's outcome lands."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN and \
                    self._clock() - self._opened_at >= self.cooldown:
                self._transition(HALF_OPEN)
                self.probes += 1
                return True
            return False

    def note_fallback(self) -> None:
        with self._lock:
            self.fallbacks += 1

    # -- outcomes ----------------------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self._state == HALF_OPEN or (
                    self._state == CLOSED and
                    self._consecutive >= self.threshold):
                self._opened_at = self._clock()
                self.opens += 1
                self._transition(OPEN)
            elif self._state == OPEN:
                # a no-fallback caller dialed the device anyway and lost:
                # push the cooldown window out from this latest evidence
                self._opened_at = self._clock()

    # -- lifecycle / observability ----------------------------------------

    def close(self) -> None:
        """Drop out of the live registry (pipeline teardown): a discarded
        breaker must not keep raising DEVICE_DEGRADED."""
        _BREAKERS.discard(self)

    def reopen(self) -> None:
        """Rejoin the live registry (pipeline reopen after an engine
        restart) — a living breaker must be visible to DEVICE_DEGRADED."""
        _BREAKERS.add(self)

    def dump(self) -> dict:
        with self._lock:
            return {"name": self.name, "state": self._state,
                    "consecutive_failures": self._consecutive,
                    "threshold": self.threshold,
                    "cooldown": self.cooldown, "opens": self.opens,
                    "probes": self.probes, "fallbacks": self.fallbacks}
