"""Exponential backoff with full jitter — the reconnect/retry pacing.

The AWS-architecture "full jitter" schedule (also what the reference's
msgr reconnect ramp approximates): attempt ``n`` sleeps a uniform random
duration in ``[0, min(cap, base * 2**n)]``.  Full jitter beats equal or
decorrelated jitter for thundering-herd spread while keeping the bound
trivial to verify — which is exactly what ``tests/test_chaos.py``'s
jitter-bounds test pins.

Every loop built on this class is bounded BY CONSTRUCTION: ``delays()``
yields at most ``max_attempts`` values and respects an optional wall
deadline (``tests/test_bounded_retry.py`` guards that no retry loop in
``net.py``/``client/``/``failure/`` escapes such a bound).
"""
from __future__ import annotations

import random
import time


class RetriesExhausted(ConnectionError):
    """The bounded retry budget (attempts or deadline) ran out."""


class ExponentialBackoff:
    """Bounded full-jitter backoff.

    ``base``/``cap`` are seconds; ``max_attempts`` bounds the schedule;
    ``deadline`` (monotonic timestamp) additionally cuts it short.
    ``rng``/``clock``/``sleep`` are injectable for deterministic tests.
    """

    def __init__(self, base: float = 0.05, cap: float = 2.0,
                 max_attempts: int = 8, deadline: float | None = None,
                 rng: random.Random | None = None, clock=time.monotonic,
                 sleep=time.sleep):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got "
                             f"{max_attempts}")
        self.base = float(base)
        self.cap = float(cap)
        self.max_attempts = int(max_attempts)
        self.deadline = deadline
        self._rng = rng if rng is not None else random.Random()
        self._clock = clock
        self._sleep = sleep

    def delay(self, attempt: int) -> float:
        """The full-jitter draw for attempt ``attempt`` (0-based):
        uniform in [0, min(cap, base * 2**attempt)]."""
        ceiling = min(self.cap, self.base * (2.0 ** attempt))
        return self._rng.uniform(0.0, ceiling)

    def delays(self):
        """Yield (attempt index, slept seconds) up to the bound; sleeps
        BETWEEN attempts (no sleep before the first).  Stops early when
        the deadline would be crossed."""
        for attempt in range(self.max_attempts):
            if attempt:
                d = self.delay(attempt - 1)
                if self.deadline is not None:
                    remaining = self.deadline - self._clock()
                    if remaining <= 0:
                        return
                    d = min(d, remaining)
                self._sleep(d)
            else:
                d = 0.0
            if self.deadline is not None and \
                    self._clock() >= self.deadline and attempt:
                return
            yield attempt, d

    def run(self, fn, retry_on=(ConnectionError, OSError, TimeoutError)):
        """Call ``fn()`` under the schedule; returns its value.  Raises
        :class:`RetriesExhausted` (chaining the last failure) when the
        attempt/deadline budget runs out."""
        last: BaseException | None = None
        for attempt, _slept in self.delays():
            try:
                return fn()
            except retry_on as e:
                last = e
        raise RetriesExhausted(
            f"gave up after {self.max_attempts} attempts") from last
