"""`rados` CLI over a durable cluster directory.

Analog of the reference's `rados` tool (reference: src/tools/rados/
rados.cc — put/get/ls/rm/stat/mksnap/rmsnap/lssnap/rollback/setxattr/
getxattr/listxattr verbs): each invocation reopens the FileStore-backed
MiniCluster under ``--data-dir`` (boot peering + log replay included),
performs one operation through the librados facade, and checkpoints on
exit — so consecutive shell commands observe each other's writes, the
way the real tool's commands do through the cluster.

    python -m ceph_tpu.tools.rados_cli --data-dir D mkpool data k=4 m=2
    python -m ceph_tpu.tools.rados_cli --data-dir D put data obj ./file
    python -m ceph_tpu.tools.rados_cli --data-dir D ls data
"""
from __future__ import annotations

import argparse
import sys



def _parse_profile(parts):
    """(kv dict, replicated?) from 'k=4 m=2' / 'replicated size=3'."""
    kv = dict(p.split("=", 1) for p in parts if "=" in p)
    return kv, "replicated" in parts


def _read_input(path: str) -> bytes:
    return sys.stdin.buffer.read() if path == "-" else \
        open(path, "rb").read()


def _write_output(path: str, data: bytes) -> None:
    if path == "-":
        sys.stdout.buffer.write(data)
    else:
        open(path, "wb").write(data)


def _fmt_df(st: dict) -> str:
    return (f"{st['pgmap']['num_pools']} pools, "
            f"{st['pgmap']['num_pgs']} pgs, "
            f"{st['osdmap']['num_up_osds']}/"
            f"{st['osdmap']['num_osds']} osds up")


def main(argv=None) -> int:
    from ..utils.platform import honour_jax_platforms_env
    honour_jax_platforms_env()   # axon sitecustomize override
    ap = argparse.ArgumentParser(prog="rados")
    ap.add_argument("--store-backend", default="file",
                    choices=["file", "bluestore"],
                    help="durable store flavour for a NEW cluster "
                         "(bluestore: extent allocator + checksums at "
                         "rest + compression); existing clusters reopen "
                         "with their recorded backend")
    ap.add_argument("--data-dir",
                    help="durable cluster directory (local mode)")
    ap.add_argument("--connect", metavar="HOST:PORT",
                    help="talk to a LIVE cluster process over TCP "
                         "(cephx-authenticated, HMAC-secured v2 frames) "
                         "instead of reopening --data-dir")
    ap.add_argument("--keyring",
                    help="client.admin keyring path (default: "
                         "<data-dir>/client.admin.keyring)")
    ap.add_argument("--n-osds", type=int, default=9,
                    help="cluster size when creating a new directory")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("mkpool")
    p.add_argument("pool")
    p.add_argument("profile", nargs="*",
                   help="k=4 m=2 ... (EC); 'replicated size=3' for a "
                        "replicated pool")
    for verb in ("put", "get"):
        p = sub.add_parser(verb)
        p.add_argument("pool")
        p.add_argument("oid")
        p.add_argument("file", help="- for stdin/stdout")
    for verb in ("rm", "stat", "listxattr", "lssnap"):
        p = sub.add_parser(verb)
        p.add_argument("pool")
        if verb in ("rm", "stat", "listxattr"):
            p.add_argument("oid")
    p = sub.add_parser("ls")
    p.add_argument("pool")
    p = sub.add_parser("setxattr")
    p.add_argument("pool"), p.add_argument("oid")
    p.add_argument("name"), p.add_argument("value")
    p = sub.add_parser("getxattr")
    p.add_argument("pool"), p.add_argument("oid"), p.add_argument("name")
    for verb in ("mksnap", "rmsnap"):
        p = sub.add_parser(verb)
        p.add_argument("pool"), p.add_argument("snap")
    p = sub.add_parser("rollback")
    p.add_argument("pool"), p.add_argument("oid"), p.add_argument("snap")
    p = sub.add_parser("df")
    p = sub.add_parser("serve")
    p.add_argument("--port", type=int, default=0,
                   help="listen port (0 = ephemeral, printed on start)")

    args = ap.parse_args(argv)
    if args.connect:
        if args.cmd == "serve":
            ap.error("serve runs the cluster locally; it cannot combine "
                     "with --connect")
        return _run_remote(args)
    if args.data_dir is None:
        ap.error("--data-dir is required (or --connect for remote mode)")

    import os
    from ..client.rados import ObjectNotFound, Rados
    from ..cluster import MiniCluster
    fresh = not os.path.exists(os.path.join(args.data_dir,
                                            "cluster_meta.pkl"))
    if fresh:
        c = MiniCluster(n_osds=args.n_osds, data_dir=args.data_dir,
                        store_backend=args.store_backend)
    else:
        c = MiniCluster.load(args.data_dir)
    try:
        if args.cmd == "serve":
            from ..net import ClusterServer
            server = ClusterServer(c, port=args.port)
            print(f"serving on 127.0.0.1:{server.port}", flush=True)
            try:
                server.serve_forever()
            except KeyboardInterrupt:
                pass
            server.stop()
            return 0

        if args.cmd == "mkpool":
            kv, replicated = _parse_profile(args.profile)
            if replicated:
                c.create_replicated_pool(args.pool,
                                         size=int(kv.get("size", 3)))
            else:
                kv.setdefault("device", "auto")
                c.create_ec_pool(args.pool, kv)
            print(f"pool {args.pool} created")
            return 0

        rados = Rados(c)
        if args.cmd == "df":
            print(_fmt_df(rados.cluster_stat()))
            return 0
        io = rados.open_ioctx(args.pool)
        if args.cmd == "put":
            io.write_full(args.oid, _read_input(args.file))
        elif args.cmd == "get":
            # object_info carries the exact size
            _write_output(args.file, io.read(args.oid))
        elif args.cmd == "ls":
            for oid in io.list_objects():
                print(oid)
        elif args.cmd == "rm":
            io.remove_object(args.oid)
        elif args.cmd == "stat":
            size, mtime = io.stat(args.oid)
            print(f"{args.pool}/{args.oid} size {size} mtime {mtime:.0f}")
        elif args.cmd == "setxattr":
            io.set_xattr(args.oid, args.name, args.value.encode())
        elif args.cmd == "getxattr":
            v = io.get_xattr(args.oid, args.name)
            print(v.decode() if isinstance(v, bytes) else v)
        elif args.cmd == "listxattr":
            for name in sorted(io.get_xattrs(args.oid)):
                print(name)
        elif args.cmd == "mksnap":
            sid = io.snap_create(args.snap)
            print(f"created pool {args.pool} snap {args.snap} ({sid})")
        elif args.cmd == "rmsnap":
            io.snap_remove(args.snap)
        elif args.cmd == "lssnap":
            for sid, name in sorted(io.snap_list().items()):
                print(f"{sid}\t{name}")
        elif args.cmd == "rollback":
            io.snap_rollback(args.oid, args.snap)
            print(f"rolled back {args.pool}/{args.oid} to {args.snap}")
        return 0
    except (IOError, KeyError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    finally:
        c.shutdown()


def _run_remote(args) -> int:
    """Remote mode: every verb through TcpRados over the live socket."""
    from ..net import cli_connect
    try:
        r = cli_connect(args.connect, args.keyring, args.data_dir)
    except Exception as e:        # AuthError/Unpickling/IO/Value: all
        print(f"error: {e}", file=sys.stderr)   # operator-facing
        return 2
    try:
        if args.cmd == "mkpool":
            kv, replicated = _parse_profile(args.profile)
            if replicated:
                r.mkpool(args.pool, replicated=True,
                         size=int(kv.get("size", 3)))
            else:
                kv.setdefault("device", "auto")
                r.mkpool(args.pool, profile=kv)
            print(f"pool {args.pool} created")
        elif args.cmd == "put":
            r.put(args.pool, args.oid, _read_input(args.file))
        elif args.cmd == "get":
            _write_output(args.file, r.get(args.pool, args.oid))
        elif args.cmd == "ls":
            for oid in r.ls(args.pool):
                print(oid)
        elif args.cmd == "rm":
            r.remove(args.pool, args.oid)
        elif args.cmd == "stat":
            size, mtime = r.stat(args.pool, args.oid)
            print(f"{args.pool}/{args.oid} size {size} mtime {mtime:.0f}")
        elif args.cmd == "setxattr":
            r.setxattr(args.pool, args.oid, args.name,
                       args.value.encode())
        elif args.cmd == "getxattr":
            v = r.getxattr(args.pool, args.oid, args.name)
            print(v.decode() if isinstance(v, bytes) else v)
        elif args.cmd == "df":
            print(_fmt_df(r.status()))
        else:
            print(f"error: {args.cmd!r} not supported over --connect",
                  file=sys.stderr)
            return 2
        return 0
    except (IOError, KeyError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    finally:
        r.close()


if __name__ == "__main__":
    sys.exit(main())
