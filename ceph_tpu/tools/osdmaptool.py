"""osdmaptool equivalent: bulk PG mapping tests and histograms.

Mirror of the reference tool's --test-map-pgs family (reference:
src/tools/osdmaptool.cc:38-40 usage, :491-610 the mapping loop, histogram
table and stddev summary) driven by the vmapped bulk mapper instead of a
per-PG loop.  Output format matches the reference line-for-line so existing
tooling can parse it:

    pool 1 pg_num 64
    #osd   count  first  primary  c wt   wt
    osd.0  12     4      4        1.0    1.0
    ...
     in 9
     avg 21 stddev 2.1 (0.1x) (expected 4.3 0.2x))
     min osd.3 18
     max osd.7 25

CLI:  python -m ceph_tpu.tools.osdmaptool MAP.json --test-map-pgs
      [--pool N] [--test-map-pgs-dump] [--test-map-pgs-dump-all]
"""
from __future__ import annotations

import argparse
import json
import math
import sys

from ..crush.map import CRUSH_ITEM_NONE
from ..osdmap import OSDMap, PG
from ..osdmap.bulk import BulkPGMapper


def device_crush_weights(crush) -> dict[int, int]:
    """Leaf item -> 16.16 weight (delegates to CrushMap.device_weights)."""
    return crush.device_weights()


def test_map_pgs(m: OSDMap, pool: int = -1, dump: bool = False,
                 dump_all: bool = False, out=None) -> dict:
    """The --test-map-pgs[-dump[-all]] loop (osdmaptool.cc:491-610).
    Returns the stats dict; prints the reference-format report to ``out``."""
    w = out.write if out is not None else (lambda s: None)
    n = m.max_osd
    count = [0] * n
    first_count = [0] * n
    primary_count = [0] * n
    size_hist: dict[int, int] = {}
    mapper = BulkPGMapper(m)

    for pid in sorted(m.pools):
        if pool != -1 and pid != pool:
            continue
        p = m.pools[pid]
        w(f"pool {pid} pg_num {p.pg_num}\n")
        pm = mapper.map_pool(pid)
        for ps in range(p.pg_num):
            acting = [int(o) for o in pm.acting[ps] if o != CRUSH_ITEM_NONE]
            primary = int(pm.acting_primary[ps])
            size_hist[len(acting)] = size_hist.get(len(acting), 0) + 1
            if dump:
                w(f"{pid}.{ps:x}\t{acting}\t{primary}\n")
            elif dump_all:
                raw, rawp = m.pg_to_raw_osds(PG(pid, ps))
                up = [int(o) for o in pm.up[ps] if o != CRUSH_ITEM_NONE]
                upp = int(pm.up_primary[ps])
                w(f"{pid}.{ps:x} raw ({raw}, p{rawp}) up ({up}, p{upp}) "
                  f"acting ({acting}, p{primary})\n")
            for o in acting:
                count[o] += 1
            if acting:
                first_count[acting[0]] += 1
            if primary >= 0:
                primary_count[primary] += 1

    cw = device_crush_weights(m.crush)
    total = 0
    n_in = 0
    min_osd = max_osd = -1
    w("#osd\tcount\tfirst\tprimary\tc wt\twt\n")
    for i in range(n):
        if not m.is_in(i) or cw.get(i, 0) <= 0:
            continue
        n_in += 1
        w(f"osd.{i}\t{count[i]}\t{first_count[i]}\t{primary_count[i]}"
          f"\t{cw.get(i, 0) / 0x10000:g}\t{m.osd_weight[i] / 0x10000:g}\n")
        total += count[i]
        if count[i] and (min_osd < 0 or count[i] < count[min_osd]):
            min_osd = i
        if count[i] and (max_osd < 0 or count[i] > count[max_osd]):
            max_osd = i
    avg = total // n_in if n_in else 0
    dev = 0.0
    for i in range(n):
        if not m.is_in(i) or cw.get(i, 0) <= 0:
            continue
        dev += (avg - count[i]) ** 2
    dev = math.sqrt(dev / n_in) if n_in else 0.0
    edev = math.sqrt(total / n_in * (1.0 - 1.0 / n_in)) if n_in else 0.0
    w(f" in {n_in}\n")
    w(f" avg {avg} stddev {dev:g} ({dev / avg if avg else 0:g}x) "
      f"(expected {edev:g} {edev / avg if avg else 0:g}x))\n")
    if min_osd >= 0:
        w(f" min osd.{min_osd} {count[min_osd]}\n")
    if max_osd >= 0:
        w(f" max osd.{max_osd} {count[max_osd]}\n")
    w(f"size {json.dumps(dict(sorted(size_hist.items())))}\n")
    return {"count": count, "first": first_count, "primary": primary_count,
            "size_hist": size_hist, "in": n_in, "avg": avg, "stddev": dev,
            "min_osd": min_osd, "max_osd": max_osd, "total": total}


def main(argv=None) -> int:
    from ..utils.platform import honour_jax_platforms_env
    honour_jax_platforms_env()   # axon sitecustomize override
    ap = argparse.ArgumentParser(
        prog="osdmaptool", description=__doc__.splitlines()[0])
    ap.add_argument("mapfile", help="OSDMap as JSON (OSDMap.to_dict)")
    ap.add_argument("--test-map-pgs", action="store_true")
    ap.add_argument("--test-map-pgs-dump", action="store_true")
    ap.add_argument("--test-map-pgs-dump-all", action="store_true")
    ap.add_argument("--test-map-pg", metavar="PGID",
                    help="map one pg, e.g. 1.7")
    ap.add_argument("--pool", type=int, default=-1)
    ap.add_argument("--print", dest="do_print", action="store_true",
                    help="summarize the map")
    ap.add_argument("--upmap", metavar="OUT",
                    help="calculate pg upmap entries to balance pg layout "
                         "and write them as JSON (osdmaptool --upmap)")
    ap.add_argument("--upmap-deviation", type=float, default=1.0)
    ap.add_argument("--upmap-max", type=int, default=32,
                    help="max optimization iterations")
    args = ap.parse_args(argv)

    import jax
    jax.config.update("jax_enable_x64", True)   # exact straw2 draws

    with open(args.mapfile) as f:
        m = OSDMap.from_dict(json.load(f))

    if args.do_print:
        print(f"epoch {m.epoch}")
        print(f"max_osd {m.max_osd}")
        for pid in sorted(m.pools):
            p = m.pools[pid]
            kind = "replicated" if p.type == 1 else "erasure"
            print(f"pool {pid} '{p.name}' {kind} size {p.size} "
                  f"pg_num {p.pg_num} crush_rule {p.crush_rule}")
    if args.test_map_pg:
        pool_s, ps_s = args.test_map_pg.split(".")
        pg = PG(int(pool_s), int(ps_s, 16))
        print(f" parsed '{args.test_map_pg}' -> {pg}")
        raw, rawp = m.pg_to_raw_osds(pg)
        up, upp, acting, actingp = m.pg_to_up_acting_osds(pg)
        print(f"{pg} raw ({raw}, p{rawp}) up ({up}, p{upp}) "
              f"acting ({acting}, p{actingp})")
    if args.test_map_pgs or args.test_map_pgs_dump or args.test_map_pgs_dump_all:
        test_map_pgs(m, pool=args.pool, dump=args.test_map_pgs_dump,
                     dump_all=args.test_map_pgs_dump_all, out=sys.stdout)
    if args.upmap:
        from ..mgr import calc_pg_upmaps
        inc = calc_pg_upmaps(
            m, max_iterations=args.upmap_max,
            max_deviation=args.upmap_deviation,
            pools=None if args.pool == -1 else [args.pool])
        entries = {f"{pg.pool}.{pg.ps}": items
                   for pg, items in inc.new_pg_upmap_items.items()}
        with open(args.upmap, "w") as f:
            json.dump({"pg_upmap_items": entries}, f, indent=1)
            f.write("\n")
        print(f"wrote {len(entries)} pg_upmap_items to {args.upmap}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
