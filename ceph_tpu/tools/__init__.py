"""Offline CLI tools: osdmaptool / crushtool equivalents (SURVEY.md §2.3).

The reference evaluates full clusters as pure functions offline
(src/tools/osdmaptool.cc --test-map-pgs, src/crush/CrushTester.cc via
crushtool --test); these modules do the same over the JAX bulk mappers."""
from .osdmaptool import test_map_pgs, device_crush_weights
from .crushtool import test_rule, test

__all__ = ["test_map_pgs", "device_crush_weights", "test_rule", "test"]
