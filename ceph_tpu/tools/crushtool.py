"""crushtool equivalent: offline CRUSH rule testing.

Mirror of the reference's ``crushtool --test`` driving ``CrushTester``
(reference: src/crush/CrushTester.{h,cc}; mapping loop + report format at
CrushTester.cc:600-700): per-x mappings, bad-mapping detection, result-size
histogram, and device utilization vs weight-proportional expectation.  Bulk
placement goes through the vmapped JAX mapper when the rule shape supports
it, with the exact host interpreter as fallback.

CLI:  python -m ceph_tpu.tools.crushtool -i MAP.json --test
      [--rule N] [--num-rep N] [--min-x A] [--max-x B] [--weight OSD W]...
      [--show-mappings] [--show-bad-mappings] [--show-statistics]
      [--show-utilization]
"""
from __future__ import annotations

import argparse
import json
import sys

import numpy as np

from ..crush.jax_mapper import BulkMapper
from ..crush.map import CRUSH_ITEM_NONE, CrushMap
from ..crush.mapper import crush_do_rule
from .osdmaptool import device_crush_weights


def _bulk_or_scalar(cmap, ruleno, xs, num_rep, weights):
    steps = cmap.rules[ruleno].steps
    firstn = any(op in (2, 6) for op, _, _ in steps)
    try:
        out, placed = BulkMapper(cmap).map_rule(
            ruleno, np.asarray(xs), reweights=weights, result_max=num_rep)
        if firstn:
            # firstn rows are short on failure, never NONE-padded
            return [[int(o) for o in row[:int(p)]]
                    for row, p in zip(out, placed)]
        return [[int(o) for o in row] for row in out]
    except (ValueError, RuntimeError):
        return [crush_do_rule(cmap, ruleno, int(x), num_rep, weights)
                for x in xs]


def test_rule(cmap: CrushMap, ruleno: int, num_rep: int,
              min_x: int = 0, max_x: int = 1023,
              weights: list[int] | None = None,
              show_mappings: bool = False, show_bad_mappings: bool = False,
              show_statistics: bool = False, show_utilization: bool = False,
              out=None) -> dict:
    """One rule's test sweep (CrushTester::test, CrushTester.cc:600-700)."""
    w = out.write if out is not None else (lambda s: None)
    xs = list(range(min_x, max_x + 1))
    results = _bulk_or_scalar(cmap, ruleno, xs, num_rep, weights)

    n_dev = cmap.max_devices
    per = [0] * n_dev
    sizes: dict[int, int] = {}
    bad = 0
    for x, row in zip(xs, results):
        vals = [o for o in row if o != CRUSH_ITEM_NONE]
        has_none = len(vals) != len(row)
        for o in vals:
            per[o] += 1
        sizes[len(row)] = sizes.get(len(row), 0) + 1
        if show_mappings:
            w(f"CRUSH rule {ruleno} x {x} {list(row)}\n")
        if (len(row) != num_rep or has_none):
            bad += 1
            if show_bad_mappings:
                w(f"bad mapping rule {ruleno} x {x} num_rep {num_rep} "
                  f"result {list(row)}\n")

    # weight-proportional expectation (CrushTester.cc:567-597)
    cw = device_crush_weights(cmap)
    eff = {}
    for dev, dw in cw.items():
        rw = weights[dev] if weights is not None and dev < len(weights) \
            else 0x10000
        eff[dev] = dw * (rw / 0x10000)
    total_w = sum(eff.values())
    n_x = len(xs)
    expected_total = min(num_rep, len(cw)) * n_x
    expected = {dev: (ew / total_w) * expected_total if total_w else 0.0
                for dev, ew in eff.items()}

    if show_statistics:
        name = next((nm for nm, rn in cmap.rule_names.items()
                     if rn == ruleno), str(ruleno))
        for sz in sorted(sizes):
            w(f"rule {ruleno} ({name}) num_rep {num_rep} result size == "
              f"{sz}:\t{sizes[sz]}/{n_x}\n")
    if show_utilization:
        for dev in sorted(cw):
            if per[dev] > 0 or expected.get(dev, 0) > 0:
                w(f"  device {dev}:\t\t stored : {per[dev]}\t "
                  f"expected : {expected.get(dev, 0):g}\n")
    return {"per_device": per, "sizes": sizes, "bad_mappings": bad,
            "expected": expected, "num_x": n_x}


def test(cmap: CrushMap, rules: list[int] | None = None,
         num_rep: int | None = None, min_x: int = 0, max_x: int = 1023,
         weights: list[int] | None = None, out=None, **show) -> dict:
    """--test over all (or selected) rules x num_rep sweep."""
    results = {}
    todo = sorted(cmap.rules) if rules is None else rules
    for ruleno in todo:
        nr_list = [num_rep] if num_rep else \
            list(range(1, _rule_max_reps(cmap, ruleno) + 1))
        for nr in nr_list:
            results[(ruleno, nr)] = test_rule(
                cmap, ruleno, nr, min_x, max_x, weights, out=out, **show)
    return results


def _rule_max_reps(cmap: CrushMap, ruleno: int) -> int:
    """Default num_rep sweep upper bound: the rule's largest choose arg
    (crushtool sweeps --min-rep..--max-rep similarly)."""
    mx = 0
    for op, arg1, _ in cmap.rules[ruleno].steps:
        if op in (2, 3, 6, 7) and arg1 > 0:
            mx = max(mx, arg1)
    return mx or 3


def main(argv=None) -> int:
    from ..utils.platform import honour_jax_platforms_env
    honour_jax_platforms_env()   # axon sitecustomize override
    ap = argparse.ArgumentParser(prog="crushtool",
                                 description=__doc__.splitlines()[0])
    ap.add_argument("-i", "--in", dest="infile", required=True,
                    help="CrushMap as JSON (CrushMap.to_dict) or, with -c, "
                         "crushmap TEXT")
    ap.add_argument("-d", "--decompile", action="store_true",
                    help="emit the map as crushmap text (crushtool -d)")
    ap.add_argument("-c", "--compile", dest="compile_text",
                    action="store_true",
                    help="treat the input as crushmap text (crushtool -c); "
                         "writes JSON with -o")
    ap.add_argument("-o", "--out", dest="outfile", default="",
                    help="output path for -d/-c (default stdout)")
    ap.add_argument("--test", action="store_true")
    ap.add_argument("--rule", type=int, default=-1)
    ap.add_argument("--num-rep", type=int, default=0)
    ap.add_argument("--min-x", type=int, default=0)
    ap.add_argument("--max-x", type=int, default=1023)
    ap.add_argument("--weight", nargs=2, action="append", default=[],
                    metavar=("OSD", "W"),
                    help="override device reweight (0.0-1.0)")
    ap.add_argument("--show-mappings", action="store_true")
    ap.add_argument("--show-bad-mappings", action="store_true")
    ap.add_argument("--show-statistics", action="store_true")
    ap.add_argument("--show-utilization", action="store_true")
    args = ap.parse_args(argv)

    if args.compile_text and args.decompile and args.outfile:
        ap.error("-c and -d share -o; run them separately")
    if args.compile_text:
        from ..crush.compiler import compile_crushmap
        with open(args.infile) as f:
            cmap = compile_crushmap(f.read())
        # emit the compiled JSON only when it is the requested product
        # (-o, or -c alone): --test/-d output must stay unpolluted
        if args.outfile:
            with open(args.outfile, "w") as f:
                f.write(json.dumps(cmap.to_dict(), indent=1) + "\n")
        elif not (args.test or args.decompile):
            sys.stdout.write(json.dumps(cmap.to_dict(), indent=1) + "\n")
        if not (args.test or args.decompile):
            return 0
    else:
        with open(args.infile) as f:
            cmap = CrushMap.from_dict(json.load(f))

    if args.decompile:
        from ..crush.compiler import decompile
        text = decompile(cmap)
        if args.outfile and not args.compile_text:
            with open(args.outfile, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        if not args.test:
            return 0

    import jax
    jax.config.update("jax_enable_x64", True)   # exact straw2 draws

    if not args.test:
        ap.error("one of --test, -d, -c is required")
    weights = None
    if args.weight:
        weights = [0x10000] * cmap.max_devices
        for osd_s, w_s in args.weight:
            weights[int(osd_s)] = int(float(w_s) * 0x10000)
    test(cmap,
         rules=None if args.rule < 0 else [args.rule],
         num_rep=args.num_rep or None,
         min_x=args.min_x, max_x=args.max_x, weights=weights,
         out=sys.stdout,
         show_mappings=args.show_mappings,
         show_bad_mappings=args.show_bad_mappings,
         show_statistics=args.show_statistics,
         show_utilization=args.show_utilization)
    return 0


if __name__ == "__main__":
    sys.exit(main())
