"""`ceph` CLI: the admin command surface over a durable cluster.

Analog of the reference's `ceph` tool verbs (reference: src/ceph.in →
mon/mgr command handlers): `-s`/`status` (now with the PGMap rate lines
— client IO B/s and op/s, recovery B/s — and the health-mute state),
`health [detail]`, `health mute|unmute <KEY>` (persisted in the cluster
meta like the mon's mutes), `top` (live rate/queue/health digest;
``--iterations``/``--interval`` pace it), `flight dump` (capture an
anomaly flight-recorder bundle), `osd tree` (the CRUSH hierarchy with
weights/status, OSDMonitor's 'osd tree' dump shape), `osd df`,
`pg dump` (PGMap's per-PG table: state, objects, log version,
up/acting), `df`.  Like the rados CLI, every invocation reopens the
FileStore-backed cluster under ``--data-dir`` — boot peering and log
replay included — so the admin view reflects exactly what is durable.

    python -m ceph_tpu.tools.ceph_cli --data-dir D status
    python -m ceph_tpu.tools.ceph_cli --data-dir D health mute SLOW_OPS
    python -m ceph_tpu.tools.ceph_cli --data-dir D top --iterations 3
"""
from __future__ import annotations

import argparse
import sys
import time


def render_osd_tree(cluster) -> str:
    """The 'ceph osd tree' table from the live CRUSH map + OSDMap:
    WEIGHT is the CRUSH weight everywhere (leaves sum to their bucket),
    REWEIGHT is the osdmap 16.16 override — the reference's two columns."""
    cmap = cluster.osdmap.crush
    lines = ["ID    WEIGHT    REWEIGHT  TYPE NAME                 STATUS"]
    # shadow (per-class clone) trees stay hidden, like the reference's
    # 'osd tree' without --show-shadow (CrushWrapper find_nonshadow_roots)
    roots = [bid for bid in cmap.buckets
             if not any(bid in b.items for b in cmap.buckets.values())
             and not cmap.is_shadow(bid)]

    def walk(item: int, depth: int, crush_w: float) -> None:
        indent = "    " * depth
        if item >= 0:
            st = "up" if cluster.osdmap.is_up(item) else "down"
            if cluster.osdmap.is_out(item):
                st += "/out"
            rw = cluster.osdmap.osd_weight[item] / 0x10000
            lines.append(f"{item:>4}  {crush_w:8.5f}  {rw:8.5f}  "
                         f"{indent}osd.{item:<12} {st}")
            return
        b = cmap.buckets[item]
        tname = cmap.type_names.get(b.type, str(b.type))
        name = cmap.item_names.get(item, f"{tname}-{-item}")
        weight = sum(b.item_weights) / 0x10000
        lines.append(f"{item:>4}  {weight:8.5f}  {'-':>8}  "
                     f"{indent}{tname} {name}")
        for child, w in zip(b.items, b.item_weights):
            walk(child, depth + 1, w / 0x10000)

    for root in sorted(roots, reverse=True):
        walk(root, 0, 0.0)
    return "\n".join(lines)


def render_pg_dump(cluster) -> str:
    """PGMap's per-PG table (the 'ceph pg dump' brief shape)."""
    lines = ["PG_ID     STATE             OBJECTS  LOG   UP/ACTING  PRIMARY"]
    for pid, pool in sorted(cluster.pools.items()):
        for ps, g in sorted(pool["pgs"].items()):
            state = cluster.pg_state(g)
            n_obj = len(g.backend._local_oids())
            lines.append(
                f"{pid}.{ps:<7} {state:<17} {n_obj:>7}  "
                f"{g.backend.pg_log.head:<5} {str(g.acting):<10} "
                f"{g.backend.whoami}")
    return "\n".join(lines)


def main(argv=None) -> int:
    from ..utils.platform import honour_jax_platforms_env
    honour_jax_platforms_env()   # axon sitecustomize override
    # '-s' is the classic status alias; argparse would eat it as an
    # unknown option before the positional, so translate it up front
    argv = [{"-s": "status", "-w": "watch"}.get(a, a)
            for a in (sys.argv[1:] if argv is None else list(argv))]
    ap = argparse.ArgumentParser(prog="ceph")
    ap.add_argument("--data-dir")
    ap.add_argument("--connect", metavar="HOST:PORT",
                    help="talk to a live cluster process over TCP "
                         "(status/health/df)")
    ap.add_argument("--keyring",
                    help="client.admin keyring (default: "
                         "<data-dir>/client.admin.keyring)")
    ap.add_argument("--iterations", type=int, default=1,
                    help="top/watch/daemonperf: refresh rounds "
                         "(watch: 0 = follow forever)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="top/watch/daemonperf: seconds between rounds")
    ap.add_argument("cmd", nargs="+",
                    help="status | -s | health [detail] | "
                         "health mute|unmute KEY | top | daemonperf | "
                         "log last [N] | watch | -w | flight dump | "
                         "slo status | slo dump | "
                         "device roofline | device profile status | "
                         "osd pool set POOL KEY VALUE | heat top [N] | "
                         "tier status | osd tree | osd df | pg dump | df")
    args = ap.parse_args(argv)

    import os
    if args.connect:
        return _run_remote(args)
    if args.data_dir is None:
        ap.error("--data-dir is required (or --connect for remote mode)")
    if args.cmd[0] == "watch":
        # `ceph -w`: follow the persisted clusterlog FILE — no cluster
        # reopen (a live process may hold the stores; the log file is
        # the one surface both can share)
        return _run_watch(os.path.join(args.data_dir, "clusterlog"),
                          args.iterations, args.interval)
    from ..cluster import MiniCluster
    if not os.path.exists(os.path.join(args.data_dir, "cluster_meta.pkl")):
        print(f"error: no cluster at {args.data_dir}", file=sys.stderr)
        return 2
    c = MiniCluster.load(args.data_dir)
    try:
        cmd = " ".join(args.cmd)
        if cmd in ("status", "-s"):
            print(_fmt_status(c.status(), c.health()))
        elif cmd in ("health", "health detail"):
            if cmd == "health detail":
                # ONE evaluation serves both the status line and the
                # detail listing (two would re-walk every pool/PG and
                # could disagree if state moved between them)
                from ..mgr.health import thin_view
                ev = c.health_detail()
                _print_health(thin_view(ev), True, detail_ev=ev)
            else:
                _print_health(c.health(), False)
        elif len(args.cmd) == 3 and args.cmd[0] == "health" and \
                args.cmd[1] in ("mute", "unmute"):
            key = args.cmd[2]
            if args.cmd[1] == "mute":
                if key not in c.health_engine.registered():
                    print(f"warning: {key!r} is not a registered check "
                          f"(muting anyway)", file=sys.stderr)
                c.mute_health(key)      # mute + persist in one step
            else:
                c.unmute_health(key)
            print(f"{args.cmd[1]}d {key}")
        elif cmd == "top":
            _run_top(c, args.iterations, args.interval)
        elif cmd == "daemonperf":
            _run_daemonperf(c, args.iterations, args.interval)
        elif args.cmd[0] == "log" and len(args.cmd) >= 2 and \
                args.cmd[1] == "last":
            n = int(args.cmd[2]) if len(args.cmd) > 2 else 20
            from ..common.clusterlog import format_entry
            for e in c.clusterlog.last(n):
                print(format_entry(e))
        elif cmd in ("slo status", "slo dump"):
            # the admin-socket fns fold the tracer ring first, so the
            # table reflects every trace this (reopened) process ran;
            # a live process's `slo status` sees the full history
            out = c.cct.admin_socket.call(cmd)
            if cmd == "slo dump":
                import json as _json
                print(_json.dumps(out, indent=2, default=str))
            else:
                from ..mgr.slo import render_status
                print(render_status(out))
        elif cmd == "device roofline":
            from ..common import roofline
            print(roofline.render_table(roofline.report(cct=c.cct)))
        elif args.cmd[:2] == ["device", "profile"]:
            sub = args.cmd[2] if len(args.cmd) > 2 else "status"
            if sub != "status":
                # a profiler window is PROCESS-scoped state: this CLI
                # reopens the cluster per invocation, so a window opened
                # here would be force-closed on exit before any work ran,
                # and a later 'stop' would land in a fresh process that
                # never saw it.  Only the live process's admin socket can
                # span start..work..stop.
                print("error: 'device profile start|stop' needs the LIVE "
                      "process — call 'device profile start' on its "
                      "admin socket (in-process or via 'rados serve'); "
                      "this reopen-per-invocation CLI can only report "
                      "'device profile status' (on-disk captures)",
                      file=sys.stderr)
                return 2
            import json as _json
            print(_json.dumps(c.profiler.status(), indent=2,
                              default=str))
        elif cmd == "flight dump":
            b = c.flight.dump(reason="cli", force=True)
            print(f"captured flight bundle seq={b['seq']} "
                  f"reason={b['reason']}"
                  + (f" -> {b['path']}" if "path" in b else ""))
        elif cmd == "osd tree":
            print(render_osd_tree(c))
        elif cmd == "osd df":
            from ..backend.pg_backend import PG_META, shard_store
            for o in range(c.n_osds):
                n_obj = 0
                for p in c.pools.values():
                    for g in p["pgs"].values():
                        if o not in g.bus.handlers:
                            continue
                        n_obj += sum(1 for gobj in
                                     shard_store(g.bus, o).list_objects()
                                     if gobj.shard == o
                                     and gobj.oid != PG_META)
                st = "up" if c.osdmap.is_up(o) else "down"
                print(f"osd.{o:<4} {st:<6} {n_obj} shard objects")
        elif args.cmd[:3] == ["osd", "pool", "set"] and len(args.cmd) == 6:
            # `ceph osd pool set <pool> <key> <value>` — live-tunable pool
            # params; hit_set_* keys re-arm the hit-set engines in place
            name, key, value = args.cmd[3:]
            if name not in c.pool_ids:
                print(f"error: no pool {name!r}", file=sys.stderr)
                return 2
            c.pool_set(c.pool_ids[name], key, value)
            print(f"set pool {name} {key} to {value}")
        elif args.cmd[:2] == ["heat", "top"]:
            n = int(args.cmd[2]) if len(args.cmd) > 2 else 20
            rows = c.cct.admin_socket.call("heat top", n=n)["top"]
            print("POOL/OID                       TEMPERATURE")
            for r in rows:
                print(f"{r['pool']}/{r['oid']:<28} {r['temperature']}")
        elif cmd == "tier status":
            import json as _json
            try:
                print(_json.dumps(c.cct.admin_socket.call(cmd),
                                  indent=2, default=str))
            except KeyError:
                # the admin command registers with the first
                # create_tier — a tier is a RUNTIME binding, so a
                # reopened CLI process has none until one is bound
                print("no cache tiers bound in this process "
                      "(bind one with MiniCluster.create_tier)",
                      file=sys.stderr)
                return 2
        elif cmd == "pg dump":
            print(render_pg_dump(c))
        elif cmd == "df":
            from ..osd.primary_log_pg import is_clone_oid
            st = c.status()
            for name, pid in sorted(c.pool_ids.items()):
                # user objects only: after a reload the bookkeeping also
                # carries snapshot clone oids (same filter rados ls uses)
                n = sum(1 for oid in c.objects.get(pid, ())
                        if not is_clone_oid(oid))
                print(f"pool {name:<12} id {pid}  objects {n}")
            print(f"total: {st['pgmap']['num_pgs']} pgs on "
                  f"{st['osdmap']['num_osds']} osds")
        else:
            print(f"error: unknown command {cmd!r}", file=sys.stderr)
            return 2
        return 0
    finally:
        c.shutdown()


def _health_line(h: dict) -> str:
    """`HEALTH_X (muted: A, B)` — ONE rendering of status + mute state
    for every surface (status header, health verb, top)."""
    status = h["status"]
    if h.get("muted"):
        status += f" (muted: {', '.join(sorted(h['muted']))})"
    return status


def _print_health(h: dict, detail: bool, detail_ev: dict | None = None
                  ) -> None:
    print(_health_line(h))
    if detail:
        if detail_ev is not None:       # rich engine evaluation (local)
            for key, c in sorted(detail_ev["checks"].items()):
                mute = " (MUTED)" if c["muted"] else ""
                print(f"[{c['severity']}] {key}{mute}: {c['summary']}")
                for line in c["detail"]:
                    print(f"    {line}")
        else:                           # thin view (remote mode)
            for key, msg in sorted(h["checks"].items()):
                print(f"[{key}] {msg}")


def _fmt_bytes_s(v: float) -> str:
    for unit in ("B/s", "KiB/s", "MiB/s", "GiB/s"):
        if v < 1024 or unit == "GiB/s":
            return f"{v:.1f} {unit}" if unit != "B/s" else f"{v:.0f} B/s"
        v /= 1024.0
    return f"{v:.1f} GiB/s"             # pragma: no cover


def _fmt_io_lines(rates: dict | None) -> str:
    """The 'io:' section (PGMap overall_client_io_rate_summary shape);
    recovery shows only when active, like the reference."""
    if not rates:
        return ""
    cl = rates["client_io"]
    lines = [f"    client:   {_fmt_bytes_s(cl['rd_bytes_s'])} rd, "
             f"{_fmt_bytes_s(cl['wr_bytes_s'])} wr, "
             f"{cl['rd_op_s']:.0f} op/s rd, {cl['wr_op_s']:.0f} op/s wr"]
    rec = rates["recovery"]
    queued = int(rec.get("queued_pgs", 0))
    active = int(rec.get("active_pgs", 0))
    if rec["bytes_s"] or rec["op_s"] or queued or active:
        line = (f"    recovery: {_fmt_bytes_s(rec['bytes_s'])}, "
                f"{rec['op_s']:.0f} obj/s")
        if queued or active:
            line += f" ({active} pgs recovering, {queued} queued)"
        lines.append(line)
    srv = rates["serving"]
    if srv["op_s"]:
        lines.append(f"    serving:  {srv['op_s']:.0f} op/s in "
                     f"{srv['batch_s']:.0f} batch/s, "
                     f"{_fmt_bytes_s(srv['bytes_s'])}")
    return "\n  io:\n" + "\n".join(lines)


def _fmt_status(st: dict, h: dict) -> str:
    states = ", ".join(f"{n} {s}" for s, n in
                       sorted(st["pgmap"]["pgs_by_state"].items()))
    # the recovery scheduler's block (queued/recovering PG jobs and
    # reservation occupancy), present only when a scheduler is attached
    rec = st["pgmap"].get("recovery")
    rec_line = ""
    if rec and (rec["queued_pgs"] or rec["active_pgs"] or
                rec["reservations"]["granted"] or
                rec["reservations"]["queued"]):
        rec_line = (f"\n    recovery: {rec['active_pgs']} pgs "
                    f"recovering, {rec['queued_pgs']} queued; "
                    f"reservations: {rec['reservations']['granted']} "
                    f"in-flight, {rec['reservations']['queued']} waiting")
    return (f"  cluster:\n    health: {_health_line(h)}\n"
            f"  services:\n"
            f"    osd: {st['osdmap']['num_osds']} osds: "
            f"{st['osdmap']['num_up_osds']} up "
            f"(epoch {st['osdmap']['epoch']})\n"
            f"  data:\n"
            f"    pools:   {st['pgmap']['num_pools']} pools, "
            f"{st['pgmap']['num_pgs']} pgs\n"
            f"    pgs:     {states}"
            + rec_line
            + _fmt_io_lines(st["pgmap"].get("io_rates")))


def render_top(c) -> str:
    """One `ceph_tpu top` frame: health, rate digest, throttle
    occupancy, jit churn, daemon queue depth — the operator's
    is-it-moving-right-now view."""
    c.stats.sample()
    d = c.stats.digest()
    h = c.health()
    lines = [f"health: {_health_line(h)}"
             + (f"  checks: {', '.join(sorted(h['checks']))}"
                if h["checks"] else ""),
             f"window: {d['window_s']:.1f}s over {d['samples']} samples"]
    cl = d["client_io"]
    lines.append(f"client io: {_fmt_bytes_s(cl['rd_bytes_s'])} rd, "
                 f"{_fmt_bytes_s(cl['wr_bytes_s'])} wr, "
                 f"{cl['rd_op_s']:.0f}/{cl['wr_op_s']:.0f} op/s rd/wr")
    rec = d["recovery"]
    rec_line = (f"recovery:  {_fmt_bytes_s(rec['bytes_s'])}, "
                f"{rec['op_s']:.0f} obj/s")
    if getattr(c, "recovery", None) is not None:
        s = c.recovery.summary()
        rec_line += (f", {s['active_pgs']} pgs recovering / "
                     f"{s['queued_pgs']} queued, "
                     f"{s['reservations']['granted']} reservations "
                     f"in-flight")
    lines.append(rec_line)
    lines.append(f"serving:   {d['serving']['op_s']:.0f} op/s, "
                 f"{d['serving']['batch_s']:.0f} batch/s")
    w = d["wire"]
    if w["tx_bytes_s"] or w["tx_msgs_s"]:
        lines.append(f"wire:      {_fmt_bytes_s(w['tx_bytes_s'])} tx, "
                     f"{w['tx_msgs_s']:.0f} msg/s")
    lines.append(f"jit:       {d['jit']['compiles']:.0f} compiles, "
                 f"{d['jit']['cache_hits']:.0f} cache hits (window)")
    from ..mgr.health import iter_throttles
    throttles = [f"{name.removeprefix('throttle.')}={int(val)}/{int(mx)}"
                 for name, val, mx in iter_throttles(c.cct)]
    if throttles:
        lines.append("throttles: " + " ".join(throttles))
    depths = {o: sum(sum(cls.values()) for cls in
                     daemon.queue_depths().values())
              for o, daemon in sorted(c.osds.items())}
    busy = {o: n for o, n in depths.items() if n}
    if busy:
        lines.append("queues:    " + " ".join(
            f"osd.{o}={n}" for o, n in sorted(busy.items())))
    return "\n".join(lines)


def _run_top(c, iterations: int, interval: float) -> None:
    for i in range(max(1, iterations)):
        if i:
            time.sleep(interval)
            print()
        print(render_top(c))


def _run_watch(path: str, iterations: int, interval: float) -> int:
    """`ceph -w`: print the clusterlog tail, then follow the FILE for
    appends (another process's MiniCluster writing it live).
    ``iterations=0`` follows forever; N bounds the poll rounds (tests,
    scripts)."""
    import os
    from ..common.clusterlog import format_entry, read_log_file
    if not os.path.exists(path):
        print(f"error: no clusterlog at {path} (cluster never ran "
              f"durable, or nothing logged yet)", file=sys.stderr)
        return 2
    entries = read_log_file(path)
    for e in entries[-10:]:
        print(format_entry(e), flush=True)
    seen = max((e.get("seq", 0) for e in entries), default=0)
    rounds = 0
    while iterations <= 0 or rounds < iterations:
        rounds += 1
        time.sleep(interval)
        for e in read_log_file(path):
            if e.get("seq", 0) > seen:
                seen = e["seq"]
                print(format_entry(e), flush=True)
    return 0


def render_daemonperf(c, prev: dict | None = None) -> tuple[str, dict]:
    """One `daemonperf` frame: per-daemon queue counter DELTAS since
    ``prev`` plus the cluster rate digest — the reference's
    ``ceph daemonperf osd.N`` columns generalized over every daemon.
    Returns (rendered text, new prev) so the caller owns the cadence."""
    c.stats.sample()
    d = c.stats.digest()
    cur = {o: dict(daemon.queue_stats) for o, daemon in sorted(c.osds.items())}
    prev = prev or {}
    lines = ["daemon   enq   deq   rej  wait_ms | "
             "wr/s   rd/s   rec_B/s   wire_B/s"]
    cluster_cols = (f"{d['client_io']['wr_op_s']:6.0f} "
                    f"{d['client_io']['rd_op_s']:6.0f} "
                    f"{d['recovery']['bytes_s']:9.0f} "
                    f"{d['wire']['tx_bytes_s']:10.0f}")
    for o, qs in cur.items():
        p = prev.get(o, {})
        enq = qs["enqueued"] - p.get("enqueued", 0)
        deq = qs["dequeued"] - p.get("dequeued", 0)
        rej = qs["throttled_rejects"] - p.get("throttled_rejects", 0)
        wait = (qs["wait_sum"] - p.get("wait_sum", 0.0)) * 1000.0
        lines.append(f"osd.{o:<4} {enq:5d} {deq:5d} {rej:5d} "
                     f"{wait:8.1f} | {cluster_cols}")
        cluster_cols = " " * len(cluster_cols)   # once per frame
    return "\n".join(lines), cur


def _run_daemonperf(c, iterations: int, interval: float) -> None:
    prev: dict | None = None
    for i in range(max(1, iterations)):
        if i:
            time.sleep(interval)
            print()
        text, prev = render_daemonperf(c, prev)
        print(text)


def _run_remote(args) -> int:
    """status/health/df against a live served cluster (TcpRados RPC)."""
    from ..net import cli_connect
    try:
        r = cli_connect(args.connect, args.keyring, args.data_dir)
    except Exception as e:        # AuthError/Unpickling/IO/Value: all
        print(f"error: {e}", file=sys.stderr)   # operator-facing
        return 2
    try:
        cmd = " ".join(args.cmd)
        if cmd in ("status", "-s"):
            print(_fmt_status(r.status(), r.call("health")))
        elif cmd in ("health", "health detail"):
            _print_health(r.call("health"), cmd == "health detail")
        elif cmd == "df":
            st = r.status()
            print(f"{st['pgmap']['num_pools']} pools, "
                  f"{st['pgmap']['num_pgs']} pgs")
        else:
            print(f"error: {cmd!r} not supported over --connect",
                  file=sys.stderr)
            return 2
        return 0
    except (IOError, KeyError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    finally:
        r.close()


if __name__ == "__main__":
    sys.exit(main())
