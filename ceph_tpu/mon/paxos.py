"""Multi-monitor Paxos: rank election, collect/begin/accept/commit.

Analog of the reference's monitor consensus (reference: src/mon/Paxos.cc,
1585 LoC — phases ``collect`` (recovery after election), ``begin`` (leader
proposes), ``handle_accept``, ``commit``; elections in src/mon/Elector.cc —
lowest rank among reachable monitors wins).  The single-``Monitor``
shortcut ("a commit IS quorum") becomes real consensus here:

- a value (an OSDMap ``Incremental``) commits only after EVERY member of
  the quorum accepts it, and a quorum is a strict majority of the monmap —
  so any committed map change survives the death of any minority of
  monitors, including the leader;
- after every election the new leader runs the COLLECT phase: peons report
  their ``last_committed``/``accepted_pn`` and any uncommitted value;
  the leader catches up laggards, adopts the highest-pn uncommitted value
  and re-proposes it — the "leader died between begin and commit" recovery
  (Paxos.cc handle_last -> begin of previously-accepted value);
- proposal numbers are ``round*100 + rank`` so they are unique and
  monotonic across leaders (Paxos.cc get_new_proposal_number).

Monitors talk over the same deterministic
:class:`~ceph_tpu.backend.messages.MessageBus` the OSDs use (mark_down =
monitor death), so elections/proposals interleave with the existing fault
injection.  Each monitor embeds a :class:`~ceph_tpu.mon.monitor.Monitor`
service (the OSDMonitor analog) whose ``propose_pending`` routes through
Paxos when quorum mode is on.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

from .monitor import Monitor
from ..backend.messages import MessageBus
from ..common import Context, default_context
from ..osdmap import Incremental, OSDMap


# -- wire payloads (MMonElection / MMonPaxos analogs) -------------------------

@dataclass
class ElectionPropose:
    from_shard: int
    epoch: int


@dataclass
class ElectionAck:
    from_shard: int
    epoch: int


@dataclass
class ElectionVictory:
    from_shard: int
    epoch: int
    quorum: tuple


@dataclass
class Collect:
    from_shard: int
    pn: int
    last_committed: int


@dataclass
class CollectReply:
    from_shard: int
    pn: int
    accepted_pn: int
    last_committed: int
    # committed versions the leader is missing: {version: (now, inc)}
    commits: dict = field(default_factory=dict)
    # (pn, version, (now, inc)) accepted but never committed, or None
    uncommitted: tuple | None = None


@dataclass
class Begin:
    from_shard: int
    pn: int
    version: int
    value: tuple            # (now, Incremental)


@dataclass
class Forward:
    """Peon -> leader: a client value (MForward).  ``seq`` is the per-peon
    reqid the leader dedups on — a duplicated forward must not commit (and,
    with XOR incremental semantics, un-commit) the value twice."""
    from_shard: int
    seq: int
    value: tuple


@dataclass
class Accept:
    from_shard: int
    pn: int
    version: int


@dataclass
class Commit:
    from_shard: int
    version: int
    value: tuple


class PaxosMonitor:
    """One monitor: elector + paxos + embedded OSDMonitor service."""

    def __init__(self, rank: int, bus: MessageBus, n_mons: int,
                 osdmap: OSDMap, cct: Context | None = None):
        self.rank = rank
        self.bus = bus
        self.n_mons = n_mons
        self.cct = cct if cct is not None else default_context()
        self.service = Monitor(osdmap, cct=self.cct)
        self.service.submit_fn = self.submit
        # paxos state (the store: committed transaction log)
        self.committed: dict[int, tuple] = {}
        self.last_committed = 0
        self.accepted_pn = 0
        self.uncommitted: tuple | None = None    # (pn, version, value)
        # election state
        self.epoch = 0
        self.leader: int | None = None
        self.quorum: set[int] = set()
        self._electing = False
        self._election_acks: set[int] = set()
        # leader proposal state
        self._collecting: set[int] | None = None
        self._collect_pn = 0
        self._collect_uncommitted: list[tuple] = []
        self._proposing: tuple | None = None     # (version, value)
        self._accepts: set[int] = set()
        self.pending_values: deque = deque()
        self._forward_seq = 0
        self._forward_seen: dict[int, int] = {}  # peon rank -> last seq
        self.on_commit: list = []                # fn(version, value)
        bus.register(rank, self)

    # -- helpers -------------------------------------------------------------

    def up_peers(self) -> list[int]:
        return [r for r in range(self.n_mons)
                if r != self.rank and r not in self.bus.down]

    def is_leader(self) -> bool:
        return (self.leader == self.rank and
                len(self.quorum) > self.n_mons // 2 and
                self._collecting is None)

    def in_quorum(self) -> bool:
        return self.leader is not None and self.rank in self.quorum

    # -- election (Elector.cc: lowest reachable rank wins) --------------------

    def start_election(self) -> None:
        self.epoch += 1
        self.leader = None
        self.quorum = set()
        # queued-but-not-begun client values die with the reign: the
        # services that produced them re-propose from their own state
        # (the PaxosService::restart semantics; clients resend)
        self.pending_values.clear()
        self._electing = True
        self._election_acks = {self.rank}
        # the deterministic analog of the elector's timeout window: wait
        # for every currently-up peer's deferral, not just a bare
        # majority, so up monitors are never left out of the quorum
        self._election_expect = {self.rank} | set(self.up_peers())
        self._proposing = None
        self._collecting = None
        for peer in self.up_peers():
            self.bus.send(peer, ElectionPropose(self.rank, self.epoch))
        self._maybe_win()

    def _maybe_win(self) -> None:
        if not self._electing or \
                len(self._election_acks) <= self.n_mons // 2 or \
                not self._election_acks >= self._election_expect:
            return
        self._electing = False
        self.leader = self.rank
        self.quorum = set(self._election_acks)
        for peer in sorted(self.quorum - {self.rank}):
            self.bus.send(peer, ElectionVictory(self.rank, self.epoch,
                                                tuple(sorted(self.quorum))))
        self._leader_init()

    def handle_message(self, msg) -> None:
        if isinstance(msg, ElectionPropose):
            if msg.from_shard > self.rank:
                # I out-rank the proposer: contest (Elector defers only to
                # lower ranks)
                if not self._electing or msg.epoch > self.epoch:
                    self.epoch = max(self.epoch, msg.epoch)
                    self.start_election()
            else:
                self.epoch = max(self.epoch, msg.epoch)
                self._electing = True
                self.leader = None
                self.bus.send(msg.from_shard,
                              ElectionAck(self.rank, msg.epoch))
        elif isinstance(msg, ElectionAck):
            if self._electing and msg.epoch == self.epoch:
                self._election_acks.add(msg.from_shard)
                self._maybe_win()
        elif isinstance(msg, ElectionVictory):
            if msg.epoch >= self.epoch:
                self.epoch = msg.epoch
                self.leader = msg.from_shard
                self.quorum = set(msg.quorum)
                self._electing = False
                self._proposing = None
                self.pending_values.clear()
        elif isinstance(msg, Forward):
            self._handle_forward(msg)
        elif isinstance(msg, Collect):
            self._handle_collect(msg)
        elif isinstance(msg, CollectReply):
            self._handle_collect_reply(msg)
        elif isinstance(msg, Begin):
            self._handle_begin(msg)
        elif isinstance(msg, Accept):
            self._handle_accept(msg)
        elif isinstance(msg, Commit):
            self._handle_commit(msg)
        else:
            raise TypeError(f"mon.{self.rank}: unexpected {msg!r}")

    # -- collect: post-election recovery (Paxos.cc collect/handle_last) -------

    def _leader_init(self) -> None:
        round_ = max(self.accepted_pn, self._collect_pn) // 100 + 1
        self._collect_pn = round_ * 100 + self.rank
        self.accepted_pn = self._collect_pn
        self._collecting = set(self.quorum) - {self.rank}
        self._collect_uncommitted = []
        if self.uncommitted is not None:
            pn, version, value = self.uncommitted
            self._collect_uncommitted.append((pn, version, value))
        if not self._collecting:
            self._finish_collect()
            return
        for peer in sorted(self._collecting):
            self.bus.send(peer, Collect(self.rank, self._collect_pn,
                                        self.last_committed))

    def _handle_collect(self, msg: Collect) -> None:
        if msg.pn >= self.accepted_pn:
            self.accepted_pn = msg.pn
            self.leader = msg.from_shard
        # ALWAYS reply (Paxos.cc handle_collect): a reply carrying a
        # higher accepted_pn is the nack that makes the collector retry
        # with a larger pn (handle_last's uncommitted_pn bump)
        reply = CollectReply(self.rank, msg.pn, self.accepted_pn,
                             self.last_committed)
        for v in range(msg.last_committed + 1, self.last_committed + 1):
            reply.commits[v] = self.committed[v]
        if self.uncommitted is not None and \
                self.uncommitted[1] > max(self.last_committed,
                                          msg.last_committed):
            reply.uncommitted = self.uncommitted
        self.bus.send(msg.from_shard, reply)

    def _handle_collect_reply(self, msg: CollectReply) -> None:
        if self._collecting is None:
            return
        if msg.accepted_pn > self._collect_pn:
            # a peon promised a higher pn under a previous reign: pick a
            # pn above it and re-run the whole collect
            self.accepted_pn = max(self.accepted_pn, msg.accepted_pn)
            self._leader_init()
            return
        if msg.pn != self._collect_pn:
            return
        # learn commits we missed while down/behind
        for v in sorted(msg.commits):
            if v == self.last_committed + 1:
                self._apply_commit(v, msg.commits[v])
        if msg.uncommitted is not None:
            self._collect_uncommitted.append(msg.uncommitted)
        self._collecting.discard(msg.from_shard)
        self._peon_last_committed = getattr(self, "_peon_last_committed", {})
        self._peon_last_committed[msg.from_shard] = msg.last_committed
        if not self._collecting:
            self._finish_collect()

    def _finish_collect(self) -> None:
        self._collecting = None
        # catch laggard peons up: ship every commit they are missing (the
        # share_state half of Paxos.cc handle_last) so future commits
        # apply in order on every quorum member
        peon_lc = getattr(self, "_peon_last_committed", {})
        for peer in sorted(self.quorum - {self.rank}):
            for v in range(peon_lc.get(peer, self.last_committed) + 1,
                           self.last_committed + 1):
                self.bus.send(peer, Commit(self.rank, v, self.committed[v]))
        # re-propose the highest-pn uncommitted value (the begin-without-
        # commit recovery: a previous leader died between begin and commit)
        redo = [u for u in self._collect_uncommitted
                if u[1] == self.last_committed + 1]
        if redo:
            pn, version, value = max(redo, key=lambda u: u[0])
            self._begin(value)
            return
        self._maybe_begin()

    # -- begin/accept/commit (Paxos.cc:1585 phases) ---------------------------

    def submit(self, now: float, inc: Incremental) -> bool:
        """PaxosService hands a pending map change to consensus.  Returns
        False when there is no quorum to accept it — the service keeps its
        pending state and re-proposes later (nothing is parked here: a
        stale Incremental replayed under a later reign would XOR-undo
        newer state)."""
        value = (now, inc)
        if self.leader is None or not self.in_quorum():
            return False
        if self.leader == self.rank:
            self.pending_values.append(value)
            self._maybe_begin()
        else:
            # forward to the leader (MForward), deduped by (rank, seq)
            self._forward_seq += 1
            self.bus.send(self.leader,
                          Forward(self.rank, self._forward_seq, value))
        return True

    def _handle_forward(self, msg: Forward) -> None:
        if msg.seq <= self._forward_seen.get(msg.from_shard, 0):
            return                       # duplicate forward (resend)
        self._forward_seen[msg.from_shard] = msg.seq
        if self.is_leader() or (self.leader == self.rank and
                                self._collecting is not None):
            self.pending_values.append(msg.value)
            self._maybe_begin()
        # not the leader (election raced the forward): drop — the origin
        # service re-proposes under the new reign

    def _maybe_begin(self) -> None:
        if (self._proposing is None and self._collecting is None and
                self.is_leader() and self.pending_values):
            self._begin(self.pending_values.popleft())

    def _begin(self, value: tuple) -> None:
        version = self.last_committed + 1
        self._proposing = (version, value)
        self._accepts = {self.rank}
        self.uncommitted = (self.accepted_pn, version, value)
        for peer in sorted(self.quorum - {self.rank}):
            self.bus.send(peer, Begin(self.rank, self.accepted_pn,
                                      version, value))
        self._maybe_commit()

    def _handle_begin(self, msg: Begin) -> None:
        if msg.pn < self.accepted_pn:
            return                       # stale proposer
        self.accepted_pn = msg.pn
        self.uncommitted = (msg.pn, msg.version, msg.value)
        self.bus.send(msg.from_shard, Accept(self.rank, msg.pn,
                                             msg.version))

    def _handle_accept(self, msg: Accept) -> None:
        if (self._proposing is None or msg.pn != self.accepted_pn or
                msg.version != self._proposing[0]):
            return
        self._accepts.add(msg.from_shard)
        self._maybe_commit()

    def _maybe_commit(self) -> None:
        """Commit once EVERY quorum member accepted (Paxos.cc commits when
        accepted == quorum; the quorum itself is a monmap majority, so the
        value is durable on a majority)."""
        if self._proposing is None or not self._accepts >= self.quorum:
            return
        version, value = self._proposing
        self._proposing = None
        self._apply_commit(version, value)
        for peer in sorted(self.quorum - {self.rank}):
            self.bus.send(peer, Commit(self.rank, version, value))
        self._maybe_begin()

    def _handle_commit(self, msg: Commit) -> None:
        if msg.version == self.last_committed + 1:
            self._apply_commit(msg.version, msg.value)

    def _apply_commit(self, version: int, value: tuple) -> None:
        self.committed[version] = value
        self.last_committed = version
        if self.uncommitted is not None and self.uncommitted[1] <= version:
            self.uncommitted = None
        now, inc = value
        self.service.apply_committed(now, inc)
        for fn in self.on_commit:
            fn(version, value)


class MonCluster:
    """N monitors on one bus with a Monitor-compatible facade: failure
    reports and ticks address the current leader's service; committed maps
    fan out to ``subscribers`` exactly once per epoch (whichever quorum
    member applies first)."""

    def __init__(self, osdmap: OSDMap, n_mons: int = 3,
                 cct: Context | None = None):
        self.cct = cct if cct is not None else default_context()
        self.bus = MessageBus()
        self.n_mons = n_mons
        self.mons = [PaxosMonitor(r, self.bus, n_mons, osdmap, cct=self.cct)
                     for r in range(n_mons)]
        self.subscribers: list = []
        self._notified = 0
        for m in self.mons:
            m.on_commit.append(self._on_commit)
        self.elect()

    def _on_commit(self, version: int, value: tuple) -> None:
        if version <= self._notified:
            return
        self._notified = version
        now, inc = value
        leader = self.leader()
        newmap = (leader or self.mons[0]).service.osdmap
        for fn in self.subscribers:
            fn(newmap, inc)

    # -- membership ----------------------------------------------------------

    def elect(self) -> "PaxosMonitor | None":
        """Run an election among up monitors and drain the bus."""
        for m in self.mons:
            if m.rank not in self.bus.down:
                m.start_election()
                break                    # lowest up rank proposes first
        self.bus.deliver_all()
        return self.leader()

    def kill(self, rank: int) -> None:
        """A monitor dies: re-elect immediately (the reference's elector
        reacts to the lost connection) so the facade keeps working when a
        majority survives."""
        self.bus.mark_down(rank)
        self.elect()

    def revive(self, rank: int) -> None:
        self.bus.mark_up(rank)
        self.elect()                     # re-peer; collect catches it up

    def leader(self) -> PaxosMonitor | None:
        for m in self.mons:
            if m.rank not in self.bus.down and m.is_leader():
                return m
        return None

    def quorum_ranks(self) -> set[int]:
        ld = self.leader()
        return set(ld.quorum) if ld else set()

    # -- Monitor facade --------------------------------------------------

    @property
    def osdmap(self) -> OSDMap:
        ld = self.leader()
        return (ld or self.mons[0]).service.osdmap

    def prepare_failure(self, target: int, reporter: int,
                        failed_since: float, now: float) -> bool:
        ld = self.leader()
        if ld is None:
            return False
        out = ld.service.prepare_failure(target, reporter, failed_since, now)
        return out

    def cancel_failure(self, target: int, reporter: int) -> None:
        ld = self.leader()
        if ld is not None:
            ld.service.cancel_failure(target, reporter)

    def osd_boot(self, osd: int, now: float | None = None) -> bool:
        ld = self.leader()
        if ld is not None:
            return ld.service.osd_boot(osd, now=now)
        return False

    @property
    def markdown(self):
        """The leader's flap-damping limiter (OSD_FLAPPING reads it)."""
        ld = self.leader()
        return (ld or self.mons[0]).service.markdown

    def clear_markdown(self, osd: int) -> bool:
        """Operator clear on EVERY replica: mark-downs are recorded by
        each quorum member's apply_committed, so a leader-only clear
        would resurrect the damping on the next failover."""
        was = False
        for m in self.mons:
            was = m.service.clear_markdown(osd) or was
        return was

    @property
    def nodown(self) -> set[int]:
        ld = self.leader()
        return (ld or self.mons[0]).service.nodown

    def propose_pending(self, now: float) -> OSDMap | None:
        ld = self.leader()
        if ld is None:
            return None
        before = ld.last_committed
        ld.service.propose_pending(now)
        self.bus.deliver_all()
        return self.osdmap if ld.last_committed > before else None

    def tick(self, now: float) -> OSDMap | None:
        ld = self.leader()
        if ld is None:
            return None
        before = ld.last_committed
        ld.service.tick(now)
        self.bus.deliver_all()
        return self.osdmap if ld.last_committed > before else None
