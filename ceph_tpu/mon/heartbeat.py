"""OSD peer heartbeats over a virtual clock.

Mirror of the reference's heartbeat machinery (reference: src/osd/OSD.cc —
``handle_osd_ping`` :4547, ``heartbeat_check`` :4746 comparing each peer's
last reply against ``osd_heartbeat_grace``, failures queued in
``failure_queue`` :4539,:4678-4692 and reported to the mon).  Time is a
``VirtualClock`` so tests drive deterministic failure timelines (the
Thrasher's clock-stepping pattern, qa/tasks/ceph_manager.py).
"""
from __future__ import annotations

from dataclasses import dataclass, field

from .monitor import Monitor


class VirtualClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


@dataclass
class HeartbeatAgent:
    """One OSD's heartbeat state: pings peers, checks replies, reports."""
    osd: int
    mon: Monitor
    clock: VirtualClock
    peers: list[int] = field(default_factory=list)
    last_rx: dict[int, float] = field(default_factory=dict)
    # the deterministic "network": agent registry, None entry = dead OSD
    network: dict[int, "HeartbeatAgent | None"] = field(default_factory=dict)
    failure_pending: set[int] = field(default_factory=set)

    def ping_peers(self) -> None:
        """Send pings; live peers reply immediately (OSD.cc:4547 ping/reply
        is request-response on the heartbeat messenger)."""
        now = self.clock.now()
        for p in self.peers:
            peer = self.network.get(p)
            if peer is not None:
                # peer processes the ping and we get the reply this tick
                peer.last_rx[self.osd] = now
                self.last_rx[p] = now

    def heartbeat_check(self) -> list[int]:
        """(OSD.cc:4746): peers silent past the grace go on the failure
        queue; recovered peers get their reports canceled, and a peer
        the map says is DOWN gets boot-reported the moment it replies
        again (the preprocess_boot path heartbeats drive).  The boot is
        NOT unconditional: the monitor's mark-down limiter refuses it
        while the peer is flap-damped — without that gate this very
        first-post-grace-reply re-mark-up is the flapping hole (down,
        up 6s later, down again, forever)."""
        now = self.clock.now()
        grace = self.mon.cct.conf.get("osd_heartbeat_grace")
        newly_failed = []
        for p in self.peers:
            last = self.last_rx.get(p)
            if last is None:
                continue                # never heard: not yet accountable
            if now - last >= grace:
                if p not in self.failure_pending:
                    self.failure_pending.add(p)
                    newly_failed.append(p)
                self.mon.prepare_failure(p, self.osd,
                                         failed_since=last, now=now)
            else:
                if p in self.failure_pending:
                    self.failure_pending.discard(p)
                    self.mon.cancel_failure(p, self.osd)
                if last >= now and self.mon.osdmap.is_down(p):
                    # fresh reply from a down-marked peer: report the
                    # boot (flap damping inside osd_boot may refuse)
                    self.mon.osd_boot(p, now=now)
        return newly_failed

    def tick(self) -> list[int]:
        self.ping_peers()
        return self.heartbeat_check()


def build_heartbeat_mesh(mon: Monitor, clock: VirtualClock,
                         n_osds: int) -> dict[int, HeartbeatAgent]:
    """All-to-all peer mesh (the reference picks subsets of up OSDs via
    maybe_update_heartbeat_peers; all-to-all is exact for small clusters)."""
    network: dict[int, HeartbeatAgent | None] = {}
    agents = {}
    for o in range(n_osds):
        agents[o] = HeartbeatAgent(
            osd=o, mon=mon, clock=clock,
            peers=[p for p in range(n_osds) if p != o],
            network=network)
        network[o] = agents[o]
    return agents
