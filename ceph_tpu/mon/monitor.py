"""Monitor: failure reports -> map commits, the control-plane authority.

Mirror of the reference's OSDMonitor failure handling (reference:
src/mon/OSDMonitor.cc): ``prepare_failure`` collects per-target reports
(:2874-2930, ``failure_info_t.add_report``), ``check_failure`` marks a
target down once the failure has aged past the heartbeat grace AND enough
*distinct failure-domain subtrees* have reported it (:2764-2850 — reporters
are grouped by ``mon_osd_reporter_subtree_level`` so one flapping host
can't take peers down), gated by ``can_mark_down``'s nodown flag and
minimum up-ratio (:2671-2705).  Commits are OSDMap incrementals (the Paxos
``propose_pending`` analog — single-monitor here, so a commit IS quorum);
subscribers receive each new map like daemons receiving osdmap epochs.
Down OSDs age out via ``mon_osd_down_out_interval`` (tick), triggering
CRUSH remapping exactly like the reference's auto-out.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..common import Context, default_context
from ..failure.markdown import MarkDownLimiter
from ..osdmap import Incremental, OSDMap, OSD_UP, apply_incremental


@dataclass
class _FailureInfo:
    """failure_info_t: reporter -> earliest failed_since."""
    reporters: dict[int, float] = field(default_factory=dict)

    def add_report(self, reporter: int, failed_since: float) -> None:
        self.reporters.setdefault(reporter, failed_since)

    def max_failed_since(self) -> float:
        return max(self.reporters.values()) if self.reporters else 0.0


class Monitor:
    def __init__(self, osdmap: OSDMap, cct: Context | None = None):
        self.cct = cct if cct is not None else default_context()
        self.osdmap = osdmap
        self.failure_info: dict[int, _FailureInfo] = {}
        self.pending = Incremental()
        self.subscribers: list = []             # fn(new_map, inc)
        self.down_stamp: dict[int, float] = {}  # osd -> when marked down
        self.nodown: set[int] = set()
        # multi-monitor mode: when set, propose_pending hands the pending
        # Incremental to the Paxos layer instead of applying it directly
        # (the PaxosService::propose_pending split; single-mon mode keeps
        # the commit==quorum shortcut)
        self.submit_fn = None
        # flap damping (osd_markdown_log analog, failure/markdown.py): an
        # OSD marked down osd_markdown_count times within
        # osd_markdown_window stays down — boots are refused until the
        # operator clears the record (clear_markdown)
        self.markdown = MarkDownLimiter(
            count=self.cct.conf.get("osd_markdown_count"),
            window=self.cct.conf.get("osd_markdown_window"))
        # optional cluster log (clog): up/down/flap transitions land
        # where an incident reads first (MiniCluster.attach_monitor
        # wires).  In a quorum, apply_committed runs on EVERY replica;
        # clog_gate (set per replica) keeps only the current leader
        # logging so one commit is one line, not n_mons lines.
        self.clog = None
        self.clog_gate = None
        self._flap_logged: set[int] = set()

    def _clog(self):
        """The cluster log iff this monitor should speak (single-mon:
        always; quorum member: only while leader)."""
        if self.clog is not None and \
                (self.clog_gate is None or self.clog_gate()):
            return self.clog
        return None

    # -- failure reports (OSDMonitor.cc:2874) ------------------------------

    def prepare_failure(self, target: int, reporter: int,
                        failed_since: float, now: float) -> bool:
        """One OSD reporting a peer failed.  Returns True when the report
        pushed the target over the down threshold (committed on the next
        propose/tick)."""
        if not self.osdmap.is_up(target):
            return False
        fi = self.failure_info.setdefault(target, _FailureInfo())
        fi.add_report(reporter, failed_since)
        if self.can_mark_down(target):
            return self.check_failure(now, target)
        return False

    def cancel_failure(self, target: int, reporter: int) -> None:
        """A peer heard from the target again (:2911-2930)."""
        fi = self.failure_info.get(target)
        if fi is None:
            return
        fi.reporters.pop(reporter, None)
        if not fi.reporters:
            del self.failure_info[target]

    def can_mark_down(self, osd: int) -> bool:
        """(:2671-2705): nodown flag + minimum up ratio."""
        if osd in self.nodown:
            return False
        num = self.osdmap.max_osd
        if num == 0:
            return False
        pending_down = sum(
            1 for o, st in self.pending.new_state.items()
            if st & OSD_UP and self.osdmap.is_up(o))
        up = sum(1 for o in range(num) if self.osdmap.is_up(o)) - pending_down
        return (up / num) >= self.cct.conf.get("mon_osd_min_up_ratio")

    def check_failure(self, now: float, target: int) -> bool:
        """(:2764-2850): grace + distinct reporter subtrees."""
        if (self.pending.new_state.get(target, 0) & OSD_UP):
            return True                          # already pending
        fi = self.failure_info.get(target)
        if fi is None or not fi.reporters:
            return False
        failed_for = now - fi.max_failed_since()
        grace = self.cct.conf.get("osd_heartbeat_grace")
        level = self.cct.conf.get("mon_osd_reporter_subtree_level")
        subtrees = set()
        for reporter in fi.reporters:
            loc = self.osdmap.crush.get_full_location(reporter)
            subtrees.add(loc.get(level, f"osd.{reporter}"))
        if (failed_for >= grace and
                len(subtrees) >=
                self.cct.conf.get("mon_osd_min_down_reporters")):
            self.pending.new_state[target] = \
                self.pending.new_state.get(target, 0) | OSD_UP
            self.cct.dout("osd", 1,
                          f"osd.{target} failed ({len(subtrees)} reporters "
                          f"from different {level} after {failed_for:.1f} "
                          f">= grace {grace})")
            return True
        return False

    # -- boots / outs ------------------------------------------------------

    def osd_boot(self, osd: int, now: float | None = None) -> bool:
        """An OSD (re)announcing itself (OSDMonitor preprocess_boot
        path).  Returns False — the boot is REFUSED — while the OSD is
        flap-damped: marked down too often inside the markdown window,
        it stays down until :meth:`clear_markdown` (the reference's
        osd_markdown_log rejection).  ``now`` is accepted for symmetry
        with the failure-report API; damping is deliberately sticky
        (operator-cleared), not time-expiring, so the boot decision
        itself is clock-free."""
        if not self.markdown.allow_up(osd):
            if osd not in self._flap_logged:
                self._flap_logged.add(osd)
                self.cct.dout("mon", 1,
                              f"osd.{osd} boot denied: flapping "
                              f"(damped until operator clear)")
                clog = self._clog()
                if clog is not None:
                    clog.warn(
                        f"mon: osd.{osd} boot denied — flapping "
                        f"({self.markdown.count} mark-downs within "
                        f"{self.markdown.window:.0f}s); down until "
                        f"cleared", channel="mon")
            return False
        if not self.osdmap.is_up(osd):
            self.pending.new_state[osd] = \
                self.pending.new_state.get(osd, 0) | OSD_UP
        self.failure_info.pop(osd, None)
        return True

    def clear_markdown(self, osd: int) -> bool:
        """Operator clear of the flap-damping record ('ceph osd
        clear-markdown' analog): boots are allowed again (the OSD still
        has to boot — clearing does not itself mark up)."""
        was = self.markdown.clear(osd)
        self._flap_logged.discard(osd)
        clog = self._clog()
        if was and clog is not None:
            clog.info(f"mon: osd.{osd} markdown record cleared by "
                           f"operator", channel="mon")
        return was

    # -- commit (the Paxos propose_pending analog) -------------------------

    def propose_pending(self, now: float) -> OSDMap | None:
        if (not self.pending.new_state and not self.pending.new_weight and
                not self.pending.new_pg_temp and
                not self.pending.new_pg_upmap_items):
            return None
        inc, self.pending = self.pending, Incremental()
        if self.submit_fn is not None:
            # quorum mode: the commit arrives back via apply_committed
            # once a majority of monitors accepted it.  A refused submit
            # (no quorum) restores the pending state — it re-proposes on a
            # later tick rather than being parked as a stale Incremental.
            if not self.submit_fn(now, inc):
                self.pending = inc
            return None
        return self.apply_committed(now, inc)

    def apply_committed(self, now: float, inc: Incremental) -> OSDMap:
        """Apply a committed incremental to this monitor's map and notify
        subscribers — the refresh path every quorum member runs after a
        Paxos commit (single-mon mode calls it directly)."""
        old = self.osdmap
        self.osdmap = apply_incremental(old, inc)
        for o, st in inc.new_state.items():
            if st & OSD_UP:
                if old.is_up(o) and not self.osdmap.is_up(o):
                    self.down_stamp[o] = now
                    self.failure_info.pop(o, None)
                    # flap accounting: every committed mark-down counts
                    # toward the damping window
                    tripped = self.markdown.record_down(o, now)
                    clog = self._clog()
                    if clog is not None:
                        clog.warn(f"mon: osd.{o} marked down",
                                  channel="mon")
                        if tripped:
                            clog.warn(
                                f"mon: osd.{o} is flapping "
                                f"(>= {self.markdown.count} mark-downs "
                                f"in {self.markdown.window:.0f}s) — "
                                f"boots damped until operator clear",
                                channel="mon")
                elif not old.is_up(o) and self.osdmap.is_up(o):
                    self.down_stamp.pop(o, None)
                    clog = self._clog()
                    if clog is not None:
                        clog.info(f"mon: osd.{o} marked up",
                                  channel="mon")
        for fn in self.subscribers:
            fn(self.osdmap, inc)
        return self.osdmap

    def tick(self, now: float) -> OSDMap | None:
        """Periodic work: age pending failures, auto-out long-down OSDs."""
        for target in list(self.failure_info):
            if self.can_mark_down(target):
                self.check_failure(now, target)
        out_after = self.cct.conf.get("mon_osd_down_out_interval")
        for o, since in list(self.down_stamp.items()):
            if (now - since >= out_after and self.osdmap.is_in(o) and
                    not self.osdmap.is_up(o)):
                self.pending.new_weight[o] = 0
                self.cct.dout("osd", 1, f"osd.{o} auto-out after "
                                        f"{now - since:.0f}s down")
                del self.down_stamp[o]
        return self.propose_pending(now)
