"""Cluster control plane: failure detection and map commits (SURVEY.md §5).

Monitor (monitor.py) mirrors the reference's OSDMonitor failure path
(src/mon/OSDMonitor.cc prepare_failure :2874, check_failure :2764,
can_mark_down :2671) over this framework's OSDMap incrementals; heartbeats
(heartbeat.py) mirror the OSD's peer-ping machinery
(src/osd/OSD.cc:4547-4996)."""
from .monitor import Monitor
from .heartbeat import HeartbeatAgent, VirtualClock
from .paxos import MonCluster, PaxosMonitor

__all__ = ["Monitor", "HeartbeatAgent", "VirtualClock", "MonCluster",
           "PaxosMonitor"]
