"""ceph_erasure_code_benchmark-compatible CLI.

Flag and output parity with the reference harness
(reference: src/test/erasure-code/ceph_erasure_code_benchmark.cc:40-139):
``--plugin --workload --size --iterations --erasures --erased
--erasures-generation --parameter k=v``; output is one line
``<elapsed_seconds>\t<iterations * size/1024 KiB>`` (:179,310), so
MiB/s = (KiB/1024)/seconds exactly as qa/workunits/erasure-code/bench.sh
computes it.

TPU-specific extensions (off by default; defaults match the reference):
  --batch B      encode/decode B stripes per device dispatch through the
                 plugin codec (the ECBackend-style cross-stripe batching
                 the per-stripe reference loop cannot do, SURVEY.md §3.2)
  --device-resident   keep buffers on device between iterations (models the
                 sidecar's persistent device buffers; excludes the PCIe/
                 tunnel transfer from the timed loop)
  --directory    plugin directory (erasure_code_dir analog)
"""
from __future__ import annotations

import argparse
import random
import sys
import time

import numpy as np

from ..plugins.registry import ErasureCodePluginRegistry


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ec_bench", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("-s", "--size", type=int, default=1024 * 1024,
                   help="size of the buffer to be encoded")
    p.add_argument("-i", "--iterations", type=int, default=1)
    p.add_argument("-p", "--plugin", default="jerasure")
    p.add_argument("-w", "--workload", choices=["encode", "decode"],
                   default="encode")
    p.add_argument("-e", "--erasures", type=int, default=1)
    p.add_argument("--erased", type=int, action="append", default=[])
    p.add_argument("-E", "--erasures-generation", dest="erasures_generation",
                   choices=["random", "exhaustive"], default="random")
    p.add_argument("-P", "--parameter", action="append", default=[],
                   metavar="KEY=VALUE")
    p.add_argument("--directory", default="")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--device-resident", dest="device_resident",
                   action="store_true")
    return p


class ErasureCodeBench:
    def __init__(self, args: argparse.Namespace):
        self.args = args
        self.profile = {}
        for kv in args.parameter:
            if kv.count("=") != 1:
                print(f"--parameter {kv} ignored because it does not contain "
                      f"exactly one =", file=sys.stderr)
                continue
            key, value = kv.split("=")
            self.profile[key] = value
        self.k = int(self.profile.get("k", "7"))
        self.m = int(self.profile.get("m", "3"))

    def _factory(self):
        registry = ErasureCodePluginRegistry.instance()
        return registry.factory(self.args.plugin, self.args.directory,
                                self.profile)

    def _input(self) -> bytes:
        return b"X" * self.args.size

    # -- encode (reference :151-181) ---------------------------------------

    def encode(self) -> int:
        ec = self._factory()
        data = self._input()
        want = set(range(ec.get_chunk_count()))
        if self.args.batch > 1 or self.args.device_resident:
            return self._encode_batched(ec, data)
        begin = time.perf_counter()
        for _ in range(self.args.iterations):
            ec.encode(want, data)
        elapsed = time.perf_counter() - begin
        print(f"{elapsed:.6f}\t{self.args.iterations * (self.args.size // 1024)}")
        return 0

    def _encode_batched(self, ec, data: bytes) -> int:
        import jax
        import jax.numpy as jnp
        batch = self.args.batch
        prepared = ec.encode_prepare(data)
        k = ec.get_data_chunk_count()
        stripe = np.stack([prepared[ec.chunk_index(i)] for i in range(k)])
        folded = np.broadcast_to(stripe, (batch,) + stripe.shape)
        folded = np.ascontiguousarray(
            folded.swapaxes(0, 1).reshape(k, batch * stripe.shape[1]))
        codec = ec.codec
        if self.args.device_resident:
            dev = jax.device_put(jnp.asarray(folded))
            codec.encode_device(dev).block_until_ready()   # warm/compile
            begin = time.perf_counter()
            for _ in range(self.args.iterations):
                codec.encode_device(dev).block_until_ready()
            elapsed = time.perf_counter() - begin
        else:
            codec.encode(folded)                            # warm/compile
            begin = time.perf_counter()
            for _ in range(self.args.iterations):
                codec.encode(folded)
            elapsed = time.perf_counter() - begin
        kib = self.args.iterations * batch * (self.args.size // 1024)
        print(f"{elapsed:.6f}\t{kib}")
        return 0

    # -- decode (reference :246-311) ---------------------------------------

    def decode(self) -> int:
        ec = self._factory()
        data = self._input()
        n = ec.get_chunk_count()
        want = set(range(n))
        encoded = ec.encode(want, data)
        if self.args.erased:
            for i in self.args.erased:
                encoded.pop(i, None)

        if self.args.batch > 1 or self.args.device_resident:
            return self._decode_batched(ec, encoded)

        begin = time.perf_counter()
        for _ in range(self.args.iterations):
            if self.args.erasures_generation == "exhaustive":
                code = self._decode_exhaustive(ec, encoded, encoded, 0,
                                               self.args.erasures)
                if code:
                    return code
            elif self.args.erased:
                ec.decode(want, encoded, 0)
            else:
                chunks = dict(encoded)
                for _ in range(self.args.erasures):
                    while True:
                        erasure = random.randrange(n)
                        if erasure in chunks:
                            break
                    del chunks[erasure]
                ec.decode(want, chunks, 0)
        elapsed = time.perf_counter() - begin
        print(f"{elapsed:.6f}\t{self.args.iterations * (self.args.size // 1024)}")
        return 0

    def _decode_exhaustive(self, ec, all_chunks, chunks, i, want_erasures) -> int:
        """Try all erasure combinations, verifying content
        (reference decode_erasures :200-245)."""
        if want_erasures == 0:
            want_to_read = set(range(ec.get_chunk_count())) - set(chunks)
            decoded = ec.decode(want_to_read, chunks, 0)
            for chunk in want_to_read:
                if not np.array_equal(decoded[chunk], all_chunks[chunk]):
                    print(f"chunk {chunk} content and recovered content are "
                          f"different", file=sys.stderr)
                    return -1
            return 0
        for j in range(i, ec.get_chunk_count()):
            if j not in chunks:
                continue
            one_less = dict(chunks)
            del one_less[j]
            code = self._decode_exhaustive(ec, all_chunks, one_less, j + 1,
                                           want_erasures - 1)
            if code:
                return code
        return 0

    def _decode_batched(self, ec, encoded) -> int:
        n = ec.get_chunk_count()
        erased = self.args.erased or \
            sorted(random.sample(range(n), self.args.erasures))
        src = [i for i in range(n) if i not in erased][:ec.get_data_chunk_count()]
        stripe = np.stack([encoded[i] for i in src])
        batch = np.broadcast_to(stripe, (self.args.batch,) + stripe.shape)
        batch = np.ascontiguousarray(batch)
        codec = ec.codec
        codec.decode_batch(batch, src, erased)              # warm/compile
        begin = time.perf_counter()
        for _ in range(self.args.iterations):
            codec.decode_batch(batch, src, erased)
        elapsed = time.perf_counter() - begin
        kib = self.args.iterations * self.args.batch * (self.args.size // 1024)
        print(f"{elapsed:.6f}\t{kib}")
        return 0

    def run(self) -> int:
        if self.args.workload == "encode":
            return self.encode()
        return self.decode()


def main(argv=None) -> int:
    from ..utils.platform import honour_jax_platforms_env
    honour_jax_platforms_env()   # axon sitecustomize override
    args = build_parser().parse_args(argv)
    try:
        return ErasureCodeBench(args).run()
    except (ValueError, FileNotFoundError, RuntimeError) as e:
        print(str(e), file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
