"""rados bench equivalent: cluster-level EC pool write/read benchmark.

Mirror of the reference's ObjBencher workloads (reference:
src/common/obj_bencher.h:64 — ``write_bench``/``seq_read_bench`` driven by
``rados bench <seconds> write|seq``; output block with total time, ops,
bandwidth MB/sec, IOPS and latency) over :class:`ceph_tpu.cluster
.MiniCluster` — this is BASELINE.md run-matrix config #4 (vstart EC pool +
rados bench) without external daemons.

CLI:  python -m ceph_tpu.bench.rados_bench --seconds 10 write
      [--osds 12] [--k 4] [--m 2] [--pg-num 8] [--object-size 4M]
      [--plugin jax_rs] [--device numpy|jax] [--concurrency 16]
"""
from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from ..common import parse_size
from ..cluster import MiniCluster

BENCH_PREFIX = "benchmark_data"


def write_bench(cluster, pool_id: int, seconds: float, object_size: int,
                concurrency: int = 16, out=None) -> dict:
    """obj_bencher.cc write_bench shape: submit `concurrency` writes, drain,
    repeat until the clock runs out."""
    w = out.write if out is not None else (lambda s: None)
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 256, size=object_size, dtype=np.uint8).tobytes()
    t0 = time.perf_counter()
    done = 0
    latencies = []
    while time.perf_counter() - t0 < seconds:
        batch_start = time.perf_counter()
        for i in range(concurrency):
            cluster.put(pool_id, f"{BENCH_PREFIX}_{done + i}", payload,
                        deliver=False)
        cluster.deliver_all()
        dt = time.perf_counter() - batch_start
        # each op's submit-to-commit latency spans the whole batch drain
        # (rados bench with N in flight reports the same shape)
        latencies.extend([dt] * concurrency)
        done += concurrency
    elapsed = time.perf_counter() - t0
    stats = _report("write", elapsed, done, object_size, latencies, w)
    return stats


def seq_read_bench(cluster, pool_id: int, max_objects: int,
                   object_size: int, out=None) -> dict:
    w = out.write if out is not None else (lambda s: None)
    t0 = time.perf_counter()
    latencies = []
    done = 0
    for i in range(max_objects):
        s0 = time.perf_counter()
        data = cluster.get(pool_id, f"{BENCH_PREFIX}_{i}", object_size)
        assert len(data) == object_size
        latencies.append(time.perf_counter() - s0)
        done += 1
    elapsed = time.perf_counter() - t0
    return _report("seq", elapsed, done, object_size, latencies, w)


def _report(kind, elapsed, ops, object_size, latencies, w) -> dict:
    bw = ops * object_size / elapsed / 1e6 if elapsed else 0.0
    iops = ops / elapsed if elapsed else 0.0
    avg_lat = sum(latencies) / len(latencies) if latencies else 0.0
    max_lat = max(latencies) if latencies else 0.0
    w(f"Total time run:         {elapsed:.6f}\n")
    w(f"Total {'writes made' if kind == 'write' else 'reads made'}:     "
      f"{ops}\n")
    w(f"{'Write' if kind == 'write' else 'Read'} size:             "
      f"{object_size}\n")
    w(f"Object size:            {object_size}\n")
    w(f"Bandwidth (MB/sec):     {bw:.4g}\n")
    w(f"Average IOPS:           {iops:.0f}\n")
    w(f"Average Latency(s):     {avg_lat:.6g}\n")
    w(f"Max latency(s):         {max_lat:.6g}\n")
    return {"elapsed": elapsed, "ops": ops, "bandwidth_mb_s": bw,
            "iops": iops, "avg_latency_s": avg_lat}


def main(argv=None) -> int:
    from ..utils.platform import honour_jax_platforms_env
    honour_jax_platforms_env()   # axon sitecustomize override
    ap = argparse.ArgumentParser(prog="rados_bench",
                                 description=__doc__.splitlines()[0])
    ap.add_argument("mode", choices=["write", "seq"])
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--osds", type=int, default=12)
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--m", type=int, default=2)
    ap.add_argument("--pg-num", type=int, default=8)
    ap.add_argument("--object-size", default="4M")
    ap.add_argument("--chunk-size", default="64K")
    ap.add_argument("--plugin", default="jax_rs")
    ap.add_argument("--device", default="numpy",
                    help="jax_rs device: numpy|jax|auto")
    ap.add_argument("--technique", default="reed_sol_van")
    ap.add_argument("--concurrency", type=int, default=16)
    args = ap.parse_args(argv)

    object_size = parse_size(args.object_size)
    cluster = MiniCluster(n_osds=args.osds,
                          chunk_size=parse_size(args.chunk_size))
    profile = {"plugin": args.plugin, "k": str(args.k), "m": str(args.m),
               "technique": args.technique}
    if args.plugin == "jax_rs":
        profile["device"] = args.device
    pool = cluster.create_ec_pool("bench", profile, pg_num=args.pg_num)
    print(f"# {args.osds} osds, pool 'bench' k={args.k} m={args.m} "
          f"pg_num={args.pg_num} plugin={args.plugin}", file=sys.stderr)

    if args.mode == "write":
        write_bench(cluster, pool, args.seconds, object_size,
                    args.concurrency, out=sys.stdout)
    else:
        # write the dataset first, then time sequential reads
        n = max(1, int(args.seconds * 4))
        for i in range(n):
            cluster.put(pool, f"{BENCH_PREFIX}_{i}",
                        b"\xab" * object_size)
        seq_read_bench(cluster, pool, n, object_size, out=sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
