"""Nearest-rank percentile: the ONE rank definition every surface uses.

Three consumers grew their own copy of this five-liner — the serving
workload generator (bench p99), the trace report (span p99) and the
time-series report — with a "change BOTH if the rank definition ever
moves" comment standing in for actual sharing.  ISSUE 10 unifies them:
bench p99, trace p99 and SLO-objective p99 are compared against each
other (the perf gate diffs bench p99; the SLO engine judges ops against
a p99 target derived from the same distribution), so a drifted rank
definition would make the gate and the health surface disagree about
the same latency data.

Stdlib-only on purpose: ``tools/trace_report.py`` / ``tools/ts_report.py``
load this file by PATH (``importlib.util.spec_from_file_location``), so
they stay runnable without importing the ``ceph_tpu`` package (which
pulls numpy).  ``tests/test_critpath.py`` carries the AST guard: no other
file in the repo may define a function named ``percentile`` /
``percentile_us`` / ``nearest_rank`` again.
"""
from __future__ import annotations

import math


def nearest_rank(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over a PRE-SORTED sequence (q in
    [0, 100]).  The empirical-distribution definition (rank =
    ceil(q/100 * n), 1-based): p100 is the max, p0 clamps to the min,
    and no interpolation ever invents a value that was not observed."""
    if not sorted_vals:
        return 0.0
    rank = max(1, math.ceil(q / 100.0 * len(sorted_vals)))
    return sorted_vals[min(rank, len(sorted_vals)) - 1]


def percentile(values, q: float) -> float:
    """Convenience over an UNSORTED sequence (sorts a copy)."""
    return nearest_rank(sorted(values), q)


def weighted_nearest_rank(sorted_pairs, q: float) -> float:
    """Nearest-rank percentile over PRE-SORTED ``(value, weight)`` pairs
    (q in [0, 100]).  Each observation stands for ``weight`` ops (the
    tracer's head-sampling 1/rate de-bias): the rank walks cumulative
    weight instead of cumulative count, and with all weights 1.0 the
    result matches :func:`nearest_rank` exactly."""
    if not sorted_pairs:
        return 0.0
    total = sum(w for _v, w in sorted_pairs)
    if total <= 0.0:
        return 0.0
    target = max(q, 1e-12) / 100.0 * total
    acc = 0.0
    for v, w in sorted_pairs:
        acc += w
        if acc >= target - 1e-9:
            return v
    return sorted_pairs[-1][0]
