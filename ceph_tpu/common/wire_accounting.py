"""Wire accounting: byte/op counters for every message on the wire.

The network plane was the last invisible subsystem: spans told us WHEN a
sub-op crossed a daemon boundary but never HOW MUCH moved, so
ROADMAP item 3's success metric (bytes-on-wire per byte repaired,
RapidRAID arXiv:1207.6744) and item 4's (wire bytes per served op) were
unmeasurable.  This module is the counting house both the in-process
cluster bus (backend/messages.py) and the TCP messenger (net.py) report
into — the role the reference's ``Messenger::dispatch_throttle`` /
``ms_crc``/perf counters play in src/msg.

One :class:`WireAccounting` owns ONE ``wire.<name>`` perf collection:

- ``tx_bytes``/``tx_msgs`` and ``rx_bytes``/``rx_msgs`` totals;
- per-op-class rollups ``class_bytes:<cls>`` / ``class_msgs:<cls>``
  attributed from the message's :class:`~ceph_tpu.common.tracer.
  TraceContext` owner class (client/serving/recovery/scrub/rebalance;
  untraced control chatter lands on ``other``).  **Invariant: the class
  rollups partition the totals** — every accounted message charges
  exactly one class, so ``sum(class_bytes:*) == tx_bytes + rx_bytes``
  (pinned by tests/test_observability.py);
- an ``rpc_latency_ms`` histogram (the messenger-side op latency the
  reference's ``ms_dispatch`` perf counters carry);
- ``send_queue_depth``/``send_queue_peak`` gauges (undelivered messages
  parked at the destination — the AsyncMessenger out_q depth).

Per-message-TYPE byte/op counts live in a plain locked dict (the type
set is open-ended; perf collections want fixed keys) and export as the
labelled ``ceph_tpu_wire_bytes{owner,msg_type,dir}`` prometheus family
via :func:`live_wire_accountants`.

Message SIZES: transports that frame real bytes (wire-mode bus, net.py
sockets) account the frame length; the deterministic in-process bus
estimates via the per-type sizer registry (:func:`register_wire_sizes`).
Every message class sent through PGChannel/RPC must register a sizer —
tests/test_wire_guard.py enforces it by AST + registry, so no message
type ships unmetered.
"""
from __future__ import annotations

import threading
import weakref
from typing import Callable

from . import instruments
from .perf_counters import PerfCountersBuilder

# the owner classes wire bytes attribute to: device_attribution's
# canonical set plus "other" for untraced control-plane chatter
# (peering queries, activation fan-out, handshakes)
WIRE_CLASSES = ("client", "serving", "recovery", "scrub", "rebalance",
                "other")

# message overhead charged per estimated (non-framed) message: stands in
# for the v2 preamble + per-segment crc + type name segment
MSG_OVERHEAD = 32

_RPC_LAT_BUCKETS_MS = [0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0, 500.0,
                       1000.0]

# live accountants, for the prometheus wire families (the weakref
# pattern of osd_daemon.live_daemons / stats.live_aggregators)
_ACCOUNTANTS: "weakref.WeakSet[WireAccounting]" = weakref.WeakSet()

# message type name -> sizer(msg) -> payload bytes
_SIZERS: dict[str, Callable] = {}


def live_wire_accountants() -> list["WireAccounting"]:
    return list(_ACCOUNTANTS)


def register_wire_sizes(mapping: dict) -> None:
    """Register payload sizers: ``{MessageClass|name: sizer(msg)->int}``.
    Called at module import next to the message definitions
    (backend/messages.py, net.py) so the registry is complete the moment
    the types are sendable."""
    for key, fn in mapping.items():
        name = key if isinstance(key, str) else key.__name__
        _SIZERS[name] = fn


def registered_wire_types() -> set[str]:
    """The metered message-type names (the test_wire_guard surface)."""
    return set(_SIZERS)


def wire_class(ctx) -> str:
    """The op class a message's bytes charge to: the riding
    TraceContext's owner class, else ``other`` (untraced control
    chatter)."""
    cls = getattr(ctx, "op_class", None)
    return cls if cls in WIRE_CLASSES else ("other" if cls is None
                                            else "client")


def wire_size(msg) -> int:
    """Estimated on-wire size of ``msg`` (payload + MSG_OVERHEAD).
    Unregistered types fall back to a pickle measurement — the bytes are
    still counted (the completeness invariant holds), but the fallback
    bumps ``unsized_msgs`` and the AST guard fails the build, so the
    fallback never quietly becomes the norm."""
    sizer = _SIZERS.get(type(msg).__name__)
    if sizer is None:
        import pickle
        try:
            return MSG_OVERHEAD + len(pickle.dumps(msg))
        except Exception:
            return MSG_OVERHEAD
    return MSG_OVERHEAD + int(sizer(msg))


def _bytes_len(v) -> int:
    return len(v) if isinstance(v, (bytes, bytearray, memoryview)) else 0


def blob_size(obj, _depth: int = 0) -> int:
    """Sum of every bytes-like payload nested in ``obj`` (dicts/lists/
    tuples/sets walked; depth-bounded).  The shared sizer for messages
    whose weight is their buffers (RPC args, read replies, omap
    payloads)."""
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return len(obj)
    if _depth >= 6:
        return 0
    if isinstance(obj, dict):
        return sum(blob_size(k, _depth + 1) + blob_size(v, _depth + 1)
                   for k, v in obj.items())
    if isinstance(obj, (list, tuple, set, frozenset)):
        return sum(blob_size(v, _depth + 1) for v in obj)
    if isinstance(obj, str):
        return len(obj)
    return 8 if isinstance(obj, (int, float)) else 0


class WireAccounting:
    """Per-transport wire counters: one ``wire.<name>`` perf collection
    plus the per-type table and RPC latency summaries."""

    def __init__(self, cct=None, name: str = "wire"):
        from .context import default_context
        self.cct = cct if cct is not None else default_context()
        self.name = name
        b = (
            PerfCountersBuilder(f"wire.{name}")
            .add_u64_counter("tx_msgs", "messages sent on the wire")
            .add_u64_counter("tx_bytes", "bytes sent on the wire")
            .add_u64_counter("rx_msgs", "messages received from the wire")
            .add_u64_counter("rx_bytes", "bytes received from the wire")
            .add_u64_counter("unsized_msgs",
                             "messages accounted via the pickle fallback "
                             "(a type missing its wire sizer)")
            .add_u64("send_queue_depth",
                     "undelivered messages parked at the busiest "
                     "destination at the last send")
            .add_u64("send_queue_peak",
                     "peak send-queue depth observed on any destination")
            .add_histogram("rpc_latency_ms", _RPC_LAT_BUCKETS_MS,
                           "RPC dispatch wall time (server side) in "
                           "milliseconds")
        )
        for cls in WIRE_CLASSES:
            b.add_u64_counter(f"class_bytes:{cls}",
                              f"wire bytes attributed to {cls} ops")
            b.add_u64_counter(f"class_msgs:{cls}",
                              f"wire messages attributed to {cls} ops")
        self.perf = b.create_perf_counters()
        self.cct.perf.add(self.perf)
        self._lock = threading.Lock()
        # type -> {"tx_msgs","tx_bytes","rx_msgs","rx_bytes"} — the
        # read-side base; live mutation happens in per-thread shards
        self._types: dict[str, dict] = {}
        # rpc method -> [count, seconds_sum] — same split
        self._rpc: dict[str, list] = {}
        # per-thread fast-path state (ISSUE 18): cached perf-counter
        # cells per (direction, class) plus this thread's type/rpc
        # shards, all mutated lock-free by their owner and folded under
        # self._lock at read boundaries
        self._tls = threading.local()
        self._type_shards: dict[int, dict] = {}
        self._rpc_shards: dict[int, dict] = {}
        self._queue_peak = 0
        _ACCOUNTANTS.add(self)

    def _fast(self) -> tuple[dict, dict, dict, dict]:
        """This thread's (cell cache, type shard, rpc shard, misc cache)
        tuple, registered for read-time folding on first use."""
        st = getattr(self._tls, "state", None)
        if st is None:
            ident = threading.get_ident()
            types: dict = {}
            rpc: dict = {}
            with self._lock:
                # a dead thread's ident was reused: bank its shards
                # into the base before the new owner takes the slot
                self._absorb_shards_locked(ident)
                self._type_shards[ident] = types
                self._rpc_shards[ident] = rpc
            st = self._tls.state = ({}, types, rpc, {})
        return st

    def _absorb_shards_locked(self, ident: int) -> None:
        old = self._type_shards.pop(ident, None)
        if old:
            for t, row in old.items():
                base = self._types.get(t)
                if base is None:
                    base = self._types[t] = {
                        "tx_msgs": 0, "tx_bytes": 0,
                        "rx_msgs": 0, "rx_bytes": 0}
                base["tx_msgs"] += row[0]
                base["tx_bytes"] += row[1]
                base["rx_msgs"] += row[2]
                base["rx_bytes"] += row[3]
        old = self._rpc_shards.pop(ident, None)
        if old:
            for m, (c, s) in old.items():
                rec = self._rpc.setdefault(m, [0, 0.0])
                rec[0] += c
                rec[1] += s

    # -- per-message -------------------------------------------------------

    def _account(self, direction: str, type_name: str, nbytes: int,
                 ctx) -> None:
        if not instruments.enabled():
            return
        n = int(nbytes)
        if n < 0:
            n = 0
        cls = wire_class(ctx)
        cells, types = self._fast()[:2]
        key = (direction, cls)
        row = cells.get(key)
        if row is None:
            # first op of this (direction, class) on this thread: bind
            # the four perf cells + the type-shard index ONCE — the
            # steady state is four list bumps and two dict lookups
            pc = self.perf
            row = cells[key] = (pc._cell(f"{direction}_msgs"),
                                pc._cell(f"{direction}_bytes"),
                                pc._cell(f"class_msgs:{cls}"),
                                pc._cell(f"class_bytes:{cls}"),
                                0 if direction == "tx" else 2)
        row[0][0] += 1
        row[1][0] += n
        row[2][0] += 1
        row[3][0] += n
        t = types.get(type_name)
        if t is None:
            t = types[type_name] = [0, 0, 0, 0]
        di = row[4]
        t[di] += 1
        t[di + 1] += n

    def account_tx(self, type_name: str, nbytes: int, ctx=None) -> None:
        self._account("tx", type_name, nbytes, ctx)

    def account_rx(self, type_name: str, nbytes: int, ctx=None) -> None:
        self._account("rx", type_name, nbytes, ctx)

    def account_msg(self, msg, nbytes: int | None = None,
                    ctx=None) -> None:
        """Account one outbound message object: real frame length when
        the transport has it, the sizer estimate otherwise."""
        if nbytes is None:
            if type(msg).__name__ not in _SIZERS:
                self.perf.inc("unsized_msgs")
            nbytes = wire_size(msg)
        self.account_tx(type(msg).__name__, nbytes,
                        ctx if ctx is not None
                        else getattr(msg, "trace", None))

    def note_queue_depth(self, depth: int) -> None:
        if not instruments.enabled():
            return
        d = int(depth)
        self.perf.set("send_queue_depth", d)
        # plain-attribute peak pre-check: the old get() folded every
        # thread's cells under the lock ON EVERY SEND; the gauge write
        # happens only on a new peak now
        if d > self._queue_peak:
            self._queue_peak = d
            self.perf.set("send_queue_peak", d)

    def observe_rpc(self, method: str, seconds: float) -> None:
        if not instruments.enabled():
            return
        st = self._fast()
        rpc, misc = st[2], st[3]
        hist = misc.get("rpc_hist")
        if hist is None:
            # bind the histogram cell + bucket bounds once per thread;
            # the steady state is one linear bucket scan + three bumps
            # (hinc() re-resolves the metric and cell on every call)
            m = self.perf._metrics["rpc_latency_ms"]
            c = self.perf._cell("rpc_latency_ms")
            if c[3] is None:
                c[3] = [0] * (len(m.buckets) + 1)
            hist = misc["rpc_hist"] = (c, tuple(m.buckets))
        c, bounds = hist
        ms = seconds * 1000.0
        i = 0
        n = len(bounds)
        while i < n and ms > bounds[i]:
            i += 1
        c[3][i] += 1
        c[1] += ms
        c[2] += 1
        rec = rpc.get(method)
        if rec is None:
            rec = rpc[method] = [0, 0.0]
        rec[0] += 1
        rec[1] += seconds

    # -- read surfaces -----------------------------------------------------

    def per_type(self) -> dict[str, dict]:
        """Per-message-type table (the prometheus ``ceph_tpu_wire_bytes``
        family + the `daemonperf` wire columns), live shards folded in
        non-destructively."""
        with self._lock:
            out = {t: dict(v) for t, v in self._types.items()}
            for shard in list(self._type_shards.values()):
                # list(dict.items()) is one GIL-atomic snapshot; the
                # owner may keep appending — later reads catch up
                for t, row in list(shard.items()):
                    e = out.get(t)
                    if e is None:
                        e = out[t] = {"tx_msgs": 0, "tx_bytes": 0,
                                      "rx_msgs": 0, "rx_bytes": 0}
                    e["tx_msgs"] += row[0]
                    e["tx_bytes"] += row[1]
                    e["rx_msgs"] += row[2]
                    e["rx_bytes"] += row[3]
        return dict(sorted(out.items()))

    def rpc_methods(self) -> dict[str, dict]:
        with self._lock:
            agg: dict[str, list] = {m: list(v)
                                    for m, v in self._rpc.items()}
            for shard in list(self._rpc_shards.values()):
                for m, row in list(shard.items()):
                    rec = agg.setdefault(m, [0, 0.0])
                    rec[0] += row[0]
                    rec[1] += row[1]
        return {m: {"count": c, "sum_s": round(s, 6),
                    "avg_ms": round(s / c * 1000.0, 3) if c else 0.0}
                for m, (c, s) in sorted(agg.items())}

    def class_bytes(self) -> dict[str, float]:
        return {cls: self.perf.get(f"class_bytes:{cls}")
                for cls in WIRE_CLASSES}

    def totals(self) -> dict[str, float]:
        return {k: self.perf.get(k)
                for k in ("tx_msgs", "tx_bytes", "rx_msgs", "rx_bytes")}

    def dump(self) -> dict:
        """The flight-recorder / admin snapshot."""
        return {"totals": self.totals(),
                "classes": self.class_bytes(),
                "types": self.per_type(),
                "rpc": self.rpc_methods(),
                "queue_peak": self.perf.get("send_queue_peak")}

    def close(self) -> None:
        self.cct.perf.remove(self.perf.name)
        _ACCOUNTANTS.discard(self)
