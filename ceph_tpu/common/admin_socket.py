"""Admin socket: named command registry answering JSON.

Mirror of the reference's admin socket (reference:
src/common/admin_socket.cc — per-daemon unix socket answering registered
commands such as ``perf dump``, ``config show``, ``dump_ops_in_flight``).
In-process here (tests and tools call it directly); the wire is ancillary,
the command surface is the contract.
"""
from __future__ import annotations

import json
import threading
from typing import Callable


class AdminSocket:
    def __init__(self):
        self._hooks: dict[str, tuple[Callable, str]] = {}
        self._lock = threading.Lock()
        self.register("help", self._help, "list available commands")

    def _help(self, **kwargs):
        with self._lock:                    # snapshot under the lock
            return {cmd: desc
                    for cmd, (_, desc) in sorted(self._hooks.items())}

    def register(self, command: str, fn: Callable[..., object],
                 description: str = "") -> None:
        with self._lock:
            if command in self._hooks:
                raise ValueError(f"command {command!r} already registered")
            self._hooks[command] = (fn, description)

    def unregister(self, command: str) -> None:
        with self._lock:
            self._hooks.pop(command, None)

    def get(self, command: str):
        """The registered hook fn, or None — lets a takeover-registered
        command's owner check it still holds the name before removing."""
        with self._lock:
            hook = self._hooks.get(command)
        return hook[0] if hook else None

    def call(self, command: str, **kwargs):
        with self._lock:
            hook = self._hooks.get(command)
        if hook is None:
            raise KeyError(f"unknown command {command!r}")
        return hook[0](**kwargs)

    def call_json(self, command: str, **kwargs) -> str:
        return json.dumps(self.call(command, **kwargs), default=str)
