"""Op tracker: per-op event history + in-flight/slow-op dumps.

Mirror of the reference's OpTracker (reference: src/common/TrackedOp.{h,cc};
``op->mark_event`` timeline entries surfaced over the admin socket as
``dump_ops_in_flight`` / ``dump_historic_ops``; the FUNCTRACE/OID event
usage at src/osd/OSD.cc:9549-9578 is the same mechanism at the dispatch
points).  Slow-op handling follows the reference's complaint path
(``osd_op_complaint_time``, TrackedOp.cc check_ops_in_flight): an op whose
duration exceeds the configurable threshold is flagged ``slow``, counted on
the owning subsystem's ``slow_ops`` perf counter, and kept in the historic
dump with the flag set.  Every ``mark_event`` also lands on the process
span tracer as an instant event, and ``finish`` emits the whole op as a
complete span, so ``trace dump`` interleaves op timelines with the
codec/kernel spans they caused.
"""
from __future__ import annotations

import itertools
import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass, field

from .tracer import default_tracer


@dataclass
class TrackedOp:
    tracker: "OpTracker"
    seq: int
    description: str
    initiated_at: float = field(default_factory=time.time)
    events: list[tuple[float, str]] = field(default_factory=list)
    slow: bool = False
    _done: bool = False

    def mark_event(self, event: str) -> None:
        self.events.append((time.time(), event))
        default_tracer().instant(f"op.{event}", cat="optracker",
                                 seq=self.seq, desc=self.description)

    def finish(self) -> None:
        if not self._done:
            self._done = True
            self.mark_event("done")
            self.tracker._finish(self)
            default_tracer().complete("op", self.initiated_at,
                                      self.duration, cat="optracker",
                                      seq=self.seq, desc=self.description,
                                      slow=self.slow)

    @property
    def age(self) -> float:
        return time.time() - self.initiated_at

    @property
    def duration(self) -> float:
        end = self.events[-1][0] if self._done and self.events \
            else time.time()
        return end - self.initiated_at

    def dump(self) -> dict:
        return {
            "description": self.description,
            "initiated_at": self.initiated_at,
            "age": self.age,
            "duration": self.duration,
            "slow": self.slow,
            "type_data": {
                "events": [{"time": t, "event": e} for t, e in self.events],
            },
        }

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.finish()
        return False


class OpTracker:
    """In-flight registry + bounded history of completed/slow ops.

    ``conf`` (a ConfigProxy) supplies — and live-updates, via observer —
    the ``osd_op_complaint_time`` slow threshold; ``perf`` is the owning
    subsystem's PerfCounters, bumped on its ``slow_ops`` key when present.
    """

    def __init__(self, history_size: int = 20, history_duration: float = 600.0,
                 complaint_time: float = 30.0, conf=None, perf=None):
        self._inflight: dict[int, TrackedOp] = {}
        self._history: deque[TrackedOp] = deque(maxlen=history_size)
        self._slow: deque[TrackedOp] = deque(maxlen=history_size)
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        self.history_duration = history_duration
        self.complaint_time = complaint_time
        self.perf = perf
        if conf is not None and "osd_op_complaint_time" in conf.schema:
            self.complaint_time = float(conf.get("osd_op_complaint_time"))
            # WEAK observer: the ConfigProxy outlives trackers (one per
            # PG backend, many per long-lived Context) and has no
            # removal API — a strong closure would pin every dead
            # tracker + its op history forever
            ref = weakref.ref(self)

            def _obs(_name, v, _ref=ref):
                t = _ref()
                if t is not None:
                    t.complaint_time = float(v)
            conf.add_observer("osd_op_complaint_time", _obs)

    def create_request(self, description: str) -> TrackedOp:
        op = TrackedOp(self, next(self._seq), description)
        op.mark_event("initiated")
        with self._lock:
            self._inflight[op.seq] = op
        return op

    def _finish(self, op: TrackedOp) -> None:
        slow = op.duration >= self.complaint_time
        with self._lock:
            self._inflight.pop(op.seq, None)
            self._history.append(op)
            if slow:
                op.slow = True
                self._slow.append(op)
        if slow and self.perf is not None:
            try:
                self.perf.inc("slow_ops")
            except KeyError:
                pass                     # owner declared no slow_ops counter

    def get_age_histogram(self) -> dict[str, int]:
        with self._lock:
            ops = list(self._inflight.values())
        hist: dict[str, int] = {}
        for op in ops:
            bucket = "<1s" if op.age < 1 else \
                "<10s" if op.age < 10 else "<60s" if op.age < 60 else ">=60s"
            hist[bucket] = hist.get(bucket, 0) + 1
        return hist

    def dump_ops_in_flight(self) -> dict:
        with self._lock:
            ops = [op.dump() for op in self._inflight.values()]
        return {"ops": ops, "num_ops": len(ops)}

    def dump_historic_ops(self) -> dict:
        with self._lock:
            ops = [op.dump() for op in self._history]
        return {"ops": ops, "num_ops": len(ops)}

    def dump_historic_slow_ops(self) -> dict:
        with self._lock:
            ops = [op.dump() for op in self._slow]
        return {"ops": ops, "num_ops": len(ops)}
