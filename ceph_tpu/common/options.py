"""Typed option schema + runtime config store with live observers.

Mirror of the reference's single typed option table and runtime store
(reference: src/common/options.cc — ~8400-line Option table, each entry
typed with level/default/description/see_also/flags; src/common/config.cc —
``md_config_t`` with registered observers notified on ``ceph config set``
style updates).  The schema here carries the subset this framework uses,
with the same names where the concept exists (erasure_code_dir
options.cc:533, osd_erasure_code_plugins :2519, osd_recovery_max_chunk
:3409, osd_pool_default_erasure_code_profile).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable

# Option levels (options.h Option::LEVEL_*)
LEVEL_BASIC = "basic"
LEVEL_ADVANCED = "advanced"
LEVEL_DEV = "dev"

# Option types (options.h Option::TYPE_*)
TYPE_STR = "str"
TYPE_INT = "int"
TYPE_UINT = "uint"
TYPE_FLOAT = "float"
TYPE_BOOL = "bool"
TYPE_SIZE = "size"          # accepts 4K/1M/2G suffixes

_SIZE_SUFFIX = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def parse_size(v) -> int:
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip().lower()
    if s and s[-1] in _SIZE_SUFFIX:
        return int(float(s[:-1]) * _SIZE_SUFFIX[s[-1]])
    return int(s, 0)


_CASTS: dict[str, Callable[[Any], Any]] = {
    TYPE_STR: str,
    TYPE_INT: lambda v: int(str(v), 0) if isinstance(v, str) else int(v),
    TYPE_UINT: lambda v: int(str(v), 0) if isinstance(v, str) else int(v),
    TYPE_FLOAT: float,
    TYPE_BOOL: lambda v: (v if isinstance(v, bool)
                          else str(v).lower() in ("1", "true", "yes", "on")),
    TYPE_SIZE: parse_size,
}


@dataclass
class Option:
    name: str
    type: str = TYPE_STR
    level: str = LEVEL_ADVANCED
    default: Any = None
    description: str = ""
    long_description: str = ""
    see_also: list[str] = field(default_factory=list)
    min: Any = None
    max: Any = None
    enum_allowed: list[str] = field(default_factory=list)
    startup: bool = False       # FLAG_STARTUP: no runtime updates

    def cast(self, value):
        v = _CASTS[self.type](value)
        if self.type in (TYPE_UINT, TYPE_SIZE) and v < 0:
            raise ValueError(f"{self.name}: negative value {v}")
        if self.min is not None and v < self.min:
            raise ValueError(f"{self.name}: {v} < min {self.min}")
        if self.max is not None and v > self.max:
            raise ValueError(f"{self.name}: {v} > max {self.max}")
        if self.enum_allowed and v not in self.enum_allowed:
            raise ValueError(
                f"{self.name}: {v!r} not in {self.enum_allowed}")
        return v


# The framework's option table (the subset of the reference's ~2000 options
# this codebase consumes; same names where the concept matches).
OPTIONS: list[Option] = [
    Option("erasure_code_dir", TYPE_STR, LEVEL_ADVANCED, default="",
           description="directory where erasure-code plugins can be found",
           startup=True),
    Option("osd_erasure_code_plugins", TYPE_STR, LEVEL_ADVANCED,
           default="jax_rs cpp_rs",
           description="erasure code plugins to preload", startup=True),
    Option("osd_pool_default_erasure_code_profile", TYPE_STR, LEVEL_ADVANCED,
           default="plugin=jax_rs technique=reed_sol_van k=2 m=2",
           description="default erasure code profile"),
    Option("osd_pool_default_size", TYPE_UINT, LEVEL_BASIC, default=3,
           description="number of replicas for replicated pools",
           min=0, max=10),
    Option("osd_pool_default_pg_num", TYPE_UINT, LEVEL_BASIC, default=32,
           description="number of PGs for new pools"),
    Option("osd_recovery_max_chunk", TYPE_SIZE, LEVEL_ADVANCED,
           default=8 << 20,
           description="max recovery read size (rounded to stripe width)"),
    Option("osd_recovery_max_active", TYPE_UINT, LEVEL_ADVANCED, default=3,
           description="concurrent recoveries per OSD (the recovery "
                       "scheduler's wave size: objects fused into one "
                       "batched reconstruct dispatch)",
           see_also=["osd_max_backfills",
                     "osd_recovery_max_bytes_per_sec"]),
    # -- recovery scheduler (ceph_tpu/recovery/): reservations + pacing ----
    Option("osd_max_backfills", TYPE_UINT, LEVEL_ADVANCED, default=1,
           min=0,
           description="max concurrent recovery/backfill reservations "
                       "per OSD (local and remote AsyncReserver "
                       "max_allowed; 0 parks every job — useful to "
                       "pause background repair)",
           see_also=["osd_recovery_max_active"]),
    Option("osd_recovery_max_bytes_per_sec", TYPE_SIZE, LEVEL_ADVANCED,
           default=0,
           description="token-bucket byte-rate cap on recovery waves "
                       "per OSD (0 = uncapped); waves run post-paid and "
                       "the next wave waits out the debt in virtual time",
           see_also=["osd_recovery_sleep"]),
    Option("osd_recovery_sleep", TYPE_FLOAT, LEVEL_ADVANCED, default=0.0,
           min=0.0,
           description="virtual-time pause between recovery waves "
                       "(throttles background repair like the "
                       "reference's recovery sleep)",
           see_also=["osd_recovery_max_bytes_per_sec"]),
    Option("osd_recovery_chain_enable", TYPE_BOOL, LEVEL_ADVANCED,
           default=True,
           description="chained streaming repair: scheduler waves plan a "
                       "partial-sum chain over survivor OSDs (each hop "
                       "GF-scales its local shard and forwards the "
                       "running sum) instead of pulling k full shards "
                       "to the primary; falls back to centralized "
                       "verified repair per object on any mid-chain "
                       "failure and for sub-chunked codes",
           see_also=["osd_recovery_chain_max_len",
                     "osd_recovery_max_active"]),
    Option("osd_recovery_chain_max_len", TYPE_UINT, LEVEL_ADVANCED,
           default=12, min=2,
           description="longest partial-sum chain (hop count = decode "
                       "sources); repairs needing more sources than "
                       "this stay centralized",
           see_also=["osd_recovery_chain_enable"]),
    Option("osd_recovery_regen_enable", TYPE_BOOL, LEVEL_ADVANCED,
           default=True,
           description="regenerating-code repair: single-erasure repairs "
                       "on a regenerating pool (pm_regen MSR/MBR) gather "
                       "d helper inner products (beta bytes each) at the "
                       "newcomer instead of decoding k full chunks; "
                       "falls back to centralized verified repair on any "
                       "abort (helper death, version skew, sub-chunk or "
                       "hash mismatch) and for multi-chunk losses",
           see_also=["osd_recovery_chain_enable",
                     "osd_recovery_max_active"]),
    Option("osd_heartbeat_interval", TYPE_INT, LEVEL_ADVANCED, default=6,
           description="seconds between peer heartbeats", min=1, max=60),
    Option("osd_heartbeat_grace", TYPE_INT, LEVEL_ADVANCED, default=20,
           description="seconds without heartbeat before reporting down"),
    Option("osd_op_complaint_time", TYPE_FLOAT, LEVEL_ADVANCED, default=30.0,
           description="ops slower than this many seconds are slow ops "
                       "(flagged in dumps, counted on slow_ops)",
           min=0.0),
    # -- observability fast path (common/instruments.py, tracer sampling) --
    Option("instruments_enabled", TYPE_BOOL, LEVEL_ADVANCED, default=True,
           description="master kill-switch for the hot-path instruments "
                       "(tracer spans/instants/completes, wire "
                       "accounting, rpc latency observation): off turns "
                       "them into cheap no-op guards so the "
                       "observability.overhead bench can measure the "
                       "full-instrumentation tax; health checks and "
                       "perf-counter math keep working either way",
           see_also=["tracer_sample_rate"]),
    Option("tracer_sample_rate", TYPE_FLOAT, LEVEL_ADVANCED, default=1.0,
           min=0.0, max=1.0,
           description="head-based per-trace sampling rate: the decision "
                       "is made ONCE when the root TraceContext is "
                       "created (client/objecter.py, msg/client.py) and "
                       "rides the context across daemons so a whole "
                       "distributed trace samples atomically; unsampled "
                       "ops keep a micro-record and are promoted into "
                       "the ring when they cross osd_op_complaint_time, "
                       "and sampled events carry 1/rate weights so "
                       "trace_report/critpath/SLO rate math stays "
                       "unbiased",
           see_also=["instruments_enabled", "osd_op_complaint_time"]),
    Option("mgr_device_refresh_ttl", TYPE_FLOAT, LEVEL_ADVANCED,
           default=5.0, min=0.0,
           description="seconds a prometheus scrape reuses the last "
                       "device-telemetry snapshot before re-probing JAX "
                       "backend state (0 = refresh every render); a "
                       "tight scrape loop stops re-snapshotting live "
                       "device memory stats every second"),
    Option("mon_osd_min_down_reporters", TYPE_UINT, LEVEL_ADVANCED,
           default=2, description="failure reports needed to mark down"),
    Option("mon_osd_min_up_ratio", TYPE_FLOAT, LEVEL_ADVANCED, default=0.3,
           description="refuse down-marks below this up fraction"),
    Option("mon_osd_down_out_interval", TYPE_INT, LEVEL_ADVANCED,
           default=600, description="seconds down before auto-out"),
    Option("mon_osd_reporter_subtree_level", TYPE_STR, LEVEL_ADVANCED,
           default="host",
           description="crush level for counting distinct failure reporters"),
    # -- fault injection & self-healing (failure/) -------------------------
    Option("osd_markdown_count", TYPE_UINT, LEVEL_ADVANCED, default=5,
           min=1,
           description="mark-downs within osd_markdown_window before an "
                       "OSD is declared flapping: further boots are "
                       "refused (OSD_FLAPPING) until the operator clears "
                       "the markdown record (osd_markdown_log analog)",
           see_also=["osd_markdown_window"]),
    Option("osd_markdown_window", TYPE_FLOAT, LEVEL_ADVANCED,
           default=600.0, min=1.0,
           description="sliding window in seconds over which "
                       "osd_markdown_count mark-downs count as flapping",
           see_also=["osd_markdown_count"]),
    Option("ms_inject_socket_failures", TYPE_UINT, LEVEL_ADVANCED,
           default=0,
           description="inject a connection reset roughly every N "
                       "post-auth messages on the TCP transport (0 "
                       "disables) — the reference's 'ms inject socket "
                       "failures'; the ClusterServer auto-arms its "
                       "fault hooks when nonzero",
           see_also=["ms_inject_delay_prob", "ms_inject_delay_ms"]),
    Option("ms_inject_delay_prob", TYPE_FLOAT, LEVEL_ADVANCED,
           default=0.0, min=0.0, max=1.0,
           description="probability a post-auth TCP message is delayed "
                       "by ms_inject_delay_ms before hitting the wire "
                       "('ms inject delay' analog)",
           see_also=["ms_inject_delay_ms"]),
    Option("ms_inject_delay_ms", TYPE_FLOAT, LEVEL_ADVANCED,
           default=0.0, min=0.0,
           description="milliseconds an ms_inject_delay_prob hit stalls "
                       "the send"),
    Option("ms_rpc_timeout", TYPE_FLOAT, LEVEL_ADVANCED, default=30.0,
           min=0.1,
           description="overall per-RPC deadline on the TCP client: a "
                       "call not answered (across resends) within this "
                       "many seconds raises TimeoutError instead of "
                       "hanging on a black-holed request"),
    Option("ms_rpc_retry_attempts", TYPE_UINT, LEVEL_ADVANCED, default=4,
           min=1,
           description="send attempts per RPC within ms_rpc_timeout: "
                       "resends after a connection reset or a silent "
                       "per-attempt timeout (the server dedups resends "
                       "by (session, rid), so retries never re-apply)",
           see_also=["ms_rpc_timeout"]),
    Option("ms_reconnect_max_attempts", TYPE_UINT, LEVEL_ADVANCED,
           default=8, min=1,
           description="bounded reconnect attempts after the TCP link "
                       "drops before the client gives up "
                       "(full-jitter exponential backoff between tries)",
           see_also=["ms_reconnect_backoff_base",
                     "ms_reconnect_backoff_cap"]),
    Option("ms_reconnect_backoff_base", TYPE_FLOAT, LEVEL_ADVANCED,
           default=0.05, min=0.0,
           description="base seconds of the reconnect backoff schedule: "
                       "attempt n sleeps uniform[0, min(cap, "
                       "base * 2^n)] (full jitter)"),
    Option("ms_reconnect_backoff_cap", TYPE_FLOAT, LEVEL_ADVANCED,
           default=2.0, min=0.0,
           description="ceiling seconds any single reconnect backoff "
                       "sleep can reach"),
    # -- async messenger (msg/) --------------------------------------------
    Option("ms_async_op_threads", TYPE_UINT, LEVEL_ADVANCED, default=3,
           min=1,
           description="dispatch worker threads per async server "
                       "transport (the reference's ms_async_op_threads): "
                       "the FIXED pool that executes RPCs off the "
                       "dmClock dispatch queue — never grows with "
                       "connection count"),
    Option("ms_async_dispatch_queue_max", TYPE_UINT, LEVEL_ADVANCED,
           default=1024, min=1,
           description="dispatch-queue depth limit the overload-shedding "
                       "ladder measures against: each dmClock class may "
                       "occupy only its fraction of this before its "
                       "arrivals bounce with EBUSY (client ops shed only "
                       "at the full limit)"),
    Option("ms_async_write_queue_bytes", TYPE_SIZE, LEVEL_ADVANCED,
           default=4 * 1024 * 1024,
           description="per-connection write-queue byte budget "
                       "(exec/throttle.py): senders block (bounded) when "
                       "a peer stops draining, and the connection closes "
                       "when the budget stays exhausted a full send "
                       "timeout — backpressure instead of unbounded "
                       "buffering"),
    Option("ms_async_batch_max", TYPE_UINT, LEVEL_ADVANCED, default=64,
           min=1,
           description="max RpcCalls the mux client coalesces into one "
                       "RpcBatch frame (one pickle, one MAC, one "
                       "syscall per admission window)"),
    Option("ms_async_batch_delay_ms", TYPE_FLOAT, LEVEL_ADVANCED,
           default=0.5, min=0.0,
           description="how long the mux client's sender waits for more "
                       "calls to coalesce once one is queued (0 sends "
                       "immediately)",
           see_also=["ms_async_batch_max"]),
    Option("ms_zero_copy", TYPE_BOOL, LEVEL_ADVANCED, default=True,
           description="serialize batch-frame payloads through the "
                       "raw sideband segment (length-prefixed bulk data "
                       "after the pickled control header) so a payload "
                       "byte is copied ~once between socket and device "
                       "staging; off forces the legacy all-pickle frame "
                       "(the bench's 'legacy' arm). Both formats decode "
                       "regardless of the setting — this only gates the "
                       "ENCODE side, so mixed-version peers interoperate",
           see_also=["ms_async_batch_max"]),
    Option("pipeline_breaker_threshold", TYPE_UINT, LEVEL_ADVANCED,
           default=3,
           description="consecutive device-side codec failures before "
                       "the pipeline's circuit breaker opens and "
                       "fallback-capable batches run the sync host "
                       "codec instead (0 disables the breaker)",
           see_also=["pipeline_breaker_cooldown"]),
    Option("pipeline_breaker_cooldown", TYPE_FLOAT, LEVEL_ADVANCED,
           default=5.0, min=0.0,
           description="seconds an open pipeline breaker waits before "
                       "admitting one half-open probe dispatch back to "
                       "the device (success re-closes, failure re-opens)",
           see_also=["pipeline_breaker_threshold"]),
    Option("ec_batch_max_stripes", TYPE_UINT, LEVEL_ADVANCED, default=256,
           description="stripes coalesced per device dispatch"),
    Option("ec_device_threshold_bytes", TYPE_SIZE, LEVEL_ADVANCED,
           default=8 * 1024 * 1024,
           description="single calls below this encode on the SIMD host "
                       "codec; above (or batched via the pipeline/queue "
                       "paths), on device — BASELINE_RESULTS.json config 2 "
                       "measures the crossover"),
    # -- device codec pipeline (ceph_tpu/ops/pipeline.py) ------------------
    Option("jax_rs_pipeline_depth", TYPE_UINT, LEVEL_ADVANCED,
           default=4,
           description="max dispatched device batches in flight before "
                       "the codec pipeline forces completion of the "
                       "oldest; batch N+1's host pack overlaps batch N's "
                       "device compute (0 = synchronous dispatch)",
           see_also=["jax_rs_mesh_devices"]),
    Option("jax_rs_mesh_devices", TYPE_UINT, LEVEL_ADVANCED,
           default=0,
           description="split coalesced codec batches across the dp axis "
                       "of a device mesh over this many devices "
                       "(parallel/mesh sharded encode/decode steps); "
                       "0 or 1 = single-chip dispatch, and the option is "
                       "ignored when fewer devices are present",
           see_also=["jax_rs_pipeline_depth"]),
    # -- serving engine (ceph_tpu/exec/): admission + dynamic batching ----
    Option("osd_serving_throttle_bytes", TYPE_SIZE, LEVEL_ADVANCED,
           default=64 << 20,
           description="serving admission throttle: max payload bytes "
                       "queued or in flight (backpressure past this)",
           see_also=["osd_serving_throttle_ops", "osd_serving_fail_fast"]),
    Option("osd_serving_throttle_ops", TYPE_UINT, LEVEL_ADVANCED,
           default=1024, min=1,
           description="serving admission throttle: max ops queued or in "
                       "flight",
           see_also=["osd_serving_throttle_bytes"]),
    Option("osd_serving_fail_fast", TYPE_BOOL, LEVEL_ADVANCED,
           default=False,
           description="when a serving throttle is full, refuse the op "
                       "(ThrottleFull) instead of blocking the submitter"),
    Option("osd_batch_max_delay_ms", TYPE_FLOAT, LEVEL_ADVANCED,
           default=2.0, min=0.0,
           description="op coalescer deadline: max milliseconds an op "
                       "waits for batch companions before dispatch",
           see_also=["osd_batch_max_ops"]),
    Option("osd_batch_max_ops", TYPE_UINT, LEVEL_ADVANCED,
           default=64, min=1,
           description="op coalescer: max ops fused into one device "
                       "dispatch",
           see_also=["osd_batch_max_delay_ms"]),
    Option("osd_queue_throttle_ops", TYPE_UINT, LEVEL_ADVANCED,
           default=0,
           description="OSD daemon op-queue admission bound (0 = "
                       "unlimited); past it ms_dispatch answers "
                       "('throttled', epoch) and the client backs off"),
    # -- mgr telemetry (stats aggregation + health checks) ----------------
    Option("mgr_stats_period", TYPE_FLOAT, LEVEL_ADVANCED, default=1.0,
           min=0.01,
           description="seconds between background StatsAggregator "
                       "samples (the mgr's tick interval)",
           see_also=["mgr_stats_window"]),
    Option("mgr_stats_window", TYPE_UINT, LEVEL_ADVANCED, default=120,
           min=2,
           description="perf-counter samples retained in the rolling "
                       "rate window (rates span first..last sample)",
           see_also=["mgr_stats_period"]),
    Option("mgr_throttle_saturation_ratio", TYPE_FLOAT, LEVEL_ADVANCED,
           default=0.9, min=0.0, max=1.0,
           description="THROTTLE_SATURATED health check fires when a "
                       "throttle's in-use/limit ratio reaches this"),
    Option("mgr_recompile_storm_compiles", TYPE_UINT, LEVEL_ADVANCED,
           default=8, min=1,
           description="RECOMPILE_STORM health check fires when jit "
                       "compilations within the stats window reach this "
                       "many AND this rate per minute (shape churn "
                       "defeating the size buckets)"),
    # -- device efficiency & profiling (roofline / profiler_capture) -------
    Option("device_peak_flops", TYPE_FLOAT, LEVEL_ADVANCED, default=0.0,
           min=0.0,
           description="roofline peak FLOP/s override for this host "
                       "(0 = resolve from the device-kind registry in "
                       "common/roofline.py)",
           see_also=["device_peak_hbm_bytes_per_sec"]),
    Option("device_peak_hbm_bytes_per_sec", TYPE_SIZE, LEVEL_ADVANCED,
           default=0,
           description="roofline peak memory bandwidth override in "
                       "bytes/s (0 = resolve from the device-kind "
                       "registry)",
           see_also=["device_peak_flops"]),
    Option("mgr_hbm_pressure_ratio", TYPE_FLOAT, LEVEL_ADVANCED,
           default=0.85, min=0.0, max=1.0,
           description="HBM_PRESSURE health check fires when a device's "
                       "high-water memory mark reaches this fraction of "
                       "its reported capacity"),
    Option("mgr_profiler_max_captures", TYPE_UINT, LEVEL_ADVANCED,
           default=8, min=1,
           description="XLA profiler capture directories kept on disk "
                       "(oldest removed past the bound)"),
    Option("mgr_profiler_cooldown", TYPE_FLOAT, LEVEL_ADVANCED,
           default=300.0, min=0.0,
           description="seconds between health-transition profiler "
                       "auto-captures (a flapping check must not churn "
                       "the profiler)",
           see_also=["mgr_profiler_auto_window"]),
    Option("mgr_profiler_auto_window", TYPE_FLOAT, LEVEL_ADVANCED,
           default=0.0, min=0.0,
           description="seconds a health-transition auto-capture stays "
                       "open before stop_trace (0 = stop immediately: a "
                       "marker artifact with zero steady-state risk; "
                       "operators open real windows with 'device "
                       "profile start')",
           see_also=["mgr_profiler_cooldown"]),
    Option("mgr_flight_capacity", TYPE_UINT, LEVEL_ADVANCED, default=8,
           min=1,
           description="flight-recorder bundles kept in the in-memory "
                       "ring (disk dumps are additionally bounded by "
                       "the operator's data dir)"),
    # -- wire & workload observability (heat / clog / timeseries) ----------
    Option("mgr_hot_shard_ratio", TYPE_FLOAT, LEVEL_ADVANCED, default=4.0,
           min=1.0,
           description="HOT_SHARD health check fires when one OSD's "
                       "primary-op rate reaches this multiple of the "
                       "median OSD load over the stats window",
           see_also=["mgr_hot_shard_min_ops"]),
    Option("mgr_hot_shard_min_ops", TYPE_FLOAT, LEVEL_ADVANCED,
           default=16.0, min=0.0,
           description="HOT_SHARD requires the hottest OSD to sustain at "
                       "least this many primary op/s before skew alone "
                       "can fire the check (idle clusters never page)",
           see_also=["mgr_hot_shard_ratio"]),
    Option("mgr_cluster_log_max", TYPE_UINT, LEVEL_ADVANCED, default=500,
           min=1,
           description="cluster log (clog) entries kept in the bounded "
                       "ring; the on-disk clusterlog file compacts back "
                       "to this bound"),
    Option("mgr_ts_interval", TYPE_FLOAT, LEVEL_ADVANCED, default=1.0,
           min=0.0,
           description="minimum seconds between embedded time-series "
                       "points (status ticks closer together are "
                       "coalesced)",
           see_also=["mgr_ts_capacity", "mgr_ts_coarse_every"]),
    Option("mgr_ts_capacity", TYPE_UINT, LEVEL_ADVANCED, default=360,
           min=2,
           description="points per time-series ring (fine and coarse "
                       "archives each hold this many; round-robin "
                       "eviction past it)",
           see_also=["mgr_ts_interval"]),
    Option("mgr_ts_coarse_every", TYPE_UINT, LEVEL_ADVANCED, default=12,
           min=1,
           description="fine time-series points folded (mean+max) into "
                       "one coarse archive point",
           see_also=["mgr_ts_capacity"]),
    # -- latency SLOs & critical-path attribution (mgr/slo.py) -------------
    Option("slo_fast_window", TYPE_FLOAT, LEVEL_ADVANCED, default=60.0,
           min=0.05,
           description="seconds of the FAST burn-rate window: SLO_BURN "
                       "needs both the fast and slow windows past "
                       "slo_burn_rate_threshold (multi-window agreement "
                       "— a blip trips the fast window alone and stays "
                       "silent)",
           see_also=["slo_slow_window", "slo_burn_rate_threshold"]),
    Option("slo_slow_window", TYPE_FLOAT, LEVEL_ADVANCED, default=600.0,
           min=0.1,
           description="seconds of the SLOW burn-rate window (budget "
                       "remaining and SLO_EXHAUSTED are judged over it)",
           see_also=["slo_fast_window"]),
    Option("slo_burn_rate_threshold", TYPE_FLOAT, LEVEL_ADVANCED,
           default=2.0, min=1.0,
           description="error-budget burn multiple past which SLO_BURN "
                       "raises when BOTH windows agree (1.0 = spending "
                       "exactly the sustainable rate)",
           see_also=["slo_exhausted_burn_rate"]),
    Option("slo_exhausted_burn_rate", TYPE_FLOAT, LEVEL_ADVANCED,
           default=10.0, min=1.0,
           description="slow-window burn multiple past which "
                       "SLO_EXHAUSTED (HEALTH_ERR) raises: the budget "
                       "is gone at any plausible compliance period",
           see_also=["slo_burn_rate_threshold"]),
    Option("slo_min_ops", TYPE_UINT, LEVEL_ADVANCED, default=8, min=1,
           description="minimum ops in BOTH burn windows before the SLO "
                       "checks can page (an idle class holds no "
                       "evidence either way)"),
    # -- cache tiering (tier/) ---------------------------------------------
    Option("tier_promote_min_recency", TYPE_UINT, LEVEL_ADVANCED,
           default=2, min=0,
           description="consecutive most-recent hit sets a missed "
                       "object must appear in before the proxy read "
                       "also promotes it into the cache pool "
                       "(min_read_recency_for_promote; 0 promotes on "
                       "first touch, higher values stop one-shot scans "
                       "from thrashing the tier)"),
    Option("tier_dirty_ratio_high", TYPE_FLOAT, LEVEL_ADVANCED,
           default=0.6, min=0.0, max=1.0,
           description="dirty objects over tier_target_max_objects "
                       "past which the agent arms flush mode "
                       "(cache_target_dirty_high_ratio)",
           see_also=["tier_dirty_ratio_low", "tier_target_max_objects"]),
    Option("tier_dirty_ratio_low", TYPE_FLOAT, LEVEL_ADVANCED,
           default=0.4, min=0.0, max=1.0,
           description="flush mode disarms once the dirty fraction "
                       "drops under this (hysteresis below "
                       "tier_dirty_ratio_high: the next absorbed write "
                       "does not immediately re-arm the agent)",
           see_also=["tier_dirty_ratio_high"]),
    Option("tier_full_ratio", TYPE_FLOAT, LEVEL_ADVANCED,
           default=0.8, min=0.0, max=1.0,
           description="resident objects over tier_target_max_objects "
                       "past which the agent evicts cold clean objects "
                       "(cache_target_full_ratio) and TIER_FULL raises",
           see_also=["tier_target_max_objects"]),
    Option("tier_target_max_objects", TYPE_UINT, LEVEL_ADVANCED,
           default=256, min=1,
           description="capacity target of the RAM-resident cache pool "
                       "in objects: the denominator of every tier "
                       "watermark (target_max_objects)",
           see_also=["tier_full_ratio", "tier_dirty_ratio_high"]),
    Option("tier_agent_max_ops", TYPE_UINT, LEVEL_ADVANCED,
           default=16, min=1,
           description="flush/evict operations one agent pass may "
                       "issue (osd_agent_max_ops): the agent shares "
                       "the cluster with clients and must not convoy "
                       "them"),
    Option("log_file", TYPE_STR, LEVEL_BASIC, default="",
           description="path to log file"),
    Option("log_max_recent", TYPE_UINT, LEVEL_ADVANCED, default=500,
           description="recent log entries kept for crash dump"),
    Option("debug_osd", TYPE_INT, LEVEL_DEV, default=1,
           description="osd subsystem log gather level", min=0, max=20),
    Option("debug_ec", TYPE_INT, LEVEL_DEV, default=1,
           description="erasure-code subsystem log level", min=0, max=20),
    Option("debug_crush", TYPE_INT, LEVEL_DEV, default=1,
           description="crush subsystem log level", min=0, max=20),
]

# per-owner-class latency objectives (mgr/slo.py): slo_<class>_p99_ms is
# the bound (0 = no objective), slo_<class>_target the fraction of ops
# that must meet it — the error budget is 1 - target.  Generated for the
# canonical owner classes (common/device_attribution.OWNER_CLASSES,
# inlined here so the schema stays import-light).
for _cls in ("client", "serving", "recovery", "scrub", "rebalance"):
    OPTIONS.append(Option(
        f"slo_{_cls}_p99_ms", TYPE_FLOAT, LEVEL_ADVANCED, default=0.0,
        min=0.0,
        description=f"latency objective for {_cls}-class ops in "
                    f"milliseconds (0 disables the objective; "
                    f"slo_{_cls}_target sets the compliance fraction)",
        see_also=[f"slo_{_cls}_target"]))
    OPTIONS.append(Option(
        f"slo_{_cls}_target", TYPE_FLOAT, LEVEL_ADVANCED, default=0.999,
        min=0.0, max=1.0,
        description=f"fraction of {_cls}-class ops that must complete "
                    f"within slo_{_cls}_p99_ms (error budget = "
                    f"1 - target)",
        see_also=[f"slo_{_cls}_p99_ms"]))

SCHEMA: dict[str, Option] = {o.name: o for o in OPTIONS}


class ConfigProxy:
    """md_config_t analog: typed values + observers (config.cc)."""

    def __init__(self, overrides: dict | None = None,
                 schema: dict[str, Option] | None = None):
        self.schema = dict(schema or SCHEMA)
        self._values: dict[str, Any] = {}
        self._observers: dict[str, list[Callable[[str, Any], None]]] = {}
        self._lock = threading.Lock()
        if overrides:
            for k, v in overrides.items():
                self.set(k, v, _startup=True)

    def get(self, name: str):
        opt = self.schema[name]
        with self._lock:
            if name in self._values:
                return self._values[name]
        return opt.cast(opt.default) if opt.default is not None else None

    def __getitem__(self, name: str):
        return self.get(name)

    def set(self, name: str, value, _startup: bool = False) -> None:
        opt = self.schema.get(name)
        if opt is None:
            raise KeyError(f"unknown option {name!r}")
        if opt.startup and not _startup:
            raise ValueError(f"option {name} can only be set at startup")
        v = opt.cast(value)
        with self._lock:
            self._values[name] = v
            observers = list(self._observers.get(name, ()))
        for fn in observers:        # outside the lock, like the reference
            fn(name, v)

    def add_observer(self, name: str, fn: Callable[[str, Any], None]) -> None:
        """Live-update hook (md_config_obs_t analog)."""
        if name not in self.schema:
            raise KeyError(f"unknown option {name!r}")
        with self._lock:
            self._observers.setdefault(name, []).append(fn)

    def show_config(self) -> dict[str, Any]:
        return {name: self.get(name) for name in sorted(self.schema)}

    def diff(self) -> dict[str, Any]:
        """Only non-default values (`ceph config diff`)."""
        with self._lock:
            return dict(self._values)
