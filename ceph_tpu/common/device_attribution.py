"""Device-time attribution: who is occupying the chip, by owner class.

The PR-6 tentpole's second leg: once serving batches, recovery waves and
the async codec pipeline all share one device, a single throughput number
cannot say *whose* work the chip is doing — "recovery is stealing 40% of
the chip from serving" must be a measurable fact before the dmClock knobs
can act on it.  This module is the process-wide ledger:

- every :class:`~ceph_tpu.ops.pipeline.CodecPipeline` dispatch is tagged
  with an **owner class** (``client``/``serving``/``recovery``/``scrub``/
  ``rebalance`` — resolved from the caller's explicit tag or the active
  :class:`~ceph_tpu.common.tracer.TraceContext`), and its wall-clock
  device occupancy is accounted at the pipeline's completion boundary;
- overlapping in-flight batches are clamped against the ledger's last
  completion edge, so the per-class seconds SUM to the pipeline's busy
  time instead of double-counting overlap (the acceptance invariant);
- :func:`record_executable` folds in XLA ``cost_analysis()`` FLOPs/bytes
  per compiled executable (fed by ``ops/traced_jit.py``), giving each
  kernel a cost model next to its measured occupancy;
- surfaces: the ``device_attribution`` PerfCounters collection, the
  ``ceph_tpu_device_time_seconds{class=...}`` prometheus family, and the
  ``device top`` admin command.

Stdlib-only (the tracer's discipline): importable before any JAX backend
initializes, and the ONLY module in the accounting path allowed a bare
clock — it IS the device-occupancy clock (see tests/test_no_bare_time.py).
"""
from __future__ import annotations

import threading
import time

#: the canonical owner classes (the COMPONENTS.md owner-class table)
OWNER_CLASSES = ("client", "serving", "recovery", "scrub", "rebalance")

# dmClock op classes / historical aliases -> canonical owner class
_OWNER_ALIASES = {
    "client": "client", "client_op": "client",
    "serving": "serving",
    "recovery": "recovery", "bg_recovery": "recovery",
    "scrub": "scrub", "bg_scrub": "scrub",
    # snaptrim is background maintenance walking the stores, like scrub
    "bg_snaptrim": "scrub",
    "rebalance": "rebalance", "backfill": "rebalance",
}


def canonical_owner(name: str | None) -> str:
    """Clamp any op-class string onto the canonical owner set."""
    return _OWNER_ALIASES.get(name or "", "client")


def resolve_owner(owner: str | None = None) -> str:
    """An explicit tag wins; otherwise the active TraceContext's op
    class; otherwise ``client`` (untagged foreground work)."""
    if owner is not None:
        return canonical_owner(owner)
    from . import tracer as tracer_mod
    ctx = tracer_mod.default_tracer().current_ctx()
    return canonical_owner(ctx.op_class if ctx is not None else None)


_lock = threading.Lock()
_classes: dict[str, dict] = {}      # owner -> {device_s, batches, bytes}
_busy_s = 0.0                       # union device-occupancy (the invariant)
_last_end = 0.0                     # trailing completion edge (clamp point)
_executables: dict[str, dict] = {}  # label -> {flops, bytes, compiles}
_fallback: dict[str, dict] = {}     # owner -> {batches, bytes} host-served
_perf = None


def perf_counters():
    """The process-wide ``device_attribution`` PerfCounters collection
    (lazy, like the tracer's jit collection): per-class device seconds +
    the busy-time total every Context registers for perf dump/prometheus."""
    global _perf
    with _lock:
        if _perf is None:
            from .perf_counters import PerfCountersBuilder
            b = PerfCountersBuilder("device_attribution")
            for cls in OWNER_CLASSES:
                b.add_time_avg(f"{cls}_device_time",
                               f"device occupancy attributed to {cls} work")
            b.add_time_avg("busy_time",
                           "total device busy time at the pipeline "
                           "completion boundary (per-class times sum to "
                           "this)")
            b.add_u64_counter("batches", "device batches accounted")
            _perf = b.create_perf_counters()
        return _perf


def dispatch_mark() -> float:
    """Timestamp an async device dispatch (call right after the launch
    returns); pass the mark to :func:`record_batch` at completion."""
    return time.perf_counter()


def record_batch(owner: str | None, dispatched_at: float,
                 nbytes: int = 0) -> float:
    """Account one completed device batch to ``owner`` (resolved through
    :func:`resolve_owner`).  The busy interval is
    ``[max(dispatched_at, last completion edge), now]`` — batches overlap
    in flight, the device serializes them, so clamping to the previous
    completion edge keeps per-class seconds summing to busy time.
    Returns the seconds accounted."""
    global _busy_s, _last_end
    cls = resolve_owner(owner)
    now = time.perf_counter()
    with _lock:
        dur = max(0.0, now - max(dispatched_at, _last_end))
        _last_end = max(_last_end, now)
        _busy_s += dur
        rec = _classes.get(cls)
        if rec is None:
            rec = _classes[cls] = {"device_s": 0.0, "batches": 0,
                                   "bytes": 0}
        rec["device_s"] += dur
        rec["batches"] += 1
        rec["bytes"] += int(nbytes)
    pc = perf_counters()
    pc.tinc(f"{cls}_device_time", dur)
    pc.tinc("busy_time", dur)
    pc.inc("batches")
    return dur


def record_host_fallback(owner: str | None, nbytes: int = 0) -> None:
    """Mark one batch served by the SYNC HOST codec because the device
    path was circuit-broken (ops/pipeline.py host fallback): the chip
    did none of this work, so nothing lands in busy_s — the separate
    fallback ledger is what ``device top``/DEVICE_DEGRADED read to show
    how degraded the device path currently is."""
    cls = resolve_owner(owner)
    with _lock:
        rec = _fallback.setdefault(cls, {"batches": 0, "bytes": 0})
        rec["batches"] += 1
        rec["bytes"] += int(nbytes)


def record_executable(label: str, flops: float, bytes_accessed: float
                      ) -> None:
    """Fold one compiled executable's XLA cost analysis into the ledger
    (``ops/traced_jit.py`` calls this once per compilation)."""
    with _lock:
        rec = _executables.get(label)
        if rec is None:
            _executables[label] = {"flops": float(flops),
                                   "bytes": float(bytes_accessed),
                                   "compiles": 1}
        else:
            rec["flops"] += float(flops)
            rec["bytes"] += float(bytes_accessed)
            rec["compiles"] += 1


def snapshot() -> dict:
    """{classes: {cls: {device_s, share, batches, bytes}}, busy_s,
    executables} — per-class shares of the accounted busy time."""
    with _lock:
        busy = _busy_s
        classes = {
            cls: {"device_s": rec["device_s"],
                  "share": (rec["device_s"] / busy) if busy else 0.0,
                  "batches": rec["batches"], "bytes": rec["bytes"]}
            for cls, rec in sorted(_classes.items())}
        execs = {label: dict(rec)
                 for label, rec in sorted(_executables.items())}
        fallback = {cls: dict(rec)
                    for cls, rec in sorted(_fallback.items())}
    return {"classes": classes, "busy_s": busy, "executables": execs,
            "host_fallback": fallback}


def device_top(limit: int = 10) -> dict:
    """The ``device top`` admin command: owner classes ranked by device
    share, plus the costliest compiled executables by modeled FLOPs."""
    snap = snapshot()
    classes = sorted(snap["classes"].items(),
                     key=lambda kv: kv[1]["device_s"], reverse=True)
    execs = sorted(snap["executables"].items(),
                   key=lambda kv: kv[1]["flops"], reverse=True)
    return {
        "busy_s": round(snap["busy_s"], 6),
        "classes": [
            {"class": cls,
             "device_s": round(rec["device_s"], 6),
             "share_pct": round(100.0 * rec["share"], 1),
             "batches": rec["batches"], "bytes": rec["bytes"]}
            for cls, rec in classes],
        "executables": [
            {"function": label, "flops": rec["flops"],
             "bytes_accessed": rec["bytes"], "compiles": rec["compiles"]}
            for label, rec in execs[:max(0, int(limit))]],
    }


def reset() -> dict:
    """Zero the ledger (tests / ``device top reset``); the PerfCounters
    collection keeps its cumulative totals like every other collection."""
    global _busy_s, _last_end
    with _lock:
        n = len(_classes)
        _classes.clear()
        _executables.clear()
        _fallback.clear()
        _busy_s = 0.0
        _last_end = 0.0
    return {"success": f"dropped {n} owner-class records"}
