"""Process-wide instrumentation kill-switch.

ISSUE 18's measurement lever: every hot-path instrument (tracer spans/
instants/completes, wire accounting, rpc latency observation) checks
:func:`enabled` before doing any work, so ``instruments_enabled=false``
turns the whole instrumentation plane into cheap no-op guards.  The
``observability.overhead`` bench block runs the mux serving workload
twice — instruments on vs off — and the delta IS the tax the gate holds
to single digits.

The flag is deliberately a bare module global read without a lock: the
hot paths pay one attribute load + truth test per instrument call, and
a torn read is impossible under the GIL (the value is a bool).  Flips
are rare (bench arms, ``config set instruments_enabled``) and take
effect on the next instrument call.

What the switch does NOT stub: perf-counter math that the control plane
*acts on* (throttle gauges, shed ladders, health inputs) keeps running —
observability must be free to drop, behavior must not change with it.
"""
from __future__ import annotations

from contextlib import contextmanager

_enabled = True


def enabled() -> bool:
    """The hot-path guard: True when the instruments should record."""
    return _enabled


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


@contextmanager
def disabled():
    """Scoped kill-switch (the bench's off arm): instruments off inside
    the block, restored to the PRIOR state on exit."""
    prior = _enabled
    set_enabled(False)
    try:
        yield
    finally:
        set_enabled(prior)


def wire_config(conf) -> None:
    """Adopt ``instruments_enabled`` from a ConfigProxy and follow live
    updates (``config set instruments_enabled false`` on a running
    cluster flips the process-wide switch, like every other option)."""
    if "instruments_enabled" not in conf.schema:
        return
    set_enabled(bool(conf.get("instruments_enabled")))
    conf.add_observer("instruments_enabled",
                      lambda _name, v: set_enabled(bool(v)))
