"""Perf counters: typed metric registry with builder + JSON dump.

Mirror of the reference's PerfCounters machinery (reference:
src/common/perf_counters.h — ``PerfCountersBuilder`` :59-116 with
``add_u64_counter``/``add_u64_avg``/``add_time_avg``/histogram adders
:83-99; per-subsystem collections registered in the CephContext and dumped
over the admin socket as ``perf dump``).  Averages store (sum, count) pairs
and dump as {avgcount, sum, avgtime} exactly like the reference so existing
``perf dump`` consumers parse them.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

PERFCOUNTER_U64 = "u64"
PERFCOUNTER_COUNTER = "counter"
PERFCOUNTER_AVG = "avg"
PERFCOUNTER_TIME_AVG = "time_avg"
PERFCOUNTER_HISTOGRAM = "histogram"


@dataclass
class _Metric:
    kind: str
    description: str = ""
    value: float = 0
    sum: float = 0.0
    count: int = 0
    buckets: list[float] = field(default_factory=list)   # histogram bounds
    bucket_counts: list[int] = field(default_factory=list)


class PerfCounters:
    """One subsystem's counters (e.g. 'osd', 'ec_backend')."""

    def __init__(self, name: str):
        self.name = name
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    # -- updates -----------------------------------------------------------

    def inc(self, key: str, amount: int = 1) -> None:
        with self._lock:
            m = self._metrics[key]
            if m.kind == PERFCOUNTER_AVG:
                m.sum += amount
                m.count += 1
            else:
                m.value += amount

    def dec(self, key: str, amount: int = 1) -> None:
        with self._lock:
            self._metrics[key].value -= amount

    def set(self, key: str, value) -> None:
        with self._lock:
            self._metrics[key].value = value

    def get(self, key: str) -> float:
        """Current value of a plain counter/gauge."""
        with self._lock:
            return self._metrics[key].value

    def tinc(self, key: str, seconds: float) -> None:
        """Add one timed sample (the reference's utime_t tinc)."""
        with self._lock:
            m = self._metrics[key]
            m.sum += seconds
            m.count += 1

    def hinc(self, key: str, value: float) -> None:
        with self._lock:
            m = self._metrics[key]
            for i, bound in enumerate(m.buckets):
                if value <= bound:
                    m.bucket_counts[i] += 1
                    break
            else:
                m.bucket_counts[-1] += 1
            m.sum += value
            m.count += 1

    class _Timer:
        def __init__(self, pc, key):
            self.pc, self.key = pc, key

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.pc.tinc(self.key, time.perf_counter() - self.t0)
            return False

    def time(self, key: str) -> "_Timer":
        return self._Timer(self, key)

    # -- dump --------------------------------------------------------------

    def dump(self) -> dict:
        out = {}
        with self._lock:
            for key, m in self._metrics.items():
                if m.kind in (PERFCOUNTER_AVG, PERFCOUNTER_TIME_AVG):
                    entry = {"avgcount": m.count, "sum": m.sum}
                    if m.count:
                        entry["avgtime" if m.kind == PERFCOUNTER_TIME_AVG
                              else "avgvalue"] = m.sum / m.count
                    out[key] = entry
                elif m.kind == PERFCOUNTER_HISTOGRAM:
                    out[key] = {"sum": m.sum, "count": m.count,
                                "buckets": dict(zip(
                                    [str(b) for b in m.buckets] + ["inf"],
                                    m.bucket_counts))}
                else:
                    out[key] = m.value
        return out


class PerfCountersBuilder:
    """(perf_counters.h:59-116)."""

    def __init__(self, name: str):
        self._pc = PerfCounters(name)

    def add_u64(self, key: str, description: str = "") -> "PerfCountersBuilder":
        self._pc._metrics[key] = _Metric(PERFCOUNTER_U64, description)
        return self

    def add_u64_counter(self, key: str,
                        description: str = "") -> "PerfCountersBuilder":
        self._pc._metrics[key] = _Metric(PERFCOUNTER_COUNTER, description)
        return self

    def add_u64_avg(self, key: str,
                    description: str = "") -> "PerfCountersBuilder":
        self._pc._metrics[key] = _Metric(PERFCOUNTER_AVG, description)
        return self

    def add_time_avg(self, key: str,
                     description: str = "") -> "PerfCountersBuilder":
        self._pc._metrics[key] = _Metric(PERFCOUNTER_TIME_AVG, description)
        return self

    def add_histogram(self, key: str, buckets: list[float],
                      description: str = "") -> "PerfCountersBuilder":
        m = _Metric(PERFCOUNTER_HISTOGRAM, description,
                    buckets=list(buckets))
        m.bucket_counts = [0] * (len(buckets) + 1)
        self._pc._metrics[key] = m
        return self

    def create_perf_counters(self) -> PerfCounters:
        return self._pc


class PerfCountersCollection:
    """Process-wide registry dumped as one JSON doc (perf dump)."""

    def __init__(self):
        self._loggers: dict[str, PerfCounters] = {}
        self._lock = threading.Lock()

    def add(self, pc: PerfCounters) -> None:
        with self._lock:
            self._loggers[pc.name] = pc

    def remove(self, name: str) -> None:
        with self._lock:
            self._loggers.pop(name, None)

    def get(self, name: str) -> PerfCounters | None:
        with self._lock:
            return self._loggers.get(name)

    def snapshot(self) -> dict[str, PerfCounters]:
        """Locked copy of the registry — the safe way to iterate
        collections while other threads register/remove them (health
        checks, exporters, `top`)."""
        with self._lock:
            return dict(self._loggers)

    def perf_dump(self) -> dict:
        with self._lock:
            loggers = dict(self._loggers)
        return {name: pc.dump() for name, pc in sorted(loggers.items())}
