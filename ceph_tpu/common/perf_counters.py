"""Perf counters: typed metric registry with builder + JSON dump.

Mirror of the reference's PerfCounters machinery (reference:
src/common/perf_counters.h — ``PerfCountersBuilder`` :59-116 with
``add_u64_counter``/``add_u64_avg``/``add_time_avg``/histogram adders
:83-99; per-subsystem collections registered in the CephContext and dumped
over the admin socket as ``perf dump``).  Averages store (sum, count) pairs
and dump as {avgcount, sum, avgtime} exactly like the reference so existing
``perf dump`` consumers parse them.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

PERFCOUNTER_U64 = "u64"
PERFCOUNTER_COUNTER = "counter"
PERFCOUNTER_AVG = "avg"
PERFCOUNTER_TIME_AVG = "time_avg"
PERFCOUNTER_HISTOGRAM = "histogram"


@dataclass
class _Metric:
    kind: str
    description: str = ""
    value: float = 0
    sum: float = 0.0
    count: int = 0
    buckets: list[float] = field(default_factory=list)   # histogram bounds
    bucket_counts: list[int] = field(default_factory=list)


class PerfCounters:
    """One subsystem's counters (e.g. 'osd', 'ec_backend').

    Monotonic accumulation (``inc`` on counter/avg kinds, ``tinc``,
    ``hinc``) shards into per-thread cells: the owning thread mutates
    its cell without the lock (single writer + GIL), and read surfaces
    (:meth:`get`, :meth:`dump`) fold base + cells under the lock.  This
    removes the instrument-lock contention class on reactor/worker hot
    paths (ISSUE 18) without changing any dump shape.  Gauges keep the
    locked base path: ``set``/``dec`` (and ``inc`` on a plain u64) are
    read-modify-write on one authoritative value, which a shard cannot
    provide — and they are control-plane-rate, not per-op-rate."""

    def __init__(self, name: str):
        self.name = name
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._local = threading.local()
        # thread ident -> that thread's {key: [value, sum, count,
        # bucket_counts|None]} cells.  Registered under _lock; folded
        # (non-destructively) by readers under _lock.
        self._cells: dict[int, dict] = {}

    # -- per-thread cells ---------------------------------------------------

    def _cell(self, key: str) -> list:
        cells = getattr(self._local, "cells", None)
        if cells is None:
            cells = self._local.cells = {}
            ident = threading.get_ident()
            with self._lock:
                old = self._cells.get(ident)
                if old is not None:
                    # a dead thread's ident was reused: bank its deltas
                    # into the base before the new owner takes the slot
                    self._absorb_locked(old)
                self._cells[ident] = cells
        c = cells.get(key)
        if c is None:
            c = cells[key] = [0, 0.0, 0, None]
        return c

    def _absorb_locked(self, cells: dict) -> None:
        """Fold one thread's cell deltas into the base metrics and zero
        them (under ``self._lock``, for a cell map whose owner is gone)."""
        for key, c in cells.items():
            m = self._metrics.get(key)
            if m is None:
                continue
            m.value += c[0]
            m.sum += c[1]
            m.count += c[2]
            if c[3] is not None:
                for i, n in enumerate(c[3]):
                    m.bucket_counts[i] += n
            cells[key] = [0, 0.0, 0, None]

    def _folded_locked(self, m: _Metric, key: str):
        """(value, sum, count, bucket_counts) with every live cell's
        deltas folded in — read-only, under ``self._lock``."""
        value, total, count = m.value, m.sum, m.count
        bc = list(m.bucket_counts) if m.bucket_counts else []
        for cells in self._cells.values():
            c = cells.get(key)
            if c is None:
                continue
            value += c[0]
            total += c[1]
            count += c[2]
            if c[3] is not None:
                for i, n in enumerate(c[3]):
                    bc[i] += n
        return value, total, count, bc

    # -- updates -----------------------------------------------------------

    def inc(self, key: str, amount: int = 1) -> None:
        m = self._metrics[key]
        if m.kind == PERFCOUNTER_AVG:
            c = self._cell(key)
            c[1] += amount
            c[2] += 1
        elif m.kind == PERFCOUNTER_COUNTER:
            self._cell(key)[0] += amount
        else:
            # plain u64 gauges share the locked path with set/dec
            with self._lock:
                m.value += amount

    def dec(self, key: str, amount: int = 1) -> None:
        with self._lock:
            self._metrics[key].value -= amount

    def set(self, key: str, value) -> None:
        with self._lock:
            self._metrics[key].value = value

    def get(self, key: str) -> float:
        """Current value of a plain counter/gauge (cell deltas folded)."""
        with self._lock:
            m = self._metrics[key]
            return self._folded_locked(m, key)[0]

    def tinc(self, key: str, seconds: float) -> None:
        """Add one timed sample (the reference's utime_t tinc)."""
        c = self._cell(key)
        c[1] += seconds
        c[2] += 1

    def hinc(self, key: str, value: float) -> None:
        m = self._metrics[key]
        c = self._cell(key)
        if c[3] is None:
            c[3] = [0] * (len(m.buckets) + 1)
        for i, bound in enumerate(m.buckets):
            if value <= bound:
                c[3][i] += 1
                break
        else:
            c[3][-1] += 1
        c[1] += value
        c[2] += 1

    class _Timer:
        def __init__(self, pc, key):
            self.pc, self.key = pc, key

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.pc.tinc(self.key, time.perf_counter() - self.t0)
            return False

    def time(self, key: str) -> "_Timer":
        return self._Timer(self, key)

    # -- dump --------------------------------------------------------------

    def dump(self) -> dict:
        out = {}
        with self._lock:
            for key, m in self._metrics.items():
                value, total, count, bc = self._folded_locked(m, key)
                if m.kind in (PERFCOUNTER_AVG, PERFCOUNTER_TIME_AVG):
                    entry = {"avgcount": count, "sum": total}
                    if count:
                        entry["avgtime" if m.kind == PERFCOUNTER_TIME_AVG
                              else "avgvalue"] = total / count
                    out[key] = entry
                elif m.kind == PERFCOUNTER_HISTOGRAM:
                    out[key] = {"sum": total, "count": count,
                                "buckets": dict(zip(
                                    [str(b) for b in m.buckets] + ["inf"],
                                    bc))}
                else:
                    out[key] = value
        return out


class PerfCountersBuilder:
    """(perf_counters.h:59-116)."""

    def __init__(self, name: str):
        self._pc = PerfCounters(name)

    def add_u64(self, key: str, description: str = "") -> "PerfCountersBuilder":
        self._pc._metrics[key] = _Metric(PERFCOUNTER_U64, description)
        return self

    def add_u64_counter(self, key: str,
                        description: str = "") -> "PerfCountersBuilder":
        self._pc._metrics[key] = _Metric(PERFCOUNTER_COUNTER, description)
        return self

    def add_u64_avg(self, key: str,
                    description: str = "") -> "PerfCountersBuilder":
        self._pc._metrics[key] = _Metric(PERFCOUNTER_AVG, description)
        return self

    def add_time_avg(self, key: str,
                     description: str = "") -> "PerfCountersBuilder":
        self._pc._metrics[key] = _Metric(PERFCOUNTER_TIME_AVG, description)
        return self

    def add_histogram(self, key: str, buckets: list[float],
                      description: str = "") -> "PerfCountersBuilder":
        m = _Metric(PERFCOUNTER_HISTOGRAM, description,
                    buckets=list(buckets))
        m.bucket_counts = [0] * (len(buckets) + 1)
        self._pc._metrics[key] = m
        return self

    def create_perf_counters(self) -> PerfCounters:
        return self._pc


class PerfCountersCollection:
    """Process-wide registry dumped as one JSON doc (perf dump)."""

    def __init__(self):
        self._loggers: dict[str, PerfCounters] = {}
        self._lock = threading.Lock()

    def add(self, pc: PerfCounters) -> None:
        with self._lock:
            self._loggers[pc.name] = pc

    def remove(self, name: str) -> None:
        with self._lock:
            self._loggers.pop(name, None)

    def get(self, name: str) -> PerfCounters | None:
        with self._lock:
            return self._loggers.get(name)

    def snapshot(self) -> dict[str, PerfCounters]:
        """Locked copy of the registry — the safe way to iterate
        collections while other threads register/remove them (health
        checks, exporters, `top`)."""
        with self._lock:
            return dict(self._loggers)

    def perf_dump(self) -> dict:
        with self._lock:
            loggers = dict(self._loggers)
        return {name: pc.dump() for name, pc in sorted(loggers.items())}
