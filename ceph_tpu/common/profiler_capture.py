"""XLA profiler capture windows: programmatic, bounded, auto-triggered.

The roofline ledger (common/roofline.py) says WHICH executable runs far
from peak; the XLA profiler trace says WHY (pipeline bubbles, transfer
stalls, fusion shapes).  The reference discipline applies: profiling is
expensive and process-global, so it must be a deliberate WINDOW — never
an always-on tax on the hot path — and every capture must land in a
BOUNDED on-disk directory.  This module is the only place in the tree
allowed to touch ``jax.profiler`` (tests/test_profiler_guard.py):

- ``device profile start|stop|status`` admin commands open/close a
  capture window on demand (TensorBoard-loadable trace under
  ``<out_dir>/capture-*``);
- :meth:`ProfilerCapture.auto_capture` takes a rate-limited one-shot
  capture on any WARN/ERR health transition (wired next to the flight
  recorder dump in ``cluster._on_health_transition``): cooldown-gated so
  a flapping check cannot churn the profiler, window-bounded by
  ``mgr_profiler_auto_window`` (0 = start+stop immediately — the
  zero-risk default: the artifact marks the moment, the operator opens
  a real window to investigate);
- the capture directory is bounded by ``mgr_profiler_max_captures``
  (oldest captures removed, the flight recorder's disk discipline).

The profiler backend is injectable (``profiler=``) so tests exercise
every path without jax; the real one loads lazily and only when an XLA
backend already initialized (device_telemetry's never-wedge rule).
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
from pathlib import Path

# jax.profiler state is process-global: two capture owners in one
# process must not interleave start/stop windows
_ACTIVE_OWNER: "ProfilerCapture | None" = None
_GLOBAL_LOCK = threading.Lock()


def _sanitize(reason: str) -> str:
    return "".join(ch if ch.isalnum() or ch in "-_" else "_"
                   for ch in reason)[:60]


class ProfilerCapture:
    """Bounded on-disk XLA profiler capture windows + auto-capture."""

    ADMIN_COMMANDS = ("device profile start", "device profile stop",
                      "device profile status")

    def __init__(self, cct=None, out_dir=None, max_captures: int | None = None,
                 cooldown_s: float | None = None,
                 auto_window_s: float | None = None, profiler=None):
        from .context import default_context
        self.cct = cct if cct is not None else default_context()
        conf = self.cct.conf
        self.out_dir = Path(out_dir) if out_dir is not None else None
        self.max_captures = int(conf.get("mgr_profiler_max_captures")
                                if max_captures is None else max_captures)
        self.cooldown_s = float(conf.get("mgr_profiler_cooldown")
                                if cooldown_s is None else cooldown_s)
        self.auto_window_s = float(conf.get("mgr_profiler_auto_window")
                                   if auto_window_s is None
                                   else auto_window_s)
        self._profiler = profiler
        self._lock = threading.Lock()
        self._active: dict | None = None
        self._last_auto = 0.0
        self._auto_timer: threading.Timer | None = None
        self._owns_admin = False
        self.auto_captures = 0
        self.auto_skipped = 0

    # -- backend -----------------------------------------------------------

    def _load_profiler(self):
        """The real ``jax.profiler``, lazily — and only when an XLA
        backend ALREADY initialized (a capture request must never be the
        thing that dials a wedged tunnel)."""
        if self._profiler is not None:
            return self._profiler
        from . import device_telemetry
        if not device_telemetry.backend_ready():
            raise RuntimeError(
                "ProfilerUnavailable: no XLA backend initialized in this "
                "process (run device work first, or device dump "
                "initialize=true)")
        import jax
        self._profiler = jax.profiler
        return self._profiler

    # -- windows -----------------------------------------------------------

    def start(self, reason: str = "manual") -> dict:
        """Open a capture window.  Returns ``{path, reason, ...}`` or
        ``{error}`` — admin/auto callers must never crash the process
        over a profiler problem."""
        global _ACTIVE_OWNER
        if self.out_dir is None:
            return {"error": "profiler captures disabled "
                             "(no capture directory: run durable)"}
        with _GLOBAL_LOCK:
            if _ACTIVE_OWNER is not None:
                return {"error": "a profiler capture is already active "
                                 "in this process"}
            _ACTIVE_OWNER = self
        path = self.out_dir / (f"capture-{int(time.time())}-"
                               f"{os.getpid()}-{_sanitize(reason)}")
        try:
            profiler = self._load_profiler()
            path.mkdir(parents=True, exist_ok=True)
            profiler.start_trace(str(path))
        except Exception as e:
            with _GLOBAL_LOCK:
                _ACTIVE_OWNER = None
            # don't leave an empty capture dir behind a failed start
            shutil.rmtree(path, ignore_errors=True)
            return {"error": repr(e)[:200]}
        with self._lock:
            self._active = {"path": str(path), "reason": reason,
                            "started": time.time()}
            return dict(self._active)

    def stop(self) -> dict:
        """Close the active window, stamp ``capture.json`` metadata into
        it, and bound the capture directory.  Any pending auto-stop
        timer is cancelled: once THIS stop closes the window, a stale
        timer firing later must not kill an unrelated window the
        operator opened in the meantime."""
        global _ACTIVE_OWNER
        with self._lock:
            active, self._active = self._active, None
            timer, self._auto_timer = self._auto_timer, None
        if timer is not None:
            timer.cancel()
        if active is None:
            return {"error": "no active profiler capture"}
        err = None
        try:
            self._load_profiler().stop_trace()
        except Exception as e:       # the window state must clear anyway
            err = repr(e)[:200]
        with _GLOBAL_LOCK:
            if _ACTIVE_OWNER is self:
                _ACTIVE_OWNER = None
        active["stopped"] = time.time()
        active["duration_s"] = round(active["stopped"] - active["started"],
                                     6)
        if err is not None:
            active["error"] = err
        try:
            with open(Path(active["path"]) / "capture.json", "w") as f:
                json.dump(active, f)
        except Exception:
            pass
        self._bound_disk()
        return active

    def status(self) -> dict:
        with self._lock:
            active = dict(self._active) if self._active else None
        return {"active": active,
                "out_dir": str(self.out_dir) if self.out_dir else None,
                "captures": self.captures(),
                "auto_captures": self.auto_captures,
                "auto_skipped": self.auto_skipped,
                "cooldown_s": self.cooldown_s}

    def captures(self) -> list[str]:
        """On-disk capture directories, oldest first."""
        if self.out_dir is None:
            return []
        try:
            return sorted((str(p) for p in self.out_dir.glob("capture-*")
                           if p.is_dir()),
                          key=lambda p: os.path.getmtime(p))
        except OSError:
            return []

    def _bound_disk(self) -> None:
        caps = self.captures()
        for stale in caps[:max(0, len(caps) - self.max_captures)]:
            shutil.rmtree(stale, ignore_errors=True)

    # -- auto-capture (health-transition hook) ------------------------------

    def auto_capture(self, reason: str = "health") -> dict | None:
        """One rate-limited capture around an anomaly: called from the
        health engine's WARN/ERR transition hook, next to the flight
        recorder dump.  Never raises; returns the capture info or None
        (disabled / already active / inside the cooldown / profiler
        unavailable).  The window is ``auto_window_s`` long — 0 stops
        immediately (marker capture), >0 stops on a daemon timer."""
        try:
            now = time.monotonic()
            with self._lock:
                if self.out_dir is None or self._active is not None or \
                        (self._last_auto and
                         now - self._last_auto < self.cooldown_s):
                    self.auto_skipped += 1
                    return None
                self._last_auto = now
            info = self.start(reason=f"auto-{reason}")
            if "error" in info:
                self.auto_skipped += 1
                return None
            self.auto_captures += 1
            if self.auto_window_s <= 0:
                return self.stop()
            t = threading.Timer(self.auto_window_s, self._auto_stop)
            t.daemon = True
            with self._lock:
                self._auto_timer = t
            t.start()
            return info
        except Exception:            # incident-time: degrade, don't die
            return None

    def _auto_stop(self) -> None:
        try:
            self.stop()
        except Exception:
            pass

    # -- admin-socket surface ----------------------------------------------

    def register_admin(self, admin_socket=None) -> None:
        """Takeover-register the three window commands (the flight
        recorder's idiom: newest owner wins; close() unregisters only
        while still the owner)."""
        sock = admin_socket if admin_socket is not None \
            else self.cct.admin_socket
        self._admin_sock = sock
        self._admin_fns = {
            "device profile start":
                lambda reason="admin", **kw: self.start(reason=reason),
            "device profile stop": lambda **kw: self.stop(),
            "device profile status": lambda **kw: self.status(),
        }
        help_text = {
            "device profile start": "open an XLA profiler capture window "
                                    "(TensorBoard trace under the "
                                    "capture directory)",
            "device profile stop": "close the active profiler capture "
                                   "window and bound the capture dir",
            "device profile status": "active window + on-disk captures "
                                     "+ auto-capture counters",
        }
        for name, fn in self._admin_fns.items():
            sock.unregister(name)
            sock.register(name, fn, help_text[name])
        self._owns_admin = True

    def close(self) -> None:
        with self._lock:
            t, self._auto_timer = self._auto_timer, None
        if t is not None:
            t.cancel()
        if self._active is not None:
            self.stop()
        if self._owns_admin:
            for name, fn in self._admin_fns.items():
                if self._admin_sock.get(name) is fn:
                    self._admin_sock.unregister(name)
            self._owns_admin = False
