"""Device telemetry: JAX/XLA backend introspection as a perf collection.

The observability gap this closes: every BENCH artifact and every perf
number in this repo is meaningless without knowing WHAT hardware produced
it (the BENCH trajectory was CPU-marked by prose only), and a serving
process needs live device-memory pressure the way the reference watches
BlueStore utilization.  This module exposes:

- :func:`device_inventory` — platform / device kind / count / jax
  version.  ``initialize=False`` (the default) never triggers a backend
  init: until an XLA backend has ACTUALLY initialized in this process
  (:func:`backend_ready` — importing jax alone is not enough, the first
  ``jax.devices()`` call is what starts init), the inventory degrades
  to version-only.  That discipline matters because backend init can
  WEDGE over the axon tunnel (bench.py probes it in a subprocess for
  exactly this reason) — telemetry must never be the thing that hangs
  the process.
- :func:`memory_stats` / :func:`live_buffer_bytes` — per-device memory
  stats where the backend exposes them (``Device.memory_stats()``; TPU
  backends report bytes_in_use/peak, CPU usually returns nothing) and the
  total bytes pinned by live jax arrays.
- :func:`compile_cache_stats` — size of the traced_jit AOT key registry
  (the compile-cache the RECOMPILE_STORM health check watches).
- :func:`refresh` — pushes all of the above into a ``device``
  PerfCounters collection on a Context, so ``perf dump`` and the
  prometheus exporter carry device gauges with zero extra wiring.

Stdlib-importable: jax is only touched inside functions, and only when
already loaded (or when ``initialize=True`` is explicit).
"""
from __future__ import annotations

import sys

from . import tracer as tracer_mod

DEVICE_COLLECTION = "device"


def jax_version() -> str | None:
    """The installed jax version WITHOUT importing jax (importlib
    metadata only — safe before any backend probe)."""
    try:
        from importlib.metadata import version
        return version("jax")
    except Exception:
        return None


def backend_ready() -> bool:
    """True only when an XLA backend has ALREADY initialized in this
    process.  ``"jax" in sys.modules`` is not enough: merely importing
    jax (which the codec does at module scope) leaves the backend
    uninitialized, and the first ``jax.devices()`` call would START init
    — the hang this module must never cause.  Reads the bridge's backend
    cache; if that private surface moves in a future jax, degrade to
    False (telemetry goes dark rather than wedging a scrape)."""
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge
        return bool(xla_bridge._backends)
    except Exception:
        return False


def device_inventory(initialize: bool = False) -> dict:
    """Platform/device summary.  Never initializes a backend unless
    ``initialize=True``; errors degrade to an ``error`` field rather than
    raising (telemetry must not take the process down)."""
    info: dict = {"jax_version": jax_version(), "platform": None,
                  "device_kind": None, "num_devices": 0}
    if not initialize and not backend_ready():
        return info
    try:
        import jax
        devs = jax.devices()
        info.update(platform=devs[0].platform,
                    device_kind=getattr(devs[0], "device_kind", None),
                    num_devices=len(devs))
        info["devices"] = [
            {"id": d.id, "platform": d.platform,
             "kind": getattr(d, "device_kind", None)} for d in devs]
    except Exception as e:                       # backend down / wedged
        info["error"] = repr(e)[:200]
    return info


def memory_stats(initialize: bool = False) -> dict[str, dict]:
    """Per-device memory stats where the backend exposes them (the PJRT
    ``memory_stats()`` surface: bytes_in_use, peak_bytes_in_use,
    bytes_limit on TPU/GPU).  Guarded per device AND per field: a CPU
    backend may lack the method entirely, return ``None``, or return a
    non-dict — every shape degrades to that device being absent from the
    snapshot (partial data, never a raise)."""
    if not initialize and not backend_ready():
        return {}
    out: dict[str, dict] = {}
    try:
        import jax
        for d in jax.devices():
            st = None
            try:
                if hasattr(d, "memory_stats"):
                    st = d.memory_stats()
            except Exception:
                st = None
            if isinstance(st, dict) and st:
                out[f"{d.platform}:{d.id}"] = dict(st)
    except Exception:
        pass
    return out


# session high-water marks per device: the backend's own
# peak_bytes_in_use can reset (client restart, stats clear); the module
# keeps the max ever observed in THIS process so HBM_PRESSURE sees the
# true watermark even between samples
_hbm_high_water: dict[str, int] = {}


def hbm_watermarks(initialize: bool = False) -> dict[str, dict]:
    """Per-device HBM watermark sample: bytes in use, backend peak,
    bytes limit, and the session high-water mark (max observed across
    samples).  Devices whose backend lacks memory stats (CPU) simply
    don't appear — the HBM_PRESSURE health check reads this and stays
    silent on such platforms."""
    out: dict[str, dict] = {}
    for dev, st in memory_stats(initialize).items():
        try:
            in_use = int(st.get("bytes_in_use", 0) or 0)
            peak = int(st.get("peak_bytes_in_use", 0) or 0)
            limit = int(st.get("bytes_limit", 0) or 0)
        except (TypeError, ValueError):     # backend-specific field shapes
            continue
        hw = max(_hbm_high_water.get(dev, 0), peak, in_use)
        _hbm_high_water[dev] = hw
        rec = {"bytes_in_use": in_use, "peak_bytes_in_use": peak,
               "bytes_limit": limit, "high_water_bytes": hw}
        if limit > 0:
            rec["high_water_ratio"] = round(hw / limit, 4)
        out[dev] = rec
    return out


def live_buffer_bytes(initialize: bool = False) -> int:
    """Total bytes held by live jax arrays in this process (the
    device-resident working set; ``jax.live_arrays``)."""
    if not initialize and not backend_ready():
        return 0
    try:
        import jax
        return int(sum(getattr(a, "nbytes", 0) or 0
                       for a in jax.live_arrays()))
    except Exception:
        return 0


def compile_cache_stats() -> dict:
    """traced_jit registry size + aggregate compile counters (the
    process compile-cache view; no jax import needed — the registry
    lives in common.tracer)."""
    jd = tracer_mod.jit_dump()
    counters = jd["counters"]
    return {"keys": jd["num_keys"],
            "compilations": counters.get("compilations", 0),
            "cache_hits": counters.get("cache_hits", 0)}


def _device_perf(cct):
    """The Context's ``device`` collection, built lazily on first
    refresh (a jax-free process never grows one)."""
    pc = cct.perf.get(DEVICE_COLLECTION)
    if pc is None:
        from .perf_counters import PerfCountersBuilder
        pc = (PerfCountersBuilder(DEVICE_COLLECTION)
              .add_u64("num_devices", "accelerator devices visible to jax")
              .add_u64("live_buffer_bytes",
                       "bytes held by live jax arrays (device-resident "
                       "working set)")
              .add_u64("mem_bytes_in_use",
                       "backend-reported bytes in use, summed over devices")
              .add_u64("mem_peak_bytes_in_use",
                       "backend-reported peak bytes in use, summed over "
                       "devices")
              .add_u64("mem_bytes_limit",
                       "backend-reported memory capacity, summed over "
                       "devices (0 where the backend lacks it)")
              .add_u64("hbm_high_water_bytes",
                       "session high-water device-memory mark, summed "
                       "over devices (feeds HBM_PRESSURE)")
              .add_u64("compile_cache_keys",
                       "distinct (function, shape) keys in the traced_jit "
                       "compile cache")
              .create_perf_counters())
        cct.perf.add(pc)
    return pc


def refresh(cct, initialize: bool = False) -> dict:
    """Take one telemetry snapshot and push it into the Context's
    ``device`` perf collection.  Returns the full snapshot (the
    ``device dump`` admin command / flight-recorder source)."""
    inv = device_inventory(initialize)
    mem = memory_stats(initialize)
    marks = hbm_watermarks(initialize)
    live = live_buffer_bytes(initialize)
    cache = compile_cache_stats()
    pc = _device_perf(cct)
    pc.set("num_devices", inv["num_devices"])
    pc.set("live_buffer_bytes", live)
    # guarded field folds: a backend may report partial stat sets
    pc.set("mem_bytes_in_use",
           sum(int(s.get("bytes_in_use", 0) or 0) for s in mem.values()))
    pc.set("mem_peak_bytes_in_use",
           sum(int(s.get("peak_bytes_in_use", 0) or 0)
               for s in mem.values()))
    pc.set("mem_bytes_limit",
           sum(m["bytes_limit"] for m in marks.values()))
    pc.set("hbm_high_water_bytes",
           sum(m["high_water_bytes"] for m in marks.values()))
    pc.set("compile_cache_keys", cache["keys"])
    return {"inventory": inv, "memory": mem, "watermarks": marks,
            "live_buffer_bytes": live, "compile_cache": cache}
