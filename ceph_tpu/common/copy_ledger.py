"""The payload copy ledger: every remaining host copy, counted.

The zero-copy data path (ROADMAP item 2) is a claim about BYTES MOVED,
so the win has to be measured, not asserted: this module is the single
place every surviving payload copy between the socket and the device
reports to, and the place payload bytes *served* (consumed by a
dispatch handler or landed in a client callback) are tallied against.
The quotient — ``bytes_copied_per_byte_served`` — is the PR's success
metric: ~3 on the legacy pickle path (pickle + frame join + unpickle
per direction), ~1 on the sideband path (one staging copy), and the
perf gate holds the fused arm under an absolute cap so a regression
that quietly reintroduces a copy fails CI instead of a code review.

Copy *sources* are a small closed vocabulary so dashboards and tests
can pin them:

- ``pickle`` / ``join`` / ``unpickle`` — the legacy codec's three
  copies per direction (``net._encode`` pickling payload-bearing
  messages, ``frame_encode``'s segment join, ``net._decode``'s loads);
- ``staging``     — the ONE sanctioned sideband copy: wire segments
  landing in a pooled staging buffer (``msg/staging.py``);
- ``materialize`` — a staged view pinned down to owned bytes where a
  consumer outlives the buffer (client result landing);
- ``compaction`` / ``fallback`` — the stream parser's own amortized
  compaction and retained-view ``BufferError`` recovery copies, counted
  so the ratio cannot silently undercount the parser (ISSUE 20
  satellite 1);
- ``relayout``    — host shard-major relayout on the codec pack path.

Counting rides the :mod:`instruments` kill-switch and the same
per-thread sharded cells as :mod:`perf_counters` (lock-free on the
reactor/worker hot paths); the ledger is a process-global singleton the
prometheus exporter and the stats digest read directly, the same
live-registry idiom ``wire_accounting`` uses.
"""
from __future__ import annotations

import threading

from . import instruments

# the closed source vocabulary (tests pin it; prometheus labels draw
# from it)
COPY_SOURCES = ("pickle", "join", "unpickle", "staging", "materialize",
                "compaction", "fallback", "relayout")

# payload-size floor shared by the sideband codec and the ledger: blobs
# under this ride the pickled control header (a 64-bit rid costs more
# to sideband than to pickle), and neither their copies nor their bytes
# count — the two sides must agree or the ratio skews
PAYLOAD_MIN = 32


class CopyLedger:
    """Sharded byte counters for payload copies vs payload bytes served."""

    def __init__(self):
        self._lock = threading.Lock()
        self._local = threading.local()
        # folded totals (absorbed from dead/hot cells on read)
        self._copied: dict[str, int] = {s: 0 for s in COPY_SOURCES}
        self._served = 0
        self._cells: list[dict] = []

    def _cell(self) -> dict:
        c = getattr(self._local, "cell", None)
        if c is None:
            c = {"served": 0}
            self._local.cell = c
            with self._lock:
                self._cells.append(c)
        return c

    # -- hot path --------------------------------------------------------

    def count_copy(self, source: str, nbytes: int) -> None:
        """One payload copy of ``nbytes`` attributed to ``source``."""
        if nbytes <= 0 or not instruments.enabled():
            return
        cell = self._cell()
        cell[source] = cell.get(source, 0) + int(nbytes)

    def count_served(self, nbytes: int) -> None:
        """``nbytes`` of payload reached its consumer (dispatch handler
        or client completion) — the denominator."""
        if nbytes <= 0 or not instruments.enabled():
            return
        self._cell()["served"] += int(nbytes)

    # -- read side -------------------------------------------------------

    def _fold_locked(self) -> None:
        for cell in self._cells:
            for k in list(cell):
                v = cell[k]
                if not v:
                    continue
                cell[k] = 0
                if k == "served":
                    self._served += v
                else:
                    self._copied[k] = self._copied.get(k, 0) + v

    def snapshot(self) -> dict:
        with self._lock:
            self._fold_locked()
            copied = dict(self._copied)
            served = self._served
        total = sum(copied.values())
        return {"copied": copied, "copied_total": total,
                "served": served,
                "copies_per_byte": (total / served) if served else 0.0}

    def copies_per_byte(self) -> float:
        return self.snapshot()["copies_per_byte"]

    def reset(self) -> None:
        """Zero everything (bench arms snapshot a clean window)."""
        with self._lock:
            self._fold_locked()
            self._copied = {s: 0 for s in COPY_SOURCES}
            self._served = 0


_LEDGER = CopyLedger()


def ledger() -> CopyLedger:
    """The process-global ledger (live-registry accessor the prometheus
    ``_copy_gauges`` family and the stats digest read)."""
    return _LEDGER


def count_copy(source: str, nbytes: int) -> None:
    _LEDGER.count_copy(source, nbytes)


def count_served(nbytes: int) -> None:
    _LEDGER.count_served(nbytes)
