"""Critical-path latency decomposition: where did this op's wall time go.

The stitched distributed traces (PR 6) carry every span of a completed
op — client dispatch, daemon queue, batch formation, device compute,
per-shard wire hops — but nothing folds them into the number an
operator (or the SLO engine, ``mgr/slo.py``) actually needs: *per-phase
attribution* — "client p99 = 41 ms: 62% batch_delay, 21% device, 9%
wire".  Online-EC tail-latency studies (PAPERS.md, arXiv:1709.05365)
show the phase MIX is what shifts under load; a single latency number
cannot distinguish "the device got slower" from "the batching deadline
got longer" from "retries are eating the budget".

This module provides:

- the **canonical phase taxonomy** (:data:`PHASES`): ``queue`` (op sat
  in a daemon/engine queue), ``admission`` (throttle wait),
  ``batch_delay`` (coalescer deadline wait for companions),
  ``dispatch`` (host-side prep of a device dispatch), ``device``
  (device compute + transfers), ``wire`` (cross-daemon hops: bus
  envelopes, RPC frames), ``retry`` (resends / backoff / host
  fallback), ``other`` (everything unattributed);
- the **span->phase registry** (:data:`SPAN_PHASES` + prefix rules):
  every span name the tracer emits maps to a declared phase, and
  ``tests/test_span_phase_guard.py`` enforces that new spans in the
  serving/recovery/pipeline layers DECLARE one (an explicit ``phase=``
  span arg overrides the registry);
- :func:`decompose`: derive one completed op's critical path from its
  stitched span tree — each span's SELF time (duration minus the union
  of its children, overlap-clamped so concurrent children never
  double-count, the ``device_attribution`` clamping convention) charges
  its phase; the per-phase seconds SUM to the trace's total wall time
  (the acceptance invariant);
- :class:`CritPathLedger`: a bounded per-op-class ledger folding
  completed traces from the tracer ring into per-class phase
  attribution + latency records — the source of ``slo status``'s
  attribution table, the ``ceph_tpu_latency_phase_seconds`` prometheus
  family, and the SLO engine's good/bad op stream.

Stdlib-only (the tracer's discipline): importable before any JAX
backend initializes, and usable by ``tools/slo_report.py`` on a trace
dump alone.
"""
from __future__ import annotations

import os
import re
import threading
import time
import weakref
from collections import defaultdict, deque

# the tracer ring's event capacity (mirrors tracer.TRACE_CAPACITY
# without importing it: this module must stay loadable by PATH for
# tools/slo_report.py).  Sizes the ledger's seen-trace bound: the ring
# holds at most this many events, hence at most this many distinct
# trace ids — a seen-set twice as large can never evict an id whose
# events are still foldable.
TRACE_CAPACITY_HINT = int(os.environ.get("CEPH_TPU_TRACE_CAPACITY",
                                         16384))

try:
    from .device_attribution import canonical_owner
    from .percentile import nearest_rank, weighted_nearest_rank
except ImportError:
    # loaded standalone by PATH (tools/slo_report.py on a raw trace
    # dump): pull the two stdlib-only siblings the same way
    import importlib.util as _ilu
    import os as _os
    _here = _os.path.dirname(_os.path.abspath(__file__))

    def _sibling(name):
        spec = _ilu.spec_from_file_location(
            f"_critpath_{name}", _os.path.join(_here, f"{name}.py"))
        mod = _ilu.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    canonical_owner = _sibling("device_attribution").canonical_owner
    _pct = _sibling("percentile")
    nearest_rank = _pct.nearest_rank
    weighted_nearest_rank = _pct.weighted_nearest_rank

# -- the canonical phase taxonomy -------------------------------------------

QUEUE = "queue"              # waiting in a daemon/engine queue
ADMISSION = "admission"      # blocked on an admission throttle
BATCH_DELAY = "batch_delay"  # coalescer deadline wait for companions
DISPATCH = "dispatch"        # host-side prep of a device dispatch
DEVICE = "device"            # device compute + host<->device transfer
WIRE = "wire"                # cross-daemon hops (bus envelopes, RPC)
RETRY = "retry"              # resends, backoff sleeps, host fallback
OTHER = "other"              # unattributed self time

PHASES = (QUEUE, ADMISSION, BATCH_DELAY, DISPATCH, DEVICE, WIRE, RETRY,
          OTHER)

# -- the span -> phase registry ---------------------------------------------
#
# Exact span names first; the two prefix rules below catch the open-ended
# families (per-message-type bus spans, per-method RPC spans).  A span
# may also carry an explicit ``phase=<name>`` arg, which wins — the API
# for call sites whose name cannot be enumerated here.

SPAN_PHASES: dict[str, str] = {
    # queue: emitted by the OSD daemon when a queued op finally runs
    "osd.queue_wait": QUEUE,
    # admission: serving-engine throttle wait (emitted only when the
    # throttle actually blocked the submitter)
    "serving.admission": ADMISSION,
    # batch formation: submit-to-dispatch wait inside the op coalescer
    "serving.batch_wait": BATCH_DELAY,
    # dispatch: host-side prep on the way to the device
    "pipeline.pack": DISPATCH,
    "pipeline.dispatch": DISPATCH,
    "pg.generate_transactions": DISPATCH,
    "crush.bulk_map": DISPATCH,
    "codec.decode_matrix_build": DISPATCH,
    "jit.trace": DISPATCH,
    "jit.compile": DISPATCH,
    "recovery.wave": DISPATCH,
    # chained streaming repair: plan building on the coordinator, then
    # one scale-accumulate per survivor hop (device or exact host GF)
    "recovery.chain": DISPATCH,
    "recovery.chain_hop": DISPATCH,
    # regenerating-code repair: plan assembly on the coordinator, then
    # one projection/combine inner product per helper/newcomer hop
    "recovery.regen": DISPATCH,
    "recovery.regen_hop": DISPATCH,
    # mux: per-riding-call stamps around batched RpcBatch /
    # RpcResultBatch frames (msg/client.py sender loop, msg/server.py
    # dispatcher) — cross-daemon frame time, hence wire
    "mux.batch_send": WIRE,
    "mux.batch_reply": WIRE,
    # device: compute + transfers (the codec spans wrap the actual
    # device/SIMD work; ec.* self-time is pack/scatter around it)
    "codec.encode": DEVICE,
    "codec.decode": DEVICE,
    "codec.decode_batch": DEVICE,
    "codec.encode_host": DEVICE,
    "codec.decode_host": DEVICE,
    "codec.table_upload": DEVICE,
    "jit.first_dispatch": DEVICE,
    "serving.batch_encode": DEVICE,
    "serving.batch_decode": DEVICE,
    "pipeline.complete": DEVICE,
    "ec.encode": DEVICE,
    "ec.decode": DEVICE,
    "ec.decode_wave": DEVICE,
    "codec.scale_accumulate": DEVICE,
    # retry: resends / backoff / circuit-broken host fallback
    "pipeline.host_fallback": RETRY,
    "net.resend": RETRY,
    "client.op_retry": RETRY,
    "client.backoff_resend": RETRY,
    # other: op-engine execution and client-side machinery (the residual
    # a dedicated phase does not yet name)
    "client.op": OTHER,
    "client.rpc": OTHER,
    "osd.op": OTHER,
    "serving.op": OTHER,
    "backfill.pg": OTHER,
    # cache tier (tier/service.py): the proxy read forwards across the
    # tier boundary to the base pool (wire-shaped hop); promotion,
    # writeback flush, and eviction are data-movement orchestration
    # whose leaf work (codec, store) claims its own phases
    "tier.read": OTHER,
    "tier.write": OTHER,
    "tier.agent": OTHER,
    "tier.proxy_read": WIRE,
    "tier.proxy_write": WIRE,
    "tier.promote": DISPATCH,
    "tier.flush": DISPATCH,
    "tier.evict": DISPATCH,
    # the dmClock-class background roots (osd_daemon.queue_background)
    "osd.client": OTHER,
    "osd.serving": OTHER,
    "osd.recovery": OTHER,
    "osd.scrub": OTHER,
    "osd.rebalance": OTHER,
}

# per-message-type bus dispatch spans: ``osd.<MsgType>`` with a CamelCase
# type name (backend/messages.py) — distinguished from the lowercase
# ``osd.op``/``osd.recovery`` daemon spans by the capital letter
_BUS_SPAN = re.compile(r"^osd\.[A-Z]")

#: (prefix, phase) rules for the open-ended span families
PREFIX_PHASES: tuple[tuple[str, str], ...] = (
    ("rpc.", WIRE),          # net.py per-method server spans
)


def declare(name: str, phase: str) -> None:
    """Register a new span name's phase (the extension point the
    span-phase guard steers new code toward)."""
    if phase not in PHASES:
        raise ValueError(f"unknown phase {phase!r} (choose from {PHASES})")
    SPAN_PHASES[name] = phase


def is_declared(name: str) -> bool:
    """True when ``name`` maps to a phase WITHOUT falling through to
    ``other``-by-default (the guard's question)."""
    if name in SPAN_PHASES or _BUS_SPAN.match(name):
        return True
    return any(name.startswith(p) for p, _ph in PREFIX_PHASES)


def phase_for(name: str, args: dict | None = None) -> str:
    """The phase a span charges its self time to: an explicit
    ``phase=`` span arg wins, then the exact-name registry, then the
    prefix rules; unknown names land in ``other``."""
    if args:
        explicit = args.get("phase")
        if explicit in PHASES:
            return explicit
    ph = SPAN_PHASES.get(name)
    if ph is not None:
        return ph
    if _BUS_SPAN.match(name):
        return WIRE
    for prefix, ph in PREFIX_PHASES:
        if name.startswith(prefix):
            return ph
    return OTHER


# -- critical-path extraction -----------------------------------------------

def _interval(ev: dict) -> tuple[float, float]:
    ts = float(ev["ts"])
    return ts, ts + float(ev.get("dur", 0.0))


def decompose(spans: list[dict], unmapped: dict | None = None
              ) -> dict | None:
    """Fold ONE trace's complete ('ph': 'X') span events into per-phase
    seconds.  ``spans`` must all belong to one trace (each carries
    ``args.span_id``/``args.parent_span_id`` the tracer stamped).

    The invariant: ``sum(phases.values()) == total_s`` (±float noise).
    Each span's self time is its duration minus the union of its
    children's intervals, every interval clipped to its parent and
    clamped against the previous sibling's trailing edge — so children
    that overlap (concurrent device batches, parallel shard hops)
    charge their UNION, never their sum, the same convention
    ``common/device_attribution`` uses for overlapping dispatches.
    Multiple roots (resent ops, sibling queue-wait events) contribute
    the union of their intervals to the total.

    Returns ``{total_s, phases, n_spans, op_class, end_ts_us}`` or None
    for an empty trace.  ``unmapped`` (optional dict) accumulates
    occurrence counts of span names that fell through to ``other``."""
    spans = [e for e in spans if e.get("ph") == "X"
             and "span_id" in e.get("args", ())]
    if not spans:
        return None
    ids = {e["args"]["span_id"] for e in spans}
    children: dict[int, list[dict]] = defaultdict(list)
    roots: list[dict] = []
    for e in spans:
        parent = e["args"].get("parent_span_id", 0)
        if parent and parent in ids:
            children[parent].append(e)
        else:
            roots.append(e)
    phases = dict.fromkeys(PHASES, 0.0)

    def charge(ev: dict, self_us: float) -> None:
        args = ev.get("args") or {}
        ph = phase_for(ev["name"], args)
        if unmapped is not None and ph == OTHER and \
                not is_declared(ev["name"]) and args.get("phase") is None:
            unmapped[ev["name"]] = unmapped.get(ev["name"], 0) + 1
        phases[ph] += self_us / 1e6

    def walk(ev: dict, lo: float, hi: float) -> None:
        s, t = _interval(ev)
        s, t = max(s, lo), min(t, hi)
        if t <= s:
            return                       # fully clamped away by siblings
        kids = sorted(children.get(ev["args"]["span_id"], ()),
                      key=lambda k: float(k["ts"]))
        covered = 0.0
        edge = s
        for k in kids:
            ks, kt = _interval(k)
            ks2, kt2 = max(ks, edge), min(kt, t)
            if kt2 > ks2:
                covered += kt2 - ks2
                edge = kt2
                walk(k, ks2, kt2)
        charge(ev, max(0.0, (t - s) - covered))

    roots.sort(key=lambda e: float(e["ts"]))
    total_us = 0.0
    edge = float("-inf")
    for r in roots:
        rs, rt = _interval(r)
        rs2 = max(rs, edge)
        if rt > rs2:
            total_us += rt - rs2
            edge = rt
            walk(r, rs2, rt)
    # op class: the root's stamped class, else the first span carrying
    # one (every ctx-linked span stamps op_class as of ISSUE 10)
    op_class = None
    for e in roots + spans:
        op_class = e.get("args", {}).get("op_class") \
            or e.get("args", {}).get("owner")
        if op_class:
            break
    # sample weight: head-sampled traces stamp 1/rate on their events
    # (tracer ISSUE 18); the trace's weight de-biases downstream rate
    # math (SLO windows, class percentiles).  Promoted slow ops carry no
    # weight — they represent only themselves.
    w = 1.0
    for e in spans:
        sw = e.get("args", {}).get("sample_weight")
        if sw:
            w = max(w, float(sw))
    return {
        "total_s": total_us / 1e6,
        "phases": phases,
        "n_spans": len(spans),
        "op_class": canonical_owner(op_class),
        "w": w,
        "start_ts_us": min(float(e["ts"]) for e in spans),
        "end_ts_us": max(_interval(e)[1] for e in spans),
    }


def group_traces(events: list[dict]) -> dict[int, list[dict]]:
    """trace_id -> its complete span events (drops untraced spans)."""
    out: dict[int, list[dict]] = defaultdict(list)
    for e in events:
        if e.get("ph") != "X":
            continue
        tid = e.get("args", {}).get("trace_id")
        if tid:
            out[tid].append(e)
    return dict(out)


# -- the bounded per-class ledger -------------------------------------------

_LEDGERS: "weakref.WeakSet[CritPathLedger]" = weakref.WeakSet()


def live_ledgers() -> list["CritPathLedger"]:
    return list(_LEDGERS)


class CritPathLedger:
    """Bounded fold of completed traces into per-op-class phase
    attribution.  ``refresh()`` pulls the tracer ring (each trace folded
    exactly once, keyed by trace id); per-class records ride bounded
    deques so memory stays fixed however long the process lives."""

    def __init__(self, cct=None, name: str = "critpath",
                 capacity: int = 1024):
        self.cct = cct
        self.name = name
        self.capacity = max(8, int(capacity))
        self._lock = threading.Lock()
        # serializes whole refresh() passes: a prometheus scrape thread
        # racing a status() tick must not double-fold the same trace
        # (the per-trace check and the ingest are not one atom)
        self._refresh_lock = threading.Lock()
        # op_class -> deque of {"t", "total_s", "phases"}; t is on the
        # perf_counter clock (comparable to time.perf_counter()) so the
        # SLO engine can window-filter without a second clock
        self._records: dict[str, deque] = {}
        # cumulative per-(class, phase) seconds — the prometheus counter
        self._phase_seconds: dict[str, dict[str, float]] = {}
        self._totals: dict[str, dict] = {}   # class -> {ops, total_s}
        # tid -> {"n": spans folded, "cls": class, "rec": the record
        # dict (shared with the class deque, amended IN PLACE when a
        # trace grows — a refresh that raced an in-flight op folds the
        # partial tree, and the next refresh after the root closes
        # replaces the truncated numbers instead of dropping them)
        self._seen: dict[int, dict] = {}
        # bound: 2x the tracer ring's EVENT capacity — the ring can
        # hold at most TRACE_CAPACITY distinct trace ids, so an id
        # evicted from here is guaranteed gone from the ring too and
        # can never be re-folded as a duplicate
        self._seen_order: deque[int] = deque(
            maxlen=2 * max(TRACE_CAPACITY_HINT, capacity))
        self.unmapped: dict[str, int] = {}
        self.folded = 0
        _LEDGERS.add(self)

    # -- folding -----------------------------------------------------------

    def refresh(self, tracer=None) -> int:
        """Fold every completed trace currently in the tracer ring;
        returns how many folded or amended.  Refreshes SERIALIZE (a
        prometheus scrape racing a status() tick must not double-fold),
        and a trace that GROWS after its first fold — a refresh caught
        it mid-flight, or late async spans (pipeline completions,
        resends) landed after the root closed — is re-decomposed and
        its record amended IN PLACE, so the final numbers are the full
        op, never a truncated snapshot."""
        if tracer is None:
            from . import tracer as tracer_mod
            tracer = tracer_mod.default_tracer()
        with self._refresh_lock:
            events = tracer.dump(stitched=False)["traceEvents"]
            folded = 0
            for tid, spans in sorted(group_traces(events).items()):
                with self._lock:
                    seen = self._seen.get(tid)
                    if seen is not None and seen["n"] >= len(spans):
                        continue
                start_us = min(float(e["ts"]) for e in spans)
                if seen is not None and \
                        start_us > seen["start_us"] + 1e-6:
                    # the ring evicted the trace's FRONT (root included)
                    # since the first fold: re-decomposing the tail
                    # would corrupt a once-complete record with orphan
                    # math.  Keep the old numbers; bump n so the next
                    # refreshes stop re-trying.
                    with self._lock:
                        seen["n"] = len(spans)
                    continue
                rec = decompose(spans, unmapped=self.unmapped)
                if rec is None:
                    continue
                # map the trace-relative end timestamp onto the process
                # perf_counter clock via the tracer's epoch pair
                t = tracer._t0 + rec["end_ts_us"] / 1e6
                if seen is None:
                    record = self.ingest(rec["op_class"], rec["total_s"],
                                         rec["phases"], t=t, w=rec["w"])
                    with self._lock:
                        if len(self._seen_order) == \
                                self._seen_order.maxlen:
                            self._seen.pop(self._seen_order[0], None)
                        self._seen_order.append(tid)
                        self._seen[tid] = {"n": len(spans),
                                           "cls": rec["op_class"],
                                           "start_us": start_us,
                                           "rec": record}
                else:
                    self._amend(seen, rec, t, len(spans))
                folded += 1
            return folded

    def _amend(self, seen: dict, rec: dict, t: float, n: int) -> None:
        """Replace a previously-folded trace's numbers with the fuller
        decomposition (record dict mutated in place — the class deque
        holds the same object; cumulative sums adjusted by delta)."""
        with self._lock:
            old = seen["rec"]
            cls = seen["cls"]
            old_w = old.get("w", 1.0)
            new_w = float(rec.get("w", old_w))
            acc = self._phase_seconds[cls]
            for p in PHASES:
                acc[p] += float(rec["phases"].get(p, 0.0)) * new_w \
                    - old["phases"][p] * old_w
            self._totals[cls]["total_s"] += \
                float(rec["total_s"]) * new_w - old["total_s"] * old_w
            self._totals[cls]["ops"] += new_w - old_w
            old["t"] = t
            old["total_s"] = float(rec["total_s"])
            old["phases"] = {p: float(rec["phases"].get(p, 0.0))
                             for p in PHASES}
            old["w"] = new_w
            seen["n"] = n
            # a late-closing root can carry an EARLIER start than the
            # spans the first fold saw: track the true front so the
            # ring-eviction guard in refresh() compares against it
            seen["start_us"] = min(seen["start_us"], rec["start_ts_us"])

    def ingest(self, op_class: str, total_s: float, phases: dict,
               t: float | None = None, w: float = 1.0) -> dict:
        """Fold one op record directly (refresh()'s sink; also the
        synthetic-record entry tests and tools use).  ``w`` is the
        record's sample weight (1/rate for head-sampled traces): the
        cumulative accumulators scale by it so rates stay unbiased.
        Returns the record dict (refresh keeps it for in-place
        amendment)."""
        t = time.perf_counter() if t is None else t
        w = float(w) if w and w > 0 else 1.0
        record = {"t": t, "total_s": float(total_s),
                  "phases": {p: float(phases.get(p, 0.0))
                             for p in PHASES},
                  "w": w}
        with self._lock:
            dq = self._records.get(op_class)
            if dq is None:
                dq = self._records[op_class] = deque(maxlen=self.capacity)
                self._phase_seconds[op_class] = dict.fromkeys(PHASES, 0.0)
                self._totals[op_class] = {"ops": 0, "total_s": 0.0}
            dq.append(record)
            acc = self._phase_seconds[op_class]
            for p in PHASES:
                acc[p] += record["phases"][p] * w
            self._totals[op_class]["ops"] += w
            self._totals[op_class]["total_s"] += record["total_s"] * w
            self.folded += 1
        return record

    # -- read --------------------------------------------------------------

    def records(self, op_class: str) -> list[dict]:
        """The bounded window of per-op records for one class (newest
        last) — the SLO engine's good/bad stream."""
        with self._lock:
            dq = self._records.get(op_class)
            return [dict(r) for r in dq] if dq else []

    def classes(self) -> list[str]:
        with self._lock:
            return sorted(self._records)

    def phase_seconds(self) -> dict[str, dict[str, float]]:
        """Cumulative per-(class, phase) seconds — the
        ``ceph_tpu_latency_phase_seconds`` source."""
        with self._lock:
            return {cls: dict(acc)
                    for cls, acc in sorted(self._phase_seconds.items())}

    def class_summary(self, op_class: str) -> dict | None:
        """p50/p99 + phase fractions over the class's record window.
        Fractions are aggregate phase seconds over aggregate total
        seconds (they sum to 1.0 whenever any time was recorded)."""
        recs = self.records(op_class)
        if not recs:
            return None
        pairs = sorted((r["total_s"], r.get("w", 1.0)) for r in recs)
        wsum = sum(w for _v, w in pairs)
        agg = dict.fromkeys(PHASES, 0.0)
        for r in recs:
            rw = r.get("w", 1.0)
            for p in PHASES:
                agg[p] += r["phases"][p] * rw
        whole = sum(agg.values())
        return {
            "ops": len(recs),
            "weighted_ops": round(wsum, 1),
            "p50_ms": round(weighted_nearest_rank(pairs, 50) * 1e3, 3),
            "p99_ms": round(weighted_nearest_rank(pairs, 99) * 1e3, 3),
            "mean_ms": round(sum(v * w for v, w in pairs) / wsum * 1e3, 3)
            if wsum else 0.0,
            "phase_ms": {p: round(agg[p] * 1e3, 3) for p in PHASES},
            "phases": {p: round(agg[p] / whole, 4) if whole else 0.0
                       for p in PHASES},
        }

    def snapshot(self) -> dict:
        """The full ledger view (flight-recorder source / `slo dump`)."""
        return {
            "classes": {cls: self.class_summary(cls)
                        for cls in self.classes()},
            "phase_seconds": self.phase_seconds(),
            "folded": self.folded,
            "unmapped_spans": dict(self.unmapped),
            "capacity": self.capacity,
        }

    def close(self) -> None:
        _LEDGERS.discard(self)


def format_phase_mix(phases: dict) -> str:
    """'62% batch_delay, 21% device, 9% wire' — THE one rendering of a
    phase-fraction dict, shared by `ceph slo status` (via
    render_attribution) and tools/slo_report.py so the live table and
    the artifact table can never drift apart."""
    parts = sorted(((p, f) for p, f in phases.items() if f),
                   key=lambda kv: kv[1], reverse=True)
    return ", ".join(f"{round(100 * f)}% {p}" for p, f in parts) \
        or "no attributed time"


def render_attribution(snapshot: dict) -> list[str]:
    """The attribution table lines ('client p99 = 41.0 ms: 62%
    batch_delay, 21% device, 9% wire') from a ledger snapshot — shared
    by `ceph slo status` and tools/slo_report.py."""
    lines = []
    for cls, summary in sorted((snapshot.get("classes") or {}).items()):
        if not summary:
            continue
        lines.append(f"{cls} p99 = {summary['p99_ms']:.1f} ms "
                     f"({summary['ops']} ops): "
                     f"{format_phase_mix(summary['phases'])}")
    return lines or ["no completed traces folded yet"]
