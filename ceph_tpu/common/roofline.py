"""Roofline ledger: per-executable achieved vs peak FLOP/s and HBM B/s.

ROADMAP item 2's missing compass: the repo can say how long a kernel ran
(``device_attribution``) and what XLA modeled it to cost
(``cost_analysis()`` folded by ``ops/traced_jit.py``), but nothing joins
the two — so "chase the next tier" has no instrument that says how close
any executable runs to what the hardware allows.  This module is that
join:

- a **peak-spec registry** (device kind -> peak FLOP/s and HBM B/s, the
  public TPU generation specs; overridable via the
  ``device_peak_flops`` / ``device_peak_hbm_bytes_per_sec`` options for
  hosts the registry does not know);
- a **per-executable ledger**: ``ops/traced_jit.py`` records each
  compiled (function, shape) key's modeled FLOPs/bytes at compile time
  and its measured dispatch seconds on every call, and :func:`snapshot`
  computes achieved FLOP/s, achieved B/s, arithmetic intensity,
  memory-vs-compute-bound classification and %-of-peak per executable;
- surfaces: the ``device_efficiency`` PerfCounters collection
  (:func:`refresh`), the ``ceph_tpu_device_efficiency{executable,stat}``
  prometheus family, the ``device roofline`` admin command
  (:func:`report`), :func:`flat_series` for the time-series ring,
  :func:`bench_block` for bench.py's ``efficiency`` JSON block (gated by
  ``tools/perf_gate.py``), and ``tools/roofline_report.py`` post-hoc.

Honesty note on the occupancy clock: per-call seconds are the WALL time
of the dispatch on the calling thread.  The first dispatch of every key
is synced (``traced_jit`` waits it out), so those samples are true
end-to-end; steady-state dispatches on an async backend can return
before the device finishes, under-counting time and producing
impossible >100%-of-peak rates.  :func:`_estimated_seconds` therefore
compares the synced-sample per-call mean against the overall mean and,
when async under-counting is evident, extrapolates the synced mean over
every call (conservative — first dispatches run cold; each derived row
carries ``estimator`` saying which clock it used, and ``synced_calls``
says how much of the sample was sync-timed).

Stdlib-only (the device_attribution discipline): importable before any
JAX backend initializes; jax facts arrive as plain numbers from callers.
"""
from __future__ import annotations

import os
import threading

# -- peak-spec registry -------------------------------------------------------

#: (device-kind substring, peak FLOP/s, peak HBM bytes/s) — public specs,
#: bf16 peak (the bitslice/pallas GF kernels ride the MXU as bf16/int8
#: matmuls).  First substring match on the lowercased device kind wins.
PEAK_SPECS: tuple[tuple[str, float, float], ...] = (
    ("v6e", 918e12, 1640e9),       # Trillium
    ("trillium", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5e", 197e12, 819e9),        # the BENCH_r baseline hardware
    ("v5 lite", 197e12, 819e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 46e12, 700e9),
)

#: nominal per-core CPU peaks (an AVX2-class core's fma throughput and a
#: share of one DDR channel) — rough on purpose: on CPU the roofline's
#: job is the memory-vs-compute CLASSIFICATION and round-over-round
#: comparison, not an absolute hardware claim (``source`` says nominal).
CPU_NOMINAL_FLOPS_PER_CORE = 5e10
CPU_NOMINAL_DRAM_BPS = 3e10


def lookup_peaks(cct=None, device_kind: str | None = None,
                 platform: str | None = None) -> dict:
    """Resolve peak FLOP/s and HBM B/s for the current (or named)
    device.  Config overrides win; then the device-kind registry; then a
    nominal CPU spec (classification still works, ``source`` marks it).
    Never initializes a backend: unknown stays unknown."""
    if device_kind is None and platform is None:
        from . import device_telemetry
        inv = device_telemetry.device_inventory()
        device_kind, platform = inv["device_kind"], inv["platform"]
    flops = hbm = 0.0
    source = None
    kind_l = (device_kind or "").lower()
    for sub, f, b in PEAK_SPECS:
        if sub in kind_l:
            flops, hbm, source = f, b, f"registry:{sub}"
            break
    if source is None and platform == "tpu":
        # an unrecognized TPU generation: assume the baseline hardware
        # rather than a meaningless nominal-CPU spec
        flops, hbm, source = PEAK_SPECS[3][1], PEAK_SPECS[3][2], \
            "default-tpu(v5e)"
    if source is None:
        cores = os.cpu_count() or 1
        flops = CPU_NOMINAL_FLOPS_PER_CORE * cores
        hbm = CPU_NOMINAL_DRAM_BPS
        source = f"nominal-cpu({cores} cores)"
    if cct is not None:
        conf_f = float(cct.conf.get("device_peak_flops") or 0.0)
        conf_b = float(cct.conf.get("device_peak_hbm_bytes_per_sec") or 0)
        if conf_f > 0:
            flops, source = conf_f, "config"
        if conf_b > 0:
            hbm = conf_b
            source = "config" if conf_f > 0 else f"{source}+config-hbm"
    return {"flops": flops, "hbm_bytes_s": hbm, "source": source,
            "device_kind": device_kind, "platform": platform,
            "ridge_flops_per_byte": (flops / hbm) if hbm else 0.0}


# -- the per-executable ledger ------------------------------------------------

_lock = threading.Lock()
_execs: dict[str, dict] = {}
_perf = None


def executable_id(label: str, key) -> str:
    """A readable executable name from traced_jit's (label, shape key):
    ``gf_apply_bitslice[4x8:uint8,8x131072:uint8]`` — one ledger row per
    compiled XLA executable, not per python function."""
    parts = []
    for p in key if isinstance(key, tuple) else (key,):
        if isinstance(p, tuple) and len(p) == 2 and isinstance(p[0], tuple):
            shape, dtype = p
            parts.append("x".join(str(d) for d in shape) + f":{dtype}")
        else:
            parts.append(str(p)[:24])
    return f"{label}[{','.join(parts)}]"


def record_compile(label: str, key, flops_per_call: float,
                   bytes_per_call: float, input_bytes: int = 0) -> None:
    """Register one compiled executable's modeled per-call cost (from
    ``cost_analysis()``).  When the backend models no byte traffic,
    the summed input-operand bytes stand in as the mandatory-traffic
    floor (``modeled_source`` records which)."""
    eid = executable_id(label, key)
    src = "cost_analysis"
    if bytes_per_call <= 0 and input_bytes > 0:
        bytes_per_call, src = float(input_bytes), "input_shapes"
    with _lock:
        rec = _execs.get(eid)
        if rec is None:
            _execs[eid] = {
                "function": label, "compiles": 1,
                "flops_per_call": float(flops_per_call),
                "bytes_per_call": float(bytes_per_call),
                "modeled_source": src,
                "calls": 0, "seconds": 0.0,
                "synced_calls": 0, "synced_s": 0.0,
                "flops": 0.0, "bytes": 0.0,
            }
        else:           # a recompile of the same key (e.g. after reset)
            rec["compiles"] += 1
            rec["flops_per_call"] = float(flops_per_call)
            rec["bytes_per_call"] = float(bytes_per_call)
            rec["modeled_source"] = src


def record_call(label: str, key, seconds: float, synced: bool = False,
                cost: tuple | None = None) -> None:
    """Account one dispatch of a compiled executable: ``seconds`` is the
    caller-measured wall time (``synced`` when it waited out the device
    — the first dispatch of every key is).  ``cost`` is the caller's
    cached ``(flops_per_call, bytes_per_call, input_bytes)`` so a ledger
    reset mid-run re-seeds the row on the next dispatch instead of going
    dark until a recompile (traced_jit passes it on every call)."""
    eid = executable_id(label, key)
    with _lock:
        rec = _execs.get(eid)
        if rec is None:
            if cost is None:     # no cost model at all: drop rather
                return           # than invent a zero-cost row
            flops, nbytes, input_bytes = cost
            src = "cost_analysis"
            if nbytes <= 0 and input_bytes > 0:
                nbytes, src = float(input_bytes), "input_shapes"
            rec = _execs[eid] = {
                "function": label, "compiles": 0,
                "flops_per_call": float(flops),
                "bytes_per_call": float(nbytes),
                "modeled_source": src,
                "calls": 0, "seconds": 0.0,
                "synced_calls": 0, "synced_s": 0.0,
                "flops": 0.0, "bytes": 0.0,
            }
        rec["calls"] += 1
        rec["seconds"] += float(seconds)
        rec["flops"] += rec["flops_per_call"]
        rec["bytes"] += rec["bytes_per_call"]
        if synced:
            rec["synced_calls"] += 1
            rec["synced_s"] += float(seconds)


def reset() -> dict:
    with _lock:
        n = len(_execs)
        _execs.clear()
    return {"success": f"dropped {n} executable records"}


# -- derived views ------------------------------------------------------------

#: when the sync-timed per-call mean exceeds the overall per-call mean by
#: this factor, the async dispatches are evidently returning before the
#: device finishes — rates are then computed over the synced mean
#: extrapolated to every call (conservative: first dispatches run cold)
_ASYNC_UNDERCOUNT_RATIO = 1.5


def _estimated_seconds(rec: dict) -> tuple[float, str]:
    """The seconds the rates divide by.  Measured wall seconds when they
    look end-to-end; the synced-sample mean extrapolated over all calls
    when async dispatch evidently under-measured (a 1-core host cannot
    run 16x its peak — better a conservative cold-sample estimate than
    an impossible achieved rate)."""
    secs, calls = rec["seconds"], rec["calls"]
    if calls and rec["synced_calls"]:
        sync_mean = rec["synced_s"] / rec["synced_calls"]
        if sync_mean > (secs / calls) * _ASYNC_UNDERCOUNT_RATIO:
            return sync_mean * calls, "synced-extrapolated"
    return secs, "measured"


def _derive(rec: dict, peaks: dict) -> dict:
    """One executable's roofline stats from its raw ledger record."""
    secs, estimator = _estimated_seconds(rec)
    out = dict(rec)
    out["est_seconds"] = round(secs, 6)
    out["estimator"] = estimator
    ach_f = (rec["flops"] / secs) if secs > 0 else 0.0
    ach_b = (rec["bytes"] / secs) if secs > 0 else 0.0
    ai = (rec["flops"] / rec["bytes"]) if rec["bytes"] > 0 else 0.0
    ridge = peaks["ridge_flops_per_byte"]
    # under the ridge the op cannot reach peak FLOP/s even at perfect
    # bandwidth: HBM is the binding resource (the roofline's knee)
    bound = "memory" if (ai < ridge or not rec["flops"]) else "compute"
    if bound == "memory":
        pct = 100.0 * ach_b / peaks["hbm_bytes_s"] \
            if peaks["hbm_bytes_s"] else 0.0
    else:
        pct = 100.0 * ach_f / peaks["flops"] if peaks["flops"] else 0.0
    out.update(
        achieved_flops_s=round(ach_f, 1),
        achieved_bytes_s=round(ach_b, 1),
        arithmetic_intensity=round(ai, 4),
        bound=bound,
        pct_of_peak=round(pct, 4),
    )
    return out


def snapshot(cct=None) -> dict:
    """The full ledger view: peaks + per-executable roofline stats +
    aggregate totals + the attribution ledger's busy-time context."""
    peaks = lookup_peaks(cct)
    with _lock:
        raw = {eid: dict(rec) for eid, rec in _execs.items()}
    execs = {eid: _derive(rec, peaks) for eid, rec in sorted(raw.items())}
    # the aggregate divides by the per-executable ESTIMATED seconds, so
    # an async-undercounted executable cannot inflate the total rate
    t_calls = sum(r["calls"] for r in raw.values())
    t_secs = sum(r["est_seconds"] for r in execs.values())
    t_flops = sum(r["flops"] for r in raw.values())
    t_bytes = sum(r["bytes"] for r in raw.values())
    agg = _derive({"calls": t_calls, "seconds": t_secs, "flops": t_flops,
                   "bytes": t_bytes, "synced_calls": 0, "synced_s": 0.0},
                  peaks)
    totals = {k: agg[k] for k in
              ("calls", "seconds", "flops", "bytes", "achieved_flops_s",
               "achieved_bytes_s", "arithmetic_intensity", "bound",
               "pct_of_peak")}
    from . import device_attribution
    busy = device_attribution.snapshot()["busy_s"]
    return {"peaks": peaks, "executables": execs, "totals": totals,
            "device_busy_s": round(busy, 6)}


def flat_series() -> dict[str, float]:
    """The time-series-ring source: aggregate efficiency as flat
    name -> value series."""
    snap = snapshot()
    t = snap["totals"]
    return {"achieved_flops_s": t["achieved_flops_s"],
            "achieved_bytes_s": t["achieved_bytes_s"],
            "pct_of_peak": t["pct_of_peak"],
            "executables": float(len(snap["executables"])),
            "device_busy_s": snap["device_busy_s"]}


def report(limit: int = 20, cct=None) -> dict:
    """The ``device roofline`` admin command: executables ranked by
    measured seconds, peaks and totals alongside."""
    snap = snapshot(cct)
    rows = sorted(snap["executables"].items(),
                  key=lambda kv: kv[1]["seconds"], reverse=True)
    return {
        "peaks": snap["peaks"],
        "totals": snap["totals"],
        "device_busy_s": snap["device_busy_s"],
        "executables": [dict(rec, executable=eid)
                        for eid, rec in rows[:max(0, int(limit))]],
    }


def bench_block(platform: str | None, cct=None, limit: int = 12) -> dict:
    """bench.py's ``efficiency`` JSON block: the roofline ledger the
    bench run populated, device-marked like every other block so
    ``tools/perf_gate.py`` can refuse cross-platform comparison."""
    snap = snapshot(cct)
    if not snap["executables"]:
        return {"device": "none", "error": "no executables recorded"}
    rows = sorted(snap["executables"].items(),
                  key=lambda kv: kv[1]["seconds"], reverse=True)
    return {
        "device": "tpu" if platform == "tpu" else "cpu",
        "peaks": snap["peaks"],
        "pct_of_peak": snap["totals"]["pct_of_peak"],
        "achieved_bytes_s": snap["totals"]["achieved_bytes_s"],
        "achieved_flops_s": snap["totals"]["achieved_flops_s"],
        "bound": snap["totals"]["bound"],
        "executables": [dict(rec, executable=eid)
                        for eid, rec in rows[:limit]],
    }


def render_table(snap_or_report: dict, limit: int = 20) -> str:
    """Human table over a :func:`snapshot`/:func:`report` shape (the
    ``ceph device roofline`` CLI rendering; tools/roofline_report.py
    carries its own standalone copy of this logic)."""
    execs = snap_or_report.get("executables")
    if isinstance(execs, dict):
        rows = [dict(rec, executable=eid) for eid, rec in execs.items()]
    else:
        rows = list(execs or [])
    rows.sort(key=lambda r: r.get("seconds", 0.0), reverse=True)
    peaks = snap_or_report.get("peaks") or {}
    lines = []
    if peaks:
        lines.append(
            f"peaks: {peaks.get('flops', 0) / 1e12:.1f} TFLOP/s, "
            f"{peaks.get('hbm_bytes_s', 0) / 1e9:.0f} GB/s "
            f"({peaks.get('source')})")
    lines.append(f"{'EXECUTABLE':<44} {'CALLS':>6} {'AI':>8} "
                 f"{'GB/S':>8} {'GF/S':>8} {'%PEAK':>7} BOUND")
    for r in rows[:limit]:
        lines.append(
            f"{r['executable'][:44]:<44} {r['calls']:>6} "
            f"{r['arithmetic_intensity']:>8.2f} "
            f"{r['achieved_bytes_s'] / 1e9:>8.3f} "
            f"{r['achieved_flops_s'] / 1e9:>8.3f} "
            f"{r['pct_of_peak']:>7.2f} {r['bound']}")
    return "\n".join(lines)


# -- perf-counter surface -----------------------------------------------------

EFFICIENCY_COLLECTION = "device_efficiency"


def _efficiency_perf(cct):
    pc = cct.perf.get(EFFICIENCY_COLLECTION)
    if pc is None:
        from .perf_counters import PerfCountersBuilder
        pc = (PerfCountersBuilder(EFFICIENCY_COLLECTION)
              .add_u64("executables",
                       "compiled executables in the roofline ledger")
              .add_u64("calls", "dispatches accounted by the ledger")
              .add_u64("achieved_flops_s",
                       "aggregate achieved FLOP/s over accounted "
                       "dispatch time")
              .add_u64("achieved_bytes_s",
                       "aggregate achieved bytes/s over accounted "
                       "dispatch time")
              .add_u64("pct_of_peak_x100",
                       "aggregate percent of the binding roofline peak, "
                       "x100 (4212 = 42.12%)")
              .add_u64("memory_bound",
                       "executables classified memory-bound (arithmetic "
                       "intensity under the ridge point)")
              .create_perf_counters())
        cct.perf.add(pc)
    return pc


def refresh(cct) -> dict:
    """Push the aggregate ledger view into the Context's
    ``device_efficiency`` collection (the prometheus render / perf dump
    hook).  Returns the full snapshot."""
    snap = snapshot(cct)
    pc = _efficiency_perf(cct)
    t = snap["totals"]
    pc.set("executables", len(snap["executables"]))
    pc.set("calls", t["calls"])
    pc.set("achieved_flops_s", int(t["achieved_flops_s"]))
    pc.set("achieved_bytes_s", int(t["achieved_bytes_s"]))
    pc.set("pct_of_peak_x100", int(round(t["pct_of_peak"] * 100)))
    pc.set("memory_bound",
           sum(1 for r in snap["executables"].values()
               if r["bound"] == "memory"))
    return snap
