"""Subsystem-gated logging with a crash-dumpable ring of recent entries.

Mirror of the reference's logging core (reference: src/log/Log.cc, 449 LoC —
an async ring-buffered Log thread keeping ``m_recent`` entries that are
dumped on crash; ``dout(level)`` macros gated per-subsystem by the
gather/log levels in src/common/subsys.h).  Python logging handles the
actual IO; this layer adds the two Ceph-shaped behaviors: per-subsystem
gather levels from debug_* config options, and the bounded recent-entry
ring with ``dump_recent()``.
"""
from __future__ import annotations

import collections
import sys
import threading
import time
from dataclasses import dataclass


@dataclass
class Entry:
    stamp: float
    subsys: str
    level: int
    message: str

    def format(self) -> str:
        whole = int(self.stamp)
        t = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(whole))
        usec = int((self.stamp - whole) * 1e6)   # truncate: no carry issues
        return f"{t}.{usec:06d} {self.level:2d} {self.subsys}: {self.message}"


class Log:
    """Ring-buffered logger; `should_gather` is the dout gate."""

    def __init__(self, config=None, max_recent: int = 500, file=None):
        self._config = config
        if config is not None:
            configured = config.get("log_max_recent")
            if configured is not None:      # 0 is valid: disables the ring
                max_recent = configured
        self._recent: collections.deque[Entry] = collections.deque(
            maxlen=max_recent)
        self._lock = threading.Lock()
        self._file = file
        self._levels: dict[str, int] = {}

    def set_level(self, subsys: str, level: int) -> None:
        self._levels[subsys] = level

    def level(self, subsys: str) -> int:
        if subsys in self._levels:
            return self._levels[subsys]
        if self._config is not None:
            try:
                return int(self._config.get(f"debug_{subsys}"))
            except KeyError:
                pass
        return 1

    def should_gather(self, subsys: str, level: int) -> bool:
        return level <= self.level(subsys)

    def dout(self, subsys: str, level: int, message: str) -> None:
        """The dout(level) macro: gated, ring-buffered, optionally sunk."""
        if not self.should_gather(subsys, level):
            return
        e = Entry(time.time(), subsys, level, message)
        with self._lock:
            self._recent.append(e)
        if self._file is not None:
            print(e.format(), file=self._file)

    def dump_recent(self, file=None) -> list[str]:
        """Crash-dump the ring (Log::dump_recent)."""
        with self._lock:
            lines = [e.format() for e in self._recent]
        out = file or sys.stderr
        print(f"--- begin dump of recent {len(lines)} log events ---",
              file=out)
        for line in lines:
            print(line, file=out)
        print("--- end dump of recent log events ---", file=out)
        return lines

    def recent(self) -> list[Entry]:
        with self._lock:
            return list(self._recent)
