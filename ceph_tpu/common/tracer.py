"""Span tracer + JIT telemetry: the process-wide timing backbone.

The reference ships three observability mechanisms — OpTracker event
timelines (src/common/TrackedOp.h), PerfCounters (src/common/perf_counters.h)
and the blkin/opentracing span hooks (src/common/zipkin_trace.h) — but the
span layer is the one this TPU-first framework needs most: a single MiB/s
number cannot tell trace time from compile time from device-resident time
from host<->device transfer (the BENCH_r05 failure mode: 570s of opaque
backend probing).  This module provides:

- :class:`Span` / :class:`Tracer`: nested spans with a thread-safe bounded
  ring buffer, exported as Chrome trace-event JSON (``chrome://tracing`` /
  Perfetto load ``trace dump`` output directly).
- per-span-name latency histograms (log-spaced bounds) that
  ``ceph_tpu.mgr.prometheus`` renders as real histogram series.
- the JIT telemetry registry behind ``ceph_tpu.ops.traced_jit``: per
  (function, shape-key) compile counts and trace/compile/first-dispatch
  wall times, plus the process-wide ``jit`` PerfCounters collection.

Everything here is stdlib-only so the bench driver can import it before
any JAX backend initializes.

Distributed tracing (the PR-6 tentpole): a :class:`TraceContext`
(trace id, parent span id, owner op class) rides every client op across
daemon boundaries — Objecter ops, net.py RPC frames, the OSD daemon's
queued dispatch, and the PG bus's ECSubRead/ECSubWrite envelopes.  Each
daemon ``activate()``s the inbound context and stamps its spans with a
per-daemon *track* (``osd.3``, ``client``), so :meth:`Tracer.dump` can
stitch the per-daemon span trees into ONE Chrome trace with one process
row per daemon, and ``tools/trace_report.py --trace`` can answer "where
did this 1 MiB write spend its 4 ms".
"""
from __future__ import annotations

import itertools
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass

# log-spaced span-latency bounds (seconds); one overflow bucket follows
LATENCY_BUCKETS_S = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0)

TRACE_CAPACITY = int(os.environ.get("CEPH_TPU_TRACE_CAPACITY", 16384))

# process-wide id allocators: ids must stay unique across every Tracer
# instance (cross-daemon stitching joins on them).  The high word is a
# per-process random salt: in multi-process mode (rados serve +
# --connect) each client process allocates its own ids, and sequential
# small ints would collide in the server's stitched dump, silently
# merging unrelated ops into one tree.
_id_salt = random.getrandbits(31) << 32
_trace_ids = itertools.count(_id_salt + 1)
_span_ids = itertools.count(_id_salt + 1)


@dataclass
class TraceContext:
    """What rides the wire: enough to stitch a child daemon's spans
    under the caller's (trace id + parent span id) and to attribute the
    work to an owner class (client/serving/recovery/scrub/rebalance).
    Picklable on purpose — net.py RPC frames and wire-mode bus envelopes
    serialize it."""
    trace_id: int
    span_id: int          # the span new children hang under (0 = root)
    op_class: str = "client"

    def child_of(self, span_id: int) -> "TraceContext":
        return TraceContext(self.trace_id, span_id, self.op_class)


class _Activation:
    """Context manager pushing a TraceContext (and optional track) onto
    the calling thread's stacks.  ``ctx=None`` is a no-op so call sites
    need no branching for untraced messages."""

    __slots__ = ("tracer", "ctx", "track", "_pushed")

    def __init__(self, tracer: "Tracer", ctx: TraceContext | None,
                 track: str | None = None):
        self.tracer = tracer
        self.ctx = ctx
        self.track = track
        self._pushed = False

    def __enter__(self) -> TraceContext | None:
        if self.ctx is not None or self.track is not None:
            self.tracer._ctx_stack().append((self.ctx, self.track))
            self._pushed = True
        return self.ctx

    def __exit__(self, *exc) -> bool:
        if self._pushed:
            self.tracer._ctx_stack().pop()
        return False


class Span:
    """One timed region; use as a context manager.  ``dur`` (seconds) is
    valid after ``__exit__``; the Chrome event is emitted on exit so the
    ring buffer holds only finished spans."""

    __slots__ = ("tracer", "name", "cat", "args", "tid", "ts_us", "dur",
                 "_t0", "trace_id", "span_id", "parent_id", "track",
                 "op_class", "_ctx_pushed")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self.tid = threading.get_ident()
        self.ts_us = 0.0
        self.dur = 0.0
        self._t0 = 0.0
        # distributed-trace linkage, filled on __enter__ when a
        # TraceContext is active on this thread
        self.trace_id = 0
        self.span_id = 0
        self.parent_id = 0
        self.op_class = ""
        self.track: str | None = None
        self._ctx_pushed = False

    def set(self, **args) -> "Span":
        """Attach results discovered mid-span (e.g. bytes moved)."""
        self.args.update(args)
        return self

    def __enter__(self) -> "Span":
        self.tracer._push(self)
        ctx = self.tracer.current_ctx()
        if ctx is not None:
            self.trace_id = ctx.trace_id
            self.span_id = next(_span_ids)
            self.parent_id = ctx.span_id
            self.op_class = ctx.op_class
            # nested spans (this thread, while we are open) chain under us
            self.tracer._ctx_stack().append((ctx.child_of(self.span_id),
                                             None))
            self._ctx_pushed = True
        self.track = self.tracer.current_track()
        self._t0 = time.perf_counter()
        self.ts_us = (self._t0 - self.tracer._t0) * 1e6
        return self

    def __exit__(self, *exc) -> bool:
        self.dur = time.perf_counter() - self._t0
        if self._ctx_pushed:
            self.tracer._ctx_stack().pop()
            self._ctx_pushed = False
        self.tracer._pop(self)
        self.tracer._finish_span(self)
        return False


class Tracer:
    """Thread-safe span recorder with a bounded ring of Chrome events."""

    def __init__(self, capacity: int = TRACE_CAPACITY):
        self._events: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._local = threading.local()
        # paired clocks: spans stamp with perf_counter; wall-clock sources
        # (TrackedOp timelines) map through the epoch pair
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self.pid = os.getpid()
        # span-name -> [bucket_counts..., overflow] plus (sum, count)
        self._hist: dict[str, dict] = {}

    # -- span stack (per thread, for nesting introspection) ----------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        st = self._stack()
        if st and st[-1] is span:
            st.pop()

    def current(self) -> Span | None:
        st = self._stack()
        return st[-1] if st else None

    def depth(self) -> int:
        return len(self._stack())

    # -- distributed trace contexts (per thread) ----------------------------

    def _ctx_stack(self) -> list:
        st = getattr(self._local, "ctx_stack", None)
        if st is None:
            st = self._local.ctx_stack = []
        return st

    def new_trace(self, op_class: str = "client") -> TraceContext:
        """A fresh root context (span_id 0): the client edge of an op."""
        return TraceContext(next(_trace_ids), 0, op_class)

    def current_ctx(self) -> TraceContext | None:
        """The innermost active TraceContext on this thread (None when
        the current work is untraced)."""
        for ctx, _track in reversed(self._ctx_stack()):
            if ctx is not None:
                return ctx
        return None

    def current_track(self) -> str | None:
        """The innermost daemon track ('osd.3', 'client', ...) active on
        this thread; spans default their track from it."""
        for _ctx, track in reversed(self._ctx_stack()):
            if track is not None:
                return track
        return None

    def activate(self, ctx: TraceContext | None,
                 track: str | None = None) -> _Activation:
        """Adopt an inbound trace context (and optionally name the local
        daemon track) for the duration of a ``with`` block.  ``ctx=None``
        activates only the track; both None is a no-op."""
        return _Activation(self, ctx, track)

    def track_scope(self, track: str) -> _Activation:
        """Name the local daemon track without touching the context."""
        return _Activation(self, None, track)

    # -- recording ----------------------------------------------------------

    def span(self, name: str, cat: str = "", **args) -> Span:
        return Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "", **args) -> None:
        ev = {"name": name, "cat": cat or "instant", "ph": "i", "s": "t",
              "ts": (time.perf_counter() - self._t0) * 1e6,
              "pid": self.pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def complete(self, name: str, start_wall: float, dur_s: float,
                 cat: str = "", ctx: TraceContext | None = None,
                 **args) -> None:
        """A span observed externally on the WALL clock (TrackedOp ops,
        queue/batch/backoff waits measured after the fact): mapped onto
        the tracer timeline via the paired epochs.  With ``ctx`` the
        event joins that distributed trace as a child span (trace/span/
        parent ids + op_class stamped like a live span) so the
        critical-path ledger can attribute it — linkage is EXPLICIT
        opt-in, never ambient, so TrackedOp timelines that happen to
        run under an active context don't double-count as tree nodes."""
        ev = {"name": name, "cat": cat or "op", "ph": "X",
              "ts": (start_wall - self._wall0) * 1e6,
              "dur": dur_s * 1e6,
              "pid": self.pid, "tid": threading.get_ident()}
        if ctx is not None:
            args["trace_id"] = ctx.trace_id
            args["span_id"] = next(_span_ids)
            args["parent_span_id"] = ctx.span_id
            args.setdefault("op_class", ctx.op_class)
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)
        self._hist_add(name, dur_s)

    def _finish_span(self, span: Span) -> None:
        ev = {"name": span.name, "cat": span.cat or "span", "ph": "X",
              "ts": span.ts_us, "dur": span.dur * 1e6,
              "pid": self.pid, "tid": span.tid}
        args = dict(span.args) if span.args else {}
        if span.trace_id:
            args["trace_id"] = span.trace_id
            args["span_id"] = span.span_id
            args["parent_span_id"] = span.parent_id
            # the owner class rides every traced span so the critical-
            # path ledger (common/critpath.py) can classify a trace
            # without re-deriving it from span-name heuristics
            args.setdefault("op_class", span.op_class)
        if args:
            ev["args"] = args
        if span.track is not None:
            ev["track"] = span.track
        with self._lock:
            self._events.append(ev)
        self._hist_add(span.name, span.dur)

    def _hist_add(self, name: str, dur_s: float) -> None:
        with self._lock:
            h = self._hist.get(name)
            if h is None:
                h = self._hist[name] = {
                    "counts": [0] * (len(LATENCY_BUCKETS_S) + 1),
                    "sum": 0.0, "count": 0}
            for i, bound in enumerate(LATENCY_BUCKETS_S):
                if dur_s <= bound:
                    h["counts"][i] += 1
                    break
            else:
                h["counts"][-1] += 1
            h["sum"] += dur_s
            h["count"] += 1

    # -- export --------------------------------------------------------------

    def dump(self, stitched: bool = True) -> dict:
        """Chrome trace-event JSON (the ``trace dump`` admin command):
        load in chrome://tracing or ui.perfetto.dev as-is.

        ``stitched`` (default) renders the cross-daemon view: events
        whose span carried a daemon *track* ('osd.3', 'client') are
        re-homed onto a synthetic pid per track — one process row per
        daemon — with ``process_name`` metadata events naming the rows,
        so one client op's spans across N daemons line up on one shared
        timeline (all tracks stamp from this tracer's clock pair)."""
        with self._lock:
            events = [dict(ev) for ev in self._events]
        if stitched:
            track_pids: dict[str, int] = {}
            meta: list[dict] = []
            for ev in events:
                track = ev.pop("track", None)
                if track is None:
                    continue
                pid = track_pids.get(track)
                if pid is None:
                    # deterministic synthetic pids, far from real ones
                    pid = track_pids[track] = 1_000_000 + len(track_pids)
                    meta.append({"name": "process_name", "ph": "M",
                                 "pid": pid, "tid": 0,
                                 "args": {"name": track}})
                ev["pid"] = pid
            events = meta + events
        else:
            for ev in events:
                ev.pop("track", None)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def reset(self) -> dict:
        with self._lock:
            n = len(self._events)
            self._events.clear()
            self._hist.clear()
        return {"success": f"dropped {n} events"}

    def histograms(self) -> dict:
        """Per-span-name latency histograms: {name: {buckets (bounds, s),
        counts (len+1, last = overflow), sum, count}}."""
        with self._lock:
            return {name: {"buckets": list(LATENCY_BUCKETS_S),
                           "counts": list(h["counts"]),
                           "sum": h["sum"], "count": h["count"]}
                    for name, h in self._hist.items()}


_default_tracer: Tracer | None = None
_default_lock = threading.Lock()


def default_tracer() -> Tracer:
    global _default_tracer
    if _default_tracer is None:
        with _default_lock:
            if _default_tracer is None:
                _default_tracer = Tracer()
    return _default_tracer


def trace_span(name: str, cat: str = "", **args) -> Span:
    """Convenience: a span on the process-default tracer."""
    return default_tracer().span(name, cat, **args)


def trace_instant(name: str, cat: str = "", **args) -> None:
    default_tracer().instant(name, cat, **args)


def new_trace(op_class: str = "client") -> TraceContext:
    """A fresh root trace context on the process-default tracer."""
    return default_tracer().new_trace(op_class)


def current_trace() -> TraceContext | None:
    """The calling thread's active TraceContext, if any."""
    return default_tracer().current_ctx()


def activate_trace(ctx: TraceContext | None,
                   track: str | None = None) -> _Activation:
    """Adopt an inbound context / daemon track on the default tracer."""
    return default_tracer().activate(ctx, track)


def root_or_ambient(op_class: str) -> _Activation:
    """Activate the calling thread's ambient trace context — or root a
    fresh ``op_class`` trace when none is active — so the sub-ops a call
    fans out attribute their wire bytes and device time to the right
    owner class (an enclosing scrub-repair/scheduler-wave context wins
    over the default)."""
    tr = default_tracer()
    return tr.activate(tr.current_ctx() or tr.new_trace(op_class))


# -- JIT telemetry registry (fed by ceph_tpu.ops.traced_jit) ----------------
#
# Keyed by (function label, shape key).  Each entry exists because exactly
# one compilation happened for that key; re-dispatches bump ``calls``.  The
# ``jit`` PerfCounters collection aggregates across keys and is registered
# into every Context's collection so `perf dump` / prometheus carry it.

_jit_lock = threading.Lock()
_jit_stats: dict[tuple, dict] = {}
_jit_perf = None


def jit_perf_counters():
    """The process-wide ``jit`` PerfCounters (built lazily: tracer must
    stay importable before perf_counters in partial environments)."""
    global _jit_perf
    with _jit_lock:
        if _jit_perf is None:
            from .perf_counters import PerfCountersBuilder
            _jit_perf = (
                PerfCountersBuilder("jit")
                .add_u64_counter("compilations",
                                 "distinct (function, shape) compiles")
                .add_u64_counter("cache_hits",
                                 "dispatches served by a compiled cache key")
                .add_time_avg("trace_time", "jaxpr trace wall time")
                .add_time_avg("compile_time", "XLA compile wall time")
                .add_time_avg("first_dispatch_time",
                              "first execution incl. completion wait")
                .create_perf_counters())
        return _jit_perf


def record_compilation(fn_label: str, key, trace_s: float, compile_s: float,
                       dispatch_s: float) -> None:
    pc = jit_perf_counters()
    pc.inc("compilations")
    pc.tinc("trace_time", trace_s)
    pc.tinc("compile_time", compile_s)
    pc.tinc("first_dispatch_time", dispatch_s)
    with _jit_lock:
        entry = _jit_stats.get((fn_label, key))
        if entry is None:
            _jit_stats[(fn_label, key)] = {
                "function": fn_label, "key": repr(key), "compiles": 1,
                "trace_s": trace_s, "compile_s": compile_s,
                "first_dispatch_s": dispatch_s, "calls": 1}
        else:
            # distinct jitted closures can share a label (e.g. one
            # BulkMapper kernel per CRUSH rule): accumulate, don't clobber
            entry["compiles"] += 1
            entry["calls"] += 1
            entry["trace_s"] += trace_s
            entry["compile_s"] += compile_s
            entry["first_dispatch_s"] += dispatch_s


def record_cache_hit(fn_label: str, key) -> None:
    jit_perf_counters().inc("cache_hits")
    with _jit_lock:
        entry = _jit_stats.get((fn_label, key))
        if entry is not None:
            entry["calls"] += 1


def jit_dump() -> dict:
    """The ``jit dump`` admin command: per-key stats + the aggregate
    counters, compile-cost-sorted so the expensive kernels lead."""
    with _jit_lock:
        entries = [dict(e) for e in _jit_stats.values()]
    entries.sort(key=lambda e: e["compile_s"], reverse=True)
    return {"functions": entries,
            "num_keys": len(entries),
            "counters": jit_perf_counters().dump()}


def jit_reset() -> dict:
    with _jit_lock:
        n = len(_jit_stats)
        _jit_stats.clear()
    return {"success": f"dropped {n} jit cache-key records"}
