"""Span tracer + JIT telemetry: the process-wide timing backbone.

The reference ships three observability mechanisms — OpTracker event
timelines (src/common/TrackedOp.h), PerfCounters (src/common/perf_counters.h)
and the blkin/opentracing span hooks (src/common/zipkin_trace.h) — but the
span layer is the one this TPU-first framework needs most: a single MiB/s
number cannot tell trace time from compile time from device-resident time
from host<->device transfer (the BENCH_r05 failure mode: 570s of opaque
backend probing).  This module provides:

- :class:`Span` / :class:`Tracer`: nested spans with a thread-safe bounded
  ring buffer, exported as Chrome trace-event JSON (``chrome://tracing`` /
  Perfetto load ``trace dump`` output directly).
- per-span-name latency histograms (log-spaced bounds) that
  ``ceph_tpu.mgr.prometheus`` renders as real histogram series.
- the JIT telemetry registry behind ``ceph_tpu.ops.traced_jit``: per
  (function, shape-key) compile counts and trace/compile/first-dispatch
  wall times, plus the process-wide ``jit`` PerfCounters collection.

Everything here is stdlib-only so the bench driver can import it before
any JAX backend initializes.

Distributed tracing (the PR-6 tentpole): a :class:`TraceContext`
(trace id, parent span id, owner op class) rides every client op across
daemon boundaries — Objecter ops, net.py RPC frames, the OSD daemon's
queued dispatch, and the PG bus's ECSubRead/ECSubWrite envelopes.  Each
daemon ``activate()``s the inbound context and stamps its spans with a
per-daemon *track* (``osd.3``, ``client``), so :meth:`Tracer.dump` can
stitch the per-daemon span trees into ONE Chrome trace with one process
row per daemon, and ``tools/trace_report.py --trace`` can answer "where
did this 1 MiB write spend its 4 ms".
"""
from __future__ import annotations

import itertools
import os
import random
import threading
import time
from bisect import bisect_left
from collections import deque
from dataclasses import dataclass

from . import instruments

# log-spaced span-latency bounds (seconds); one overflow bucket follows
LATENCY_BUCKETS_S = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0)

TRACE_CAPACITY = int(os.environ.get("CEPH_TPU_TRACE_CAPACITY", 16384))

# finished events buffered per thread before the batch folds into the
# shared ring: the owning thread touches the ring lock once per batch
# (or at an explicit completion-boundary flush()) instead of per span —
# the reactor-thread contention class behind the PR 15 races
FLUSH_BATCH = 64

# unsampled-trace micro-records kept for slow-op promotion (one small
# dict entry per in-flight unsampled op; FIFO eviction past the bound)
MICRO_CAPACITY = 4096

# process-wide id allocators: ids must stay unique across every Tracer
# instance (cross-daemon stitching joins on them).  The high word is a
# per-process random salt: in multi-process mode (rados serve +
# --connect) each client process allocates its own ids, and sequential
# small ints would collide in the server's stitched dump, silently
# merging unrelated ops into one tree.
_id_salt = random.getrandbits(31) << 32
_trace_ids = itertools.count(_id_salt + 1)
_span_ids = itertools.count(_id_salt + 1)


@dataclass
class TraceContext:
    """What rides the wire: enough to stitch a child daemon's spans
    under the caller's (trace id + parent span id) and to attribute the
    work to an owner class (client/serving/recovery/scrub/rebalance).
    Picklable on purpose — net.py RPC frames and wire-mode bus envelopes
    serialize it.

    ``sampled``/``weight`` are the head-based sampling decision, made
    ONCE at :meth:`Tracer.new_trace` and carried here so the whole
    distributed trace samples atomically across daemons: an unsampled
    context suppresses every span it touches (locally and remotely)
    except slow-op promotions, and a sampled one stamps its 1/rate
    weight on every event so downstream rate math stays unbiased."""
    trace_id: int
    span_id: int          # the span new children hang under (0 = root)
    op_class: str = "client"
    sampled: bool = True
    weight: float = 1.0   # 1/sample_rate, decided at the root

    def child_of(self, span_id: int) -> "TraceContext":
        return TraceContext(self.trace_id, span_id, self.op_class,
                            self.sampled, self.weight)


class _Activation:
    """Context manager pushing a TraceContext (and optional track) onto
    the calling thread's stacks.  ``ctx=None`` is a no-op so call sites
    need no branching for untraced messages."""

    __slots__ = ("tracer", "ctx", "track", "_pushed")

    def __init__(self, tracer: "Tracer", ctx: TraceContext | None,
                 track: str | None = None):
        self.tracer = tracer
        self.ctx = ctx
        self.track = track
        self._pushed = False

    def __enter__(self) -> TraceContext | None:
        if self.ctx is not None or self.track is not None:
            self.tracer._ctx_stack().append((self.ctx, self.track))
            self._pushed = True
        return self.ctx

    def __exit__(self, *exc) -> bool:
        if self._pushed:
            self.tracer._ctx_stack().pop()
        return False


class Span:
    """One timed region; use as a context manager.  ``dur`` (seconds) is
    valid after ``__exit__``; the Chrome event is emitted on exit so the
    ring buffer holds only finished spans."""

    __slots__ = ("tracer", "name", "cat", "args", "ts_us", "dur",
                 "_t0", "trace_id", "span_id", "parent_id", "track",
                 "op_class", "sampled", "weight")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self.dur = 0.0
        # distributed-trace linkage (span_id/parent/class/weight) is
        # filled on __enter__ only when a TraceContext is active; a
        # nonzero trace_id is the "linked" flag (_trace_ids starts at 1)
        self.trace_id = 0
        self.track: str | None = None

    def set(self, **args) -> "Span":
        """Attach results discovered mid-span (e.g. bytes moved)."""
        self.args.update(args)
        return self

    def __enter__(self) -> "Span":
        tracer = self.tracer
        tracer._push(self)
        # one fused walk for the innermost ctx AND track (two separate
        # current_ctx()/current_track() sweeps cost real time per op)
        ctx = track = None
        for c, t in reversed(tracer._ctx_stack()):
            if ctx is None and c is not None:
                ctx = c
            if track is None and t is not None:
                track = t
            if ctx is not None and track is not None:
                break
        if ctx is not None:
            self.trace_id = ctx.trace_id
            self.span_id = next(_span_ids)
            self.parent_id = ctx.span_id
            self.op_class = ctx.op_class
            self.sampled = getattr(ctx, "sampled", True)
            self.weight = getattr(ctx, "weight", 1.0)
            # nested spans (this thread, while we are open) chain under
            # us — even when unsampled, so child daemons inherit the
            # head decision through child_of()
            tracer._ctx_stack().append((ctx.child_of(self.span_id),
                                        None))
        self.track = track
        self._t0 = time.perf_counter()
        self.ts_us = (self._t0 - tracer._t0) * 1e6
        return self

    def __exit__(self, *exc) -> bool:
        self.dur = time.perf_counter() - self._t0
        tracer = self.tracer
        if self.trace_id:
            tracer._ctx_stack().pop()
        tracer._pop(self)
        tracer._finish_span(self)
        return False


class _NullSpan:
    """The kill-switch span: context-manager compatible, records
    nothing.  One shared instance serves every call site — no per-op
    allocation when ``instruments_enabled=false``."""

    __slots__ = ()
    dur = 0.0
    ts_us = 0.0
    args: dict = {}

    def set(self, **args) -> "_NullSpan":
        return self

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    """Thread-safe span recorder with a bounded ring of Chrome events.

    Finished events buffer per thread and fold into the shared ring in
    batches (``FLUSH_BATCH``, or an explicit completion-boundary
    :meth:`flush`), so hot threads touch the ring lock ~1/64th as often
    as they emit.  Read surfaces (:meth:`dump`, :meth:`histograms`)
    drain every thread's pending batch first, so nothing observable
    changes except the lock traffic."""

    def __init__(self, capacity: int = TRACE_CAPACITY):
        # finished events: dicts, or lite tuples (name, cat, ts_us,
        # dur_us, tid) from the untraced fast path — materialized by
        # dump()
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._local = threading.local()
        # per-thread pending-event buffers (thread ident -> list); the
        # owner appends without the lock (single writer + GIL), batches
        # fold under the ring lock
        self._pending: dict[int, list] = {}
        # paired clocks: spans stamp with perf_counter; wall-clock sources
        # (TrackedOp timelines) map through the epoch pair
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self.pid = os.getpid()
        # span-name -> [bucket_counts..., overflow] plus (sum, count)
        self._hist: dict[str, list] = {}
        # head-based sampling (ISSUE 18): decided once per root context
        # in new_trace(); unsampled traces keep only a micro-record here
        # until they finish fast (dropped) or cross slow_threshold_s
        # (promoted into the ring)
        self.sample_rate = 1.0
        self.slow_threshold_s = 30.0
        self._micro: dict[int, dict] = {}
        self._micro_lock = threading.Lock()

    # -- span stack (per thread, for nesting introspection) ----------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        st = self._stack()
        if st and st[-1] is span:
            st.pop()

    def current(self) -> Span | None:
        st = self._stack()
        return st[-1] if st else None

    def depth(self) -> int:
        return len(self._stack())

    # -- distributed trace contexts (per thread) ----------------------------

    def _ctx_stack(self) -> list:
        st = getattr(self._local, "ctx_stack", None)
        if st is None:
            st = self._local.ctx_stack = []
        return st

    def new_trace(self, op_class: str = "client") -> TraceContext:
        """A fresh root context (span_id 0): the client edge of an op.

        The head-based sampling decision happens HERE, once per trace:
        the result rides the context (and every child_of() derived from
        it, across daemons), so a distributed trace is all-in or
        all-out.  Unsampled roots leave a micro-record (start, class,
        id) for retroactive slow-op promotion; sampled roots carry a
        1/rate weight so dump consumers can de-bias rate math."""
        tid = next(_trace_ids)
        if self._sample(tid):
            rate = self.sample_rate
            w = 1.0 / rate if 0.0 < rate < 1.0 else 1.0
            return TraceContext(tid, 0, op_class, True, w)
        self._note_micro(tid, op_class)
        return TraceContext(tid, 0, op_class, False, 1.0)

    def _sample(self, trace_id: int) -> bool:
        """Deterministic per-trace-id decision (Knuth multiplicative
        hash): equidistributed over sequential ids, reproducible for a
        given id, and free of shared RNG state on the hot path."""
        rate = self.sample_rate
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        return ((trace_id * 2654435761) & 0xFFFFFFFF) < rate * 4294967296.0

    # -- unsampled-op micro-records (slow-op promotion) ---------------------

    def _note_micro(self, trace_id: int, op_class: str) -> None:
        with self._micro_lock:
            self._micro[trace_id] = {"trace_id": trace_id,
                                     "start_wall": time.time(),
                                     "op_class": op_class}
            while len(self._micro) > MICRO_CAPACITY:
                self._micro.pop(next(iter(self._micro)))

    def _drop_micro(self, trace_id: int) -> None:
        if trace_id in self._micro:          # cheap pre-check, racy is fine
            with self._micro_lock:
                self._micro.pop(trace_id, None)

    def micro_records(self) -> list[dict]:
        """The in-flight unsampled ops (start wall time, op class, trace
        id) — what SLOW_OPS triage sees for ops the sampler skipped that
        have not completed yet."""
        with self._micro_lock:
            return [dict(r) for r in self._micro.values()]

    def current_ctx(self) -> TraceContext | None:
        """The innermost active TraceContext on this thread (None when
        the current work is untraced)."""
        for ctx, _track in reversed(self._ctx_stack()):
            if ctx is not None:
                return ctx
        return None

    def current_track(self) -> str | None:
        """The innermost daemon track ('osd.3', 'client', ...) active on
        this thread; spans default their track from it."""
        for _ctx, track in reversed(self._ctx_stack()):
            if track is not None:
                return track
        return None

    def activate(self, ctx: TraceContext | None,
                 track: str | None = None) -> _Activation:
        """Adopt an inbound trace context (and optionally name the local
        daemon track) for the duration of a ``with`` block.  ``ctx=None``
        activates only the track; both None is a no-op."""
        return _Activation(self, ctx, track)

    def track_scope(self, track: str) -> _Activation:
        """Name the local daemon track without touching the context."""
        return _Activation(self, None, track)

    # -- recording ----------------------------------------------------------

    def span(self, name: str, cat: str = "", **args) -> Span:
        if not instruments.enabled():
            return _NULL_SPAN
        return Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "", **args) -> None:
        if not instruments.enabled():
            return
        ctx = self.current_ctx()
        if ctx is not None and not getattr(ctx, "sampled", True):
            return                   # unsampled trace: no per-event record
        ev = {"name": name, "cat": cat or "instant", "ph": "i", "s": "t",
              "ts": (time.perf_counter() - self._t0) * 1e6,
              "pid": self.pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._emit(ev)

    def observe(self, name: str, t0: float, t1: float | None = None,
                cat: str = "") -> None:
        """Record a finished region measured with ``time.perf_counter()``
        — the allocation-light fast path for hot UNTRACED spans (the
        per-op rpc dispatch).  No Span object, no context-manager
        protocol, no event dict: a lite tuple rides the pending buffer
        and the ring, and :meth:`dump` materializes whatever survived
        eviction.  Use :meth:`span` whenever a TraceContext may be
        active — this path carries no trace linkage."""
        if not instruments.enabled():
            return
        if t1 is None:
            t1 = time.perf_counter()
        # inlined _emit_lite: this is the single hottest instrument call
        # (one per RPC dispatch), so it pays for zero extra frames
        buf = getattr(self._local, "pending", None)
        if buf is None:
            buf = self._pending_buf()
        buf.append((name, cat, (t0 - self._t0) * 1e6, (t1 - t0) * 1e6,
                    threading.get_ident()))
        if len(buf) >= FLUSH_BATCH:
            self._flush_buf(buf)

    def complete(self, name: str, start_wall: float, dur_s: float,
                 cat: str = "", ctx: TraceContext | None = None,
                 **args) -> None:
        """A span observed externally on the WALL clock (TrackedOp ops,
        queue/batch/backoff waits measured after the fact): mapped onto
        the tracer timeline via the paired epochs.  With ``ctx`` the
        event joins that distributed trace as a child span (trace/span/
        parent ids + op_class stamped like a live span) so the
        critical-path ledger can attribute it — linkage is EXPLICIT
        opt-in, never ambient, so TrackedOp timelines that happen to
        run under an active context don't double-count as tree nodes."""
        if not instruments.enabled():
            return
        promoted = False
        if ctx is not None and not getattr(ctx, "sampled", True):
            if dur_s < self.slow_threshold_s:
                if ctx.span_id == 0:         # the trace's root completed fast
                    self._drop_micro(ctx.trace_id)
                return
            promoted = True                  # slow op: into the ring anyway
            self._drop_micro(ctx.trace_id)
        ev = {"name": name, "cat": cat or "op", "ph": "X",
              "ts": (start_wall - self._wall0) * 1e6,
              "dur": dur_s * 1e6,
              "pid": self.pid, "tid": threading.get_ident()}
        if ctx is not None:
            args["trace_id"] = ctx.trace_id
            args["span_id"] = next(_span_ids)
            args["parent_span_id"] = ctx.span_id
            args.setdefault("op_class", ctx.op_class)
            if promoted:
                # promoted events represent only themselves: weight 1
                args["promoted"] = True
            elif getattr(ctx, "weight", 1.0) != 1.0:
                args["sample_weight"] = ctx.weight
        if args:
            ev["args"] = args
        self._emit(ev, name, dur_s)

    def _finish_span(self, span: Span) -> None:
        promoted = False
        if span.trace_id and not span.sampled:
            # unsampled trace: the span vanishes unless it crossed the
            # complaint time — then it is promoted into the ring so
            # SLOW_OPS / flight bundles / slo_report never go dark
            if span.dur < self.slow_threshold_s:
                if span.parent_id == 0:      # the root finished fast
                    self._drop_micro(span.trace_id)
                return
            promoted = True
            self._drop_micro(span.trace_id)
        if not span.trace_id and not span.args and span.track is None:
            # the hot shape (untraced, no args, no track): defer the
            # event-dict build to dump() — evicted events never pay it
            self._emit_lite((span.name, span.cat,
                             span.ts_us, span.dur * 1e6,
                             threading.get_ident()))
            return
        ev = {"name": span.name, "cat": span.cat or "span", "ph": "X",
              "ts": span.ts_us, "dur": span.dur * 1e6,
              "pid": self.pid, "tid": threading.get_ident()}
        args = dict(span.args) if span.args else {}
        if span.trace_id:
            args["trace_id"] = span.trace_id
            args["span_id"] = span.span_id
            args["parent_span_id"] = span.parent_id
            # the owner class rides every traced span so the critical-
            # path ledger (common/critpath.py) can classify a trace
            # without re-deriving it from span-name heuristics
            args.setdefault("op_class", span.op_class)
            if promoted:
                args["promoted"] = True
            elif span.weight != 1.0:
                args["sample_weight"] = span.weight
        if args:
            ev["args"] = args
        if span.track is not None:
            ev["track"] = span.track
        self._emit(ev, span.name, span.dur)

    # -- per-thread batching -------------------------------------------------

    def _pending_buf(self) -> list:
        buf = getattr(self._local, "pending", None)
        if buf is None:
            buf = self._local.pending = []
            with self._lock:
                old = self._pending.get(threading.get_ident())
                if old:
                    # a dead thread's ident was reused: fold its
                    # leftovers before the new owner takes the slot
                    self._fold_locked(old)
                self._pending[threading.get_ident()] = buf
        return buf

    def _emit(self, ev: dict, name: str | None = None,
              dur_s: float = 0.0) -> None:
        buf = self._pending_buf()
        buf.append((ev, name, dur_s))
        if len(buf) >= FLUSH_BATCH:
            self._flush_buf(buf)

    def _emit_lite(self, ev: tuple) -> None:
        # a lite event rides the buffer BARE (no wrapper triple): the
        # fold recognizes the 5-tuple shape and derives name/duration
        # from it, so the hot path allocates one tuple per op, not two
        buf = getattr(self._local, "pending", None)
        if buf is None:
            buf = self._pending_buf()
        buf.append(ev)
        if len(buf) >= FLUSH_BATCH:
            self._flush_buf(buf)

    def _flush_buf(self, buf: list) -> None:
        with self._lock:
            self._fold_locked(buf)

    def _fold_locked(self, buf: list) -> None:
        # under self._lock.  The owner may append concurrently (without
        # the lock): capture len first, drain exactly that prefix — the
        # append lands at the tail and survives for the next flush.
        n = len(buf)
        if not n:
            return
        items = buf[:n]
        del buf[:n]
        for item in items:
            if len(item) == 5:
                # bare lite event: (name, cat, ts_us, dur_us, tid)
                self._events.append(item)
                self._hist_add_locked(item[0], item[3] * 1e-6)
            else:
                ev, name, dur_s = item
                self._events.append(ev)
                if name is not None:
                    self._hist_add_locked(name, dur_s)

    def flush(self) -> None:
        """Fold the CALLING thread's pending batch into the ring — the
        completion-boundary hook (pipeline complete, dispatcher worker
        loop, serving finisher, mux sender loop)."""
        buf = getattr(self._local, "pending", None)
        if buf:
            self._flush_buf(buf)

    def _drain_all_locked(self) -> None:
        for buf in list(self._pending.values()):
            self._fold_locked(buf)

    def _hist_add_locked(self, name: str, dur_s: float) -> None:
        # cells are flat lists [counts, sum, count] and the bucket scan
        # is a C-level bisect: this runs once per event inside the fold
        # critical section, so it is the floor of the batched ring cost
        h = self._hist.get(name)
        if h is None:
            h = self._hist[name] = [[0] * (len(LATENCY_BUCKETS_S) + 1),
                                    0.0, 0]
        h[0][bisect_left(LATENCY_BUCKETS_S, dur_s)] += 1
        h[1] += dur_s
        h[2] += 1

    # -- export --------------------------------------------------------------

    def _materialize(self, ev) -> dict:
        """A ring entry as a Chrome event dict.  Lite tuples (the
        untraced span/observe fast path) build their dict HERE, once
        per surviving event, instead of once per op."""
        if type(ev) is tuple:
            name, cat, ts, dur, tid = ev
            return {"name": name, "cat": cat or "span", "ph": "X",
                    "ts": ts, "dur": dur, "pid": self.pid, "tid": tid}
        return dict(ev)

    def dump(self, stitched: bool = True) -> dict:
        """Chrome trace-event JSON (the ``trace dump`` admin command):
        load in chrome://tracing or ui.perfetto.dev as-is.

        ``stitched`` (default) renders the cross-daemon view: events
        whose span carried a daemon *track* ('osd.3', 'client') are
        re-homed onto a synthetic pid per track — one process row per
        daemon — with ``process_name`` metadata events naming the rows,
        so one client op's spans across N daemons line up on one shared
        timeline (all tracks stamp from this tracer's clock pair)."""
        with self._lock:
            self._drain_all_locked()
            events = [self._materialize(ev) for ev in self._events]
        if stitched:
            track_pids: dict[str, int] = {}
            meta: list[dict] = []
            for ev in events:
                track = ev.pop("track", None)
                if track is None:
                    continue
                pid = track_pids.get(track)
                if pid is None:
                    # deterministic synthetic pids, far from real ones
                    pid = track_pids[track] = 1_000_000 + len(track_pids)
                    meta.append({"name": "process_name", "ph": "M",
                                 "pid": pid, "tid": 0,
                                 "args": {"name": track}})
                ev["pid"] = pid
            events = meta + events
        else:
            for ev in events:
                ev.pop("track", None)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def reset(self) -> dict:
        with self._lock:
            self._drain_all_locked()
            n = len(self._events)
            self._events.clear()
            self._hist.clear()
        with self._micro_lock:
            self._micro.clear()
        return {"success": f"dropped {n} events"}

    def histograms(self) -> dict:
        """Per-span-name latency histograms: {name: {buckets (bounds, s),
        counts (len+1, last = overflow), sum, count}}."""
        with self._lock:
            self._drain_all_locked()
            return {name: {"buckets": list(LATENCY_BUCKETS_S),
                           "counts": list(h[0]),
                           "sum": h[1], "count": h[2]}
                    for name, h in self._hist.items()}


_default_tracer: Tracer | None = None
_default_lock = threading.Lock()


def default_tracer() -> Tracer:
    global _default_tracer
    if _default_tracer is None:
        with _default_lock:
            if _default_tracer is None:
                _default_tracer = Tracer()
    return _default_tracer


def wire_config(conf) -> None:
    """Adopt the default tracer's sampling knobs from a ConfigProxy and
    follow live updates: ``tracer_sample_rate`` sets the head-based
    sampling probability, ``osd_op_complaint_time`` doubles as the
    slow-op promotion threshold (the same bound SLOW_OPS health uses, so
    'promoted into the ring' and 'flagged slow' agree by construction)."""
    tr = default_tracer()
    if "tracer_sample_rate" in conf.schema:
        tr.sample_rate = float(conf.get("tracer_sample_rate"))

        def _on_rate(_name, v, _tr=tr):
            _tr.sample_rate = float(v)
        conf.add_observer("tracer_sample_rate", _on_rate)
    if "osd_op_complaint_time" in conf.schema:
        tr.slow_threshold_s = float(conf.get("osd_op_complaint_time"))

        def _on_complaint(_name, v, _tr=tr):
            _tr.slow_threshold_s = float(v)
        conf.add_observer("osd_op_complaint_time", _on_complaint)


def trace_span(name: str, cat: str = "", **args) -> Span:
    """Convenience: a span on the process-default tracer."""
    return default_tracer().span(name, cat, **args)


def trace_instant(name: str, cat: str = "", **args) -> None:
    default_tracer().instant(name, cat, **args)


def new_trace(op_class: str = "client") -> TraceContext:
    """A fresh root trace context on the process-default tracer."""
    return default_tracer().new_trace(op_class)


def current_trace() -> TraceContext | None:
    """The calling thread's active TraceContext, if any."""
    return default_tracer().current_ctx()


def activate_trace(ctx: TraceContext | None,
                   track: str | None = None) -> _Activation:
    """Adopt an inbound context / daemon track on the default tracer."""
    return default_tracer().activate(ctx, track)


def root_or_ambient(op_class: str) -> _Activation:
    """Activate the calling thread's ambient trace context — or root a
    fresh ``op_class`` trace when none is active — so the sub-ops a call
    fans out attribute their wire bytes and device time to the right
    owner class (an enclosing scrub-repair/scheduler-wave context wins
    over the default)."""
    tr = default_tracer()
    return tr.activate(tr.current_ctx() or tr.new_trace(op_class))


# -- JIT telemetry registry (fed by ceph_tpu.ops.traced_jit) ----------------
#
# Keyed by (function label, shape key).  Each entry exists because exactly
# one compilation happened for that key; re-dispatches bump ``calls``.  The
# ``jit`` PerfCounters collection aggregates across keys and is registered
# into every Context's collection so `perf dump` / prometheus carry it.

_jit_lock = threading.Lock()
_jit_stats: dict[tuple, dict] = {}
_jit_perf = None


def jit_perf_counters():
    """The process-wide ``jit`` PerfCounters (built lazily: tracer must
    stay importable before perf_counters in partial environments)."""
    global _jit_perf
    with _jit_lock:
        if _jit_perf is None:
            from .perf_counters import PerfCountersBuilder
            _jit_perf = (
                PerfCountersBuilder("jit")
                .add_u64_counter("compilations",
                                 "distinct (function, shape) compiles")
                .add_u64_counter("cache_hits",
                                 "dispatches served by a compiled cache key")
                .add_time_avg("trace_time", "jaxpr trace wall time")
                .add_time_avg("compile_time", "XLA compile wall time")
                .add_time_avg("first_dispatch_time",
                              "first execution incl. completion wait")
                .create_perf_counters())
        return _jit_perf


def record_compilation(fn_label: str, key, trace_s: float, compile_s: float,
                       dispatch_s: float) -> None:
    pc = jit_perf_counters()
    pc.inc("compilations")
    pc.tinc("trace_time", trace_s)
    pc.tinc("compile_time", compile_s)
    pc.tinc("first_dispatch_time", dispatch_s)
    with _jit_lock:
        entry = _jit_stats.get((fn_label, key))
        if entry is None:
            _jit_stats[(fn_label, key)] = {
                "function": fn_label, "key": repr(key), "compiles": 1,
                "trace_s": trace_s, "compile_s": compile_s,
                "first_dispatch_s": dispatch_s, "calls": 1}
        else:
            # distinct jitted closures can share a label (e.g. one
            # BulkMapper kernel per CRUSH rule): accumulate, don't clobber
            entry["compiles"] += 1
            entry["calls"] += 1
            entry["trace_s"] += trace_s
            entry["compile_s"] += compile_s
            entry["first_dispatch_s"] += dispatch_s


def record_cache_hit(fn_label: str, key) -> None:
    jit_perf_counters().inc("cache_hits")
    with _jit_lock:
        entry = _jit_stats.get((fn_label, key))
        if entry is not None:
            entry["calls"] += 1


def jit_dump() -> dict:
    """The ``jit dump`` admin command: per-key stats + the aggregate
    counters, compile-cost-sorted so the expensive kernels lead."""
    with _jit_lock:
        entries = [dict(e) for e in _jit_stats.values()]
    entries.sort(key=lambda e: e["compile_s"], reverse=True)
    return {"functions": entries,
            "num_keys": len(entries),
            "counters": jit_perf_counters().dump()}


def jit_reset() -> dict:
    with _jit_lock:
        n = len(_jit_stats)
        _jit_stats.clear()
    return {"success": f"dropped {n} jit cache-key records"}
