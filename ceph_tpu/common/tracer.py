"""Span tracer + JIT telemetry: the process-wide timing backbone.

The reference ships three observability mechanisms — OpTracker event
timelines (src/common/TrackedOp.h), PerfCounters (src/common/perf_counters.h)
and the blkin/opentracing span hooks (src/common/zipkin_trace.h) — but the
span layer is the one this TPU-first framework needs most: a single MiB/s
number cannot tell trace time from compile time from device-resident time
from host<->device transfer (the BENCH_r05 failure mode: 570s of opaque
backend probing).  This module provides:

- :class:`Span` / :class:`Tracer`: nested spans with a thread-safe bounded
  ring buffer, exported as Chrome trace-event JSON (``chrome://tracing`` /
  Perfetto load ``trace dump`` output directly).
- per-span-name latency histograms (log-spaced bounds) that
  ``ceph_tpu.mgr.prometheus`` renders as real histogram series.
- the JIT telemetry registry behind ``ceph_tpu.ops.traced_jit``: per
  (function, shape-key) compile counts and trace/compile/first-dispatch
  wall times, plus the process-wide ``jit`` PerfCounters collection.

Everything here is stdlib-only so the bench driver can import it before
any JAX backend initializes.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque

# log-spaced span-latency bounds (seconds); one overflow bucket follows
LATENCY_BUCKETS_S = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0)

TRACE_CAPACITY = int(os.environ.get("CEPH_TPU_TRACE_CAPACITY", 16384))


class Span:
    """One timed region; use as a context manager.  ``dur`` (seconds) is
    valid after ``__exit__``; the Chrome event is emitted on exit so the
    ring buffer holds only finished spans."""

    __slots__ = ("tracer", "name", "cat", "args", "tid", "ts_us", "dur",
                 "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self.tid = threading.get_ident()
        self.ts_us = 0.0
        self.dur = 0.0
        self._t0 = 0.0

    def set(self, **args) -> "Span":
        """Attach results discovered mid-span (e.g. bytes moved)."""
        self.args.update(args)
        return self

    def __enter__(self) -> "Span":
        self.tracer._push(self)
        self._t0 = time.perf_counter()
        self.ts_us = (self._t0 - self.tracer._t0) * 1e6
        return self

    def __exit__(self, *exc) -> bool:
        self.dur = time.perf_counter() - self._t0
        self.tracer._pop(self)
        self.tracer._finish_span(self)
        return False


class Tracer:
    """Thread-safe span recorder with a bounded ring of Chrome events."""

    def __init__(self, capacity: int = TRACE_CAPACITY):
        self._events: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._local = threading.local()
        # paired clocks: spans stamp with perf_counter; wall-clock sources
        # (TrackedOp timelines) map through the epoch pair
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self.pid = os.getpid()
        # span-name -> [bucket_counts..., overflow] plus (sum, count)
        self._hist: dict[str, dict] = {}

    # -- span stack (per thread, for nesting introspection) ----------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop(self, span: Span) -> None:
        st = self._stack()
        if st and st[-1] is span:
            st.pop()

    def current(self) -> Span | None:
        st = self._stack()
        return st[-1] if st else None

    def depth(self) -> int:
        return len(self._stack())

    # -- recording ----------------------------------------------------------

    def span(self, name: str, cat: str = "", **args) -> Span:
        return Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "", **args) -> None:
        ev = {"name": name, "cat": cat or "instant", "ph": "i", "s": "t",
              "ts": (time.perf_counter() - self._t0) * 1e6,
              "pid": self.pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def complete(self, name: str, start_wall: float, dur_s: float,
                 cat: str = "", **args) -> None:
        """A span observed externally on the WALL clock (TrackedOp ops):
        mapped onto the tracer timeline via the paired epochs."""
        ev = {"name": name, "cat": cat or "op", "ph": "X",
              "ts": (start_wall - self._wall0) * 1e6,
              "dur": dur_s * 1e6,
              "pid": self.pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)
        self._hist_add(name, dur_s)

    def _finish_span(self, span: Span) -> None:
        ev = {"name": span.name, "cat": span.cat or "span", "ph": "X",
              "ts": span.ts_us, "dur": span.dur * 1e6,
              "pid": self.pid, "tid": span.tid}
        if span.args:
            ev["args"] = dict(span.args)
        with self._lock:
            self._events.append(ev)
        self._hist_add(span.name, span.dur)

    def _hist_add(self, name: str, dur_s: float) -> None:
        with self._lock:
            h = self._hist.get(name)
            if h is None:
                h = self._hist[name] = {
                    "counts": [0] * (len(LATENCY_BUCKETS_S) + 1),
                    "sum": 0.0, "count": 0}
            for i, bound in enumerate(LATENCY_BUCKETS_S):
                if dur_s <= bound:
                    h["counts"][i] += 1
                    break
            else:
                h["counts"][-1] += 1
            h["sum"] += dur_s
            h["count"] += 1

    # -- export --------------------------------------------------------------

    def dump(self) -> dict:
        """Chrome trace-event JSON (the ``trace dump`` admin command):
        load in chrome://tracing or ui.perfetto.dev as-is."""
        with self._lock:
            events = list(self._events)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def reset(self) -> dict:
        with self._lock:
            n = len(self._events)
            self._events.clear()
            self._hist.clear()
        return {"success": f"dropped {n} events"}

    def histograms(self) -> dict:
        """Per-span-name latency histograms: {name: {buckets (bounds, s),
        counts (len+1, last = overflow), sum, count}}."""
        with self._lock:
            return {name: {"buckets": list(LATENCY_BUCKETS_S),
                           "counts": list(h["counts"]),
                           "sum": h["sum"], "count": h["count"]}
                    for name, h in self._hist.items()}


_default_tracer: Tracer | None = None
_default_lock = threading.Lock()


def default_tracer() -> Tracer:
    global _default_tracer
    if _default_tracer is None:
        with _default_lock:
            if _default_tracer is None:
                _default_tracer = Tracer()
    return _default_tracer


def trace_span(name: str, cat: str = "", **args) -> Span:
    """Convenience: a span on the process-default tracer."""
    return default_tracer().span(name, cat, **args)


def trace_instant(name: str, cat: str = "", **args) -> None:
    default_tracer().instant(name, cat, **args)


# -- JIT telemetry registry (fed by ceph_tpu.ops.traced_jit) ----------------
#
# Keyed by (function label, shape key).  Each entry exists because exactly
# one compilation happened for that key; re-dispatches bump ``calls``.  The
# ``jit`` PerfCounters collection aggregates across keys and is registered
# into every Context's collection so `perf dump` / prometheus carry it.

_jit_lock = threading.Lock()
_jit_stats: dict[tuple, dict] = {}
_jit_perf = None


def jit_perf_counters():
    """The process-wide ``jit`` PerfCounters (built lazily: tracer must
    stay importable before perf_counters in partial environments)."""
    global _jit_perf
    with _jit_lock:
        if _jit_perf is None:
            from .perf_counters import PerfCountersBuilder
            _jit_perf = (
                PerfCountersBuilder("jit")
                .add_u64_counter("compilations",
                                 "distinct (function, shape) compiles")
                .add_u64_counter("cache_hits",
                                 "dispatches served by a compiled cache key")
                .add_time_avg("trace_time", "jaxpr trace wall time")
                .add_time_avg("compile_time", "XLA compile wall time")
                .add_time_avg("first_dispatch_time",
                              "first execution incl. completion wait")
                .create_perf_counters())
        return _jit_perf


def record_compilation(fn_label: str, key, trace_s: float, compile_s: float,
                       dispatch_s: float) -> None:
    pc = jit_perf_counters()
    pc.inc("compilations")
    pc.tinc("trace_time", trace_s)
    pc.tinc("compile_time", compile_s)
    pc.tinc("first_dispatch_time", dispatch_s)
    with _jit_lock:
        entry = _jit_stats.get((fn_label, key))
        if entry is None:
            _jit_stats[(fn_label, key)] = {
                "function": fn_label, "key": repr(key), "compiles": 1,
                "trace_s": trace_s, "compile_s": compile_s,
                "first_dispatch_s": dispatch_s, "calls": 1}
        else:
            # distinct jitted closures can share a label (e.g. one
            # BulkMapper kernel per CRUSH rule): accumulate, don't clobber
            entry["compiles"] += 1
            entry["calls"] += 1
            entry["trace_s"] += trace_s
            entry["compile_s"] += compile_s
            entry["first_dispatch_s"] += dispatch_s


def record_cache_hit(fn_label: str, key) -> None:
    jit_perf_counters().inc("cache_hits")
    with _jit_lock:
        entry = _jit_stats.get((fn_label, key))
        if entry is not None:
            entry["calls"] += 1


def jit_dump() -> dict:
    """The ``jit dump`` admin command: per-key stats + the aggregate
    counters, compile-cost-sorted so the expensive kernels lead."""
    with _jit_lock:
        entries = [dict(e) for e in _jit_stats.values()]
    entries.sort(key=lambda e: e["compile_s"], reverse=True)
    return {"functions": entries,
            "num_keys": len(entries),
            "counters": jit_perf_counters().dump()}


def jit_reset() -> dict:
    with _jit_lock:
        n = len(_jit_stats)
        _jit_stats.clear()
    return {"success": f"dropped {n} jit cache-key records"}
