"""Common runtime services (SURVEY.md §5): typed config with observers,
perf counters, ring-buffered log, admin-socket command registry, op
tracker, span tracer + JIT telemetry, bundled by Context (the CephContext
analog)."""
from .options import (ConfigProxy, Option, OPTIONS, SCHEMA, parse_size,
                      LEVEL_BASIC, LEVEL_ADVANCED, LEVEL_DEV,
                      TYPE_STR, TYPE_INT, TYPE_UINT, TYPE_FLOAT, TYPE_BOOL,
                      TYPE_SIZE)
from .perf_counters import (PerfCounters, PerfCountersBuilder,
                            PerfCountersCollection)
from .log import Log, Entry
from .admin_socket import AdminSocket
from .tracer import (Span, Tracer, default_tracer, trace_span,
                     trace_instant, jit_dump, jit_perf_counters)
from .optracker import OpTracker, TrackedOp
from .context import Context, default_context
from .flight_recorder import FlightRecorder
from .profiler_capture import ProfilerCapture
from . import device_telemetry
from . import roofline

__all__ = [
    "ConfigProxy", "Option", "OPTIONS", "SCHEMA", "parse_size",
    "LEVEL_BASIC", "LEVEL_ADVANCED", "LEVEL_DEV",
    "TYPE_STR", "TYPE_INT", "TYPE_UINT", "TYPE_FLOAT", "TYPE_BOOL",
    "TYPE_SIZE",
    "PerfCounters", "PerfCountersBuilder", "PerfCountersCollection",
    "Log", "Entry", "AdminSocket", "OpTracker", "TrackedOp",
    "Span", "Tracer", "default_tracer", "trace_span", "trace_instant",
    "jit_dump", "jit_perf_counters",
    "Context", "default_context",
    "FlightRecorder", "ProfilerCapture", "device_telemetry", "roofline",
]
