"""Cluster log: severity-tagged cluster-wide events in a bounded ring.

Analog of the reference's ``clog`` (reference: src/common/LogClient.h —
daemons send cluster-log entries to the mon, which persists a bounded
history and streams it to ``ceph -w`` / ``ceph log last``).  The span
tracer records micro-events for machines; THIS log records the dozen
lines a human reads first in an incident: OSD up/down, health
transitions, recovery start/finish, scrub findings, throttle
saturation.

- bounded in-memory ring (``mgr_cluster_log_max`` entries);
- optional on-disk persistence as JSON-lines at ``<data_dir>/clusterlog``
  — append-only so a live ``ceph -w`` in another PROCESS can follow the
  file by offset, compacted back to the ring bound when the file grows
  past ``COMPACT_FACTOR`` times it (a bounded file, like the flight
  ring);
- an existing file is reloaded at open so the ring (and the seq
  counter) survives cluster reopens;
- :meth:`last` / :meth:`tail_since` serve ``ceph log last`` and the
  ``ceph -w`` follow loop; :meth:`dump` is the flight-recorder source,
  so a bundle alone replays the run-up.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from pathlib import Path

SEVERITIES = ("DBG", "INF", "WRN", "ERR")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}

# file compaction threshold: rewrite once the file holds this many times
# the ring bound (append-only between compactions keeps `ceph -w` cheap)
COMPACT_FACTOR = 4


def format_entry(e: dict) -> str:
    """One ``ceph -w`` line: time, severity, channel, message."""
    t = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(e["time"]))
    return f"{t} {e['severity']:<3} [{e['channel']}] {e['message']}"


def read_log_file(path, n: int | None = None) -> list[dict]:
    """Parse a persisted clusterlog (JSON-lines); tolerates a torn final
    line (a concurrent append).  ``n`` keeps only the newest entries."""
    entries: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue           # torn tail mid-append
                if isinstance(e, dict) and "message" in e:
                    entries.append(e)
    except OSError:
        return []
    return entries[-n:] if n is not None else entries


class ClusterLog:
    """Bounded, optionally persisted, severity-tagged event log."""

    def __init__(self, cct=None, path=None, capacity: int | None = None):
        from .context import default_context
        self.cct = cct if cct is not None else default_context()
        if capacity is None:
            capacity = int(self.cct.conf.get("mgr_cluster_log_max"))
        self.capacity = max(1, capacity)
        self.path = Path(path) if path is not None else None
        self.entries: deque[dict] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._file_lines = 0
        if self.path is not None and self.path.exists():
            persisted = read_log_file(self.path)
            old = persisted[-self.capacity:]
            self.entries.extend(old)
            self._file_lines = len(persisted)
            self._seq = max((e.get("seq", 0) for e in old), default=0)

    # -- write -------------------------------------------------------------

    def log(self, severity: str, message: str, channel: str = "cluster",
            **fields) -> dict:
        if severity not in _SEV_RANK:
            raise ValueError(f"severity {severity!r} not in {SEVERITIES}")
        with self._lock:
            self._seq += 1
            entry = {"seq": self._seq, "time": time.time(),
                     "severity": severity, "channel": channel,
                     "message": str(message)}
            if fields:
                entry.update(fields)
            self.entries.append(entry)
            if self.path is not None:
                self._persist(entry)
        return entry

    def debug(self, message: str, **kw) -> dict:
        return self.log("DBG", message, **kw)

    def info(self, message: str, **kw) -> dict:
        return self.log("INF", message, **kw)

    def warn(self, message: str, **kw) -> dict:
        return self.log("WRN", message, **kw)

    def error(self, message: str, **kw) -> dict:
        return self.log("ERR", message, **kw)

    def _persist(self, entry: dict) -> None:
        """Append one JSON line; compact the file back to the ring once
        it grows past COMPACT_FACTOR x capacity lines.  Best-effort: a
        full disk must not take the data path down with it."""
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with open(self.path, "a") as f:
                f.write(json.dumps(entry, default=str) + "\n")
            self._file_lines += 1
            if self._file_lines > self.capacity * COMPACT_FACTOR:
                import os
                tmp = self.path.with_suffix(".tmp")
                with open(tmp, "w") as f:
                    for e in self.entries:
                        f.write(json.dumps(e, default=str) + "\n")
                os.replace(tmp, self.path)
                self._file_lines = len(self.entries)
        except OSError:
            pass

    # -- read --------------------------------------------------------------

    def last(self, n: int = 20, severity: str | None = None) -> list[dict]:
        """The newest ``n`` entries (``ceph log last``), optionally at or
        above a severity floor."""
        with self._lock:
            entries = list(self.entries)
        if severity is not None:
            floor = _SEV_RANK[severity]
            entries = [e for e in entries
                       if _SEV_RANK.get(e["severity"], 1) >= floor]
        return entries[-n:] if n > 0 else []

    def tail_since(self, seq: int) -> list[dict]:
        """Entries newer than ``seq`` — the ``ceph -w`` poll step."""
        with self._lock:
            return [e for e in self.entries if e.get("seq", 0) > seq]

    def dump(self) -> list[dict]:
        """The flight-recorder source: the whole ring."""
        with self._lock:
            return list(self.entries)

    def close(self) -> None:
        """Nothing persistent to release beyond the file handles already
        closed per append; kept for the telemetry-spine teardown shape."""
