"""Flight recorder: snapshot everything the moment something goes wrong.

The reference keeps per-daemon ring buffers (recent log entries, historic
ops) precisely so that a crash dump carries the run-up, not just the
corpse.  This module is the cluster-wide version of that idea for the
telemetry stack PR 1-3 built: when a health check enters WARN/ERR (the
:class:`~ceph_tpu.mgr.health.HealthCheckEngine` transition hook), or when
an operator asks via the ``flight dump`` admin command, the recorder
captures ONE timestamped JSON bundle holding

- the span tracer's event ring (``trace dump`` — Chrome trace-event),
- the jit telemetry registry (``jit dump``),
- every perf-counter collection (``perf dump``),
- the device-telemetry snapshot,
- every attached source (the owning cluster attaches its health
  evaluation and stats digest),

so the question "what was the system doing when X went wrong" is
answered from the artifact alone — no reproduction required (the
BENCH_r05 lesson applied to incidents instead of benchmarks).

Bundles land in a bounded in-memory ring and, when ``out_dir`` is set,
as ``flight-<seq>-<reason>.json`` files.  Every source is exception-
guarded: the recorder runs DURING incidents, when subsystems may be in
exactly the broken state that triggered it.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from pathlib import Path

from . import device_telemetry
from . import tracer as tracer_mod
from .context import default_context

FLIGHT_BUNDLE_VERSION = 1


def _sanitize(reason: str) -> str:
    return "".join(ch if ch.isalnum() or ch in "-_" else "_"
                   for ch in reason)[:80]


class FlightRecorder:
    """Bounded ring of diagnostic bundles + optional on-disk dumps."""

    def __init__(self, cct=None, out_dir=None, capacity: int = 8,
                 max_disk_bundles: int = 64,
                 min_repeat_interval_s: float = 300.0):
        self.cct = cct if cct is not None else default_context()
        self.out_dir = Path(out_dir) if out_dir is not None else None
        self.bundles: deque[dict] = deque(maxlen=max(1, capacity))
        # the on-disk ring is larger than the in-memory one (disk is the
        # durable evidence) but still BOUNDED: a flapping check must not
        # fill the data dir with bundles
        self.max_disk_bundles = max(max(1, capacity),
                                    int(max_disk_bundles))
        # per-reason disk cooldown: every fresh PROCESS starts with an
        # empty transition map, so a still-degraded cluster re-fires the
        # same transition on each CLI poll — without the cooldown, a
        # `watch ceph status` loop would write a bundle per poll and
        # rotate the ORIGINAL incident's evidence out of the disk ring.
        # Disk mtimes persist across processes, so this dedups there.
        self.min_repeat_interval_s = float(min_repeat_interval_s)
        self._sources: dict[str, object] = {}
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        self._owns_admin = False

    def add_source(self, name: str, fn) -> None:
        """Attach a named snapshot provider (called at dump time)."""
        with self._lock:
            self._sources[name] = fn

    # -- capture -----------------------------------------------------------

    def _recent_disk_duplicate(self, reason: str, now: float) -> bool:
        try:
            for p in self.out_dir.glob(
                    f"flight-*-{_sanitize(reason)}.json"):
                if now - p.stat().st_mtime < self.min_repeat_interval_s:
                    return True
        except Exception:
            pass
        return False

    def dump(self, reason: str = "manual", force: bool = False) -> dict:
        """Capture one bundle NOW.  Never raises: a failing source
        records its error in place of its snapshot.  The in-memory ring
        always gets the bundle; the DISK write is skipped when a bundle
        for the same reason landed within ``min_repeat_interval_s``
        (unless ``force`` — operator-requested dumps always write)."""
        seq = next(self._seq)
        bundle: dict = {
            "version": FLIGHT_BUNDLE_VERSION,
            "seq": seq,
            "reason": reason,
            "time": time.time(),
        }
        with self._lock:
            sources = dict(self._sources)
        captures = [
            ("trace", lambda: tracer_mod.default_tracer().dump()),
            ("jit", tracer_mod.jit_dump),
            ("perf", self.cct.perf.perf_dump),
            ("device", lambda: device_telemetry.refresh(self.cct)),
        ] + list(sources.items())
        for name, fn in captures:
            try:
                bundle[name] = fn()
            except Exception as e:       # incident-time: degrade, don't die
                bundle[name] = {"error": repr(e)[:200]}
        if self.out_dir is not None and not force and \
                self._recent_disk_duplicate(reason, bundle["time"]):
            bundle["path_skipped"] = (
                f"bundle for {reason!r} written within the last "
                f"{self.min_repeat_interval_s:.0f}s")
        elif self.out_dir is not None:
            try:
                self.out_dir.mkdir(parents=True, exist_ok=True)
                # timestamp + pid in the name: the seq counter restarts
                # every process, and a later run overwriting an earlier
                # run's bundle would destroy exactly the incident
                # evidence the recorder exists to preserve
                path = self.out_dir / (
                    f"flight-{int(bundle['time'])}-{os.getpid()}-"
                    f"{seq:04d}-{_sanitize(reason)}.json")
                with open(path, "w") as f:
                    json.dump(bundle, f, default=str)
                bundle["path"] = str(path)
                # bound the directory, oldest-first by mtime (the name's
                # epoch-seconds prefix is too coarse to order bundles
                # captured within the same second)
                old = sorted(self.out_dir.glob("flight-*.json"),
                             key=lambda p: p.stat().st_mtime)
                for stale in old[:-self.max_disk_bundles]:
                    stale.unlink()
            except Exception as e:
                bundle["path_error"] = repr(e)[:200]
        self.bundles.append(bundle)
        return bundle

    def list_bundles(self) -> list[dict]:
        """Bundle index (seq/reason/time/path) — the cheap view for the
        admin surface; full bundles stay in ``self.bundles``."""
        return [{k: b.get(k) for k in ("seq", "reason", "time", "path")}
                for b in self.bundles]

    # -- admin-socket surface ----------------------------------------------

    ADMIN_COMMAND = "flight dump"

    def register_admin(self, admin_socket=None) -> None:
        """Takeover-register ``flight dump`` (the pg_backend idiom: the
        newest owner of a shared command name wins; close() only
        unregisters if still the owner)."""
        sock = admin_socket if admin_socket is not None \
            else self.cct.admin_socket
        self._admin_sock = sock
        # pin ONE callable object: bound-method attribute access creates
        # a fresh object each time, which would defeat the identity check
        # close() uses to confirm it still owns the registration
        self._admin_fn = lambda reason="admin", **kw: self.dump(
            reason=reason, force=True)
        sock.unregister(self.ADMIN_COMMAND)
        sock.register(self.ADMIN_COMMAND, self._admin_fn,
                      "capture a flight-recorder bundle "
                      "(tracer + perf + health + stats snapshot)")
        self._owns_admin = True

    def close(self) -> None:
        if self._owns_admin:
            sock = self._admin_sock
            if sock.get(self.ADMIN_COMMAND) is self._admin_fn:
                sock.unregister(self.ADMIN_COMMAND)
            self._owns_admin = False
