"""Context: the per-process service bundle (CephContext analog).

Mirror of the reference's ``CephContext`` (reference:
src/common/ceph_context.cc, ~950 LoC): owns the config store, the log, the
perf-counter collection, and the admin socket, and pre-registers the
standard admin commands (``perf dump``, ``config show``, ``config set``,
``log dump``).  Daemon-ish objects (ECBackend, shards) take a Context and
hang their counters/commands off it.
"""
from __future__ import annotations

from . import tracer as tracer_mod
from .admin_socket import AdminSocket
from .log import Log
from .options import ConfigProxy
from .perf_counters import PerfCountersCollection


class Context:
    def __init__(self, overrides: dict | None = None):
        self.conf = ConfigProxy(overrides)
        self.log = Log(self.conf)
        self.perf = PerfCountersCollection()
        self.admin_socket = AdminSocket()
        # observability fast path (ISSUE 18): adopt the kill-switch and
        # the tracer's sampling/slow-promotion knobs from this conf and
        # follow live updates.  Both targets are process-wide (there is
        # ONE default tracer), matching the reference's md_config
        # observers feeding process singletons.
        from . import instruments
        instruments.wire_config(self.conf)
        tracer_mod.wire_config(self.conf)
        # the process-wide jit telemetry collection: shared by every
        # Context so any `perf dump` / prometheus render carries it
        self.perf.add(tracer_mod.jit_perf_counters())
        # the device-time attribution ledger (who occupies the chip, by
        # owner class) — process-wide for the same reason
        from . import device_attribution
        self.perf.add(device_attribution.perf_counters())

        self.admin_socket.register(
            "perf dump", lambda **kw: self.perf.perf_dump(),
            "dump all perf counters")
        self.admin_socket.register(
            "config show", lambda **kw: self.conf.show_config(),
            "show all config values")
        self.admin_socket.register(
            "config diff", lambda **kw: self.conf.diff(),
            "show non-default config values")

        def _config_set(name: str = "", value: str = "", **kw):
            self.conf.set(name, value)
            return {"success": f"{name} = {value}"}
        self.admin_socket.register("config set", _config_set,
                                   "set a config option")
        self.admin_socket.register(
            "log dump", lambda **kw: self.log.dump_recent(),
            "dump recent log entries")
        self.admin_socket.register(
            "trace dump",
            lambda **kw: tracer_mod.default_tracer().dump(),
            "dump the span tracer as Chrome trace-event JSON")
        self.admin_socket.register(
            "trace reset",
            lambda **kw: tracer_mod.default_tracer().reset(),
            "clear the span tracer ring buffer and histograms")
        self.admin_socket.register(
            "jit dump", lambda **kw: tracer_mod.jit_dump(),
            "per-(function, shape) JIT compile/dispatch telemetry")

        def _device_dump(initialize: str = "", **kw):
            from . import device_telemetry
            # SAFE by default: initializing a backend from an admin call
            # can wedge the process over a dead tunnel (the hang
            # device_telemetry exists to avoid).  Operators opt in with
            # initialize=true when they accept that risk.
            return device_telemetry.refresh(
                self, initialize=str(initialize).lower()
                in ("1", "true", "yes"))
        self.admin_socket.register(
            "device dump", _device_dump,
            "JAX/XLA device inventory + memory/compile-cache telemetry "
            "(pass initialize=true to force backend init — may hang on "
            "a dead tunnel)")
        self.admin_socket.register(
            "jit reset", lambda **kw: tracer_mod.jit_reset(),
            "clear the per-(function, shape) JIT telemetry records")

        def _device_top(limit: str = "10", **kw):
            return device_attribution.device_top(int(limit))
        self.admin_socket.register(
            "device top", _device_top,
            "device occupancy by owner class (client/serving/recovery/"
            "scrub/rebalance) + costliest compiled executables")

        def _device_roofline(limit: str = "20", **kw):
            from . import roofline
            return roofline.report(int(limit), cct=self)
        self.admin_socket.register(
            "device roofline", _device_roofline,
            "per-executable roofline ledger: achieved vs peak FLOP/s "
            "and HBM B/s, arithmetic intensity, memory/compute-bound "
            "classification")

    def dout(self, subsys: str, level: int, message: str) -> None:
        self.log.dout(subsys, level, message)


_default: Context | None = None


def default_context() -> Context:
    global _default
    if _default is None:
        _default = Context()
    return _default
