"""TierService: the promote/proxy read-write paths of a cache tier.

One service binds a replicated cache pool to an EC base pool on the same
cluster (the mon's ``osd tier add`` + ``osd tier cache-mode``):

- **read**: the cache pool is tried first — a resident object is a
  *hit* and serves without touching the EC base at all.  When a sharded
  frontend is wired, the hit is admitted through its shed ladder first
  (:meth:`~ceph_tpu.msg.frontend.ShardedFrontend.serve_read`): the
  "free" path still competes for admission, so an overloaded shard
  sheds tier hits by dmClock class instead of letting them bypass
  overload control.  A miss *proxies* the read to the base pool and
  promotes the object into the cache when its hit-set recency reaches
  ``tier_promote_min_recency`` (PrimaryLogPG::maybe_handle_cache's
  min_read_recency_for_promote) — one cold read does not thrash the
  tier, a re-read within the recency window does promote.
- **write** (by cache mode): ``writeback`` absorbs the write in the
  cache pool as ONE atomic op vector (write_full + the dirty xattr),
  which runs through the hosting OSD's ordinary op engine and store WAL
  — the ack means the same thing it means for any other write, and
  survives ``kill -9`` the same way; ``proxy`` forwards writes to the
  base pool and drops any now-stale cached copy; ``readonly`` refuses
  writes (EROFS) — the reference's readonly mode is for immutable data
  and has the same coherence caveat.

Dirtiness rides an object xattr (``tier.dirty``, shared with the
seed agent in osd/tiering.py) so it is exactly as durable as the data
it describes.
"""
from __future__ import annotations

import threading
import weakref

from ..common.tracer import default_tracer
from ..osd.hit_set import is_hit_set_oid
from ..osd.mclock import CLIENT_OP
from ..osd.osd_ops import ObjectOperation
from ..osd.tiering import DIRTY_ATTR

MODES = ("writeback", "proxy", "readonly")

_SERVICES: "weakref.WeakSet[TierService]" = weakref.WeakSet()


def live_tier_services() -> list["TierService"]:
    """Every live tier service (prometheus family source)."""
    return list(_SERVICES)


class TierService:
    """Promote/proxy paths over a (cache pool, base pool) binding."""

    def __init__(self, cluster, cache_pool: int, base_pool: int, *,
                 mode: str = "writeback", frontend=None,
                 name: str | None = None):
        if mode not in MODES:
            raise ValueError(f"unknown cache mode {mode!r} "
                             f"(one of {MODES})")
        self.c = cluster
        self.cache = cache_pool
        self.base = base_pool
        self.mode = mode
        self.frontend = frontend
        self.name = name or f"p{cache_pool}"
        self.cct = cluster.cct
        self._lock = threading.Lock()
        # per-dmClock-class hit/miss/proxy tallies (the fixed class set
        # bounds this dict; perf counters stay class-blind like the
        # reference's l_osd_tier_* slots)
        self.class_ops: dict[str, dict[str, int]] = {}
        from ..common.perf_counters import PerfCountersBuilder
        b = PerfCountersBuilder(f"tier.{self.name}")
        b.add_u64_counter("hit", description="reads served from the "
                          "cache pool (no base-pool touch)")
        b.add_u64_counter("miss", description="reads not resident in "
                          "the cache pool")
        b.add_u64_counter("proxy_read", description="missed reads "
                          "forwarded to the EC base pool")
        b.add_u64_counter("proxy_write", description="writes forwarded "
                          "to the base pool (proxy cache mode)")
        b.add_u64_counter("promote", description="objects copied into "
                          "the cache pool after recency crossed "
                          "tier_promote_min_recency")
        b.add_u64_counter("promote_skip", description="missed reads "
                          "whose hit-set recency stayed below the "
                          "promotion threshold (served via proxy only)")
        b.add_u64_counter("writeback", description="writes absorbed "
                          "dirty in the cache pool (writeback mode)")
        b.add_u64_counter("flush", description="dirty objects written "
                          "back to the EC base pool")
        b.add_u64_counter("evict", description="clean objects removed "
                          "from the cache pool by the agent")
        b.add_u64_counter("invalidate", description="stale cached "
                          "copies dropped after a proxied write")
        b.add_u64("objects", description="objects resident in the "
                  "cache pool at the agent's last pass")
        b.add_u64("dirty", description="dirty objects in the cache "
                  "pool at the agent's last pass")
        self.perf = b.create_perf_counters()
        self.cct.perf.add(self.perf)
        _SERVICES.add(self)

    def close(self) -> None:
        self.cct.perf.remove(self.perf.name)
        _SERVICES.discard(self)

    # -- read path (maybe_handle_cache: hit / proxy / promote) --------------

    def read(self, oid: str, op_class: str = CLIENT_OP) -> bytes:
        """Serve one read through the tier.  Raises FrontendBusy when
        the owning frontend shard sheds the class, IOError(ENOENT) when
        the object exists in neither pool.  A cache PG that went
        INACTIVE (tier OSD deaths below min_size) degrades the read to
        a base-pool proxy instead of blocking the client — and skips
        promotion, since the cache pool cannot absorb the copy."""
        from ..cluster import BlockedWriteError
        tr = default_tracer()
        with tr.span("tier.read", owner="client", oid=oid):
            degraded = False
            try:
                if self.frontend is not None:
                    _sid, data = self.frontend.serve_read(
                        oid, lambda: self._cache_read(oid)[0], op_class)
                else:
                    data = self._cache_read(oid)[0]
            except BlockedWriteError:
                degraded = True
            except IOError as e:
                if getattr(e, "errno", None) != -2:
                    raise
            else:
                self.perf.inc("hit")
                self._class_tally(op_class, "hit")
                return data
            # miss: proxy the read to the EC base (the client is NOT
            # blocked behind the promotion copy — proxy first, like
            # do_proxy_read ahead of promote_object)
            self.perf.inc("miss")
            self._class_tally(op_class, "miss")
            with tr.span("tier.proxy_read", owner="client", oid=oid):
                data, attrs = self._base_read(oid)
            self.perf.inc("proxy_read")
            self._class_tally(op_class, "proxy")
            if degraded:
                self.perf.inc("promote_skip")
                return data
            min_rec = self.cct.conf.get("tier_promote_min_recency")
            if self.recency(oid) >= min_rec:
                # promotion is OPPORTUNISTIC: a cache PG that can serve
                # reads but not absorb writes (degraded below min_size)
                # must not block the client behind the copy
                try:
                    self.promote(oid, data, attrs)
                except BlockedWriteError:
                    self.perf.inc("promote_skip")
            else:
                self.perf.inc("promote_skip")
            return data

    def _cache_read(self, oid: str):
        """Read data + xattrs from the cache pool.  NOT internal: the
        access lands in the cache PG's hit set — misses included (the
        engine records before executing, exactly the evidence recency-
        gated promotion needs).  An inactive cache PG is refused UP
        FRONT: parking the op would leave a zombie that resurfaces as a
        late error after the PG revives, when the client was already
        answered by the base-pool proxy."""
        self._require_active(oid)
        op = ObjectOperation().read(0, 0).getxattrs()
        reply = self.c.operate(self.cache, oid, op)
        return bytes(reply.ops[0].outdata), dict(reply.ops[1].outdata)

    def _require_active(self, oid: str) -> None:
        from ..cluster import BlockedWriteError
        g = self.c.pg_group(self.cache, oid)
        if self.c.pg_state(g) == "inactive":
            raise BlockedWriteError(
                f"cache PG {g.pgid} inactive (tier OSDs down)")

    def _base_read(self, oid: str):
        op = ObjectOperation().read(0, 0).getxattrs()
        reply = self.c.operate(self.base, oid, op, internal=True)
        return bytes(reply.ops[0].outdata), dict(reply.ops[1].outdata)

    def recency(self, oid: str) -> int:
        """Consecutive most-recent hit sets (current first, then the
        archive ring newest-first) containing ``oid`` — the reference's
        min_read_recency_for_promote evidence."""
        eng = self.c.pg_group(self.cache, oid).engine
        sets = []
        if eng.hit_set is not None:
            sets.append(eng.hit_set)
        sets.extend(reversed(eng.hit_set_archives()))
        r = 0
        for hs in sets:
            if not hs.contains(oid):
                break
            r += 1
        return r

    def temperature(self, oid: str) -> int:
        """Membership count across ALL of the cache PG's hit sets (the
        agent's heat rank; 0 = cold)."""
        return self.c.pg_group(self.cache, oid).engine \
            .object_temperature(oid)

    def promote(self, oid: str, data: bytes, attrs: dict) -> None:
        """Copy a base object into the cache pool, CLEAN (it matches the
        base, so an eviction needs no flush).  Internal: promotion
        traffic is system work and must not heat its own hit set."""
        tr = default_tracer()
        self._require_active(oid)        # never park a promotion copy
        with tr.span("tier.promote", owner="client", oid=oid):
            op = ObjectOperation().write_full(bytes(data))
            for k in sorted(attrs):
                if k != DIRTY_ATTR:
                    op.setxattr(k, attrs[k])
            self.c.operate(self.cache, oid, op, internal=True)
        self.perf.inc("promote")

    # -- write path (by cache mode) -----------------------------------------

    def write(self, oid: str, data: bytes,
              op_class: str = CLIENT_OP) -> None:
        tr = default_tracer()
        if self.mode == "writeback":
            # ONE atomic vector: the data and its dirty mark commit (and
            # replay from the WAL) together — there is no window where a
            # crash leaves absorbed data the flush agent cannot see
            with tr.span("tier.write", owner="client", oid=oid):
                op = ObjectOperation().write_full(bytes(data)) \
                    .setxattr(DIRTY_ATTR, True)
                self.c.operate(self.cache, oid, op)
            self.perf.inc("writeback")
            return
        if self.mode == "readonly":
            err = IOError(f"pool {self.cache} is a readonly cache tier: "
                          f"write {oid} to the base pool directly")
            err.errno = -30          # EROFS
            raise err
        # proxy: the base pool is the write target; any cached copy is
        # stale the moment the base write commits
        with tr.span("tier.proxy_write", owner="client", oid=oid):
            self.c.operate(self.base, oid,
                           ObjectOperation().write_full(bytes(data)))
        self.perf.inc("proxy_write")
        self._invalidate(oid)

    def _invalidate(self, oid: str) -> None:
        try:
            self.c.operate(self.cache, oid,
                           ObjectOperation().remove(), internal=True)
        except IOError as e:
            if getattr(e, "errno", None) != -2:
                raise
        else:
            self.perf.inc("invalidate")

    # -- flush / evict primitives (the agent's verbs) -----------------------

    def is_dirty(self, oid: str) -> bool:
        try:
            self.c.operate(self.cache, oid,
                           ObjectOperation().getxattr(DIRTY_ATTR),
                           internal=True)
        except IOError:
            return False
        return True

    def flush(self, oid: str) -> None:
        """Write a dirty cached object back through the EC base pool's
        small-write path, then clear its dirty mark.  Order matters for
        crash safety: the base write commits BEFORE the mark clears, so
        a crash between the two re-flushes (idempotent) instead of
        losing the write."""
        tr = default_tracer()
        with tr.span("tier.flush", owner="rebalance", oid=oid):
            op = ObjectOperation().read(0, 0).getxattrs()
            reply = self.c.operate(self.cache, oid, op, internal=True)
            data = bytes(reply.ops[0].outdata)
            attrs = dict(reply.ops[1].outdata)
            out = ObjectOperation().write_full(data)
            for k in sorted(attrs):
                if k != DIRTY_ATTR:
                    out.setxattr(k, attrs[k])
            self.c.operate(self.base, oid, out, internal=True)
            self.c.operate(self.cache, oid,
                           ObjectOperation().rmxattr(DIRTY_ATTR),
                           internal=True)
        self.perf.inc("flush")

    def evict(self, oid: str) -> None:
        """Drop a CLEAN cached copy (the caller flushes first when
        dirty); the base pool still holds the object, so the next read
        is a miss + proxy, not a loss."""
        tr = default_tracer()
        with tr.span("tier.evict", owner="rebalance", oid=oid):
            self.c.operate(self.cache, oid,
                           ObjectOperation().remove(), internal=True)
        self.perf.inc("evict")

    # -- bookkeeping ---------------------------------------------------------

    def resident(self) -> list[str]:
        """Objects currently resident in the cache pool (hit-set archive
        objects excluded — they are the instrument, not the cargo)."""
        return sorted(o for o in self.c.objects.get(self.cache, set())
                      if not is_hit_set_oid(o))

    def _class_tally(self, op_class: str, kind: str) -> None:
        with self._lock:
            per = self.class_ops.setdefault(
                op_class, {"hit": 0, "miss": 0, "proxy": 0})
            per[kind] += 1

    def stats(self) -> dict:
        with self._lock:
            by_class = {k: dict(v) for k, v in self.class_ops.items()}
        hits, misses = self.perf.get("hit"), self.perf.get("miss")
        total = hits + misses
        return {"mode": self.mode,
                "cache_pool": self.cache,
                "base_pool": self.base,
                "objects": len(self.resident()),
                "hit_rate": (hits / total) if total else 0.0,
                "counters": {k: self.perf.get(k) for k in
                             ("hit", "miss", "proxy_read", "proxy_write",
                              "promote", "promote_skip", "writeback",
                              "flush", "evict", "invalidate")},
                "by_class": by_class}
