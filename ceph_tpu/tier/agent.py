"""TierAgent: watermark-driven flush/evict over a TierService.

The background half of the cache tier (TierAgentState.h): each
:meth:`tick` measures the tier against its watermarks and moves data —

- **flush mode** arms when the dirty fraction of ``tier_target_max_
  objects`` passes ``tier_dirty_ratio_high``: dirty objects flush back
  through the EC base pool coldest-first (hit-set heat rank ascending)
  until the fraction drops under ``tier_dirty_ratio_low`` — hysteresis,
  so the agent is not re-armed by the very next absorbed write;
- **evict mode** arms when residency passes ``tier_full_ratio``: cold
  CLEAN objects drop (dirty ones flush first), again coldest-first,
  skipping anything the hit sets still call hot — unless the tier is
  at/over its hard capacity, where Ceph's agent also stops being
  polite.

Every pass is bounded by ``tier_agent_max_ops`` (one flush or evict =
one op): the agent shares the cluster with clients and must not convoy
them.  All watermarks read live from the config — ``ceph config set``
retunes a running agent.

Consecutive passes that END still above the high-dirty watermark mean
the base pool is not absorbing flushes as fast as writes arrive: that
counter feeds the ``TIER_FLUSH_BACKLOG`` health check
(mgr/health.py), and residency feeds ``TIER_FULL``.
"""
from __future__ import annotations

from ..common.tracer import default_tracer


class TierAgent:
    """Flush/evict agent bound to one :class:`TierService`."""

    def __init__(self, service):
        self.svc = service
        self.conf = service.cct.conf
        # consecutive ticks that ended dirty-ratio > high: the flush
        # backlog signal (0 = keeping up)
        self.backlog_ticks = 0
        self.last = {"flushes": 0, "evictions": 0, "skipped_hot": 0,
                     "dirty_ratio": 0.0, "fullness": 0.0}

    # -- measurement ---------------------------------------------------------

    def measure(self) -> dict:
        """Residency and dirtiness against tier_target_max_objects.
        O(resident) xattr probes — the tier is RAM-resident and bounded
        by the target, so this stays cheap."""
        objs = self.svc.resident()
        dirty = [o for o in objs if self.svc.is_dirty(o)]
        target = max(1, self.conf.get("tier_target_max_objects"))
        return {"objects": objs, "dirty": dirty, "target": target,
                "fullness": len(objs) / target,
                "dirty_ratio": len(dirty) / target}

    def _heat_order(self, oids) -> list[str]:
        """Coldest first (heat rank ascending, oid tie-break): the
        eviction/flush order — hot data stays resident longest."""
        return sorted(oids, key=lambda o: (self.svc.temperature(o), o))

    # -- one agent pass ------------------------------------------------------

    def tick(self, max_ops: int | None = None, age: bool = False) -> dict:
        """One bounded agent pass; returns what moved.  ``age=True``
        force-persists the cache PGs' accumulating hit sets first (a
        deterministic stand-in for the reference's period timer) so
        heat decays even on an idle tier."""
        if age:
            self.age()
        budget = max_ops if max_ops is not None \
            else self.conf.get("tier_agent_max_ops")
        tr = default_tracer()
        stats = {"flushes": 0, "evictions": 0, "skipped_hot": 0}
        with tr.span("tier.agent", owner="rebalance"):
            m = self.measure()
            high = self.conf.get("tier_dirty_ratio_high")
            low = self.conf.get("tier_dirty_ratio_low")
            full = self.conf.get("tier_full_ratio")
            dirty = set(m["dirty"])
            n_dirty, n_objs = len(dirty), len(m["objects"])
            if m["dirty_ratio"] > high:
                for oid in self._heat_order(dirty):
                    if budget <= 0 or n_dirty / m["target"] <= low:
                        break
                    self.svc.flush(oid)
                    dirty.discard(oid)
                    n_dirty -= 1
                    budget -= 1
                    stats["flushes"] += 1
            # arm at >= and drive STRICTLY below: the TIER_FULL health
            # check fires at >= full, so stopping exactly at the
            # watermark would leave it latched forever
            if n_objs / m["target"] >= full:
                hard_full = n_objs >= m["target"]
                for oid in self._heat_order(m["objects"]):
                    if budget <= 0 or n_objs / m["target"] < full:
                        break
                    if self.svc.temperature(oid) > 0 and not hard_full:
                        stats["skipped_hot"] += 1
                        continue
                    if oid in dirty:
                        if budget <= 1:
                            break      # flush+evict is two ops
                        self.svc.flush(oid)
                        dirty.discard(oid)
                        n_dirty -= 1
                        budget -= 1
                        stats["flushes"] += 1
                    self.svc.evict(oid)
                    n_objs -= 1
                    budget -= 1
                    stats["evictions"] += 1
            stats["dirty_ratio"] = n_dirty / m["target"]
            stats["fullness"] = n_objs / m["target"]
            self.backlog_ticks = self.backlog_ticks + 1 \
                if stats["dirty_ratio"] > high else 0
            self.svc.perf.set("objects", n_objs)
            self.svc.perf.set("dirty", n_dirty)
        self.last = stats
        return stats

    def age(self) -> None:
        """Persist the cache PGs' accumulating hit sets (hit_set
        aging): rotation is what makes heat DECAY — an object untouched
        for a full ring of periods ranks cold."""
        for g in self.svc.c.pools[self.svc.cache]["pgs"].values():
            if g.engine.hit_set_params is not None:
                g.engine.hit_set_persist()
                g.bus.deliver_all()

    # -- health-check inputs -------------------------------------------------

    def fullness(self) -> float:
        """Residency over target, WITHOUT xattr probes (cheap enough
        for a health evaluation)."""
        target = max(1, self.conf.get("tier_target_max_objects"))
        return len(self.svc.resident()) / target
