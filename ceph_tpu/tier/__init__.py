"""Cache tiering: a replicated hot tier bound to an EC base pool.

The Ceph cache-tier analog (PrimaryLogPG's promote/proxy paths plus the
tier agent, ``src/osd/TierAgentState.h``): reads serve straight out of
the replicated cache pool when the object is resident (a *hit*, admitted
through the sharded frontend's shed ladder), proxy to the EC base on a
miss, and promote when the object's hit-set recency crosses
``tier_promote_min_recency``.  Write-back mode absorbs writes in the
tier — journaled through the hosting OSDs' existing FileStore/BlueStore
WAL, so acked writes survive ``kill -9`` with no new durability
machinery — while :class:`~ceph_tpu.tier.agent.TierAgent` flushes dirty
data and evicts cold objects by heat rank against the dirty-ratio and
fullness watermarks.

This is the first subsystem that *consumes* the observability stack
(per-PG hit sets + ``mgr/heat.py``) rather than feeding it: the agent's
promotion/demotion decisions close the loop from measured skew.
"""
from .agent import TierAgent
from .service import (DIRTY_ATTR, MODES, TierService, live_tier_services)

__all__ = ["DIRTY_ATTR", "MODES", "TierAgent", "TierService",
           "live_tier_services"]
