"""cephx: ticket-based mutual authentication.

Analog of the reference's cephx protocol (reference: src/auth/cephx/ —
CephxProtocol.{h,cc}, CephxKeyServer.{h,cc}; ~5.8k LoC per SURVEY §2.4),
modeling the protocol structure faithfully over an authenticated
stream cipher built from SHA-256 (the reference uses AES; the primitive
is swappable, the PROTOCOL is the point):

1. the client proves knowledge of its entity secret to the monitor's
   KeyServer via challenge-response (CEPHX_GET_AUTH_SESSION_KEY:
   client_challenge + server_challenge hashed under the entity key) and
   receives a SESSION KEY sealed under its entity secret;
2. it then requests SERVICE TICKETS (CEPHX_GET_PRINCIPAL_SESSION_KEY):
   each ticket carries a service session key and expiry, sealed under
   the service's ROTATING secret (so the service can open it without
   talking to the monitor), plus a copy of the service session key
   sealed under the client's session key;
3. to connect to a service the client builds an AUTHORIZER — the ticket
   blob plus a nonce proof sealed under the service session key; the
   service unseals the ticket with its rotating secret (current or
   previous generation, allowing rotation grace), checks expiry, then
   proves ITS identity by answering nonce+1 (mutual auth,
   CephxAuthorizeReply) and challenges the client once per connection to
   defeat authorizer replay (CephxAuthorizeChallenge).
"""
from __future__ import annotations

import hashlib
import hmac
import os
import pickle
from dataclasses import dataclass, field


class AuthError(Exception):
    pass


# -- sealed boxes (the AES role; authenticated stream cipher) -----------------

def _stream(key: bytes, nonce: bytes, n: int) -> bytes:
    out = b""
    counter = 0
    while len(out) < n:
        out += hashlib.sha256(key + nonce + counter.to_bytes(8, "big")).digest()
        counter += 1
    return out[:n]


def seal(key: bytes, obj) -> bytes:
    """Encrypt-then-MAC under ``key``."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    nonce = os.urandom(16)
    ct = bytes(a ^ b for a, b in zip(payload,
                                     _stream(key, nonce, len(payload))))
    tag = hmac.new(key, nonce + ct, hashlib.sha256).digest()
    return nonce + tag + ct


def unseal(key: bytes, blob: bytes):
    nonce, tag, ct = blob[:16], blob[16:48], blob[48:]
    want = hmac.new(key, nonce + ct, hashlib.sha256).digest()
    if not hmac.compare_digest(tag, want):
        raise AuthError("bad magic / corrupt sealed blob")
    payload = bytes(a ^ b for a, b in zip(ct, _stream(key, nonce, len(ct))))
    return pickle.loads(payload)


def _proof(key: bytes, *parts: bytes) -> bytes:
    return hmac.new(key, b"|".join(parts), hashlib.sha256).digest()


# -- tickets ------------------------------------------------------------------

@dataclass
class Ticket:
    """A service ticket as held by the CLIENT: the opaque blob for the
    service + the service session key it shares (CephXTicketBlob +
    session_key, CephxProtocol.h)."""
    service: str
    blob: bytes                 # sealed under the service's rotating secret
    secret_id: int              # which rotating generation sealed it
    session_key: bytes
    expires: float


@dataclass
class Authorizer:
    """CephXAuthorizer: ticket blob + a nonce proof under the service
    session key."""
    service: str
    blob: bytes
    secret_id: int
    nonce: int
    proof: bytes                # seal(service_session_key, {nonce, ...})


# -- the monitor side ---------------------------------------------------------

@dataclass
class _RotatingSecret:
    secrets: dict[int, bytes] = field(default_factory=dict)
    current: int = 0


class KeyServer:
    """Entity secrets + per-service rotating secrets (CephxKeyServer)."""

    TICKET_VALIDITY = 3600.0

    def __init__(self):
        self.entity_keys: dict[str, bytes] = {}
        self.rotating: dict[str, _RotatingSecret] = {}
        self._pending: dict[str, bytes] = {}      # name -> server_challenge
        self._sessions: dict[str, bytes] = {}     # name -> session key

    def create_entity(self, name: str) -> bytes:
        key = os.urandom(32)
        self.entity_keys[name] = key
        return key

    def rotate(self, service: str) -> int:
        rs = self.rotating.setdefault(service, _RotatingSecret())
        rs.current += 1
        rs.secrets[rs.current] = os.urandom(32)
        # keep one previous generation (the rotation grace window)
        for sid in list(rs.secrets):
            if sid < rs.current - 1:
                del rs.secrets[sid]
        return rs.current

    def service_secret(self, service: str, secret_id: int | None = None):
        rs = self.rotating.get(service)
        if rs is None or not rs.secrets:
            raise AuthError(f"no rotating secret for {service}")
        sid = rs.current if secret_id is None else secret_id
        if sid not in rs.secrets:
            raise AuthError(f"{service} secret generation {sid} expired")
        return sid, rs.secrets[sid]

    # CEPHX_GET_AUTH_SESSION_KEY, step 1: hand out the server challenge
    def get_challenge(self, name: str) -> bytes:
        if name not in self.entity_keys:
            raise AuthError(f"unknown entity {name}")
        ch = os.urandom(16)
        self._pending[name] = ch
        return ch

    # step 2: verify the proof, issue the session key
    def issue_session_key(self, name: str, client_challenge: bytes,
                          proof: bytes, now: float):
        server_challenge = self._pending.pop(name, None)
        if server_challenge is None:
            raise AuthError("no challenge outstanding")
        key = self.entity_keys[name]
        want = _proof(key, server_challenge, client_challenge)
        if not hmac.compare_digest(proof, want):
            raise AuthError(f"bad authenticate for {name}")
        session_key = os.urandom(32)
        env = seal(key, {"session_key": session_key,
                         "expires": now + self.TICKET_VALIDITY})
        self._sessions[name] = session_key
        return env

    # CEPHX_GET_PRINCIPAL_SESSION_KEY: service tickets under the session
    def issue_service_ticket(self, name: str, service: str, now: float):
        sessions = self._sessions
        if name not in sessions:
            raise AuthError(f"{name} has no session")
        sid, svc_secret = self.service_secret(service)
        svc_session_key = os.urandom(32)
        expires = now + self.TICKET_VALIDITY
        blob = seal(svc_secret, {"name": name,
                                 "session_key": svc_session_key,
                                 "expires": expires})
        env = seal(sessions[name], {"service": service, "blob": blob,
                                    "secret_id": sid,
                                    "session_key": svc_session_key,
                                    "expires": expires})
        return env


# -- the client side ----------------------------------------------------------

class CephxClient:
    def __init__(self, name: str, key: bytes):
        self.name = name
        self.key = key
        self.session_key: bytes | None = None
        self.tickets: dict[str, Ticket] = {}
        self._nonce = 0

    def authenticate(self, keyserver: KeyServer, now: float) -> None:
        server_challenge = keyserver.get_challenge(self.name)
        client_challenge = os.urandom(16)
        proof = _proof(self.key, server_challenge, client_challenge)
        env = keyserver.issue_session_key(self.name, client_challenge,
                                          proof, now)
        self.session_key = unseal(self.key, env)["session_key"]

    def get_ticket(self, keyserver: KeyServer, service: str,
                   now: float) -> Ticket:
        if self.session_key is None:
            raise AuthError("authenticate first")
        env = keyserver.issue_service_ticket(self.name, service, now)
        t = unseal(self.session_key, env)
        ticket = Ticket(service=service, blob=t["blob"],
                        secret_id=t["secret_id"],
                        session_key=t["session_key"], expires=t["expires"])
        self.tickets[service] = ticket
        return ticket

    def build_authorizer(self, service: str, now: float) -> Authorizer:
        ticket = self.tickets.get(service)
        if ticket is None:
            raise AuthError(f"no ticket for {service}")
        if now >= ticket.expires:
            raise AuthError(f"ticket for {service} expired")
        self._nonce += 1
        nonce = int.from_bytes(os.urandom(8), "big") + self._nonce
        proof = seal(ticket.session_key, {"nonce": nonce,
                                          "name": self.name})
        return Authorizer(service=service, blob=ticket.blob,
                          secret_id=ticket.secret_id, nonce=nonce,
                          proof=proof)

    def verify_reply(self, service: str, reply: bytes, nonce: int) -> None:
        """Mutual auth: the service answers nonce+1 under the session key
        (CephXAuthorizeReply.nonce_plus_one)."""
        t = self.tickets[service]
        got = unseal(t.session_key, reply)
        if got.get("nonce_plus_one") != nonce + 1:
            raise AuthError(f"{service} failed mutual auth")


# -- the service side ---------------------------------------------------------

class CephxServiceHandler:
    """An OSD/MDS verifying authorizers with its rotating secret."""

    def __init__(self, service: str, keyserver: KeyServer):
        self.service = service
        self.keyserver = keyserver
        self._seen_nonces: set[int] = set()

    def verify_authorizer(self, authz: Authorizer, now: float) -> tuple:
        """Returns (entity name, reply blob).  Raises AuthError on any
        tamper/expiry/replay."""
        if authz.service != self.service:
            raise AuthError("authorizer for the wrong service")
        _, secret = self.keyserver.service_secret(self.service,
                                                  authz.secret_id)
        ticket = unseal(secret, authz.blob)
        if now >= ticket["expires"]:
            raise AuthError("ticket expired")
        svc_session_key = ticket["session_key"]
        proof = unseal(svc_session_key, authz.proof)
        if proof.get("nonce") != authz.nonce or \
                proof.get("name") != ticket["name"]:
            raise AuthError("authorizer proof mismatch")
        # replay defense (the role CephxAuthorizeChallenge plays per
        # connection): a nonce may establish at most one session
        if authz.nonce in self._seen_nonces:
            raise AuthError("authorizer replay")
        self._seen_nonces.add(authz.nonce)
        reply = seal(svc_session_key, {"nonce_plus_one": authz.nonce + 1})
        return ticket["name"], reply
