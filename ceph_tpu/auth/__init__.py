"""Authentication: the cephx ticket protocol (SURVEY.md §2.4 src/auth/)."""
from .cephx import (AuthError, Authorizer, CephxClient, CephxServiceHandler,
                    KeyServer, Ticket)

__all__ = ["AuthError", "Authorizer", "CephxClient", "CephxServiceHandler",
           "KeyServer", "Ticket"]
