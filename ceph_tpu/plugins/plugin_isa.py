"""isa: profile-compatible plugin mapped onto the TPU codec.

Accepts the reference isa plugin's profile shape
(reference: src/erasure-code/isa/ErasureCodeIsa.h:36-38): k=7 m=3 defaults,
technique reed_sol_van (ISA's geometric Vandermonde, gf_gen_rs_matrix) or
cauchy (gf_gen_cauchy1_matrix), with the Vandermonde parameter envelope
k<=32, m<=4, m=4 => k<=21 (ErasureCodeIsa.cc:323-364) enforced by the codec.
"""
from __future__ import annotations

from .. import __version__
from .plugin_jax_rs import ErasureCodeJaxRS
from .interface import ErasureCodeProfile
from .registry import ErasureCodePlugin, ErasureCodePluginRegistry

_TECHNIQUE_MAP = {
    "reed_sol_van": "vandermonde",
    "cauchy": "cauchy",
}


class ErasureCodeIsaCompat(ErasureCodeJaxRS):
    def init(self, profile: ErasureCodeProfile) -> None:
        technique = profile.get("technique") or "reed_sol_van"
        if technique not in _TECHNIQUE_MAP:
            raise ValueError(
                f"technique={technique} must be one of {sorted(_TECHNIQUE_MAP)}")
        profile = dict(profile)
        profile["technique"] = _TECHNIQUE_MAP[technique]
        super().init(profile)
        self._profile["technique"] = technique


class ErasureCodePluginIsa(ErasureCodePlugin):
    def factory(self, directory: str,
                profile: ErasureCodeProfile) -> ErasureCodeIsaCompat:
        instance = ErasureCodeIsaCompat()
        instance.init(dict(profile))
        return instance


def __erasure_code_version__() -> str:
    return __version__


def __erasure_code_init__(name: str, directory: str) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginIsa())
