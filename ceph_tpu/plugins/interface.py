"""The erasure-code plugin contract.

Python mirror of the reference's ``ErasureCodeInterface``
(reference: src/erasure-code/ErasureCodeInterface.h:170-462).  All codes are
systematic (interface doc :20-141).  Buffers are ``bytes``/``numpy uint8``
instead of bufferlists; an ``ErasureCodeProfile`` is a ``dict[str, str]``
(:155) validated by the plugin's ``init`` (:188).
"""
from __future__ import annotations

import abc
from typing import Mapping

import numpy as np

ErasureCodeProfile = dict  # map<string,string> (ErasureCodeInterface.h:155)


class ErasureCodeInterface(abc.ABC):
    """Abstract erasure code, method-for-method with the reference contract."""

    @abc.abstractmethod
    def init(self, profile: ErasureCodeProfile) -> None:
        """Initialize from a profile; raise ValueError on invalid parameters.

        On success the instance's get_profile() reflects the defaults it
        filled in (ErasureCodeInterface.h:188-196 semantics).
        """

    @abc.abstractmethod
    def get_profile(self) -> ErasureCodeProfile:
        """The profile as completed during init (:196)."""

    @abc.abstractmethod
    def create_rule(self, name: str, crush) -> int:
        """Create a CRUSH rule suited to this code in ``crush`` (:212)."""

    @abc.abstractmethod
    def get_chunk_count(self) -> int:
        """k + m (:227)."""

    @abc.abstractmethod
    def get_data_chunk_count(self) -> int:
        """k (:237)."""

    def get_coding_chunk_count(self) -> int:
        """m (:249)."""
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        """>1 only for array/regenerating codes like clay (:259)."""
        return 1

    @abc.abstractmethod
    def get_chunk_size(self, object_size: int) -> int:
        """Chunk size for an object: get_chunk_size(n) * k >= n (:278)."""

    @abc.abstractmethod
    def minimum_to_decode(self, want_to_read: set, available: set
                          ) -> dict[int, list[tuple[int, int]]]:
        """Chunks (and per-chunk (sub-chunk offset, count) runs) needed to
        decode ``want_to_read`` out of ``available`` (:297).  Raises IOError
        when decoding is impossible."""

    @abc.abstractmethod
    def minimum_to_decode_with_cost(self, want_to_read: set,
                                    available: Mapping[int, int]) -> set:
        """Like minimum_to_decode but with per-chunk retrieval costs (:326)."""

    def supports_regenerating_repair(self) -> bool:
        """True when the code repairs a single lost chunk from d helper
        inner products (beta bytes each) instead of a k-chunk decode —
        the capability probe recovery/regen.py plans against."""
        return False

    def minimum_to_repair(self, shard: int, d: int,
                          costs: Mapping[int, int]) -> "set | list":
        """Helper set for repairing ``shard`` given per-chunk retrieval
        ``costs``.  Default: the cheapest decode set — non-regenerating
        codes repair by decoding, so helper selection degenerates to
        :meth:`minimum_to_decode_with_cost`.  Regenerating plugins
        override to return exactly ``d`` ranked helpers (and that rank
        order is the stream order their combine matrix expects)."""
        avail = {c: v for c, v in costs.items() if c != shard}
        return self.minimum_to_decode_with_cost({shard}, avail)

    @abc.abstractmethod
    def encode(self, want_to_encode: set, data: bytes) -> dict[int, np.ndarray]:
        """Split+pad ``data`` into k chunks, compute m parity chunks, return
        the requested subset {chunk index: chunk bytes} (:365)."""

    @abc.abstractmethod
    def encode_chunks(self, want_to_encode: set,
                      encoded: dict[int, np.ndarray]) -> None:
        """Low-level: fill the parity chunks of ``encoded`` in place (:370)."""

    @abc.abstractmethod
    def decode(self, want_to_read: set, chunks: Mapping[int, np.ndarray],
               chunk_size: int = 0) -> dict[int, np.ndarray]:
        """Decode the requested chunks from the available ones (:407)."""

    @abc.abstractmethod
    def decode_chunks(self, want_to_read: set, chunks: Mapping[int, np.ndarray],
                      decoded: dict[int, np.ndarray]) -> None:
        """Low-level: reconstruct missing chunks in ``decoded`` in place (:411)."""

    @abc.abstractmethod
    def get_chunk_mapping(self) -> list[int]:
        """Chunk index remapping, [] if identity (:448)."""

    @abc.abstractmethod
    def decode_concat(self, chunks: Mapping[int, np.ndarray]) -> bytes:
        """Decode the data chunks and return their concatenation (:460)."""
