"""jerasure: profile-compatible plugin mapped onto the TPU codec.

Accepts the reference jerasure plugin's profile shape
(reference: src/erasure-code/jerasure/ErasureCodeJerasure.h:81-252):
techniques reed_sol_van (default, k=7 m=3), reed_sol_r6_op (m forced to 2,
parity rows P=XOR / Q=sum 2^j d_j — exactly the geometric Vandermonde rows),
cauchy_orig/cauchy_good (Cauchy matrices).  The bitmatrix-only techniques
(liberation, blaum_roth, liber8tion) target word-level XOR scheduling that
has no TPU analog and are rejected with a clear error.
"""
from __future__ import annotations

from .. import __version__
from .plugin_jax_rs import ErasureCodeJaxRS
from .interface import ErasureCodeProfile
from .registry import ErasureCodePlugin, ErasureCodePluginRegistry

_TECHNIQUE_MAP = {
    "reed_sol_van": "reed_sol_van",
    "reed_sol_r6_op": "vandermonde",
    "cauchy_orig": "cauchy",
    "cauchy_good": "cauchy",
}
_UNSUPPORTED = ("liberation", "blaum_roth", "liber8tion")


class ErasureCodeJerasureCompat(ErasureCodeJaxRS):
    def init(self, profile: ErasureCodeProfile) -> None:
        technique = profile.get("technique") or "reed_sol_van"
        if technique in _UNSUPPORTED:
            raise ValueError(
                f"technique={technique} is a CPU bitmatrix/XOR-schedule "
                f"technique with no TPU mapping; use one of "
                f"{sorted(_TECHNIQUE_MAP)}")
        if technique not in _TECHNIQUE_MAP:
            raise ValueError(f"unknown jerasure technique {technique}")
        if technique == "reed_sol_r6_op":
            # RAID6: m is always 2 (ErasureCodeJerasure.h:111-140)
            profile["m"] = "2"
        profile = dict(profile)
        profile["technique"] = _TECHNIQUE_MAP[technique]
        super().init(profile)
        # report the jerasure-visible technique name in the profile
        self._profile["technique"] = technique


class ErasureCodePluginJerasure(ErasureCodePlugin):
    def factory(self, directory: str,
                profile: ErasureCodeProfile) -> ErasureCodeJerasureCompat:
        instance = ErasureCodeJerasureCompat()
        instance.init(dict(profile))
        return instance


def __erasure_code_version__() -> str:
    return __version__


def __erasure_code_init__(name: str, directory: str) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginJerasure())
