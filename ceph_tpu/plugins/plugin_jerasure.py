"""jerasure: profile-compatible plugin mapped onto the TPU codec.

Accepts the reference jerasure plugin's profile shape
(reference: src/erasure-code/jerasure/ErasureCodeJerasure.h:81-252):

- reed_sol_van (default, k=7 m=3), reed_sol_r6_op (m forced to 2, parity
  rows P=XOR / Q=sum 2^j d_j — exactly the geometric Vandermonde rows),
  cauchy_orig/cauchy_good (Cauchy matrices): mapped onto the GF(2^8) byte
  codec (ceph_tpu.ops.RSCodec).
- liberation, blaum_roth, liber8tion: true bitmatrix RAID-6 codes with
  jerasure's packet layout, run as GF(2) XOR-matmuls on the MXU
  (gf/bitmatrix.py + ops.rs_kernels.xor_apply).  The reference compiles
  these into word-XOR schedules (ErasureCodeJerasure.cc:453-509); on TPU
  the bitmatrix apply is itself the native operation, so no scheduling
  pass exists.

Parameter envelopes follow the reference exactly: liberation needs prime
w > 2, k <= w, packetsize set and a multiple of 4
(ErasureCodeJerasure.cc:368-414); blaum_roth needs w+1 prime with w=7
tolerated for backward compat (:461-471); liber8tion forces w=8, m=2,
k <= 8 (:484-505).
"""
from __future__ import annotations

from typing import Mapping

import numpy as np

from .. import __version__
from ..gf import bitmatrix as bm
from .plugin_jax_rs import ErasureCodeJaxRS
from .base import DeviceRouting, ErasureCode
from .interface import ErasureCodeProfile
from .registry import ErasureCodePlugin, ErasureCodePluginRegistry

_TECHNIQUE_MAP = {
    "reed_sol_van": "reed_sol_van",
    "reed_sol_r6_op": "vandermonde",
    "cauchy_orig": "cauchy",
    "cauchy_good": "cauchy",
}
_BITMATRIX = ("liberation", "blaum_roth", "liber8tion")
# scalar techniques that run the wide (w=16/32) bitmatrix path
_WIDE = ("reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good")
DEFAULT_PACKETSIZE = "2048"     # ErasureCodeJerasure.h:139


class ErasureCodeJerasureCompat(ErasureCodeJaxRS):
    def init(self, profile: ErasureCodeProfile) -> None:
        technique = profile.get("technique") or "reed_sol_van"
        if technique not in _TECHNIQUE_MAP:
            raise ValueError(
                f"unknown jerasure technique {technique}; bitmatrix "
                f"techniques {_BITMATRIX} use ErasureCodeJerasureBitmatrix")
        if technique == "reed_sol_r6_op":
            # RAID6: m is always 2 (ErasureCodeJerasure.h:111-140)
            profile["m"] = "2"
        profile = dict(profile)
        profile["technique"] = _TECHNIQUE_MAP[technique]
        super().init(profile)
        # report the jerasure-visible technique name in the profile
        self._profile["technique"] = technique


class ErasureCodeJerasureBitmatrix(DeviceRouting, ErasureCode):
    """Packet-layout GF(2) bitmatrix codes on the MXU.

    Two families share this machinery:
    - the RAID-6 bitmatrix techniques (liberation/blaum_roth/liber8tion,
      m forced to 2, their own w envelopes);
    - the WIDE-word scalar techniques (reed_sol_van/cauchy at w in
      {16, 32}): the GF(2^w) coding matrix expands to a [w*m, w*k]
      GF(2) bitmatrix (gf/gfw.py) and the data path is identical —
      word size only changes how many packets a chunk splits into,
      the MXU kernel never sees it.
    """

    DEFAULT_K = "2"             # ErasureCodeJerasure.h:202-204
    # The reference's blaum_roth inherits DEFAULT_W="7" from Liberation and
    # tolerates it (ErasureCodeJerasure.cc:461-471) — but w=7 makes
    # 1+x+...+x^7 = (1+x)^7 reducible, so double-DATA erasures are
    # UNDECODABLE.  Defaulting a RAID-6 pool to a non-MDS profile loses
    # data; here the default is the nearest valid w (w+1=7 prime) and w=7
    # stays accept-on-explicit-request for profile compat only.
    DEFAULT_W = {"liberation": "7", "blaum_roth": "6", "liber8tion": "8",
                 "reed_sol_van": "16", "reed_sol_r6_op": "16",
                 "cauchy_orig": "16", "cauchy_good": "16"}

    def __init__(self, technique: str):
        super().__init__()
        self.technique = technique
        self.k = 0
        self.m = 2
        self.w = 0
        self.packetsize = 0
        self.coding: np.ndarray | None = None
        self.device = "auto"

    def init(self, profile: ErasureCodeProfile) -> None:
        super().init(profile)
        self.parse_mapping(profile)
        technique = self.technique
        if technique == "liber8tion":
            # w and m are not parameters (ErasureCodeJerasure.cc:484-495)
            profile.pop("w", None)
            profile.pop("m", None)
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, "2")
        self.w = self.to_int("w", profile, self.DEFAULT_W[technique])
        self.packetsize = self.to_int("packetsize", profile,
                                      DEFAULT_PACKETSIZE)
        self.parse_device_routing(profile)
        self.sanity_check_k_m(self.k, self.m)
        if self.packetsize <= 0:
            raise ValueError("packetsize must be set")
        if self.packetsize % 4:
            raise ValueError(
                f"packetsize={self.packetsize} must be a multiple of 4")
        if technique in _WIDE:
            from ..gf.gfw import GFW
            if self.w not in (16, 32):
                raise ValueError(f"w={self.w} must be 16 or 32 here "
                                 f"(w=8 {technique} runs the byte codec)")
            if technique == "reed_sol_r6_op":
                self.m = 2          # RAID6 (ErasureCodeJerasure.h:111-140)
            gf = GFW(self.w)
            mat = (gf.vandermonde(self.k, self.m)
                   if technique.startswith("reed_sol")
                   else gf.cauchy(self.k, self.m))
            self.coding = gf.expand_bitmatrix(mat)
        else:
            if self.m != 2:
                raise ValueError(f"m={self.m}: {technique} is a RAID-6 "
                                 f"code, m must be 2")
            if technique == "liberation":
                self.coding = bm.liberation_bitmatrix(self.k, self.w)
            elif technique == "blaum_roth":
                self.coding = bm.blaum_roth_bitmatrix(self.k, self.w)
            else:
                self.coding = bm.liber8tion_bitmatrix(self.k)
        if self.chunk_mapping and len(self.chunk_mapping) != self.k + self.m:
            raise ValueError(
                f"mapping maps {len(self.chunk_mapping)} chunks "
                f"instead of {self.k + self.m}")
        self._profile = dict(profile)
        self._profile["technique"] = technique

    # -- sizing ------------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        # chunks must split into whole groups of w packets
        # (cf. ErasureCodeJerasureLiberation::get_alignment,
        # ErasureCodeJerasure.cc:367-373)
        return self.w * self.packetsize

    # -- encode/decode -----------------------------------------------------

    def _apply(self, W: np.ndarray, packets: np.ndarray) -> np.ndarray:
        if self.use_device(packets.nbytes):
            from ..ops.rs_kernels import xor_apply
            import jax
            return np.asarray(jax.device_get(xor_apply(W, packets)))
        return bm.xor_apply_host(W, packets)

    def encode_chunks(self, want_to_encode: set,
                      encoded: dict[int, np.ndarray]) -> None:
        data = np.stack([encoded[self.chunk_index(i)] for i in range(self.k)])
        packets = bm.to_packets(data, self.w, self.packetsize)
        out = self._apply(self.coding, packets)
        parity = bm.from_packets(out, self.w, self.packetsize)
        for i in range(self.m):
            encoded[self.chunk_index(self.k + i)][:] = parity[i]

    def decode_chunks(self, want_to_read: set,
                      chunks: Mapping[int, np.ndarray],
                      decoded: dict[int, np.ndarray]) -> None:
        erasures = [i for i in range(self.k + self.m) if i not in chunks]
        if not erasures:
            return
        avail, erasures_l = self.remap_for_decode(
            {i: decoded[i] for i in chunks}, erasures)
        D, src = bm.decode_bitmatrix(
            self.coding, self.k, self.w, erasures_l, available=list(avail))
        stack = np.stack([np.asarray(avail[c], dtype=np.uint8) for c in src])
        packets = bm.to_packets(stack, self.w, self.packetsize)
        rec = bm.from_packets(self._apply(D, packets), self.w,
                              self.packetsize)
        for row, e in enumerate(sorted(erasures_l)):
            decoded[self.chunk_index(e)][:] = rec[row]


class ErasureCodePluginJerasure(ErasureCodePlugin):
    def factory(self, directory: str,
                profile: ErasureCodeProfile) -> ErasureCode:
        technique = profile.get("technique") or "reed_sol_van"
        w = int(profile.get("w", "8") or "8")
        if technique in _BITMATRIX or (technique in _WIDE and w != 8):
            instance: ErasureCode = ErasureCodeJerasureBitmatrix(technique)
        else:
            instance = ErasureCodeJerasureCompat()
        instance.init(dict(profile))
        return instance


def __erasure_code_version__() -> str:
    return __version__


def __erasure_code_init__(name: str, directory: str) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginJerasure())
