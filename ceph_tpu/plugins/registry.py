"""Erasure-code plugin registry.

Python mirror of ``ErasureCodePluginRegistry``
(reference: src/erasure-code/ErasureCodePlugin.{h,cc}): a process-wide
singleton mapping plugin name -> plugin object.  Where the reference
``dlopen``s ``libec_<name>.so`` and calls the C entry points
``__erasure_code_version()`` / ``__erasure_code_init(name, dir)``
(ErasureCodePlugin.cc:126-184), we import a Python module
``ceph_tpu.plugins.plugin_<name>`` (or ``<directory>/plugin_<name>.py``)
and call the same-named module hooks:

    __erasure_code_version__() -> str   must equal ceph_tpu.__version__
    __erasure_code_init__(name, directory) -> None   must self-register

The failure paths match the reference's registry tests (missing entry
point, version mismatch, init failure, init-without-register; cf.
src/test/erasure-code/TestErasureCodePlugin*.cc).
"""
from __future__ import annotations

import importlib
import importlib.util
import os
import threading

from .. import __version__
from .interface import ErasureCodeInterface, ErasureCodeProfile


class ErasureCodePlugin:
    """Base plugin: a named factory of codec instances
    (reference: src/erasure-code/ErasureCodePlugin.h:33-43)."""

    def factory(self, directory: str,
                profile: ErasureCodeProfile) -> ErasureCodeInterface:
        raise NotImplementedError


class ErasureCodePluginRegistry:
    _instance = None
    _instance_lock = threading.Lock()
    # While load() runs a plugin's __erasure_code_init__, instance() resolves
    # to the loading registry, so self-registration lands in the registry
    # that initiated the load (keeps non-singleton registries testable).
    _loading = threading.local()

    def __init__(self):
        self._plugins: dict[str, ErasureCodePlugin] = {}
        self._lock = threading.Lock()
        self.disable_dlclose = True  # parity knob; module unload never happens

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        current = getattr(cls._loading, "registry", None)
        if current is not None:
            return current
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    # -- add/get (ErasureCodePlugin.cc:51-90) ------------------------------

    def add(self, name: str, plugin: ErasureCodePlugin) -> None:
        with self._lock:
            if name in self._plugins:
                raise ValueError(f"plugin {name} already registered (-EEXIST)")
            self._plugins[name] = plugin

    def get(self, name: str) -> ErasureCodePlugin | None:
        with self._lock:
            return self._plugins.get(name)

    def remove(self, name: str) -> None:
        with self._lock:
            self._plugins.pop(name, None)

    # -- load (ErasureCodePlugin.cc:126-184) -------------------------------

    def load(self, plugin_name: str, directory: str = "") -> ErasureCodePlugin:
        if directory:
            path = os.path.join(directory, f"plugin_{plugin_name}.py")
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"load dlopen({path}): no such plugin (-ENOENT)")
            spec = importlib.util.spec_from_file_location(
                f"ceph_tpu_ext_plugin_{plugin_name}", path)
            module = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(module)
        else:
            try:
                module = importlib.import_module(
                    f"ceph_tpu.plugins.plugin_{plugin_name}")
            except ImportError as e:
                raise FileNotFoundError(
                    f"load dlopen(libec_{plugin_name}): {e} (-ENOENT)") from e

        version_fn = getattr(module, "__erasure_code_version__", None)
        if version_fn is None:
            raise RuntimeError(
                f"{plugin_name} plugin has no __erasure_code_version__ (-EXDEV)")
        version = version_fn()
        if version != __version__:
            raise RuntimeError(
                f"{plugin_name} plugin version {version} != expected "
                f"{__version__} (-EXDEV)")

        init_fn = getattr(module, "__erasure_code_init__", None)
        if init_fn is None:
            raise RuntimeError(
                f"{plugin_name} plugin has no __erasure_code_init__ (-ENOENT)")
        type(self)._loading.registry = self
        try:
            init_fn(plugin_name, directory)
        finally:
            type(self)._loading.registry = None

        plugin = self.get(plugin_name)
        if plugin is None:
            raise RuntimeError(
                f"{plugin_name} plugin init did not register itself (-EBADF)")
        return plugin

    # -- factory (ErasureCodePlugin.cc:92-120) -----------------------------

    def factory(self, plugin_name: str, directory: str,
                profile: ErasureCodeProfile,
                cct=None) -> ErasureCodeInterface:
        with self._lock:
            plugin = self._plugins.get(plugin_name)
        if plugin is None:
            plugin = self.load(plugin_name, directory)
        profile = dict(profile)
        profile.setdefault("plugin", plugin_name)
        if profile["plugin"] != plugin_name:
            raise ValueError(
                f"profile plugin={profile['plugin']} != factory({plugin_name})")
        instance = plugin.factory(directory, profile)
        if cct is not None:
            # bind the caller's context so live config (e.g. the device
            # routing cutoff) is read from its store, not the global one
            instance.cct = cct
            if hasattr(instance, "_conf"):
                instance._conf = cct.conf
        return instance

    # -- preload (ErasureCodePlugin.cc:186-202) ----------------------------

    def preload(self, plugins: list[str], directory: str = "") -> None:
        """Load a list of plugins at startup, like the daemons do from the
        osd_erasure_code_plugins option (reference: src/common/options.cc:2519,
        called from global_init.cc:577)."""
        for name in plugins:
            if self.get(name) is None:
                self.load(name, directory)


def default_registry() -> ErasureCodePluginRegistry:
    return ErasureCodePluginRegistry.instance()
