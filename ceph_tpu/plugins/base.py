"""ErasureCode base class: shared default behaviour for all plugins.

Python mirror of the reference base class (reference:
src/erasure-code/ErasureCode.{h,cc}): profile parsing helpers, chunk
remapping via ``mapping=DDD_D_`` strings, ``encode_prepare`` padding,
first-k-available ``minimum_to_decode`` and ``decode_concat``.

Alignment divergence (deliberate, TPU-first): the reference aligns chunks to
SIMD_ALIGN=32 bytes for AVX (ErasureCode.cc:42); we align to 128 bytes — the
TPU lane width — so chunk buffers tile cleanly onto the VPU/MXU minor
dimension.  get_chunk_size(n)*k >= n still holds, which is the only contract
the interface requires (ErasureCodeInterface.h:278).
"""
from __future__ import annotations

from typing import Mapping

import numpy as np

from .interface import ErasureCodeInterface, ErasureCodeProfile

SIMD_ALIGN = 32          # reference AVX alignment (ErasureCode.cc:42)
TPU_LANE_ALIGN = 128     # TPU minor-dim tile width; our chunk alignment


class DeviceRouting:
    """ONE copy of the device/jax-threshold routing policy shared by every
    TPU-backed plugin (the dispatch-economics split from SURVEY §7): a
    profile ``jax-threshold`` pins the cutoff, otherwise the live config
    option ``ec_device_threshold_bytes`` decides per call."""

    def parse_device_routing(self, profile) -> None:
        self.device = self.to_string("device", profile, "auto")
        if self.device not in ("jax", "numpy", "auto"):
            raise ValueError(f"device={self.device} must be jax|numpy|auto")
        if "jax-threshold" in profile:
            self.jax_threshold: int | None = self.to_int(
                "jax-threshold", profile, "65536")
        else:
            self.jax_threshold = None
        from ..common.context import default_context
        self._conf = default_context().conf

    def use_device(self, nbytes: int) -> bool:
        """Should this call run on the accelerator?"""
        if self.device != "auto":
            return self.device == "jax"
        cutoff = self.jax_threshold
        if cutoff is None:
            cutoff = int(self._conf.get("ec_device_threshold_bytes"))
        return nbytes >= cutoff


class ErasureCode(ErasureCodeInterface):
    DEFAULT_RULE_ROOT = "default"
    DEFAULT_RULE_FAILURE_DOMAIN = "host"

    def __init__(self):
        self._profile: ErasureCodeProfile = {}
        self.chunk_mapping: list[int] = []
        self.rule_root = self.DEFAULT_RULE_ROOT
        self.rule_failure_domain = self.DEFAULT_RULE_FAILURE_DOMAIN
        self.rule_device_class = ""

    # -- profile helpers (ErasureCode.cc:295-343) --------------------------

    @staticmethod
    def to_int(name: str, profile: ErasureCodeProfile, default: str) -> int:
        if not profile.get(name):
            profile[name] = default
        try:
            return int(profile[name])
        except ValueError as e:
            raise ValueError(f"could not convert {name}={profile[name]} to int") from e

    @staticmethod
    def to_bool(name: str, profile: ErasureCodeProfile, default: str) -> bool:
        if not profile.get(name):
            profile[name] = default
        return profile[name] in ("yes", "true")

    @staticmethod
    def to_string(name: str, profile: ErasureCodeProfile, default: str) -> str:
        if not profile.get(name):
            profile[name] = default
        return profile[name]

    @staticmethod
    def sanity_check_k_m(k: int, m: int) -> None:
        if k < 2:
            raise ValueError(f"k={k} must be >= 2")
        if m < 1:
            raise ValueError(f"m={m} must be >= 1")

    # -- init / rules ------------------------------------------------------

    def init(self, profile: ErasureCodeProfile) -> None:
        self.rule_root = self.to_string("crush-root", profile,
                                        self.DEFAULT_RULE_ROOT)
        self.rule_failure_domain = self.to_string("crush-failure-domain", profile,
                                                  self.DEFAULT_RULE_FAILURE_DOMAIN)
        self.rule_device_class = self.to_string("crush-device-class", profile, "")
        self._profile = profile

    def get_profile(self) -> ErasureCodeProfile:
        return self._profile

    def create_rule(self, name: str, crush) -> int:
        """ErasureCode::create_rule semantics (ErasureCode.cc:64-83): an
        'indep' rule rooted at crush-root over crush-failure-domain."""
        return crush.add_simple_rule(
            name, self.rule_root, self.rule_failure_domain,
            self.rule_device_class, mode="indep",
            num_rep=self.get_chunk_count())

    # -- chunk mapping (ErasureCode.cc:274-293) ----------------------------

    def parse_mapping(self, profile: ErasureCodeProfile) -> None:
        mapping = profile.get("mapping")
        if not mapping:
            return
        data_pos, coding_pos = [], []
        for position, ch in enumerate(mapping):
            (data_pos if ch == "D" else coding_pos).append(position)
        self.chunk_mapping = data_pos + coding_pos

    def chunk_index(self, i: int) -> int:
        return self.chunk_mapping[i] if len(self.chunk_mapping) > i else i

    def remap_for_decode(self, chunks, erasures):
        """Translate physically-keyed available chunks + erasure ids into
        the codec's logical row space (decode-side counterpart of the
        chunk_index remap encode applies)."""
        if not self.chunk_mapping:
            return dict(chunks), list(erasures)
        inv = [0] * len(self.chunk_mapping)
        for logical, phys in enumerate(self.chunk_mapping):
            inv[phys] = logical
        return ({inv[i]: v for i, v in chunks.items()},
                [inv[i] for i in erasures])

    def get_chunk_mapping(self) -> list[int]:
        return self.chunk_mapping

    # -- sizes -------------------------------------------------------------

    def get_alignment(self) -> int:
        return TPU_LANE_ALIGN

    def get_chunk_size(self, object_size: int) -> int:
        """Per-chunk-aligned sizing (cf. ErasureCodeJerasure.cc:80-104
        per_chunk_alignment branch, with the TPU lane width as alignment)."""
        k = self.get_data_chunk_count()
        alignment = self.get_alignment()
        chunk_size = (object_size + k - 1) // k
        modulo = chunk_size % alignment
        if modulo:
            chunk_size += alignment - modulo
        return max(chunk_size, alignment)

    # -- minimum_to_decode (ErasureCode.cc:103-146) ------------------------

    def _minimum_to_decode(self, want_to_read: set, available: set) -> set:
        want_to_read = set(want_to_read)
        available = set(available)
        if want_to_read <= available:
            return set(want_to_read)
        k = self.get_data_chunk_count()
        if len(available) < k:
            raise IOError(
                f"cannot decode: {len(available)} chunks available, need {k}")
        return set(sorted(available)[:k])

    def minimum_to_decode(self, want_to_read: set, available: set
                          ) -> dict[int, list[tuple[int, int]]]:
        minimum = self._minimum_to_decode(want_to_read, available)
        sub = [(0, self.get_sub_chunk_count())]
        return {i: list(sub) for i in sorted(minimum)}

    def minimum_to_decode_with_cost(self, want_to_read: set,
                                    available: Mapping[int, int]) -> set:
        """Pick decode sources by repair cost (ErasureCode.cc:137-146
        semantics, made topology-aware): when the wanted chunks all
        survive, read them directly regardless of cost; otherwise take
        the cheapest |minimum| sources — ``available`` maps chunk id to
        a cost such as CRUSH distance from the repair target, so chains
        prefer near survivors (cf. the repair-cost-aware selection of
        the product-matrix regenerating-code work, arXiv:1412.3022)."""
        if set(want_to_read) <= set(available):
            return set(want_to_read)
        base = self._minimum_to_decode(want_to_read, set(available))
        ranked = sorted(available, key=lambda c: (available[c], c))
        return set(ranked[:len(base)])

    def partial_sum_coefficients(self, erasures: set, sources: list[int]):
        """Per-source decode coefficients for chained streaming repair:
        ``(coeffs, rows)`` where ``coeffs[source chunk]`` is one GF
        coefficient per erased row and ``rows`` lists the erased chunk
        each row reconstructs, such that XOR over sources of
        ``coeff * chunk`` yields each erased chunk — the partial sums a
        RapidRAID-style hop chain accumulates.  None (the default) means
        the code has no whole-chunk linear repair form (sub-chunked/
        clay, LRC locality) and the caller must keep centralized
        decode."""
        return None

    # -- encode (ErasureCode.cc:151-204) -----------------------------------

    def encode_prepare(self, raw: bytes) -> dict[int, np.ndarray]:
        """Split+pad ``raw`` into k data chunks and allocate m parity chunks,
        with the reference's padding layout (ErasureCode.cc:151-186): chunks
        fully covered by the payload are slices; the straddling chunk is
        zero-padded; fully-padded chunks are zeros."""
        k = self.get_data_chunk_count()
        m = self.get_coding_chunk_count()
        raw = np.frombuffer(raw, dtype=np.uint8) if isinstance(raw, (bytes, bytearray)) \
            else np.asarray(raw, dtype=np.uint8)
        blocksize = self.get_chunk_size(len(raw))
        padded_chunks = k - len(raw) // blocksize
        encoded: dict[int, np.ndarray] = {}
        for i in range(k - padded_chunks):
            encoded[self.chunk_index(i)] = raw[i * blocksize:(i + 1) * blocksize].copy()
        if padded_chunks:
            remainder = len(raw) - (k - padded_chunks) * blocksize
            buf = np.zeros(blocksize, dtype=np.uint8)
            buf[:remainder] = raw[(k - padded_chunks) * blocksize:]
            encoded[self.chunk_index(k - padded_chunks)] = buf
            for i in range(k - padded_chunks + 1, k):
                encoded[self.chunk_index(i)] = np.zeros(blocksize, dtype=np.uint8)
        for i in range(k, k + m):
            encoded[self.chunk_index(i)] = np.zeros(blocksize, dtype=np.uint8)
        return encoded

    def encode(self, want_to_encode: set, data: bytes) -> dict[int, np.ndarray]:
        encoded = self.encode_prepare(data)
        self.encode_chunks(set(range(self.get_chunk_count())), encoded)
        return {i: encoded[i] for i in want_to_encode}

    # -- decode (ErasureCode.cc:212-253) -----------------------------------

    def _decode(self, want_to_read: set,
                chunks: Mapping[int, np.ndarray]) -> dict[int, np.ndarray]:
        chunks = {i: np.asarray(v, dtype=np.uint8) for i, v in chunks.items()}
        if set(want_to_read) <= set(chunks):
            return {i: chunks[i] for i in want_to_read}
        k = self.get_data_chunk_count()
        m = self.get_coding_chunk_count()
        blocksize = len(next(iter(chunks.values())))
        decoded: dict[int, np.ndarray] = {}
        for i in range(k + m):
            if i in chunks:
                decoded[i] = chunks[i]
            else:
                decoded[i] = np.zeros(blocksize, dtype=np.uint8)
        self.decode_chunks(set(want_to_read), chunks, decoded)
        return {i: decoded[i] for i in want_to_read}

    def decode(self, want_to_read: set, chunks: Mapping[int, np.ndarray],
               chunk_size: int = 0) -> dict[int, np.ndarray]:
        return self._decode(want_to_read, chunks)

    def decode_concat(self, chunks: Mapping[int, np.ndarray]) -> bytes:
        """Decode and concatenate the data chunks (ErasureCode.cc:345-361)."""
        k = self.get_data_chunk_count()
        want = {self.chunk_index(i) for i in range(k)}
        decoded = self._decode(want, chunks)
        return b"".join(decoded[self.chunk_index(i)].tobytes() for i in range(k))

    # subclasses must provide encode_chunks/decode_chunks and the counts
    def encode_chunks(self, want_to_encode, encoded):
        raise NotImplementedError("encode_chunks not implemented")

    def decode_chunks(self, want_to_read, chunks, decoded):
        raise NotImplementedError("decode_chunks not implemented")
