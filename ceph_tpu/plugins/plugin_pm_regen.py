"""Product-matrix regenerating codes: exact-repair MSR/MBR plugin.

Implements the Rashmi-Shah-Kumar product-matrix construction
(arXiv:1005.4178; the batched-GF formulation of arXiv:1412.3022, "Fast
Product-Matrix Regenerating Codes"): every stored chunk is ``alpha``
symbol rows produced as an encoding-vector x message-matrix product, so
a lost chunk is rebuilt from ``d`` helpers that each ship ONE inner
product ``psi_f . stored_chunk`` (beta = chunk/alpha bytes) instead of
their whole chunk — total repair wire d*beta instead of the k-chunk
decode floor.

Two operating points (the alpha/beta/gamma tradeoff):

- **MBR** (minimum bandwidth, any ``k <= d <= n-1``): alpha = d symbol
  rows per chunk, B = kd - k(k-1)/2 message symbols.  Repair wire is
  d*beta = alpha*beta = exactly the lost chunk's stored bytes
  (~1.0 B/B), but storage expands: each stored chunk holds
  alpha = d > B/k message-symbol equivalents (the expansion is stated,
  not hidden — ``get_stored_chunk_size`` returns the real on-disk
  size).  The code is NOT systematic: every read decodes from any k
  stored chunks.
- **MSR** (minimum storage, ``d = 2k-2`` exactly): alpha = k-1,
  B = k*alpha, systematized via ``G = A . A_top^-1`` so data chunks are
  stored raw (zero storage overhead beyond the usual m parity chunks).
  Repair wire is d*beta = d/alpha = 2.0 B/B at d = 2k-2 — between the
  MBR point and the k floor.

The whole chunk row is ONE codeword (no per-stripe sub-blocking): the
backend's write planner already forces sub-chunked codes to
whole-object rewrites, and MSR with alpha = 1 is positionwise linear,
so a stored chunk reshaped ``(alpha, N)`` gives the symbol rows
directly.  All GF matrix products route host/device through
:mod:`ceph_tpu.ops.codec`'s jitted inner-product kernel via the shared
:class:`~ceph_tpu.plugins.base.DeviceRouting` policy.
"""
from __future__ import annotations

import collections
import math
import threading
from typing import Mapping

import numpy as np

from .. import __version__
from ..gf import matrix as gfm
from ..gf import ref as gfref
from ..gf import tables as gft
from .base import DeviceRouting, ErasureCode, TPU_LANE_ALIGN
from .interface import ErasureCodeProfile
from .registry import ErasureCodePlugin, ErasureCodePluginRegistry

# decode-plan LRU capacity (erasure-signature cache, the isa table-cache
# sizing ops/codec.py also uses)
PLAN_CACHE_SIZE = 256


def _select_rows(enc: np.ndarray, avail: list[int], alpha: int,
                 need: int) -> list[int]:
    """Greedy GF(2^8) row-pivot selection: scan the available chunks'
    symbol rows in order and keep the first ``need`` linearly
    independent ones.  Returns global row indices into ``enc``; raises
    IOError when the available rows do not reach full rank."""
    pivots: list[tuple[int, np.ndarray]] = []
    chosen: list[int] = []
    for c in avail:
        for r in range(alpha):
            gi = c * alpha + r
            row = enc[gi].copy()
            for pc, pr in pivots:
                f = int(row[pc])
                if f:
                    row ^= gft.gf_mul_vec(f, pr)
            nz = np.nonzero(row)[0]
            if nz.size == 0:
                continue
            pc = int(nz[0])
            row = gft.gf_mul_vec(gft.gf_inv(int(row[pc])), row)
            pivots.append((pc, row))
            chosen.append(gi)
            if len(chosen) == need:
                return chosen
    raise IOError(
        f"cannot decode: {len(avail)} chunks supply rank "
        f"{len(chosen)} < {need}")


class ErasureCodePMRegen(DeviceRouting, ErasureCode):
    """Product-matrix MSR/MBR over GF(2^8), poly 0x11D."""

    def __init__(self, directory: str = ""):
        super().__init__()
        self.directory = directory
        self.k = 0
        self.m = 0
        self.d = 0
        self.mode = "mbr"
        self.alpha = 0
        self.B = 0

    # -- init --------------------------------------------------------------

    def init(self, profile: ErasureCodeProfile) -> None:
        super().init(profile)
        if profile.get("mapping"):
            raise ValueError("pm_regen does not support mapping=")
        k = self.to_int("k", profile, "3")
        m = self.to_int("m", profile, "2")
        self.sanity_check_k_m(k, m)
        mode = self.to_string("mode", profile, "mbr")
        if mode not in ("mbr", "msr"):
            raise ValueError(f"mode={mode} must be mbr|msr")
        n = k + m
        if n > 255:
            raise ValueError(f"k+m={n} exceeds the GF(2^8) node limit 255")
        d = self.to_int("d", profile,
                        str(k if mode == "mbr" else 2 * k - 2))
        if mode == "mbr":
            if not k <= d <= n - 1:
                raise ValueError(
                    f"mbr requires k <= d <= k+m-1; got k={k} d={d} n={n}")
            self.alpha = d
            self.B = k * d - k * (k - 1) // 2
        else:
            if d != 2 * k - 2:
                raise ValueError(
                    f"msr is implemented at the d=2k-2 point only; "
                    f"got k={k} d={d} (want d={2 * k - 2})")
            if d > n - 1:
                raise ValueError(
                    f"msr d=2k-2={d} needs k+m-1 >= d; got n={n}")
            self.alpha = k - 1
            self.B = k * self.alpha
        w = self.to_int("w", profile, "8")
        if w != 8:
            raise ValueError(f"w={w} must be 8")
        self.k, self.m, self.d, self.mode = k, m, d, mode
        self.parse_device_routing(profile)
        profile["plugin"] = profile.get("plugin", "pm_regen")
        self._profile = profile
        self._build_matrices()
        self._plan_cache: collections.OrderedDict = collections.OrderedDict()
        self._plan_lock = threading.Lock()

    def _build_matrices(self) -> None:
        """Encoding vectors + the flattened symbol-space generator.

        ``_psi`` (n x d) are the encoding vectors; ``_enc`` (n*alpha x B)
        maps the B free message symbols to every node's symbol rows —
        the symmetric message-matrix structure folded into one plain
        linear map so decode is a rank-B solve."""
        k, d, n, alpha, B = self.k, self.d, self.k + self.m, self.alpha, self.B
        if self.mode == "mbr":
            xs = list(range(1, n + 1))
        else:
            # lambda_i = x_i^alpha must be distinct (x -> x^alpha is not
            # injective when gcd(alpha, 255) > 1, e.g. alpha=3)
            xs, seen = [], set()
            for cand in range(1, 256):
                lam = gft.gf_pow(cand, alpha)
                if lam in seen:
                    continue
                xs.append(cand)
                seen.add(lam)
                if len(xs) == n:
                    break
            if len(xs) < n:
                raise ValueError(
                    f"cannot pick {n} encoding vectors with distinct "
                    f"lambda for alpha={alpha}")
        self._x = xs
        psi = np.zeros((n, d), dtype=np.uint8)
        enc = np.zeros((n * alpha, B), dtype=np.uint8)
        if self.mode == "mbr":
            # message matrix M (d x d) = [[S, T], [T^T, 0]]: S symmetric
            # k x k, T arbitrary k x (d-k).  slot() maps entry (r, j) of
            # M to its free-symbol index (None inside the zero block).
            idx: dict[tuple[int, int], int] = {}
            s = 0
            for i in range(k):
                for j in range(i, k):
                    idx[(i, j)] = s
                    s += 1
            for i in range(k):
                for j in range(k, d):
                    idx[(i, j)] = s
                    s += 1
            assert s == B

            def slot(r: int, j: int) -> int | None:
                if r < k and j < k:
                    return idx[(min(r, j), max(r, j))]
                if r < k:
                    return idx[(r, j)]
                if j < k:
                    return idx[(j, r)]
                return None

            for i, x in enumerate(xs):
                for t in range(d):
                    psi[i][t] = gft.gf_pow(x, t)
            for i in range(n):
                for r in range(alpha):          # chunk_i row r = M[r] . psi_i
                    for t in range(d):
                        sl = slot(r, t)
                        if sl is not None:
                            enc[i * alpha + r][sl] ^= int(psi[i][t])
            self._enc = enc
        else:
            # message matrix M (2alpha x alpha) = [S1; S2], both
            # symmetric alpha x alpha; psi_i = (phi_i, lambda_i * phi_i)
            half = alpha * (alpha + 1) // 2
            pair: dict[tuple[int, int], int] = {}
            s = 0
            for i in range(alpha):
                for j in range(i, alpha):
                    pair[(i, j)] = s
                    s += 1
            assert 2 * half == B

            self._lam = [gft.gf_pow(x, alpha) for x in xs]
            for i, x in enumerate(xs):
                for t in range(alpha):
                    phi = gft.gf_pow(x, t)
                    psi[i][t] = phi
                    psi[i][alpha + t] = gft.gf_mul(self._lam[i], phi)
            for i in range(n):
                for r in range(alpha):   # chunk_i row r = phi S1[:,r] + lam phi S2[:,r]
                    for t in range(alpha):
                        sl = pair[(min(r, t), max(r, t))]
                        enc[i * alpha + r][sl] ^= int(psi[i][t])
                        enc[i * alpha + r][half + sl] ^= int(psi[i][alpha + t])
            # systematize: G = A . A_top^-1 so the first k chunks store
            # the raw data rows (A_top is invertible by the MDS property)
            try:
                top_inv = gfm.gf_invert(enc[:k * alpha])
            except np.linalg.LinAlgError as e:
                raise ValueError(
                    "msr systematization failed (A_top singular)") from e
            self._enc = gfm.gf_matmul(enc, top_inv)
        self._psi = psi

    # -- counts / sizes ----------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_coding_chunk_count(self) -> int:
        return self.m

    def get_sub_chunk_count(self) -> int:
        return self.alpha

    def get_alignment(self) -> int:
        if self.mode == "mbr":
            # k * chunk_size must divide into B message symbols
            quantum = self.B // math.gcd(self.B, self.k)
        else:
            quantum = self.alpha      # chunk reshapes to (alpha, N)
        return math.lcm(TPU_LANE_ALIGN, quantum)

    def get_stored_chunk_size(self, chunk_size: int) -> int:
        """On-disk bytes per chunk for a logical share of ``chunk_size``
        bytes.  MBR expands by alpha*k/B (> 1: the bandwidth-vs-storage
        trade, stated honestly); MSR stores exactly the share."""
        if self.mode == "msr":
            return chunk_size
        if (self.k * chunk_size) % self.B:
            raise ValueError(
                f"chunk_size={chunk_size} is not aligned: k*chunk_size "
                f"must be a multiple of B={self.B}")
        return self.alpha * (self.k * chunk_size // self.B)

    @property
    def requires_full_chunk_io(self) -> bool:
        """MBR chunks are non-systematic linear blends of the whole
        object — every read/degraded-RMW must fetch whole chunks."""
        return self.mode == "mbr"

    # -- minimum_to_decode -------------------------------------------------

    def minimum_to_decode(self, want_to_read: set, available: set
                          ) -> dict[int, list[tuple[int, int]]]:
        if self.mode == "msr":
            return super().minimum_to_decode(want_to_read, available)
        # MBR stores no raw shares: a data-chunk want is NOT satisfied by
        # the chunk of the same id, so never take the direct-read
        # shortcut — any k stored chunks decode everything.
        avail = set(available)
        if len(avail) < self.k:
            raise IOError(
                f"cannot decode: {len(avail)} chunks available, "
                f"need {self.k}")
        sub = [(0, self.alpha)]
        return {i: list(sub) for i in sorted(avail)[:self.k]}

    def minimum_to_decode_with_cost(self, want_to_read: set,
                                    available: Mapping[int, int]) -> set:
        if self.mode == "msr":
            return super().minimum_to_decode_with_cost(want_to_read,
                                                       available)
        if len(available) < self.k:
            raise IOError(
                f"cannot decode: {len(available)} chunks available, "
                f"need {self.k}")
        ranked = sorted(available, key=lambda c: (available[c], c))
        return set(ranked[:self.k])

    # -- encode ------------------------------------------------------------

    def encode_chunks(self, want_to_encode: set, encoded: dict) -> None:
        k, n, alpha = self.k, self.k + self.m, self.alpha
        rows = [np.asarray(encoded[i], dtype=np.uint8) for i in range(k)]
        Lc = len(rows[0])
        if self.mode == "mbr":
            W = np.concatenate(rows)
            if W.size % self.B:
                raise ValueError(
                    f"k*chunk_size={W.size} not a multiple of B={self.B}")
            msg = W.reshape(self.B, W.size // self.B)
            sym = self._matmul(self._enc, msg)            # (n*alpha, N)
            for i in range(n):
                encoded[i] = np.ascontiguousarray(
                    sym[i * alpha:(i + 1) * alpha].reshape(-1))
        else:
            if Lc % alpha:
                raise ValueError(
                    f"chunk_size={Lc} not a multiple of alpha={alpha}")
            D = np.concatenate(rows).reshape(k * alpha, Lc // alpha)
            P = self._matmul(self._enc[k * alpha:], D)    # (m*alpha, N)
            for j in range(self.m):
                encoded[k + j] = np.ascontiguousarray(
                    P[j * alpha:(j + 1) * alpha].reshape(-1))

    # -- decode ------------------------------------------------------------

    def _decode_plan(self, avail: tuple[int, ...]
                     ) -> tuple[list[int], np.ndarray]:
        """(selected row indices, inverse of the selected B x B system)
        for an availability signature, LRU-cached per signature."""
        with self._plan_lock:
            hit = self._plan_cache.get(avail)
            if hit is not None:
                self._plan_cache.move_to_end(avail)
                return hit
        chosen = _select_rows(self._enc, list(avail), self.alpha, self.B)
        inv = gfm.gf_invert(self._enc[chosen])
        with self._plan_lock:
            self._plan_cache[avail] = (chosen, inv)
            if len(self._plan_cache) > PLAN_CACHE_SIZE:
                self._plan_cache.popitem(last=False)
        return chosen, inv

    def _solve_message(self, chunks: Mapping[int, np.ndarray]) -> np.ndarray:
        """Recover the (B, N) message-symbol matrix from any rank-B set
        of available stored chunks."""
        alpha = self.alpha
        avail = tuple(sorted(chunks))
        chosen, inv = self._decode_plan(avail)
        sym = {c: np.asarray(chunks[c], dtype=np.uint8).reshape(alpha, -1)
               for c in avail}
        y = np.stack([sym[gi // alpha][gi % alpha] for gi in chosen])
        return self._matmul(inv, y)

    def decode_chunks(self, want_to_read: set, chunks: Mapping,
                      decoded: dict) -> None:
        alpha = self.alpha
        missing = set(want_to_read) - set(chunks)
        if not missing:
            return
        msg = self._solve_message(chunks)
        for i in missing:
            out = self._matmul(self._enc[i * alpha:(i + 1) * alpha], msg)
            decoded[i][:] = out.reshape(-1)

    def decode_concat(self, chunks: Mapping[int, np.ndarray]) -> bytes:
        if self.mode == "msr":
            return super().decode_concat(chunks)
        # MBR: the data bytes ARE the message symbols (W reshaped), not
        # any stored chunk — always a full rank-B solve.
        return self._solve_message(chunks).tobytes()

    # -- regenerating repair ----------------------------------------------

    def supports_regenerating_repair(self) -> bool:
        return True

    def minimum_to_repair(self, shard: int, d: int,
                          costs: Mapping[int, int]) -> list[int]:
        """The d cheapest helpers for regenerating ``shard``, in rank
        order (the order the combine matrix expects)."""
        avail = {c: costs[c] for c in costs if c != shard}
        if len(avail) < d:
            raise IOError(
                f"cannot regenerate chunk {shard}: {len(avail)} helpers "
                f"available, need {d}")
        ranked = sorted(avail, key=lambda c: (avail[c], c))
        return ranked[:d]

    def repair_projection(self, lost: int) -> np.ndarray:
        """(1, alpha) projection row a helper applies to its stored
        chunk's symbol rows: psi_lost (MBR) / phi_lost (MSR)."""
        if self.mode == "mbr":
            return self._psi[lost].reshape(1, self.alpha).copy()
        return self._psi[lost][:self.alpha].reshape(1, self.alpha).copy()

    def repair_combine(self, lost: int, helpers: list[int]) -> np.ndarray:
        """(alpha, d) matrix the newcomer applies to the d stacked
        helper beta-streams (in ``helpers`` order) to regenerate the
        lost chunk's symbol rows bitwise-exactly."""
        if len(set(helpers)) != self.d or lost in helpers:
            raise ValueError(f"need {self.d} distinct helpers != {lost}")
        psi_rep = np.stack([self._psi[h] for h in helpers])
        try:
            inv = gfm.gf_invert(psi_rep)
        except np.linalg.LinAlgError as e:     # cannot happen: distinct x
            raise IOError("repair matrix singular") from e
        if self.mode == "mbr":
            return inv
        alpha = self.alpha
        left = np.zeros((alpha, 2 * alpha), dtype=np.uint8)
        for j in range(alpha):
            left[j][j] = 1
            left[j][alpha + j] = self._lam[lost]
        return gfm.gf_matmul(left, inv)

    # -- GF matmul routing -------------------------------------------------

    def _matmul(self, mat: np.ndarray, data: np.ndarray) -> np.ndarray:
        mat = np.ascontiguousarray(mat, dtype=np.uint8)
        data = np.ascontiguousarray(data, dtype=np.uint8)
        if self.use_device(data.nbytes):
            try:
                from ..ops import codec as _codec
                return np.asarray(
                    _codec.gf_inner_product_device(mat, data))
            except Exception:
                if self.device == "jax":
                    raise
        return gfref.apply_matrix_fast(mat, data)


class ErasureCodePluginPMRegen(ErasureCodePlugin):
    def factory(self, directory: str, profile: ErasureCodeProfile):
        interface = ErasureCodePMRegen(directory)
        interface.init(profile)
        return interface


def __erasure_code_version__() -> str:
    return __version__


def __erasure_code_init__(name: str, directory: str) -> None:
    ErasureCodePluginRegistry.instance().add(name,
                                             ErasureCodePluginPMRegen())
