"""clay: Coupled-LAYer MSR regenerating code plugin.

Behavioural mirror of the reference clay plugin
(reference: src/erasure-code/clay/ErasureCodeClay.{h,cc}): an MSR
(minimum-storage regenerating) code built by coupling the planes of a
scalar MDS code, so that repairing a single lost chunk reads only a
``1/q`` fraction of each helper chunk instead of whole chunks.

Geometry (ErasureCodeClay.h:29-31, parse at ErasureCodeClay.cc:185-282):
  q = d - k + 1, nu pads k+m to a multiple of q, t = (k + m + nu) / q.
  The k+m+nu chunks sit on a q x t grid (node = y*q + x); each chunk has
  sub_chunk_no = q^t sub-chunks ("planes" z, indexed by base-q digit
  vectors).  A plane point (x, y, z) is a *dot* when z_vec[y] == x; other
  points pair with their *sewing partner* (z_vec[y], y, z_sw), z_sw being z
  with digit y replaced by x.

Two sub-codecs (ErasureCodeClay.h:35-40):
  mds   scalar RS(k+nu, m) applied per-plane to the uncoupled values
  pft   pairwise transform: an RS(2, 2) on (C_hi, C_lo) -> (U_hi, U_lo)
        whose partial solves convert between coupled chunk data C and
        uncoupled values U (any 2 of the 4 determine the rest)

Parameters: k, m (defaults 4, 2), d in [k, k+m-1] (default k+m-1, the
repair helper count), scalar_mds in {jerasure, isa, shec, jax_rs},
technique per sub-plugin.  Profile device=... is forwarded to sub-codecs.

Python buffers: every chunk is a numpy array viewed as
[sub_chunk_no, sc_size]; sub-chunk views alias the parent buffer so the
in-place sub-codec writes land directly in the output chunks.
"""
from __future__ import annotations

from typing import Mapping

import numpy as np

from .. import __version__
from .base import ErasureCode
from .interface import ErasureCodeProfile
from .registry import ErasureCodePlugin, ErasureCodePluginRegistry


class ErasureCodeClay(ErasureCode):
    DEFAULT_K = "4"
    DEFAULT_M = "2"

    def __init__(self, directory: str = ""):
        super().__init__()
        self.directory = directory
        self.k = 0
        self.m = 0
        self.d = 0
        self.w = 8
        self.q = 0
        self.t = 0
        self.nu = 0
        self.sub_chunk_no = 0
        self.mds: ErasureCode | None = None
        self.pft: ErasureCode | None = None

    # -- init / parse (ErasureCodeClay.cc:62-88,185-282) --------------------

    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse(profile)
        super().init(profile)
        registry = ErasureCodePluginRegistry.instance()
        self.mds = registry.factory(self.mds_profile["plugin"],
                                    self.directory, self.mds_profile)
        self.pft = registry.factory(self.pft_profile["plugin"],
                                    self.directory, self.pft_profile)
        profile["plugin"] = profile.get("plugin", "clay")
        self._profile = profile

    def parse(self, profile: ErasureCodeProfile) -> None:
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self.sanity_check_k_m(self.k, self.m)
        self.d = self.to_int("d", profile, str(self.k + self.m - 1))

        scalar_mds = profile.get("scalar_mds") or "jerasure"
        if scalar_mds not in ("jerasure", "isa", "shec", "jax_rs"):
            raise ValueError(
                f"scalar_mds {scalar_mds!r} is not supported, use one of "
                f"'jerasure', 'isa', 'shec', 'jax_rs'")
        technique = profile.get("technique") or ""
        if not technique:
            technique = "single" if scalar_mds == "shec" else "reed_sol_van"
        allowed = {
            "jerasure": ("reed_sol_van", "reed_sol_r6_op", "cauchy_orig",
                         "cauchy_good", "liber8tion"),
            "isa": ("reed_sol_van", "cauchy"),
            "shec": ("single", "multiple"),
            "jax_rs": ("reed_sol_van", "vandermonde", "cauchy"),
        }[scalar_mds]
        if technique not in allowed:
            raise ValueError(
                f"technique {technique!r} is not supported with "
                f"scalar_mds={scalar_mds}, use one of {allowed}")
        if not (self.k <= self.d <= self.k + self.m - 1):
            raise ValueError(
                f"value of d {self.d} must be within "
                f"[{self.k}, {self.k + self.m - 1}]")

        self.q = self.d - self.k + 1
        self.nu = (self.q - (self.k + self.m) % self.q) % self.q
        if self.k + self.m + self.nu > 254:
            raise ValueError(f"k+m+nu={self.k + self.m + self.nu} > 254")
        self.t = (self.k + self.m + self.nu) // self.q
        self.sub_chunk_no = self.q ** self.t

        device = profile.get("device", "")
        common = {"technique": technique, "w": "8"}
        if device:
            common["device"] = device
        if scalar_mds == "shec":
            common["c"] = "2"
        self.mds_profile = dict(common, plugin=scalar_mds,
                                k=str(self.k + self.nu), m=str(self.m))
        self.pft_profile = dict(common, plugin=scalar_mds, k="2", m="2")

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_no

    def get_chunk_size(self, object_size: int) -> int:
        """Chunks must split into sub_chunk_no aligned sub-chunks
        (ErasureCodeClay.cc:90-96)."""
        scalar_align = self.pft.get_chunk_size(1)
        alignment = self.sub_chunk_no * self.k * scalar_align
        padded = -(-object_size // alignment) * alignment
        return padded // self.k

    # -- plane geometry -----------------------------------------------------

    def _plane_vector(self, z: int) -> list[int]:
        """Base-q digits of z, most significant first (get_plane_vector,
        ErasureCodeClay.cc:888-894)."""
        v = [0] * self.t
        for i in range(self.t):
            v[self.t - 1 - i] = z % self.q
            z //= self.q
        return v

    def _z_sw(self, x: int, y: int, z: int, z_vec: list[int]) -> int:
        return z + (x - z_vec[y]) * self.q ** (self.t - 1 - y)

    # -- pairwise transform helpers -----------------------------------------

    def _pft_solve(self, known: dict[int, np.ndarray],
                   want: dict[int, np.ndarray]) -> None:
        """Solve the RS(2,2) pair relation: indices 0/1 are the coupled
        values (high-x node first), 2/3 the uncoupled ones.  ``known`` maps
        2 indices to value views, ``want`` maps the missing indices to
        output views (all 4 present between them); writes in place."""
        decoded = dict(known)
        decoded.update(want)
        for i in range(4):
            if i not in decoded:  # throwaway output (temp_buf in the C++)
                decoded[i] = np.zeros_like(next(iter(known.values())))
        self.pft.decode_chunks(set(want), known, decoded)

    def _pair_views(self, x: int, y: int, z_vec: list[int]):
        """Canonical pft index mapping for the pair at (x, y): returns
        (iC_xy, iC_sw, iU_xy, iU_sw) — the coupled/uncoupled pft indices of
        node_xy and its sewing partner (the i0..i3 permutation at
        ErasureCodeClay.cc:436-441)."""
        if z_vec[y] > x:
            return 1, 0, 3, 2
        return 0, 1, 2, 3

    # -- encode / decode (ErasureCodeClay.cc:127-183) -----------------------

    def encode_chunks(self, want_to_encode: set,
                      encoded: dict[int, np.ndarray]) -> None:
        k, m, nu = self.k, self.m, self.nu
        chunk_size = len(encoded[0])
        chunks: dict[int, np.ndarray] = {}
        parity_chunks: set[int] = set()
        for i in range(k + m):
            if i < k:
                chunks[i] = encoded[i]
            else:
                chunks[i + nu] = encoded[i]
                parity_chunks.add(i + nu)
        for i in range(k, k + nu):  # shortening: virtual zero chunks
            chunks[i] = np.zeros(chunk_size, dtype=np.uint8)
        self._decode_layered(parity_chunks, chunks)

    def decode_chunks(self, want_to_read: set, chunks: Mapping[int, np.ndarray],
                      decoded: dict[int, np.ndarray]) -> None:
        k, m, nu = self.k, self.m, self.nu
        erasures: set[int] = set()
        coded: dict[int, np.ndarray] = {}
        for i in range(k + m):
            if i not in chunks:
                erasures.add(i if i < k else i + nu)
            coded[i if i < k else i + nu] = decoded[i]
        chunk_size = len(coded[0])
        for i in range(k, k + nu):
            coded[i] = np.zeros(chunk_size, dtype=np.uint8)
        self._decode_layered(erasures, coded)

    def decode(self, want_to_read: set, chunks: Mapping[int, np.ndarray],
               chunk_size: int = 0) -> dict[int, np.ndarray]:
        """Route single-failure reads with fractional helper chunks through
        the repair path (ErasureCodeClay.cc:107-122)."""
        chunks = {i: np.asarray(v, dtype=np.uint8) for i, v in chunks.items()}
        if chunks and self.is_repair(set(want_to_read), set(chunks)) and \
                chunk_size > len(next(iter(chunks.values()))):
            return self._repair(set(want_to_read), chunks, chunk_size)
        return self._decode(want_to_read, chunks)

    # -- repair predicates (ErasureCodeClay.cc:284-329) ---------------------

    def is_repair(self, want_to_read: set, available: set) -> bool:
        if want_to_read <= available:
            return False
        if len(want_to_read) > 1:
            return False
        lost = next(iter(want_to_read))
        lost_node = lost if lost < self.k else lost + self.nu
        for x in range(self.q):
            node = (lost_node // self.q) * self.q + x
            node = node if node < self.k else node - self.nu
            if node != lost and node not in available:
                return False
        return len(available) >= self.d

    def get_repair_subchunks(self, lost_node: int) -> list[tuple[int, int]]:
        """(offset, count) runs of the sub-chunks a helper must send to
        repair lost_node (ErasureCodeClay.cc:363-379): the planes whose
        y_lost digit equals x_lost."""
        q, t = self.q, self.t
        y_lost, x_lost = lost_node // q, lost_node % q
        seq_sc_count = q ** (t - 1 - y_lost)
        num_seq = q ** y_lost
        index = x_lost * seq_sc_count
        runs = []
        for _ in range(num_seq):
            runs.append((index, seq_sc_count))
            index += q * seq_sc_count
        return runs

    def get_repair_sub_chunk_count(self, want_to_read: set) -> int:
        weight = [0] * self.t
        for node in want_to_read:
            weight[node // self.q] += 1
        remaining = 1
        for y in range(self.t):
            remaining *= self.q - weight[y]
        return self.sub_chunk_no - remaining

    def minimum_to_decode(self, want_to_read: set, available: set
                          ) -> dict[int, list[tuple[int, int]]]:
        if self.is_repair(set(want_to_read), set(available)):
            return self._minimum_to_repair(set(want_to_read), set(available))
        return super().minimum_to_decode(want_to_read, available)

    def _minimum_to_repair(self, want_to_read: set, available: set
                           ) -> dict[int, list[tuple[int, int]]]:
        """d helpers, sub-chunk runs only (ErasureCodeClay.cc:331-361)."""
        lost = next(iter(want_to_read))
        lost_node = lost if lost < self.k else lost + self.nu
        runs = self.get_repair_subchunks(lost_node)
        minimum: dict[int, list[tuple[int, int]]] = {}
        for j in range(self.q):  # same-column nodes first
            if j == lost_node % self.q:
                continue
            rep = (lost_node // self.q) * self.q + j
            if rep < self.k:
                minimum[rep] = list(runs)
            elif rep >= self.k + self.nu:
                minimum[rep - self.nu] = list(runs)
        for chunk in sorted(available):
            if len(minimum) >= self.d:
                break
            minimum.setdefault(chunk, list(runs))
        assert len(minimum) == self.d
        return minimum

    # -- layered decode (ErasureCodeClay.cc:646-739) ------------------------

    def _decode_layered(self, erased_chunks: set[int],
                        chunks: dict[int, np.ndarray]) -> None:
        """Recover every erased chunk in place.  ``chunks`` maps all q*t
        node ids to full-size buffers; erased ones hold garbage/zeros."""
        q, t, m = self.q, self.t, self.m
        k, nu = self.k, self.nu
        erased = set(erased_chunks)
        size = len(chunks[0])
        assert size % self.sub_chunk_no == 0
        sc_size = size // self.sub_chunk_no
        assert erased

        # pad erasures to m with virtual/parity nodes so the MDS decode has
        # a fixed shape (ErasureCodeClay.cc:656-663)
        for i in range(k + nu, q * t):
            if len(erased) >= m:
                break
            erased.add(i)
        assert len(erased) == m

        # work on copies: the erasure padding above recruits intact parity
        # nodes, whose buffers belong to the caller (and may be read-only
        # np.frombuffer views) — results are written back at the end
        C = {node: np.array(buf, dtype=np.uint8).reshape(
                self.sub_chunk_no, sc_size)
             for node, buf in chunks.items()}
        U = np.zeros((q * t, self.sub_chunk_no, sc_size), dtype=np.uint8)

        # plane order = number of erased nodes whose dot lies in the plane
        order = np.zeros(self.sub_chunk_no, dtype=np.int64)
        z_vecs = [self._plane_vector(z) for z in range(self.sub_chunk_no)]
        for z in range(self.sub_chunk_no):
            order[z] = sum(1 for i in erased if i % q == z_vecs[z][i // q])
        max_iscore = len({i // q for i in erased})

        for iscore in range(max_iscore + 1):
            planes = [z for z in range(self.sub_chunk_no) if order[z] == iscore]
            for z in planes:
                self._decode_erasures(erased, z, z_vecs[z], C, U, sc_size)
            for z in planes:
                z_vec = z_vecs[z]
                for node_xy in sorted(erased):
                    x, y = node_xy % q, node_xy // q
                    node_sw = y * q + z_vec[y]
                    if z_vec[y] != x:
                        z_sw = self._z_sw(x, y, z, z_vec)
                        iC_xy, iC_sw, iU_xy, iU_sw = \
                            self._pair_views(x, y, z_vec)
                        if node_sw not in erased:
                            # type-1: partner data is intact
                            # (recover_type1_erasure, ErasureCodeClay.cc:776-812)
                            self._pft_solve(
                                {iC_sw: C[node_sw][z_sw], iU_xy: U[node_xy][z]},
                                {iC_xy: C[node_xy][z]})
                        elif z_vec[y] < x:
                            # both of the pair erased: coupled from the two
                            # uncoupled (get_coupled_from_uncoupled, :814-840)
                            self._pft_solve(
                                {2: U[node_xy][z], 3: U[node_sw][z_sw]},
                                {0: C[node_xy][z], 1: C[node_sw][z_sw]})
                    else:  # hole-dot: C == U
                        C[node_xy][z] = U[node_xy][z]

        for node in erased_chunks:
            chunks[node][:] = C[node].reshape(-1)

    def _decode_erasures(self, erased: set[int], z: int, z_vec: list[int],
                         C: dict[int, np.ndarray], U: np.ndarray,
                         sc_size: int) -> None:
        """Fill plane z of U for intact nodes, then MDS-solve the erased
        ones (decode_erasures, ErasureCodeClay.cc:741-768)."""
        q, t = self.q, self.t
        for x in range(q):
            for y in range(t):
                node_xy = q * y + x
                node_sw = q * y + z_vec[y]
                if node_xy in erased:
                    continue
                if z_vec[y] < x:
                    self._uncouple_pair(x, y, z, z_vec, C, U, sc_size)
                elif z_vec[y] == x:
                    U[node_xy][z] = C[node_xy][z]
                elif node_sw in erased:
                    self._uncouple_pair(x, y, z, z_vec, C, U, sc_size)
        self._decode_uncoupled(erased, z, U)

    def _uncouple_pair(self, x: int, y: int, z: int, z_vec: list[int],
                       C: dict[int, np.ndarray], U: np.ndarray,
                       sc_size: int) -> None:
        """U values of a pair from its two coupled values
        (get_uncoupled_from_coupled, ErasureCodeClay.cc:842-868)."""
        node_xy = y * self.q + x
        node_sw = y * self.q + z_vec[y]
        z_sw = self._z_sw(x, y, z, z_vec)
        iC_xy, iC_sw, iU_xy, iU_sw = self._pair_views(x, y, z_vec)
        self._pft_solve(
            {iC_xy: C[node_xy][z], iC_sw: C[node_sw][z_sw]},
            {iU_xy: U[node_xy][z], iU_sw: U[node_sw][z_sw]})

    def _decode_uncoupled(self, erased: set[int], z: int,
                          U: np.ndarray) -> None:
        """Per-plane scalar MDS decode of the uncoupled values
        (decode_uncoupled, ErasureCodeClay.cc:770-788)."""
        known = {i: U[i][z] for i in range(self.q * self.t) if i not in erased}
        decoded = {i: U[i][z] for i in range(self.q * self.t)}
        self.mds.decode_chunks(set(erased), known, decoded)

    # -- single-chunk repair (ErasureCodeClay.cc:396-643) -------------------

    def _repair(self, want_to_read: set, chunks: Mapping[int, np.ndarray],
                chunk_size: int) -> dict[int, np.ndarray]:
        q, t, k, m, nu, d = self.q, self.t, self.k, self.m, self.nu, self.d
        assert len(want_to_read) == 1 and len(chunks) == d
        repair_sub_count = self.get_repair_sub_chunk_count(
            {next(iter(want_to_read)) if next(iter(want_to_read)) < k
             else next(iter(want_to_read)) + nu})
        repair_blocksize = len(next(iter(chunks.values())))
        assert repair_blocksize % repair_sub_count == 0
        sc_size = repair_blocksize // repair_sub_count
        assert self.sub_chunk_no * sc_size == chunk_size

        lost = next(iter(want_to_read))
        lost_node = lost if lost < k else lost + nu

        helper: dict[int, np.ndarray] = {}
        aloof: set[int] = set()
        for i in range(k + m):
            node = i if i < k else i + nu
            if i in chunks:
                helper[node] = np.asarray(chunks[i], dtype=np.uint8).reshape(
                    repair_sub_count, sc_size)
            elif i != lost:
                aloof.add(node)
        for i in range(k, k + nu):  # shortened: zero helpers
            helper[i] = np.zeros((repair_sub_count, sc_size), dtype=np.uint8)
        out = np.zeros(chunk_size, dtype=np.uint8)
        recovered = out.reshape(self.sub_chunk_no, sc_size)
        assert len(helper) + len(aloof) + 1 == q * t

        self._repair_one_lost_chunk(lost_node, recovered, aloof, helper,
                                    sc_size)
        return {lost: out}

    def _repair_one_lost_chunk(self, lost: int, recovered: np.ndarray,
                               aloof: set[int], helper: dict[int, np.ndarray],
                               sc_size: int) -> None:
        """(repair_one_lost_chunk, ErasureCodeClay.cc:469-643).  ``helper``
        holds only the repair planes, indexed densely; ``recovered`` is the
        full [sub_chunk_no, sc_size] output."""
        q, t = self.q, self.t
        runs = self.get_repair_subchunks(lost)
        repair_planes = [j for index, count in runs
                         for j in range(index, index + count)]
        plane_ind = {z: i for i, z in enumerate(repair_planes)}

        # order repair planes by intersection score with {lost} | aloof
        ordered: dict[int, list[int]] = {}
        for z in repair_planes:
            z_vec = self._plane_vector(z)
            score = sum(1 for node in ({lost} | aloof)
                        if node % q == z_vec[node // q])
            assert score > 0
            ordered.setdefault(score, []).append(z)

        U = np.zeros((q * t, self.sub_chunk_no, sc_size), dtype=np.uint8)
        erasures = {lost - lost % q + i for i in range(q)} | aloof

        for score in sorted(ordered):
            for z in ordered[score]:
                z_vec = self._plane_vector(z)
                for y in range(t):
                    for x in range(q):
                        node_xy = y * q + x
                        if node_xy in erasures:
                            continue
                        node_sw = y * q + z_vec[y]
                        z_sw = self._z_sw(x, y, z, z_vec)
                        iC_xy, iC_sw, iU_xy, iU_sw = \
                            self._pair_views(x, y, z_vec)
                        if node_sw in aloof:
                            # partner coupled value unknown; use its already
                            # computed uncoupled value (:447-460)
                            self._pft_solve(
                                {iC_xy: helper[node_xy][plane_ind[z]],
                                 iU_sw: U[node_sw][z_sw]},
                                {iU_xy: U[node_xy][z]})
                        elif z_vec[y] != x:
                            self._pft_solve(
                                {iC_xy: helper[node_xy][plane_ind[z]],
                                 iC_sw: helper[node_sw][plane_ind[z_sw]]},
                                {iU_xy: U[node_xy][z]})
                        else:  # dot point
                            U[node_xy][z] = helper[node_xy][plane_ind[z]]
                assert len(erasures) <= self.m
                self._decode_uncoupled(erasures, z, U)
                for i in sorted(erasures):
                    x, y = i % q, i // q
                    node_sw = y * q + z_vec[y]
                    z_sw = self._z_sw(x, y, z, z_vec)
                    if i in aloof:
                        continue
                    iC_xy, iC_sw, iU_xy, iU_sw = self._pair_views(x, y, z_vec)
                    if x == z_vec[y]:  # hole-dot pair (:609-619)
                        recovered[z] = U[i][z]
                    else:
                        # recover the lost chunk's z_sw sub-chunk from this
                        # helper's coupled value + its uncoupled value (:621-637)
                        assert y == lost // q and node_sw == lost
                        self._pft_solve(
                            {iC_xy: helper[i][plane_ind[z]], iU_xy: U[i][z]},
                            {iC_sw: recovered[z_sw]})


class ErasureCodePluginClay(ErasureCodePlugin):
    def factory(self, directory: str,
                profile: ErasureCodeProfile) -> ErasureCodeClay:
        instance = ErasureCodeClay(directory)
        instance.init(dict(profile))
        return instance


def __erasure_code_version__() -> str:
    return __version__


def __erasure_code_init__(name: str, directory: str) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginClay())
