"""cpp_rs: the native C++ Reed-Solomon codec as a framework plugin.

Wraps the native runtime (native/src/plugin_cpp_rs.cc, loaded through the
C registry's dlopen contract, see ceph_tpu/native) in the Python plugin
interface — the same layering as the reference, where the C++ isa plugin
wraps the isa-l assembly kernels (reference:
src/erasure-code/isa/ErasureCodeIsa.cc).  This is the synchronous CPU path:
single-stripe latency without a device dispatch; the jax_rs plugin is the
batched TPU path.

Profile: k, m, technique in {reed_sol_van (default), cauchy,
vandermonde_isa}.
"""
from __future__ import annotations

from typing import Mapping

import numpy as np

from .. import __version__
from .base import ErasureCode
from .interface import ErasureCodeProfile
from .registry import ErasureCodePlugin, ErasureCodePluginRegistry


class ErasureCodeCppRS(ErasureCode):
    def __init__(self):
        super().__init__()
        self.k = 7
        self.m = 3
        self._codec = None

    def init(self, profile: ErasureCodeProfile) -> None:
        super().init(profile)
        from ..native import NativeRegistry
        self.parse_mapping(profile)
        self.k = self.to_int("k", profile, "7")
        self.m = self.to_int("m", profile, "3")
        if self.chunk_mapping and len(self.chunk_mapping) != self.k + self.m:
            raise ValueError(
                f"mapping {profile.get('mapping')} maps "
                f"{len(self.chunk_mapping)} chunks instead of {self.k + self.m}")
        technique = self.to_string("technique", profile, "reed_sol_van")
        self.sanity_check_k_m(self.k, self.m)
        self._codec = NativeRegistry.instance().factory(
            "cpp_rs", {"k": self.k, "m": self.m, "technique": technique})
        profile["plugin"] = profile.get("plugin", "cpp_rs")
        self._profile = profile

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def encode_chunks(self, want_to_encode: set,
                      encoded: dict[int, np.ndarray]) -> None:
        data = np.stack([encoded[self.chunk_index(i)]
                         for i in range(self.k)])
        parity = self._codec.encode(data)
        for i in range(self.m):
            encoded[self.chunk_index(self.k + i)][:] = parity[i]

    def decode_chunks(self, want_to_read: set,
                      chunks: Mapping[int, np.ndarray],
                      decoded: dict[int, np.ndarray]) -> None:
        n = self.k + self.m
        erasures = [i for i in range(n) if i not in chunks]
        if not erasures:
            return
        # physical wire positions -> logical matrix rows (see jax_rs)
        avail, erasures_l = self.remap_for_decode(chunks, erasures)
        chunk_size = next(iter(chunks.values())).nbytes
        out = self._codec.decode(avail, erasures_l, chunk_size)
        for e, buf in out.items():
            decoded[self.chunk_index(e)][:] = buf


class ErasureCodePluginCppRS(ErasureCodePlugin):
    def factory(self, directory: str,
                profile: ErasureCodeProfile) -> ErasureCodeCppRS:
        instance = ErasureCodeCppRS()
        instance.init(dict(profile))
        return instance


def __erasure_code_version__() -> str:
    return __version__


def __erasure_code_init__(name: str, directory: str) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginCppRS())
