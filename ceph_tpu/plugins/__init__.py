from .interface import ErasureCodeInterface, ErasureCodeProfile
from .base import ErasureCode, SIMD_ALIGN, TPU_LANE_ALIGN
from .registry import (ErasureCodePlugin, ErasureCodePluginRegistry,
                       default_registry)

__all__ = ["ErasureCodeInterface", "ErasureCodeProfile", "ErasureCode",
           "SIMD_ALIGN", "TPU_LANE_ALIGN", "ErasureCodePlugin",
           "ErasureCodePluginRegistry", "default_registry"]
