"""lrc: Locally Repairable (layered) erasure code plugin — a meta-code.

Behavioural mirror of the reference lrc plugin
(reference: src/erasure-code/lrc/ErasureCodeLrc.{h,cc}): a stack of layers,
each a full erasure code over a subset of the chunk positions, so that a
small local layer can repair common single failures while the global layer
guards against correlated loss.

Profile (ErasureCodeLrc.h:47-76, parse at ErasureCodeLrc.cc:293-498):
  layers        JSON array of [chunks_map, config] pairs; chunks_map is a
                string over positions with 'D' (data in this layer),
                'c' (coding in this layer), '_' (not in this layer); config
                is a JSON object (or JSON-object string) completing the
                sub-plugin profile (defaults: plugin=jerasure,
                technique=reed_sol_van, k=#D, m=#c)
  mapping       global DDD_D_-style string defining which positions hold
                object data ('D') vs coding ('_'); its length is the chunk
                count
  k, m, l       shorthand (parse_kml, ErasureCodeLrc.cc:293-415): generates
                mapping + a global layer + (k+m)/l local layers; requires
                l | (k+m), ((k+m)/l) | k and ((k+m)/l) | m
  crush-steps / crush-locality / crush-failure-domain
                multi-step CRUSH rule description (rule_steps)

Decode walks layers from the last (local) to the first (global), repairing
whatever each layer can, re-using chunks recovered by earlier layers
(ErasureCodeLrc.cc:777-860).
"""
from __future__ import annotations

import json
from typing import Mapping

import numpy as np

from .. import __version__
from .base import ErasureCode
from .interface import ErasureCodeProfile
from .registry import ErasureCodePlugin, ErasureCodePluginRegistry

DEFAULT_KML = "-1"


class Layer:
    """One code layer over a subset of positions (ErasureCodeLrc.h:47-60)."""

    def __init__(self, chunks_map: str):
        self.chunks_map = chunks_map
        self.erasure_code: ErasureCode | None = None
        self.data: list[int] = []
        self.coding: list[int] = []
        self.chunks: list[int] = []
        self.chunks_as_set: set[int] = set()
        self.profile: ErasureCodeProfile = {}


class ErasureCodeLrc(ErasureCode):
    def __init__(self, directory: str = ""):
        super().__init__()
        self.directory = directory
        self.layers: list[Layer] = []
        self._chunk_count = 0
        self._data_chunk_count = 0
        # default rule: one chooseleaf step over hosts (ErasureCodeLrc.h:76-81)
        self.rule_steps: list[tuple[str, str, int]] = [("chooseleaf", "host", 0)]

    def get_chunk_count(self) -> int:
        return self._chunk_count

    def get_data_chunk_count(self) -> int:
        return self._data_chunk_count

    def get_chunk_size(self, object_size: int) -> int:
        return self.layers[0].erasure_code.get_chunk_size(object_size)

    # -- kml shorthand (parse_kml, ErasureCodeLrc.cc:293-415) ---------------

    def parse_kml(self, profile: ErasureCodeProfile) -> None:
        k = int(self.to_string("k", profile, DEFAULT_KML))
        m = int(self.to_string("m", profile, DEFAULT_KML))
        l = int(self.to_string("l", profile, DEFAULT_KML))
        if k == -1 and m == -1 and l == -1:
            return
        if k == -1 or m == -1 or l == -1:
            raise ValueError("all of k, m, l must be set or none of them")
        for generated in ("mapping", "layers", "crush-steps"):
            if generated in profile:
                raise ValueError(
                    f"the {generated} parameter cannot be set "
                    f"when k, m, l are set")
        if l == 0 or (k + m) % l:
            raise ValueError(f"k + m must be a multiple of l (k={k} m={m} l={l})")
        groups = (k + m) // l
        if k % groups:
            raise ValueError(f"k must be a multiple of (k + m) / l = {groups}")
        if m % groups:
            raise ValueError(f"m must be a multiple of (k + m) / l = {groups}")

        profile["mapping"] = "".join(
            "D" * (k // groups) + "_" * (m // groups) + "_"
            for _ in range(groups))

        layers = [["".join("D" * (k // groups) + "c" * (m // groups) + "_"
                           for _ in range(groups)), ""]]
        for i in range(groups):
            layers.append(["".join(("D" * l + "c") if i == j else "_" * (l + 1)
                                   for j in range(groups)), ""])
        profile["layers"] = json.dumps(layers)

        locality = profile.get("crush-locality", "")
        failure_domain = profile.get("crush-failure-domain", "host") or "host"
        if locality:
            self.rule_steps = [("choose", locality, groups),
                               ("chooseleaf", failure_domain, l + 1)]
        elif failure_domain:
            self.rule_steps = [("chooseleaf", failure_domain, 0)]

    # -- rule description (parse_rule, ErasureCodeLrc.cc:400-490) -----------

    def parse_rule(self, profile: ErasureCodeProfile) -> None:
        self.rule_root = self.to_string("crush-root", profile, "default")
        self.rule_device_class = self.to_string("crush-device-class", profile, "")
        if "crush-steps" in profile:
            try:
                description = json.loads(profile["crush-steps"])
            except json.JSONDecodeError as e:
                raise ValueError(f"failed to parse crush-steps: {e}") from e
            if not isinstance(description, list):
                raise ValueError("crush-steps must be a JSON array")
            self.rule_steps = []
            for step in description:
                if not isinstance(step, list) or len(step) != 3:
                    raise ValueError(f"crush-steps element {step!r} must be "
                                     f"an [op, type, n] array")
                op, type_, n = step
                if not isinstance(op, str) or not isinstance(type_, str):
                    raise ValueError(f"crush-steps op/type in {step!r} must "
                                     f"be strings")
                if not isinstance(n, int):
                    raise ValueError(f"crush-steps n in {step!r} must be int")
                self.rule_steps.append((op, type_, n))

    def create_rule(self, name: str, crush) -> int:
        """Multi-step rule from rule_steps (ErasureCodeLrc.cc:60-112)."""
        from ..crush.map import (CRUSH_RULE_CHOOSELEAF_INDEP,
                                 CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
                                 CRUSH_RULE_TAKE)
        if name in crush.rule_names:
            raise ValueError(f"rule {name!r} already exists")
        # crush-device-class routes the take through the per-class shadow
        # tree (ErasureCodeLrc.cc create_rule -> CrushWrapper class take)
        root = crush.take_with_class(self.rule_root,
                                     self.rule_device_class)
        steps = [(CRUSH_RULE_TAKE, root, 0)]
        for op, type_, n in self.rule_steps:
            if op == "choose":
                opcode = CRUSH_RULE_CHOOSE_INDEP
            elif op == "chooseleaf":
                opcode = CRUSH_RULE_CHOOSELEAF_INDEP
            else:
                raise ValueError(f"unknown crush rule op {op!r}")
            steps.append((opcode, n, crush.type_id(type_)))
        steps.append((CRUSH_RULE_EMIT, 0, 0))
        ruleno = crush.add_rule(steps)
        crush.rule_names[name] = ruleno
        return ruleno

    # -- layers (layers_parse/layers_init, ErasureCodeLrc.cc:143-251) -------

    def layers_parse(self, description) -> None:
        for position, entry in enumerate(description):
            if not isinstance(entry, list) or not entry:
                raise ValueError(
                    f"layers element at position {position} must be a "
                    f"non-empty JSON array, got {entry!r}")
            chunks_map = entry[0]
            if not isinstance(chunks_map, str):
                raise ValueError(
                    f"first element of layer {position} must be a string")
            layer = Layer(chunks_map)
            if len(entry) > 1:
                config = entry[1]
                if isinstance(config, str):
                    layer.profile = json.loads(config) if config.strip() else {}
                elif isinstance(config, dict):
                    layer.profile = {key: str(v) for key, v in config.items()}
                else:
                    raise ValueError(
                        f"second element of layer {position} must be a "
                        f"string or object")
            self.layers.append(layer)

    def layers_init(self) -> None:
        registry = ErasureCodePluginRegistry.instance()
        for layer in self.layers:
            for position, ch in enumerate(layer.chunks_map):
                if ch == "D":
                    layer.data.append(position)
                if ch == "c":
                    layer.coding.append(position)
                if ch in ("c", "D"):
                    layer.chunks_as_set.add(position)
            layer.chunks = layer.data + layer.coding
            layer.profile.setdefault("k", str(len(layer.data)))
            layer.profile.setdefault("m", str(len(layer.coding)))
            layer.profile.setdefault("plugin", "jerasure")
            layer.profile.setdefault("technique", "reed_sol_van")
            layer.erasure_code = registry.factory(
                layer.profile["plugin"], self.directory, layer.profile)

    def layers_sanity_checks(self) -> None:
        if len(self.layers) < 1:
            raise ValueError("layers parameter must list at least one layer")
        for layer in self.layers:
            if len(layer.chunks_map) != self._chunk_count:
                raise ValueError(
                    f"layer map {layer.chunks_map!r} is "
                    f"{len(layer.chunks_map)} characters long, expected "
                    f"{self._chunk_count} (the mapping length)")

    # -- init (ErasureCodeLrc.cc:493-547) -----------------------------------

    def init(self, profile: ErasureCodeProfile) -> None:
        self.parse_kml(profile)
        super().init(profile)          # crush-root/failure-domain defaults
        self.parse_rule(profile)
        if "layers" not in profile:
            raise ValueError(f"could not find 'layers' in {profile}")
        description = json.loads(profile["layers"])
        if not isinstance(description, list):
            raise ValueError("layers must be a JSON array")
        self.layers_parse(description)
        self.layers_init()
        if "mapping" not in profile:
            raise ValueError("the 'mapping' profile is missing")
        mapping = profile["mapping"]
        self._data_chunk_count = mapping.count("D")
        self._chunk_count = len(mapping)
        self.parse_mapping(profile)
        self.layers_sanity_checks()
        # kml-generated parameters are not exposed (ErasureCodeLrc.cc:536-545)
        if profile.get("l") and profile["l"] != DEFAULT_KML:
            profile.pop("mapping", None)
            profile.pop("layers", None)
        profile["plugin"] = profile.get("plugin", "lrc")
        self._profile = profile

    # -- minimum_to_decode (ErasureCodeLrc.cc:566-733) ----------------------

    def _minimum_to_decode(self, want_to_read: set, available: set) -> set:
        want_to_read = set(want_to_read)
        available = set(available)
        n = self.get_chunk_count()
        erasures_total = {i for i in range(n) if i not in available}
        erasures_not_recovered = set(erasures_total)
        erasures_want = erasures_total & want_to_read

        # Case 1: nothing wanted is missing
        if not erasures_want:
            return set(want_to_read)

        # Case 2: repair wanted erasures with as few chunks as possible,
        # preferring later (local) layers
        minimum: set[int] = set()
        for layer in reversed(self.layers):
            layer_want = want_to_read & layer.chunks_as_set
            if not layer_want:
                continue
            layer_erasures = layer_want & erasures_want
            if not layer_erasures:
                minimum |= layer_want
                continue
            erasures = layer.chunks_as_set & erasures_not_recovered
            if len(erasures) > layer.erasure_code.get_coding_chunk_count():
                continue    # too many for this layer; hope an upper one helps
            minimum |= layer.chunks_as_set - erasures_not_recovered
            erasures_not_recovered -= erasures
            erasures_want -= erasures
        if not erasures_want:
            minimum |= want_to_read
            minimum -= erasures_total
            return minimum

        # Case 3: cascade — repair anything any layer can, in the hope it
        # unlocks the upper layers; then read everything available
        erasures_total = {i for i in range(n) if i not in available}
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures_total
            if not layer_erasures:
                continue
            if len(layer_erasures) <= layer.erasure_code.get_coding_chunk_count():
                erasures_total -= layer_erasures
        if not erasures_total:
            return set(available)

        raise IOError(
            f"not enough chunks in {sorted(available)} to read "
            f"{sorted(want_to_read)}")

    # -- encode/decode (ErasureCodeLrc.cc:737-860) --------------------------

    def encode_chunks(self, want_to_encode: set,
                      encoded: dict[int, np.ndarray]) -> None:
        # find the last layer covering everything wanted; apply it and all
        # the layers after it, each over its own chunk subset
        top = len(self.layers)
        for layer in reversed(self.layers):
            top -= 1
            if set(want_to_encode) <= layer.chunks_as_set:
                break
        for layer in self.layers[top:]:
            layer_encoded = {j: encoded[c] for j, c in enumerate(layer.chunks)}
            layer_want = {j for j, c in enumerate(layer.chunks)
                          if c in want_to_encode}
            layer.erasure_code.encode_chunks(layer_want, layer_encoded)
            for j, c in enumerate(layer.chunks):
                encoded[c] = layer_encoded[j]

    def decode_chunks(self, want_to_read: set, chunks: Mapping[int, np.ndarray],
                      decoded: dict[int, np.ndarray]) -> None:
        n = self.get_chunk_count()
        available = {i for i in range(n) if i in chunks}
        erasures = {i for i in range(n) if i not in chunks}
        want_to_read_erasures = erasures & set(want_to_read)

        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures
            if len(layer_erasures) > layer.erasure_code.get_coding_chunk_count():
                continue    # too many erasures for this layer
            if not layer_erasures:
                continue    # nothing to do here
            layer_chunks = {}
            layer_decoded = {}
            layer_want = set()
            for j, c in enumerate(layer.chunks):
                # read repaired values from ``decoded`` so chunks recovered
                # by previous (more local) layers are reused
                if c not in erasures:
                    layer_chunks[j] = decoded[c]
                if c in want_to_read:
                    layer_want.add(j)
                layer_decoded[j] = decoded[c]
            layer.erasure_code.decode_chunks(layer_want, layer_chunks,
                                             layer_decoded)
            for j, c in enumerate(layer.chunks):
                decoded[c] = layer_decoded[j]
                erasures.discard(c)
            want_to_read_erasures = erasures & set(want_to_read)
            if not want_to_read_erasures:
                break

        if want_to_read_erasures:
            raise IOError(
                f"want to read {sorted(want_to_read)} with available "
                f"{sorted(available)}: unable to read "
                f"{sorted(want_to_read_erasures)}")


class ErasureCodePluginLrc(ErasureCodePlugin):
    def factory(self, directory: str,
                profile: ErasureCodeProfile) -> ErasureCodeLrc:
        instance = ErasureCodeLrc(directory)
        instance.init(dict(profile))
        return instance


def __erasure_code_version__() -> str:
    return __version__


def __erasure_code_init__(name: str, directory: str) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginLrc())
