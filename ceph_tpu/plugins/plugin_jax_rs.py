"""jax_rs: the flagship TPU Reed-Solomon plugin.

The TPU-native sibling of the reference's jerasure/isa plugins
(reference: src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc},
src/erasure-code/isa/ErasureCodeIsa.{h,cc}): systematic RS over GF(2^8)
whose encode_chunks/decode_chunks run as jit'd XLA kernels (MXU bitslice or
VPU lookup) via ceph_tpu.ops.RSCodec.

Profile parameters:
  k, m        chunk counts (defaults 7/3, jerasure's defaults,
              ErasureCodeJerasure.h:81)
  technique   reed_sol_van (systematic ext-Vandermonde; default) |
              vandermonde (ISA gf_gen_rs_matrix) | cauchy (gf_gen_cauchy1)
  w           Galois field width; only 8 is supported (the reference accepts
              {8,16,32}, ErasureCodeJerasure.cc:191-197 — GF(2^8) is the only
              field ISA-L supports and the one every corpus profile uses)
  device      jax (TPU) | numpy (exact CPU fallback) | auto (numpy below
              jax-threshold bytes per call, jax above — the latency-vs-
              throughput split from SURVEY.md §7 "dispatch economics")
  jax-threshold   byte cutoff for device=auto; when absent, the config
              option ``ec_device_threshold_bytes`` is read live per call
  variant     bitslice | lookup | auto (kernel choice)
  mapping     DDD_D_-style chunk remapping (ErasureCode.cc:274-293)
"""
from __future__ import annotations

from typing import Mapping

import numpy as np

from .. import __version__
from ..ops.codec import RSCodec, TECHNIQUES
from .base import DeviceRouting, ErasureCode
from .interface import ErasureCodeProfile
from .registry import ErasureCodePlugin, ErasureCodePluginRegistry


class ErasureCodeJaxRS(DeviceRouting, ErasureCode):
    DEFAULT_K = "7"
    DEFAULT_M = "3"

    def __init__(self, technique: str = "reed_sol_van"):
        super().__init__()
        self.technique = technique
        self.k = 0
        self.m = 0
        self.w = 8
        self.codec: RSCodec | None = None
        self.device = "auto"
        self.jax_threshold = 65536
        self.variant = "auto"

    # -- init --------------------------------------------------------------

    def init(self, profile: ErasureCodeProfile) -> None:
        super().init(profile)
        self.parse_mapping(profile)
        self.k = self.to_int("k", profile, self.DEFAULT_K)
        self.m = self.to_int("m", profile, self.DEFAULT_M)
        self.w = self.to_int("w", profile, "8")
        if self.w != 8:
            raise ValueError(f"w={self.w} must be 8 (GF(2^8))")
        if self.chunk_mapping and len(self.chunk_mapping) != self.k + self.m:
            raise ValueError(
                f"mapping {profile.get('mapping')} maps "
                f"{len(self.chunk_mapping)} chunks instead of {self.k + self.m}")
        self.sanity_check_k_m(self.k, self.m)
        technique = self.to_string("technique", profile, self.technique)
        if technique not in TECHNIQUES:
            raise ValueError(
                f"technique={technique} must be one of {sorted(TECHNIQUES)}")
        self.technique = technique
        self.parse_device_routing(profile)
        self.variant = self.to_string("variant", profile, "auto")
        # one codec per backend; 'auto' keeps both and routes per call size
        dev = "numpy" if self.device == "numpy" else "jax"
        self.codec = RSCodec(self.k, self.m, technique=self.technique,
                             device=dev, variant=self.variant)
        self._cpu_codec = self.codec if dev == "numpy" else \
            RSCodec(self.k, self.m, technique=self.technique, device="numpy")
        profile["plugin"] = profile.get("plugin", "jax_rs")
        self._profile = profile

    def _route(self, nbytes: int) -> RSCodec:
        if self.device != "auto":
            return self.codec
        return self.codec if self.use_device(nbytes) else self._cpu_codec

    def device_codec(self, nbytes: int) -> RSCodec | None:
        """The device-resident codec the pipeline path may dispatch
        through for a call of this size, or None when routing says host
        (numpy device, or an auto call below the threshold).  The
        capability hook ``ecutil``'s pipelined variants probe for."""
        codec = self._route(int(nbytes))
        return codec if codec.device == "jax" else None

    # -- counts ------------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    # -- encode/decode -----------------------------------------------------

    def encode_chunks(self, want_to_encode: set,
                      encoded: dict[int, np.ndarray]) -> None:
        k, m = self.k, self.m
        data = np.stack([encoded[self.chunk_index(i)] for i in range(k)])
        parity = self._route(data.nbytes).encode(data)
        for i in range(m):
            encoded[self.chunk_index(k + i)][:] = parity[i]

    def decode_chunks(self, want_to_read: set, chunks: Mapping[int, np.ndarray],
                      decoded: dict[int, np.ndarray]) -> None:
        erasures = [i for i in range(self.k + self.m) if i not in chunks]
        if not erasures:
            return
        # chunk ids on the wire are PHYSICAL positions; the codec's matrix
        # rows are LOGICAL — translate through the profile mapping both
        # ways (encode remaps via chunk_index; decode must invert it)
        avail, erasures_l = self.remap_for_decode(
            {i: decoded[i] for i in chunks}, erasures)
        nbytes = sum(v.nbytes for v in avail.values())
        rec = self._route(nbytes).decode(avail, erasures_l)
        for e, buf in rec.items():
            decoded[self.chunk_index(e)][:] = buf

    def partial_sum_coefficients(self, erasures: set, sources: list[int]):
        """RS is linear over GF(2^8): the decode matrix row for each
        erased chunk IS the per-source coefficient vector, so a hop
        chain can accumulate ``coeff * local_chunk`` partial sums and
        reconstruct without centralizing k shards.  Chunk ids in and
        out are PHYSICAL; the codec works in logical rows (the same
        remap decode_chunks applies).  Returns ``(coeffs, rows)`` —
        ``coeffs[source] = (c_row0, c_row1, ...)`` and ``rows`` the
        erased physical chunk each coefficient row reconstructs."""
        # remap_for_decode carries the VALUE through: {logical: physical}
        avail_l, erasures_l = self.remap_for_decode(
            {int(c): int(c) for c in sources},
            sorted(int(e) for e in erasures))
        if len(avail_l) < self.k or not erasures_l:
            return None
        erasures_l = sorted(erasures_l)
        D, src = self.codec.decode_matrix(erasures_l,
                                          available=list(avail_l))
        coeffs = {int(avail_l[s]): tuple(int(D[r, i])
                                         for r in range(D.shape[0]))
                  for i, s in enumerate(src)}
        rows = [self.chunk_index(e) for e in erasures_l]
        return coeffs, rows


class ErasureCodePluginJaxRS(ErasureCodePlugin):
    def factory(self, directory: str,
                profile: ErasureCodeProfile) -> ErasureCodeJaxRS:
        technique = profile.get("technique", "reed_sol_van")
        instance = ErasureCodeJaxRS(technique)
        instance.init(dict(profile))
        return instance


def __erasure_code_version__() -> str:
    return __version__


def __erasure_code_init__(name: str, directory: str) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginJaxRS())
