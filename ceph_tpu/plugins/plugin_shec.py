"""shec: Shingled Erasure Code plugin.

Behavioural mirror of the reference shec plugin
(reference: src/erasure-code/shec/ErasureCodeShec.{h,cc}): a Reed-Solomon
Vandermonde coding matrix with shingle-shaped zero windows so each parity
covers only a sliding window of data chunks, trading durability (c < m
arbitrary-failure tolerance) for cheaper local repair.

Parameters (ErasureCodeShec.h:36-60, parse at ErasureCodeShec.cc:276-344):
  k, m, c     data/parity counts and durability estimate; defaults (4, 3, 2);
              constraints: all > 0, c <= m <= k, k <= 12, k + m <= 20
  technique   multiple (default; the (m1,c1)/(m2,c2) split minimising
              recovery effort) | single (one shingle group)
  w           GF width; only 8 is supported here (GF(2^8), same field as
              the TPU kernels; the reference also allows 16/32)
  device      jax | numpy | auto (same routing as the jax_rs plugin)

The decode-plan search (``_make_decoding``) mirrors
``shec_make_decoding_matrix`` (ErasureCodeShec.cc:531-755): enumerate parity
subsets from small to large, build the square window system over the touched
data chunks, accept the first invertible minimal one; plans are cached per
(want, avails) signature like ErasureCodeShecTableCache.
"""
from __future__ import annotations

import collections
import threading
from typing import Mapping

import numpy as np

from .. import __version__
from ..gf import matrix as gfm
from ..gf import ref as gfref
from ..ops import rs_kernels
from .base import ErasureCode
from .interface import ErasureCodeProfile
from .registry import ErasureCodePlugin, ErasureCodePluginRegistry

MULTIPLE = 0
SINGLE = 1

PLAN_CACHE_SIZE = 2516  # same budget as the isa/shec table caches


def _recovery_efficiency(k: int, m1: int, m2: int, c1: int, c2: int) -> float:
    """shec_calc_recovery_efficiency1 (ErasureCodeShec.cc:420-459): average
    chunks read to repair one failure under the (m1,c1)/(m2,c2) split."""
    if m1 < c1 or m2 < c2:
        return -1.0
    if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
        return -1.0
    r_eff_k = [10 ** 8] * k
    r_e1 = 0.0
    for m_g, c_g in ((m1, c1), (m2, c2)):
        for rr in range(m_g):
            start = ((rr * k) // m_g) % k
            end = (((rr + c_g) * k) // m_g) % k
            cc = start
            first = True
            while first or cc != end:
                first = False
                r_eff_k[cc] = min(r_eff_k[cc],
                                  ((rr + c_g) * k) // m_g - (rr * k) // m_g)
                cc = (cc + 1) % k
            r_e1 += ((rr + c_g) * k) // m_g - (rr * k) // m_g
    r_e1 += sum(r_eff_k)
    return r_e1 / (k + m1 + m2)


def shec_coding_matrix(k: int, m: int, c: int,
                       technique: int = MULTIPLE) -> np.ndarray:
    """The shingled coding matrix [m, k]
    (shec_reedsolomon_coding_matrix, ErasureCodeShec.cc:461-528): an RS
    Vandermonde matrix with each parity row's coverage restricted to a
    shingle window by zeroing the complement."""
    if technique != SINGLE:
        m1_best, c1_best = -1, -1
        min_r_e1 = 100.0
        for c1 in range(c // 2 + 1):
            for m1 in range(m + 1):
                c2, m2 = c - c1, m - m1
                if m1 < c1 or m2 < c2:
                    continue
                if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
                    continue
                if (m1 != 0 and c1 == 0) or (m2 != 0 and c2 == 0):
                    continue
                r_e1 = _recovery_efficiency(k, m1, m2, c1, c2)
                if min_r_e1 - r_e1 > np.finfo(float).eps and r_e1 < min_r_e1:
                    min_r_e1, c1_best, m1_best = r_e1, c1, m1
        m1, c1 = m1_best, c1_best
        m2, c2 = m - m1, c - c1
    else:
        m1, c1, m2, c2 = 0, 0, m, c

    mat = gfm.rs_vandermonde_jerasure(k, m).copy()
    for row_base, m_g, c_g in ((0, m1, c1), (m1, m2, c2)):
        for rr in range(m_g):
            end = ((rr * k) // m_g) % k
            start = (((rr + c_g) * k) // m_g) % k
            cc = start
            while cc != end:
                mat[row_base + rr, cc] = 0
                cc = (cc + 1) % k
    return mat


class ErasureCodeShec(ErasureCode):
    DEFAULT_K, DEFAULT_M, DEFAULT_C = 4, 3, 2

    def __init__(self, technique: int = MULTIPLE):
        super().__init__()
        self.technique = technique
        self.k = 0
        self.m = 0
        self.c = 0
        self.w = 8
        self.matrix: np.ndarray | None = None
        self.device = "auto"
        self.jax_threshold = 65536
        self._plan_cache: collections.OrderedDict = collections.OrderedDict()
        self._cache_lock = threading.Lock()

    # -- init (parse, ErasureCodeShec.cc:276-384) ---------------------------

    def init(self, profile: ErasureCodeProfile) -> None:
        super().init(profile)
        has = [name for name in ("k", "m", "c") if profile.get(name)]
        if not has:
            self.k, self.m, self.c = self.DEFAULT_K, self.DEFAULT_M, self.DEFAULT_C
            profile.update(k=str(self.k), m=str(self.m), c=str(self.c))
        elif len(has) != 3:
            raise ValueError("(k, m, c) must all be chosen or all defaulted")
        else:
            self.k = self.to_int("k", profile, str(self.DEFAULT_K))
            self.m = self.to_int("m", profile, str(self.DEFAULT_M))
            self.c = self.to_int("c", profile, str(self.DEFAULT_C))
        k, m, c = self.k, self.m, self.c
        if k <= 0 or m <= 0 or c <= 0:
            raise ValueError(f"k={k} m={m} c={c} must be positive")
        if m < c:
            raise ValueError(f"c={c} must be <= m={m}")
        if k > 12:
            raise ValueError(f"k={k} must be <= 12")
        if k + m > 20:
            raise ValueError(f"k+m={k + m} must be <= 20")
        if k < m:
            raise ValueError(f"m={m} must be <= k={k}")
        self.w = self.to_int("w", profile, "8")
        if self.w != 8:
            raise ValueError(f"w={self.w} must be 8 (GF(2^8))")
        self.device = self.to_string("device", profile, "auto")
        if self.device not in ("jax", "numpy", "auto"):
            raise ValueError(f"device={self.device} must be jax|numpy|auto")
        self.jax_threshold = self.to_int("jax-threshold", profile, "65536")
        self.matrix = shec_coding_matrix(k, m, c, self.technique)
        profile["plugin"] = profile.get("plugin", "shec")
        self._profile = profile

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    # -- decode-plan search (shec_make_decoding_matrix) ---------------------

    def _make_decoding(self, want: tuple[int, ...], avails: tuple[int, ...]):
        """Find the minimal repair plan for the (want, avails) 0/1 vectors.

        Returns (minimum_chunks, plan); plan is None when no matrix solve is
        needed, else (in_ids, out_cols, Dinv): recovered data chunk
        ``out_cols[i]`` = XOR_j Dinv[i, j] * chunk[in_ids[j]].  Raises
        IOError when no invertible repair window exists.
        """
        k, m = self.k, self.m
        mat = self.matrix
        want = list(want)
        # a wanted missing parity needs every data chunk its row touches
        # (ErasureCodeShec.cc:540-548)
        for i in range(m):
            if want[k + i] and not avails[k + i]:
                for j in range(k):
                    if mat[i, j]:
                        want[j] = 1

        sig = (tuple(want), tuple(avails))
        with self._cache_lock:
            hit = self._plan_cache.get(sig)
            if hit is not None:
                self._plan_cache.move_to_end(sig)
                return hit

        mindup, minp = k + 1, k + 1
        best = None
        for pp in range(1 << m):
            p = [i for i in range(m) if pp & (1 << i)]
            if len(p) > minp:
                continue
            if any(not avails[k + pi] for pi in p):
                continue
            tmprow = [0] * (k + m)
            tmpcol = [0] * k
            for i in range(k):
                if want[i] and not avails[i]:
                    tmpcol[i] = 1
            for pi in p:
                tmprow[k + pi] = 1
                for j in range(k):
                    if mat[pi, j]:
                        tmpcol[j] = 1
                        if avails[j]:
                            tmprow[j] = 1
            dup_row = sum(tmprow)
            dup_col = sum(tmpcol)
            if dup_row != dup_col:
                continue
            dup = dup_row
            if dup == 0:
                mindup = 0
                best = ([], [], None)
                break
            if dup < mindup:
                rows = [i for i in range(k + m) if tmprow[i]]
                cols = [j for j in range(k) if tmpcol[j]]
                tmpmat = np.zeros((dup, dup), dtype=np.uint8)
                for ri, i in enumerate(rows):
                    for ci, j in enumerate(cols):
                        tmpmat[ri, ci] = (1 if i == j else 0) if i < k \
                            else mat[i - k, j]
                try:
                    dinv = gfm.gf_invert(tmpmat)
                except np.linalg.LinAlgError:
                    continue
                mindup, minp = dup, len(p)
                best = (rows, cols, dinv)
        if best is None:
            raise IOError("shec: can't find recover matrix")

        rows, cols, dinv = best
        minimum = set(rows)
        for i in range(k):
            if want[i] and avails[i]:
                minimum.add(i)
        # an available wanted parity still counts itself unless its whole
        # window is already being read (ErasureCodeShec.cc:712-721)
        for i in range(m):
            if want[k + i] and avails[k + i] and (k + i) not in minimum:
                if any(mat[i, j] and not want[j] for j in range(k)):
                    minimum.add(k + i)
        # the cached minimum is a frozenset so callers mutating the returned
        # set cannot corrupt the cache
        result = (frozenset(minimum),
                  None if dinv is None else (rows, cols, dinv))
        with self._cache_lock:
            self._plan_cache[sig] = result
            if len(self._plan_cache) > PLAN_CACHE_SIZE:
                self._plan_cache.popitem(last=False)
        return result

    def _vectors(self, want_to_read, available):
        n = self.k + self.m
        for i in list(want_to_read) + list(available):
            if i < 0 or i >= n:
                raise ValueError(f"chunk index {i} out of range")
        want = tuple(1 if i in want_to_read else 0 for i in range(n))
        avails = tuple(1 if i in available else 0 for i in range(n))
        return want, avails

    def minimum_to_decode(self, want_to_read: set, available: set
                          ) -> dict[int, list[tuple[int, int]]]:
        want, avails = self._vectors(set(want_to_read), set(available))
        minimum, _ = self._make_decoding(want, avails)
        return {i: [(0, 1)] for i in sorted(minimum)}

    def minimum_to_decode_with_cost(self, want_to_read: set,
                                    available: Mapping[int, int]) -> set:
        want, avails = self._vectors(set(want_to_read), set(available))
        minimum, _ = self._make_decoding(want, avails)
        return set(minimum)

    # -- encode/decode ------------------------------------------------------

    def _apply(self, mat: np.ndarray, stack: np.ndarray) -> np.ndarray:
        if self.device == "numpy" or (
                self.device == "auto" and stack.nbytes < self.jax_threshold):
            return gfref.apply_matrix(mat, stack)
        import jax
        return np.asarray(jax.device_get(rs_kernels.gf_apply(mat, stack)))

    def encode_chunks(self, want_to_encode: set,
                      encoded: dict[int, np.ndarray]) -> None:
        k, m = self.k, self.m
        data = np.stack([encoded[i] for i in range(k)])
        parity = self._apply(self.matrix, data)
        for i in range(m):
            encoded[k + i][:] = parity[i]

    def decode_chunks(self, want_to_read: set, chunks: Mapping[int, np.ndarray],
                      decoded: dict[int, np.ndarray]) -> None:
        k, m = self.k, self.m
        want, avails = self._vectors(
            set(want_to_read), set(chunks))
        _, plan = self._make_decoding(want, avails)
        if plan is not None:
            rows, cols, dinv = plan
            stack = np.stack([decoded[i] for i in rows])
            rec = self._apply(dinv, stack)
            for i, col in enumerate(cols):
                if not avails[col]:
                    decoded[col][:] = rec[i]
        # re-encode wanted erased parities from the (now repaired) data
        # (ErasureCodeShec.cc:803-808)
        lost_parity = [i for i in range(m)
                       if want[k + i] and not avails[k + i]]
        if lost_parity:
            data = np.stack([decoded[i] for i in range(k)])
            rec = self._apply(self.matrix[lost_parity, :], data)
            for i, pi in enumerate(lost_parity):
                decoded[k + pi][:] = rec[i]


class ErasureCodePluginShec(ErasureCodePlugin):
    def factory(self, directory: str,
                profile: ErasureCodeProfile) -> ErasureCodeShec:
        t = profile.get("technique", "multiple")
        if t == "single":
            technique = SINGLE
        elif t == "multiple":
            technique = MULTIPLE
        else:
            raise ValueError(
                f"technique={t} is not a valid coding technique "
                f"(single, multiple)")
        profile = dict(profile)
        profile["technique"] = t
        instance = ErasureCodeShec(technique)
        instance.init(profile)
        return instance


def __erasure_code_version__() -> str:
    return __version__


def __erasure_code_init__(name: str, directory: str) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginShec())
