"""xor: the trivial k-data/1-parity example plugin.

Mirror of the reference's example plugin
(reference: src/test/erasure-code/ErasureCodeExample.h — XOR k=2, m=1),
generalised to any k >= 2, m = 1.  Exists for the same reason the
reference's does: a minimal real plugin for registry and interface tests,
and the m=1 region_xor fast path (cf. ErasureCodeIsa.cc:119-131).
"""
from __future__ import annotations

from typing import Mapping

import numpy as np

from .. import __version__
from .base import ErasureCode
from .interface import ErasureCodeProfile
from .registry import ErasureCodePlugin, ErasureCodePluginRegistry


class ErasureCodeXor(ErasureCode):
    def __init__(self):
        super().__init__()
        self.k = 2

    def init(self, profile: ErasureCodeProfile) -> None:
        super().init(profile)
        self.k = self.to_int("k", profile, "2")
        m = self.to_int("m", profile, "1")
        if m != 1:
            raise ValueError(f"xor plugin requires m=1, got m={m}")
        self.sanity_check_k_m(self.k, 1)
        profile["plugin"] = profile.get("plugin", "xor")
        self._profile = profile

    def get_chunk_count(self) -> int:
        return self.k + 1

    def get_data_chunk_count(self) -> int:
        return self.k

    def encode_chunks(self, want_to_encode: set,
                      encoded: dict[int, np.ndarray]) -> None:
        parity = encoded[0].copy()
        for i in range(1, self.k):
            parity ^= encoded[i]
        encoded[self.k][:] = parity

    def decode_chunks(self, want_to_read: set, chunks: Mapping[int, np.ndarray],
                      decoded: dict[int, np.ndarray]) -> None:
        erasures = [i for i in range(self.k + 1) if i not in chunks]
        if len(erasures) > 1:
            raise IOError(f"xor cannot recover {len(erasures)} erasures")
        if not erasures:
            return
        e = erasures[0]
        acc = None
        for i in range(self.k + 1):
            if i == e:
                continue
            acc = decoded[i].copy() if acc is None else acc ^ decoded[i]
        decoded[e][:] = acc


class ErasureCodePluginXor(ErasureCodePlugin):
    def factory(self, directory: str, profile: ErasureCodeProfile) -> ErasureCodeXor:
        instance = ErasureCodeXor()
        instance.init(dict(profile))
        return instance


def __erasure_code_version__() -> str:
    return __version__


def __erasure_code_init__(name: str, directory: str) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginXor())
