"""Vectorized CRUSH placement: one jitted call maps millions of PGs.

TPU-first reformulation of the reference's bulk mapping
(reference: src/osd/OSDMapMapping.{h,cc} ParallelPGMapper — a thread pool
looping crush_do_rule per PG; here the whole PG axis is vmapped and the
data-dependent retry loops become bounded lax.while_loops with masking,
cf. SURVEY.md §7 "CRUSH's data-dependent loops").

Scope (the production shape): maps whose buckets are all non-empty STRAW2
(the default since jewel) and rules of the form
    take <root>; choose[leaf]_{firstn,indep} <n> <type>; emit
with optimal-profile local-retry tunables (choose_local_tries=0,
choose_local_fallback_tries=0) and either chooseleaf_stable=1 or
chooseleaf_descend_once=1 (single-try leaf recursion).  Anything outside
this envelope is rejected with ValueError at compile/map time — run it
through the exact host interpreter (ceph_tpu.crush.mapper) instead, which
is also the oracle these kernels are tested against bit-for-bit.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .hash import crush_hash32_2_jax, crush_hash32_3_jax
from .ln import LN_TABLE_S64
from .map import (CRUSH_BUCKET_STRAW2, CRUSH_ITEM_NONE,
                  CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSELEAF_INDEP,
                  CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP,
                  CRUSH_RULE_EMIT, CRUSH_RULE_TAKE, CrushMap)

S64_MIN = -(1 << 63)
LN_BIAS = 0x1000000000000  # 2^48
UNDEF = 0x7FFFFFFE         # CRUSH_ITEM_UNDEF


@dataclass(frozen=True)
class CompiledMap:
    """Dense-array form of a straw2-only CrushMap for device kernels."""
    items: np.ndarray        # [B, S] int32 (device ids >= 0, bucket ids < 0)
    weights: np.ndarray      # [B, S] int64 (16.16 fixed point)
    sizes: np.ndarray        # [B] int32
    types: np.ndarray        # [B] int32
    row_of_id: np.ndarray    # [max_buckets] int32 (-1 if absent)
    max_devices: int
    max_depth: int
    tunables: dict

    @classmethod
    def compile(cls, cmap: CrushMap) -> "CompiledMap":
        for b in cmap.buckets.values():
            if b.alg != CRUSH_BUCKET_STRAW2:
                raise ValueError(
                    f"bucket {b.id} alg={b.alg}: JAX path supports straw2 "
                    f"only; use the host interpreter")
            if b.size == 0:
                raise ValueError("empty buckets need the host interpreter")
        t = cmap.tunables
        if t["choose_local_tries"] or t["choose_local_fallback_tries"]:
            raise ValueError("local retry tunables need the host interpreter")
        if not t["chooseleaf_descend_once"]:
            # without descend_once the chooseleaf recursion retries inside
            # the chosen domain (recurse_tries=choose_tries, mapper.c
            # do_rule firstn branch); the kernels do a single-try descent
            raise ValueError(
                "chooseleaf_descend_once=0 needs the host interpreter")
        ids = sorted(cmap.buckets)
        nb = len(ids)
        smax = max(b.size for b in cmap.buckets.values())
        items = np.full((nb, smax), CRUSH_ITEM_NONE, dtype=np.int32)
        weights = np.zeros((nb, smax), dtype=np.int64)
        sizes = np.zeros(nb, dtype=np.int32)
        types = np.zeros(nb, dtype=np.int32)
        row_of_id = np.full(max(-i for i in ids), -1, dtype=np.int32)
        for row, bid in enumerate(ids):
            b = cmap.buckets[bid]
            items[row, :b.size] = b.items
            weights[row, :b.size] = b.item_weights
            sizes[row] = b.size
            types[row] = b.type
            row_of_id[-1 - bid] = row
        # longest bucket chain via memoized DFS (bucket ids carry no
        # ordering guarantee: Ceph assigns the root -1 and children -2...)
        depth: dict[int, int] = {}

        def bucket_depth(bid: int, seen: frozenset = frozenset()) -> int:
            if bid in depth:
                return depth[bid]
            if bid in seen:
                raise ValueError(f"bucket cycle through {bid}")
            d = 1
            for it in cmap.buckets[bid].items:
                if it < 0 and it in cmap.buckets:
                    d = max(d, bucket_depth(it, seen | {bid}) + 1)
            depth[bid] = d
            return d

        for bid in ids:
            bucket_depth(bid)
        return cls(items=items, weights=weights, sizes=sizes, types=types,
                   row_of_id=row_of_id, max_devices=cmap.max_devices,
                   max_depth=max(depth.values()), tunables=dict(t))


class BulkMapper:
    """jit/vmap CRUSH placement over a compiled straw2 map.

    map_rule(ruleno, xs) -> (out [N, numrep] int32 with CRUSH_ITEM_NONE
    holes/padding, placed [N] int32).
    """

    # process-wide kernel cache keyed by map content: cloned/equal maps
    # (the balancer clones per optimization pass) share compilations.
    # LRU-bounded: reweight churn produces a new digest per distinct map,
    # and each entry pins jitted closures over the compiled arrays.
    _global_cache: "collections.OrderedDict" = None
    _GLOBAL_CACHE_CAP = 16

    def __init__(self, cmap: CrushMap):
        import collections
        import hashlib
        cls = type(self)
        if cls._global_cache is None:
            cls._global_cache = collections.OrderedDict()
        self.cm = CompiledMap.compile(cmap)
        self.cmap = cmap
        h = hashlib.sha256()
        for part in (self.cm.items.tobytes(), self.cm.weights.tobytes(),
                     self.cm.sizes.tobytes(), self.cm.types.tobytes()):
            h.update(part)
        h.update(repr(sorted(self.cm.tunables.items())).encode())
        self._digest = h.hexdigest()
        cache = cls._global_cache
        if self._digest in cache:
            cache.move_to_end(self._digest)
        else:
            cache[self._digest] = {}
            while len(cache) > cls._GLOBAL_CACHE_CAP:
                cache.popitem(last=False)
        self._cache = cache[self._digest]

    # -- choose_args compilation (mapper.c:309-326) --------------------------

    def _compile_choose_args(self, choose_args: dict | None):
        """Dense tensors for per-position weight-set overrides: ws
        [P, B, S] (position-major weights; buckets without an override
        replicate their base weights) and hash-id overrides ids [B, S]
        (``arg->ids``: alternate ids fed to the straw2 hash while the
        RETURNED item stays the bucket's own).  These are TRACED kernel
        inputs (one compilation per P, not per weight-set content — the
        balancer's crush-compat loop mutates the values every iteration)."""
        cm = self.cm
        if not choose_args:
            return 1, cm.weights[None, :, :], cm.items
        row_of = {bid: row for row, bid in enumerate(sorted(self.cmap.buckets))}
        P = max((len(a.get("weight_set") or [()])
                 for a in choose_args.values()), default=1) or 1
        ws = np.broadcast_to(cm.weights, (P,) + cm.weights.shape).copy()
        ids = cm.items.copy()
        for bid, arg in choose_args.items():
            row = row_of.get(bid)
            if row is None:
                continue
            size = int(cm.sizes[row])
            wset = arg.get("weight_set")
            if wset:
                for p in range(P):
                    # positions past the set reuse the LAST entry
                    # (mapper.c:318 "choose_args_index >= size -> size-1")
                    wrow = wset[min(p, len(wset) - 1)]
                    ws[p, row, :size] = np.asarray(wrow[:size],
                                                   dtype=np.int64)
            if arg.get("ids"):
                ids[row, :size] = np.asarray(arg["ids"][:size],
                                             dtype=np.int32)
        return P, ws, ids

    # -- kernel construction ------------------------------------------------

    def _kernel(self, kind: str, root: int, numrep: int, out_size: int,
                target_type: int, leaf: bool, n_pos: int):
        key = (kind, root, numrep, out_size, target_type, leaf, n_pos)
        if key in self._cache:
            return self._cache[key]
        import jax
        # straw2 draws are exact int64 fixed-point quotients (mapper.c
        # div64_s64); JAX's default 32-bit mode would silently truncate the
        # 2^48-scale ln values.  Refuse to run rather than flip the
        # process-global flag behind the caller's back.  (On TPU, XLA
        # emulates s64 with i32 pairs — fine for placement workloads.)
        if not jax.config.jax_enable_x64:
            raise RuntimeError(
                "CRUSH bulk mapping needs 64-bit JAX types: call "
                "jax.config.update('jax_enable_x64', True) first "
                "(or set JAX_ENABLE_X64=1)")
        import jax.numpy as jnp
        from jax import lax

        cm = self.cm
        items_d = jnp.asarray(cm.items)
        last_pos = n_pos - 1
        sizes_d = jnp.asarray(cm.sizes)
        types_d = jnp.asarray(cm.types)
        row_of_id_d = jnp.asarray(cm.row_of_id)
        ln_d = jnp.asarray(LN_TABLE_S64)
        smax = cm.items.shape[1]
        slot = jnp.arange(smax, dtype=jnp.int32)
        tries = cm.tunables["choose_total_tries"] + 1
        vary_r = cm.tunables["chooseleaf_vary_r"]
        stable = cm.tunables["chooseleaf_stable"]
        root_row = int(cm.row_of_id[-1 - root])
        max_devices = cm.max_devices
        NONE = jnp.int32(CRUSH_ITEM_NONE)

        def make_one(ws_pos_d, hash_ids_d):
            """Build the per-x chooser over TRACED choose_args tensors
            (ws_pos_d [P, B, S] weights, hash_ids_d [B, S] hash ids) so
            one compilation serves every weight-set content."""
            def straw2_choose(row, x, r, pos):
                """mapper.c:361-384 vectorized over one bucket's item slots;
                ``pos`` selects the choose_args weight-set position (clamped
                to the last entry, mapper.c:309-326), and the hash runs over
                the (possibly overridden) ids while the returned item is the
                bucket's own."""
                ids = items_d[row]
                hids = hash_ids_d[row]
                ws = ws_pos_d[jnp.minimum(pos, last_pos), row]
                u = crush_hash32_3_jax(
                    jnp.broadcast_to(x, hids.shape),
                    hids,
                    jnp.broadcast_to(r, hids.shape)) & jnp.uint32(0xFFFF)
                ln = ln_d[u.astype(jnp.int32)]
                # trunc((ln - 2^48)/w): numerator <= 0, equals -((2^48-ln)//w)
                draw = -((LN_BIAS - ln) // jnp.maximum(ws, 1))
                draw = jnp.where((ws > 0) & (slot < sizes_d[row]), draw, S64_MIN)
                return ids[jnp.argmax(draw)]

            def is_out(reweights, item, x):
                """mapper.c:424-438"""
                w = reweights[jnp.clip(item, 0, reweights.shape[0] - 1)]
                oob = item >= reweights.shape[0]
                h = crush_hash32_2_jax(x, item.astype(jnp.uint32)) & jnp.uint32(0xFFFF)
                return oob | (w == 0) | ((w < 0x10000) & (h.astype(jnp.int64) >= w))

            def descend(row0, x, r, ttype, pos):
                """Walk intervening buckets until an item of type ttype
                (mapper.c:547-565 / :787-800).  Returns (item, ok, skip):
                ok = landed on the target type; skip = structurally bad
                (device at the wrong level or id >= max_devices -> the
                reference's skip_rep / CRUSH_ITEM_NONE cases)."""
                def body(_, carry):
                    row, item, done, skip = carry
                    nxt = straw2_choose(row, x, r, pos)
                    is_bucket = nxt < jnp.int32(0)
                    nrow = jnp.where(is_bucket, row_of_id_d[-1 - nxt], 0)
                    ntype = jnp.where(is_bucket, types_d[nrow], 0)
                    oob_dev = (~is_bucket) & (nxt >= max_devices)
                    hit = (ntype == ttype) & (~oob_dev)
                    bad = oob_dev | ((~hit) & (~is_bucket))
                    new_done = done | hit | bad
                    return (jnp.where(new_done, row, nrow),
                            jnp.where(done, item, nxt),
                            new_done,
                            jnp.where(done, skip, bad))
                init = (jnp.int32(row0), jnp.int32(0), jnp.bool_(False),
                        jnp.bool_(False))
                _, item, done, skip = lax.fori_loop(0, cm.max_depth, body, init)
                # depth exhaustion without landing: treat as retryable reject
                return item, done & (~skip), skip

            def leaf_from(item, x, r, outpos):
                """Single-try chooseleaf recursion (recurse_tries=1):
                r_leaf = (stable ? 0 : outpos) + sub_r (mapper.c:570-596);
                the recursion's bucket_choose position stays outpos."""
                sub_r = (r >> (vary_r - 1)) if vary_r else jnp.int32(0)
                base = jnp.int32(0) if stable else outpos
                drow = jnp.where(item < 0, row_of_id_d[-1 - item], 0)
                return descend(drow, x, base + sub_r, 0, outpos)

            def firstn_one(x, reweights):
                """crush_choose_firstn (mapper.c:460-651), no local retries.
                Places at most out_size items while scanning numrep reps
                (the reference's count/out_size vs numrep split)."""
                out = jnp.full((out_size,), NONE, dtype=jnp.int32)
                out2 = jnp.full((out_size,), NONE, dtype=jnp.int32)
                outpos = jnp.int32(0)

                for rep in range(numrep):
                    def cond(st):
                        placed, dead, ftotal, _o, _o2, outpos = st
                        return (~placed) & (~dead) & (ftotal < tries) & \
                            (outpos < out_size)

                    def body(st):
                        placed, dead, ftotal, out, out2, outpos = st
                        r = jnp.int32(rep) + ftotal
                        item, ok, skip = descend(root_row, x, r, target_type,
                                                 outpos)
                        pos_mask = jnp.arange(out_size) < outpos
                        collide = jnp.any(pos_mask & (out == item))
                        reject = ~ok
                        if leaf:
                            lf, lok, _ = leaf_from(item, x, r, outpos)
                            lcollide = jnp.any(pos_mask & (out2 == lf))
                            reject = reject | (~lok) | lcollide | \
                                is_out(reweights, lf, x)
                            leaf_item = lf
                        else:
                            leaf_item = item
                            if target_type == 0:
                                reject = reject | is_out(reweights, item, x)
                        good = (~skip) & (~reject) & (~collide)
                        new_out = jnp.where(good, out.at[outpos].set(item), out)
                        new_out2 = jnp.where(good,
                                             out2.at[outpos].set(leaf_item), out2)
                        return (good, skip, ftotal + 1, new_out, new_out2,
                                jnp.where(good, outpos + 1, outpos))

                    _, _, _, out, out2, outpos = lax.while_loop(
                        cond, body,
                        (jnp.bool_(False), jnp.bool_(False), jnp.int32(0),
                         out, out2, outpos))

                result = out2 if leaf else out
                keep = jnp.arange(out_size) < outpos
                return jnp.where(keep, result, NONE), outpos

            def indep_one(x, reweights):
                """crush_choose_indep (mapper.c:658-847): positionally stable."""
                out = jnp.full((out_size,), UNDEF, dtype=jnp.int32)
                out2 = jnp.full((out_size,), UNDEF, dtype=jnp.int32)

                def cond(st):
                    out, out2, ftotal = st
                    return (ftotal < tries) & jnp.any(out == UNDEF)

                def body(st):
                    out, out2, ftotal = st
                    for rep in range(out_size):
                        undef = out[rep] == UNDEF
                        r = jnp.int32(rep) + jnp.int32(numrep) * ftotal
                        # top-level indep position = the do_rule outpos (0
                        # here); the leaf recursion's position = rep
                        # (crush_choose_indep passes outpos=rep down)
                        item, ok, skip = descend(root_row, x, r, target_type,
                                                 jnp.int32(0))
                        collide = jnp.any(out == item)
                        reject = (~ok) | collide
                        if leaf:
                            # recursion: out2[rep], parent_r = r, one try
                            drow = jnp.where(item < 0, row_of_id_d[-1 - item], 0)
                            lf, lok, _ = descend(drow, x, jnp.int32(rep) + r, 0,
                                                 jnp.int32(rep))
                            reject = reject | (~lok) | is_out(reweights, lf, x)
                            leaf_item = lf
                        else:
                            leaf_item = item
                            if target_type == 0:
                                reject = reject | is_out(reweights, item, x)
                        # structural badness pins the hole permanently
                        pin_none = undef & skip
                        good = undef & (~skip) & (~reject)
                        out = jnp.where(pin_none, out.at[rep].set(NONE), out)
                        out2 = jnp.where(pin_none, out2.at[rep].set(NONE), out2)
                        out = jnp.where(good, out.at[rep].set(item), out)
                        out2 = jnp.where(good, out2.at[rep].set(leaf_item), out2)
                    return out, out2, ftotal + 1

                out, out2, _ = lax.while_loop(cond, body,
                                              (out, out2, jnp.int32(0)))
                result = out2 if leaf else out
                return jnp.where(result == UNDEF, NONE, result), jnp.int32(out_size)

            return firstn_one if kind == "firstn" else indep_one

        from ..ops.traced_jit import traced_jit

        @traced_jit(name=f"crush.bulk.{kind}")
        def bulk(xs, reweights, ws_pos, hash_ids):
            one = make_one(ws_pos, hash_ids)
            return jax.vmap(lambda x: one(x, reweights))(xs)

        self._cache[key] = bulk
        return bulk

    # -- public API ---------------------------------------------------------

    def map_rule(self, ruleno: int, xs, reweights=None, result_max: int = 0,
                 choose_args: dict | None = None):
        import jax
        import jax.numpy as jnp
        rule = self.cmap.rules[ruleno]
        steps = rule.steps
        if (len(steps) != 3 or steps[0][0] != CRUSH_RULE_TAKE or
                steps[2][0] != CRUSH_RULE_EMIT):
            raise ValueError("JAX path supports take/choose/emit rules only")
        op, arg1, arg2 = steps[1]
        kind_map = {
            CRUSH_RULE_CHOOSE_FIRSTN: ("firstn", False),
            CRUSH_RULE_CHOOSELEAF_FIRSTN: ("firstn", True),
            CRUSH_RULE_CHOOSE_INDEP: ("indep", False),
            CRUSH_RULE_CHOOSELEAF_INDEP: ("indep", True),
        }
        if op not in kind_map:
            raise ValueError(f"unsupported op {op} on JAX path")
        kind, leaf = kind_map[op]
        if leaf and arg2 == 0:
            # chooseleaf over failure-domain osd: the reference copies the
            # chosen device straight into the leaf vector (mapper.c:592-596)
            leaf = False
        numrep = arg1
        if numrep <= 0:
            if result_max <= 0:
                raise ValueError("numrep<=0 rule needs result_max")
            numrep += result_max
        # the reference clamps only the output size; the retry stride keeps
        # the rule's numrep (crush_do_rule: out_size = min(numrep,
        # result_max-osize) while crush_choose_indep still gets numrep)
        out_size = min(numrep, result_max) if result_max else numrep
        root = steps[0][1]
        if reweights is None:
            reweights = np.full(self.cm.max_devices, 0x10000, dtype=np.int64)
        reweights = jnp.asarray(np.asarray(reweights, dtype=np.int64))
        # tracer-friendly: inside jit/shard_map (the distributed
        # ParallelPGMapper, parallel/mesh.sharded_placement_step) xs is a
        # traced array and results stay on device; host callers get numpy
        traced = isinstance(xs, jax.core.Tracer)
        xs = (xs.astype(jnp.uint32) if traced
              else jnp.asarray(np.asarray(xs, dtype=np.uint32)))
        n_pos, ws_arr, ids_arr = self._compile_choose_args(choose_args)
        bulk = self._kernel(kind, root, int(numrep), int(out_size),
                            int(arg2), leaf, int(n_pos))
        if traced:
            # inside an enclosing jit/shard_map: stay on-device, no spans
            return bulk(xs, reweights, jnp.asarray(ws_arr),
                        jnp.asarray(ids_arr))
        from ..common.tracer import trace_span
        with trace_span("crush.bulk_map", pgs=int(xs.shape[0]),
                        rule=int(ruleno), kind=kind, numrep=int(numrep)):
            out, placed = bulk(xs, reweights, jnp.asarray(ws_arr),
                               jnp.asarray(ids_arr))
        return np.asarray(out), np.asarray(placed)
