"""CRUSH rjenkins1 hash, bit-exact to the reference
(reference: src/crush/hash.c:12-90, seed 1315423911 at :24).

Three implementations sharing one algorithm:
- scalar Python ints (used by the exact rule interpreter),
- vectorized numpy uint32,
- jax uint32 (vmappable; feeds the bulk placement kernels).

All arithmetic is uint32 with C wraparound; shifts are logical.
"""
from __future__ import annotations

import numpy as np

CRUSH_HASH_SEED = 1315423911
_M = 0xFFFFFFFF


def _mix(a: int, b: int, c: int) -> tuple[int, int, int]:
    a = (a - b) & _M; a = (a - c) & _M; a ^= c >> 13
    b = (b - c) & _M; b = (b - a) & _M; b ^= (a << 8) & _M
    c = (c - a) & _M; c = (c - b) & _M; c ^= b >> 13
    a = (a - b) & _M; a = (a - c) & _M; a ^= c >> 12
    b = (b - c) & _M; b = (b - a) & _M; b ^= (a << 16) & _M
    c = (c - a) & _M; c = (c - b) & _M; c ^= b >> 5
    a = (a - b) & _M; a = (a - c) & _M; a ^= c >> 3
    b = (b - c) & _M; b = (b - a) & _M; b ^= (a << 10) & _M
    c = (c - a) & _M; c = (c - b) & _M; c ^= b >> 15
    return a, b, c


def crush_hash32(a: int) -> int:
    a &= _M
    h = (CRUSH_HASH_SEED ^ a) & _M
    b, x, y = a, 231232, 1232
    b, x, h = _mix(b, x, h)
    y, a, h = _mix(y, a, h)
    return h


def crush_hash32_2(a: int, b: int) -> int:
    a &= _M; b &= _M
    h = (CRUSH_HASH_SEED ^ a ^ b) & _M
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def crush_hash32_3(a: int, b: int, c: int) -> int:
    a &= _M; b &= _M; c &= _M
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c) & _M
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


def crush_hash32_4(a: int, b: int, c: int, d: int) -> int:
    a &= _M; b &= _M; c &= _M; d &= _M
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c ^ d) & _M
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    a, x, h = _mix(a, x, h)
    y, b, h = _mix(y, b, h)
    c, x, h = _mix(c, x, h)
    y, d, h = _mix(y, d, h)
    return h


def crush_hash32_5(a: int, b: int, c: int, d: int, e: int) -> int:
    a &= _M; b &= _M; c &= _M; d &= _M; e &= _M
    h = (CRUSH_HASH_SEED ^ a ^ b ^ c ^ d ^ e) & _M
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    e, x, h = _mix(e, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    d, x, h = _mix(d, x, h)
    y, e, h = _mix(y, e, h)
    return h


# -- numpy vectorized -------------------------------------------------------

def _mix_np(a, b, c):
    a = a - b; a = a - c; a = a ^ (c >> np.uint32(13))
    b = b - c; b = b - a; b = b ^ (a << np.uint32(8))
    c = c - a; c = c - b; c = c ^ (b >> np.uint32(13))
    a = a - b; a = a - c; a = a ^ (c >> np.uint32(12))
    b = b - c; b = b - a; b = b ^ (a << np.uint32(16))
    c = c - a; c = c - b; c = c ^ (b >> np.uint32(5))
    a = a - b; a = a - c; a = a ^ (c >> np.uint32(3))
    b = b - c; b = b - a; b = b ^ (a << np.uint32(10))
    c = c - a; c = c - b; c = c ^ (b >> np.uint32(15))
    return a, b, c


def crush_hash32_3_np(a, b, c):
    """Vectorized 3-arg hash over numpy uint32 arrays (broadcasting)."""
    a = np.asarray(a).astype(np.uint32)
    b = np.asarray(b).astype(np.uint32)
    c = np.asarray(c).astype(np.uint32)
    with np.errstate(over="ignore"):
        h = np.uint32(CRUSH_HASH_SEED) ^ a ^ b ^ c
        x = np.uint32(231232) + np.zeros_like(h)
        y = np.uint32(1232) + np.zeros_like(h)
        a, b, h = _mix_np(a, b, h)
        c, x, h = _mix_np(c, x, h)
        y, a, h = _mix_np(y, a, h)
        b, x, h = _mix_np(b, x, h)
        y, c, h = _mix_np(y, c, h)
    return h


def crush_hash32_2_np(a, b):
    a = np.asarray(a).astype(np.uint32)
    b = np.asarray(b).astype(np.uint32)
    with np.errstate(over="ignore"):
        h = np.uint32(CRUSH_HASH_SEED) ^ a ^ b
        x = np.uint32(231232) + np.zeros_like(h)
        y = np.uint32(1232) + np.zeros_like(h)
        a, b, h = _mix_np(a, b, h)
        x, a, h = _mix_np(x, a, h)
        b, y, h = _mix_np(b, y, h)
    return h


# -- jax --------------------------------------------------------------------

def _mix_jax(a, b, c):
    import jax.numpy as jnp
    u = lambda n: jnp.uint32(n)
    a = a - b; a = a - c; a = a ^ (c >> u(13))
    b = b - c; b = b - a; b = b ^ (a << u(8))
    c = c - a; c = c - b; c = c ^ (b >> u(13))
    a = a - b; a = a - c; a = a ^ (c >> u(12))
    b = b - c; b = b - a; b = b ^ (a << u(16))
    c = c - a; c = c - b; c = c ^ (b >> u(5))
    a = a - b; a = a - c; a = a ^ (c >> u(3))
    b = b - c; b = b - a; b = b ^ (a << u(10))
    c = c - a; c = c - b; c = c ^ (b >> u(15))
    return a, b, c


def crush_hash32_3_jax(a, b, c):
    """3-arg hash on jax uint32 arrays — the straw2 draw hash."""
    import jax.numpy as jnp
    a = a.astype(jnp.uint32)
    b = b.astype(jnp.uint32)
    c = c.astype(jnp.uint32)
    h = jnp.uint32(CRUSH_HASH_SEED) ^ a ^ b ^ c
    x = jnp.broadcast_to(jnp.uint32(231232), h.shape)
    y = jnp.broadcast_to(jnp.uint32(1232), h.shape)
    a, b, h = _mix_jax(a, b, h)
    c, x, h = _mix_jax(c, x, h)
    y, a, h = _mix_jax(y, a, h)
    b, x, h = _mix_jax(b, x, h)
    y, c, h = _mix_jax(y, c, h)
    return h


def crush_hash32_2_jax(a, b):
    """2-arg hash on jax uint32 arrays — is_out / pps hashing."""
    import jax.numpy as jnp
    a = a.astype(jnp.uint32)
    b = b.astype(jnp.uint32)
    h = jnp.uint32(CRUSH_HASH_SEED) ^ a ^ b
    x = jnp.broadcast_to(jnp.uint32(231232), h.shape)
    y = jnp.broadcast_to(jnp.uint32(1232), h.shape)
    a, b, h = _mix_jax(a, b, h)
    x, a, h = _mix_jax(x, a, h)
    b, y, h = _mix_jax(b, y, h)
    return h
