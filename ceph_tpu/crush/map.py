"""CRUSH map data model + builder.

Python analog of the reference's map structs and builder API
(reference: src/crush/crush.h:52-239, src/crush/builder.c): buckets with the
five algorithms (UNIFORM/LIST/TREE/STRAW/STRAW2), rules as (op, arg1, arg2)
step lists, and the map-level tunables.  The builder computes the derived
per-algorithm data (list sum_weights, tree node_weights) the same way the
reference does, and ``finalize`` computes ``max_devices``.

Serialisable via from_dict/to_dict — the golden tests load maps dumped by
the reference builder (tools/golden/golden_gen.c) through from_dict.
"""
from __future__ import annotations

from dataclasses import dataclass, field

# bucket algorithms (crush.h:123-191)
CRUSH_BUCKET_UNIFORM = 1
CRUSH_BUCKET_LIST = 2
CRUSH_BUCKET_TREE = 3
CRUSH_BUCKET_STRAW = 4
CRUSH_BUCKET_STRAW2 = 5

# rule step opcodes (crush.h:52-70)
CRUSH_RULE_NOOP = 0
CRUSH_RULE_TAKE = 1
CRUSH_RULE_CHOOSE_FIRSTN = 2
CRUSH_RULE_CHOOSE_INDEP = 3
CRUSH_RULE_EMIT = 4
CRUSH_RULE_CHOOSELEAF_FIRSTN = 6
CRUSH_RULE_CHOOSELEAF_INDEP = 7
CRUSH_RULE_SET_CHOOSE_TRIES = 8
CRUSH_RULE_SET_CHOOSELEAF_TRIES = 9
CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES = 10
CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11
CRUSH_RULE_SET_CHOOSELEAF_VARY_R = 12
CRUSH_RULE_SET_CHOOSELEAF_STABLE = 13

CRUSH_ITEM_UNDEF = 0x7FFFFFFE   # crush.h (mapping undefined)
CRUSH_ITEM_NONE = 0x7FFFFFFF    # no item (EC positional hole)

CRUSH_HASH_RJENKINS1 = 0


@dataclass
class Bucket:
    id: int
    alg: int
    type: int
    items: list[int]
    weight: int = 0                         # 16.16 cumulative
    hash: int = CRUSH_HASH_RJENKINS1
    item_weights: list[int] | None = None   # list/straw/straw2
    sum_weights: list[int] | None = None    # list
    item_weight: int | None = None          # uniform
    num_nodes: int | None = None            # tree
    node_weights: list[int] | None = None   # tree
    straws: list[int] | None = None         # straw v1

    @property
    def size(self) -> int:
        return len(self.items)


@dataclass
class Rule:
    steps: list[tuple[int, int, int]]
    ruleno: int = -1
    # rule mask metadata (crush_rule_mask; carried for the text-format
    # round trip, reference: CrushCompiler.cc:365-377)
    type: int = 1                 # 1=replicated, 3=erasure
    min_size: int = 1
    max_size: int = 10


def calc_straw_lengths(weights: list[int], version: int = 1) -> list[int]:
    """Legacy straw(v1) straw lengths (builder.c:427 crush_calc_straw,
    transcribed exactly — including its acknowledged-flawed horizontal
    slicing — because placement bit-equality with reference-built straw
    maps is the requirement).  Honours both straw_calc_version profiles
    (crush.h:446): v1 (modern default) and the v0 legacy same-weight
    special case; they differ only for repeated or zero weights."""
    import math
    size = len(weights)
    straws = [0] * size
    if not size:
        return straws
    # builder.c's insertion sort is ascending and tie-stable
    order = sorted(range(size), key=lambda i: weights[i])
    numleft = size
    straw = 1.0
    wbelow = 0.0
    lastw = 0.0
    i = 0
    while i < size:
        if version == 0:
            if weights[order[i]] == 0:
                straws[order[i]] = 0
                i += 1
                continue
            straws[order[i]] = int(straw * 0x10000)
            i += 1
            if i == size:
                break
            if weights[order[i]] == weights[order[i - 1]]:
                continue                # same straw for equal weights
            wbelow += (weights[order[i - 1]] - lastw) * numleft
            j = i
            while j < size and weights[order[j]] == weights[order[i]]:
                numleft -= 1
                j += 1
            wnext = numleft * (weights[order[i]] - weights[order[i - 1]])
            pbelow = wbelow / (wbelow + wnext)
            straw *= math.pow(1.0 / pbelow, 1.0 / numleft)
            lastw = weights[order[i - 1]]
        else:
            if weights[order[i]] == 0:
                straws[order[i]] = 0
                i += 1
                numleft -= 1
                continue
            straws[order[i]] = int(straw * 0x10000)
            i += 1
            if i == size:
                break
            wbelow += (weights[order[i - 1]] - lastw) * numleft
            numleft -= 1
            wnext = numleft * (weights[order[i]] - weights[order[i - 1]])
            pbelow = wbelow / (wbelow + wnext)
            straw *= math.pow(1.0 / pbelow, 1.0 / numleft)
            lastw = weights[order[i - 1]]
    return straws


# optimal tunable profile (builder.c set_optimal_crush_map semantics)
OPTIMAL_TUNABLES = dict(choose_local_tries=0, choose_local_fallback_tries=0,
                        choose_total_tries=50, chooseleaf_descend_once=1,
                        chooseleaf_vary_r=1, chooseleaf_stable=1)
# legacy profile (builder.h set_legacy_crush_map doc)
LEGACY_TUNABLES = dict(choose_local_tries=2, choose_local_fallback_tries=5,
                       choose_total_tries=19, chooseleaf_descend_once=0,
                       chooseleaf_vary_r=0, chooseleaf_stable=0)


class CrushMap:
    def __init__(self, tunables: dict | None = None):
        self.buckets: dict[int, Bucket] = {}       # id (negative) -> Bucket
        self.rules: dict[int, Rule] = {}
        self.tunables = dict(OPTIMAL_TUNABLES)
        if tunables:
            self.tunables.update(tunables)
        self.max_devices = 0
        # CrushWrapper-style naming (reference: src/crush/CrushWrapper.h)
        self.type_names: dict[int, str] = {0: "osd"}
        self.item_names: dict[int, str] = {}
        self.rule_names: dict[str, int] = {}
        self.choose_args: dict[int, object] = {}
        self.device_classes: dict[int, str] = {}
        # original bucket id -> device class -> shadow bucket id
        # (CrushWrapper::class_bucket, CrushWrapper.h:1335)
        self.class_bucket: dict[int, dict[str, int]] = {}
        # (original id, class) -> shadow id reservations, installed by the
        # text compiler from 'id <sid> class <c>' lines so recompiled maps
        # keep their shadow ids (the reference's old_class_bucket reuse,
        # CrushWrapper.cc:2707)
        self._shadow_id_hints: dict[tuple[int, str], int] = {}

    # -- builder (builder.c semantics) -------------------------------------

    def add_bucket(self, alg: int, type: int, items: list[int],
                   weights: list[int] | None = None, id: int | None = None,
                   uniform_weight: int | None = None) -> int:
        if id is None:
            id = -1
            while id in self.buckets:
                id -= 1
        if id >= 0 or id in self.buckets:
            raise ValueError(f"bad bucket id {id}")
        items = [int(i) for i in items]
        b = Bucket(id=id, alg=alg, type=type, items=items)
        if alg == CRUSH_BUCKET_UNIFORM:
            if uniform_weight is None:
                uniform_weight = weights[0] if weights else 0x10000
            b.item_weight = int(uniform_weight)
            b.weight = b.item_weight * len(items)
        elif alg == CRUSH_BUCKET_LIST:
            b.item_weights = [int(w) for w in weights]
            # sum_weights[i] = sum of item_weights[j] for j <= i (builder.c
            # crush_make_list_bucket: cumulative including self)
            acc, sums = 0, []
            for w in b.item_weights:
                acc += w
                sums.append(acc)
            b.sum_weights = sums
            b.weight = acc
        elif alg == CRUSH_BUCKET_STRAW2:
            b.item_weights = [int(w) for w in weights]
            b.weight = sum(b.item_weights)
        elif alg == CRUSH_BUCKET_TREE:
            b.item_weights = [int(w) for w in weights]
            self._build_tree(b)
        elif alg == CRUSH_BUCKET_STRAW:
            b.item_weights = [int(w) for w in weights]
            self._calc_straws(b)
        else:
            raise ValueError(f"unknown bucket alg {alg}")
        self.buckets[id] = b
        return id

    def _calc_straws(self, b: Bucket) -> None:
        """Legacy straw(v1) straw lengths for the map's configured
        straw_calc_version (see :func:`calc_straw_lengths`)."""
        b.straws = calc_straw_lengths(
            b.item_weights, int(self.tunables.get("straw_calc_version", 1)))
        b.weight = sum(b.item_weights)

    @staticmethod
    def _build_tree(b: Bucket) -> None:
        """Tree bucket node table (builder.c crush_make_tree_bucket
        semantics): leaves at odd node indices, internal weights cumulative."""
        n = len(b.items)
        depth = 0
        t = 1
        while t < n:
            t <<= 1
            depth += 1
        num_nodes = 1 << (depth + 1)
        node_weights = [0] * num_nodes
        for i, w in enumerate(b.item_weights):
            node = (i << 1) + 1
            node_weights[node] = int(w)
        # propagate up: each internal node at even index sums its subtree
        for h in range(1, depth + 1):
            step = 1 << h
            for node in range(step, num_nodes, step << 1):
                lo = node - (step >> 1)
                hi = node + (step >> 1)
                node_weights[node] = node_weights[lo] + (
                    node_weights[hi] if hi < num_nodes else 0)
        b.num_nodes = num_nodes
        b.node_weights = node_weights
        b.weight = node_weights[num_nodes >> 1]

    # -- map surgery (builder.c + CrushWrapper tree ops) -------------------

    def _rebuild_bucket(self, b: Bucket) -> None:
        """Recompute a bucket's aggregate/aux arrays after its items or
        item_weights changed (builder.c crush_bucket_adjust/remove paths)."""
        if b.alg == CRUSH_BUCKET_STRAW:
            self._calc_straws(b)
            return
        if b.alg == CRUSH_BUCKET_UNIFORM:
            b.weight = (b.item_weight or 0) * len(b.items)
            return
        if b.item_weights is None and b.alg == CRUSH_BUCKET_TREE and \
                b.node_weights is not None:
            # golden dumps carry only the node table; recover the per-item
            # weights from the leaf nodes (leaves live at odd indices)
            b.item_weights = [b.node_weights[(i << 1) + 1]
                              for i in range(len(b.items))]
        if b.alg == CRUSH_BUCKET_LIST:
            acc, sums = 0, []
            for w in b.item_weights:
                acc += w
                sums.append(acc)
            b.sum_weights = sums
            b.weight = acc
        elif b.alg == CRUSH_BUCKET_TREE:
            self._build_tree(b)
        else:                       # straw2
            b.weight = sum(b.item_weights)

    def _ensure_item_weights(self, b: Bucket) -> None:
        """Tree buckets from golden dumps carry only the node table;
        recover per-item weights BEFORE any mutation touches them (a
        post-mutation recovery would read stale/misaligned leaves)."""
        if b.item_weights is None and b.alg == CRUSH_BUCKET_TREE and \
                b.node_weights is not None:
            b.item_weights = [b.node_weights[(i << 1) + 1]
                              for i in range(len(b.items))]

    def _propagate_weight(self, bucket_id: int) -> None:
        """Push a bucket's recomputed weight into its ancestors
        (CrushWrapper::adjust_item_weight's upward walk)."""
        cur = bucket_id
        while True:
            parent = self.parent_of(cur)
            if parent is None:
                return
            pb = self.buckets[parent]
            self._ensure_item_weights(pb)
            idx = pb.items.index(cur)
            if pb.item_weights is not None:
                pb.item_weights[idx] = self.buckets[cur].weight
            self._rebuild_bucket(pb)
            cur = parent

    def _check_no_cycle(self, item: int, bucket_id: int) -> None:
        """Attaching ``item`` under ``bucket_id`` must not close a loop
        (the reference's _search_item_exists/loop checks)."""
        if item >= 0:
            return
        cur = bucket_id
        while cur is not None:
            if cur == item:
                raise ValueError(
                    f"inserting {item} under {bucket_id} would create a "
                    f"bucket cycle")
            cur = self.parent_of(cur)

    def insert_item(self, item: int, weight: int, bucket_id: int) -> None:
        """Add a device/bucket to a bucket and reweight the ancestry
        (CrushWrapper::insert_item)."""
        b = self.buckets[bucket_id]
        if item in b.items:
            raise ValueError(f"item {item} already in bucket {bucket_id}")
        self._check_no_cycle(item, bucket_id)
        if b.alg == CRUSH_BUCKET_UNIFORM:
            # builder.c crush_bucket_add_item: uniform buckets reject a
            # mismatched weight (-EINVAL) instead of silently dropping it
            if b.items and int(weight) != (b.item_weight or 0):
                raise ValueError(
                    f"uniform bucket {bucket_id} holds items of weight "
                    f"{b.item_weight:#x}; cannot insert weight {weight:#x}")
            if not b.items:
                b.item_weight = int(weight)
            b.items.append(int(item))
        else:
            self._ensure_item_weights(b)
            b.items.append(int(item))
            b.item_weights.append(int(weight))
        self._rebuild_bucket(b)
        self._propagate_weight(bucket_id)
        if item >= 0:
            self.max_devices = max(self.max_devices, item + 1)

    def remove_item(self, item: int) -> None:
        """Detach an item from its parent(s) and reweight the ancestry
        (CrushWrapper::remove_item; buckets must be emptied first, like
        the reference's non-recursive remove).  A device is detached from
        EVERY containing bucket — real and per-class shadow clones alike
        — or a stale shadow entry would keep placing on it."""
        if item < 0 and item in self.buckets and self.buckets[item].items:
            raise ValueError(f"bucket {item} not empty; move or remove its "
                             f"items first")
        parents = [bid for bid, b in self.buckets.items()
                   if item in b.items]
        for parent in parents:
            pb = self.buckets[parent]
            self._ensure_item_weights(pb)
            idx = pb.items.index(item)
            pb.items.pop(idx)
            if pb.item_weights is not None:
                pb.item_weights.pop(idx)
            self._rebuild_bucket(pb)
            self._propagate_weight(parent)
        if item < 0:
            self.buckets.pop(item, None)
            for cb in self.class_bucket.values():
                for c, sid in list(cb.items()):
                    if sid == item:
                        del cb[c]
            self.class_bucket.pop(item, None)
        self.item_names.pop(item, None)
        self.device_classes.pop(item, None)

    def move_bucket(self, bucket_id: int, new_parent_id: int) -> None:
        """Re-home a bucket under a new parent, carrying its weight
        (CrushWrapper::move_bucket = detach + insert)."""
        if bucket_id not in self.buckets:
            raise ValueError(f"no bucket {bucket_id}")
        # cycle guard: the new parent must not live under the moved bucket
        cur = new_parent_id
        while cur is not None:
            if cur == bucket_id:
                raise ValueError("move would create a bucket cycle")
            cur = self.parent_of(cur)
        # validate the DESTINATION before detaching: a failed insert after
        # the detach would orphan the whole subtree
        w = self.buckets[bucket_id].weight
        dest = self.buckets[new_parent_id]
        if bucket_id in dest.items:
            raise ValueError(f"{bucket_id} already under {new_parent_id}")
        if dest.alg == CRUSH_BUCKET_UNIFORM and dest.items and \
                w != (dest.item_weight or 0):
            raise ValueError(
                f"uniform bucket {new_parent_id} holds items of weight "
                f"{dest.item_weight:#x}; cannot move in weight {w:#x}")
        parent = self.parent_of(bucket_id)
        if parent is not None:
            pb = self.buckets[parent]
            self._ensure_item_weights(pb)
            idx = pb.items.index(bucket_id)
            pb.items.pop(idx)
            if pb.item_weights is not None:
                pb.item_weights.pop(idx)
            self._rebuild_bucket(pb)
            self._propagate_weight(parent)
        self.insert_item(bucket_id, w, new_parent_id)

    def adjust_item_weight(self, item: int, weight: int) -> None:
        """Set an item's weight in its parent bucket and propagate the
        change to the root (CrushWrapper::adjust_item_weight)."""
        parent = self.parent_of(item)
        if parent is None:
            raise ValueError(f"item {item} has no parent bucket")
        pb = self.buckets[parent]
        self._ensure_item_weights(pb)
        idx = pb.items.index(item)
        if pb.alg == CRUSH_BUCKET_UNIFORM:
            pb.item_weight = int(weight)
        else:
            pb.item_weights[idx] = int(weight)
        self._rebuild_bucket(pb)
        self._propagate_weight(parent)

    def adjust_subtree_weight(self, bucket_id: int, device_weight: int
                              ) -> int:
        """Set EVERY device under ``bucket_id`` to ``device_weight`` and
        reweight the tree (CrushWrapper::adjust_subtree_weight — the
        ``crushtool --reweight-subtree`` operation).  Returns the number
        of devices changed."""
        changed = 0

        def walk(bid: int) -> None:
            nonlocal changed
            b = self.buckets[bid]
            for i, item in enumerate(b.items):
                if item >= 0:
                    if b.alg == CRUSH_BUCKET_UNIFORM:
                        b.item_weight = int(device_weight)
                    else:
                        b.item_weights[i] = int(device_weight)
                    changed += 1
                elif item in self.buckets:     # skip dangling references
                    walk(item)
                    if b.item_weights is not None:
                        b.item_weights[i] = self.buckets[item].weight
            self._rebuild_bucket(b)

        walk(bucket_id)
        self._propagate_weight(bucket_id)
        return changed

    def reweight(self) -> None:
        """Recompute every bucket weight bottom-up from the leaves
        (builder.c crush_reweight)."""
        done: set[int] = set()

        def walk(bid: int) -> None:
            if bid in done:
                return
            b = self.buckets[bid]
            for i, item in enumerate(b.items):
                if item < 0 and item in self.buckets:
                    walk(item)
                    if b.item_weights is not None:
                        b.item_weights[i] = self.buckets[item].weight
            self._rebuild_bucket(b)
            done.add(bid)

        for bid in self.buckets:
            walk(bid)

    def add_rule(self, steps: list[tuple[int, int, int]],
                 ruleno: int | None = None) -> int:
        if ruleno is None:
            ruleno = 0
            while ruleno in self.rules:
                ruleno += 1
        if ruleno in self.rules:
            raise ValueError(f"rule {ruleno} exists")
        self.rules[ruleno] = Rule(steps=[tuple(s) for s in steps],
                                  ruleno=ruleno)
        return ruleno

    def finalize(self) -> None:
        """Compute max_devices (builder.c crush_finalize)."""
        md = 0
        for b in self.buckets.values():
            for i in b.items:
                if i >= 0:
                    md = max(md, i + 1)
        self.max_devices = md

    # -- naming / convenience (CrushWrapper-shaped) ------------------------

    def set_type_name(self, type_id: int, name: str) -> None:
        self.type_names[type_id] = name

    def type_id(self, name: str) -> int:
        for t, n in self.type_names.items():
            if n == name:
                return t
        raise KeyError(f"unknown crush type {name}")

    def set_item_name(self, item: int, name: str) -> None:
        self.item_names[item] = name

    def item_id(self, name: str) -> int:
        for i, n in self.item_names.items():
            if n == name:
                return i
        raise KeyError(f"unknown crush item {name}")

    def device_weights(self) -> dict[int, int]:
        """Leaf item -> 16.16 weight from its containing bucket
        (CrushWrapper::get_item_weight semantics)."""
        out: dict[int, int] = {}
        for b in self.buckets.values():
            for i, item in enumerate(b.items):
                if item >= 0:
                    if b.item_weights is not None:
                        out[item] = b.item_weights[i]
                    elif b.item_weight is not None:
                        out[item] = b.item_weight
        return out

    def parent_of(self, item: int) -> int | None:
        """Containing bucket id (None at a root).  Devices live in BOTH
        the real hierarchy and any per-class shadow clones: the REAL
        parent wins, unless the queried item is itself a shadow bucket
        (whose parent is the enclosing shadow bucket)."""
        want_shadow = item < 0 and self.is_shadow(item)
        for bid, b in self.buckets.items():
            if item in b.items and self.is_shadow(bid) == want_shadow:
                return bid
        return None

    def get_full_location(self, item: int) -> dict[str, str]:
        """type-name -> bucket/item-name chain from item to root
        (CrushWrapper::get_full_location shape; feeds the failure
        reporter-subtree grouping, OSDMonitor.cc:2772-2820)."""
        loc: dict[str, str] = {}
        cur = item
        while True:
            parent = self.parent_of(cur)
            if parent is None:
                return loc
            b = self.buckets[parent]
            tname = self.type_names.get(b.type, str(b.type))
            loc[tname] = self.item_names.get(parent, str(parent))
            cur = parent

    # -- device-class shadow trees (CrushWrapper.cc:2648) ------------------

    def set_device_class(self, item: int, device_class: str) -> None:
        """Assign a device's class (CrushWrapper::update_device_class).
        Classes must be settled before shadow trees are cloned — a
        reassignment would leave existing clones stale, so it is refused
        (the reference rebuilds its shadow forest on the mon instead)."""
        if item < 0:
            raise ValueError("device classes apply to devices, not buckets")
        if any(self.class_bucket.values()):
            raise ValueError(
                "device classes are fixed once shadow trees exist; "
                "rebuild the map to reclassify")
        self.device_classes[item] = device_class

    def is_shadow(self, item: int) -> bool:
        """Shadow (per-class clone) buckets carry the intentionally
        invalid name '<orig>~<class>' (CrushWrapper::is_shadow_item,
        CrushWrapper.h:583)."""
        return "~" in self.item_names.get(item, "")

    def nonshadow_roots(self) -> list[int]:
        """Parentless buckets that are not per-class clones
        (CrushWrapper::find_nonshadow_roots, CrushWrapper.h:624)."""
        children = {i for b in self.buckets.values() for i in b.items
                    if i < 0}
        return sorted(b for b in self.buckets
                      if b not in children and not self.is_shadow(b))

    def device_class_clone(self, original_id: int,
                           device_class: str) -> int:
        """Clone ``original_id``'s subtree keeping only devices of
        ``device_class`` (CrushWrapper::device_class_clone,
        CrushWrapper.cc:2648 / CrushWrapper.h:1342).  The clone is named
        '<orig>~<class>' (invalid on purpose), registered in
        class_bucket, and carries per-class choose_args weight sets
        derived from the original's.  Idempotent per (bucket, class)."""
        existing = self.class_bucket.get(original_id, {}).get(device_class)
        if existing is not None:
            return existing
        name = self.item_names.get(original_id)
        if name is None:
            raise KeyError(f"bucket {original_id} has no name; "
                           f"name it before cloning per class")
        copy_name = f"{name}~{device_class}"
        for i, n in self.item_names.items():   # name_exists fast path
            if n == copy_name:
                self.class_bucket.setdefault(
                    original_id, {})[device_class] = i
                return i
        orig = self.buckets[original_id]
        self._ensure_item_weights(orig)
        items: list[int] = []
        weights: list[int] = []
        orig_pos: list[int] = []               # new item pos -> orig pos
        for i, item in enumerate(orig.items):
            if item >= 0:
                if self.device_classes.get(item) != device_class:
                    continue
                w = (orig.item_weights[i] if orig.item_weights is not None
                     else (orig.item_weight or 0))
            else:
                item = self.device_class_clone(item, device_class)
                w = self.buckets[item].weight
            items.append(item)
            weights.append(w)
            orig_pos.append(i)
        hint = self._shadow_id_hints.get((original_id, device_class))
        if orig.alg == CRUSH_BUCKET_UNIFORM:
            sid = self.add_bucket(orig.alg, orig.type, items, id=hint,
                                  uniform_weight=orig.item_weight)
        else:
            sid = self.add_bucket(orig.alg, orig.type, items, weights,
                                  id=hint)
        self.buckets[sid].hash = orig.hash
        self.item_names[sid] = copy_name
        self.class_bucket.setdefault(original_id, {})[device_class] = sid
        # per-class choose_args: device entries keep their original
        # positional weights; child-clone entries contribute the SUM of
        # their own cloned weight set per position (the reference's
        # cmap_item_weight bookkeeping, CrushWrapper.cc:2735-2773)
        for args in self.choose_args.values():
            oarg = args.get(original_id)
            ws = (oarg or {}).get("weight_set")
            if not ws:
                continue
            new_ws = []
            for s, row in enumerate(ws):
                new_row = []
                for p, item in zip(orig_pos, items):
                    if item >= 0:
                        new_row.append(row[p])
                    else:
                        carg = args.get(item)
                        cws = (carg or {}).get("weight_set")
                        new_row.append(sum(cws[s]) if cws
                                       else self.buckets[item].weight)
                new_ws.append(new_row)
            args[sid] = {"weight_set": new_ws}
        return sid

    def populate_classes(self) -> int:
        """Clone every non-shadow root for every device class in use
        (CrushWrapper::populate_classes, CrushWrapper.h:1350).  Returns
        the number of clones created."""
        classes = sorted(set(self.device_classes.values()))
        made = 0
        for root in self.nonshadow_roots():
            for c in classes:
                before = self.class_bucket.get(root, {}).get(c)
                if before is None:
                    self.device_class_clone(root, c)
                    made += 1
        return made

    def take_with_class(self, root_name: str, device_class: str) -> int:
        """Resolve 'take <root> class <c>' to the shadow bucket id,
        cloning on first use (what the reference's rule-creation paths do
        via class_bucket lookups)."""
        root = self.item_id(root_name)
        if not device_class:
            return root
        if device_class not in set(self.device_classes.values()):
            raise ValueError(
                f"device class {device_class!r} is not assigned to any "
                f"device (EINVAL, like CrushWrapper::add_simple_rule)")
        return self.device_class_clone(root, device_class)

    def add_simple_rule(self, name: str, root_name: str,
                        failure_domain: str, device_class: str = "",
                        mode: str = "firstn", num_rep: int = 0) -> int:
        """CrushWrapper::add_simple_rule semantics (CrushWrapper.h; used by
        ErasureCode::create_rule with mode='indep', ErasureCode.cc:64-83).
        With ``device_class`` the rule takes the per-class shadow tree."""
        root = self.take_with_class(root_name, device_class)
        steps = [(CRUSH_RULE_TAKE, root, 0)]
        if failure_domain == "osd" or failure_domain == "":
            op = (CRUSH_RULE_CHOOSE_INDEP if mode == "indep"
                  else CRUSH_RULE_CHOOSE_FIRSTN)
            steps.append((op, num_rep, 0))
        else:
            ftype = self.type_id(failure_domain)
            op = (CRUSH_RULE_CHOOSELEAF_INDEP if mode == "indep"
                  else CRUSH_RULE_CHOOSELEAF_FIRSTN)
            steps.append((op, num_rep, ftype))
        steps.append((CRUSH_RULE_EMIT, 0, 0))
        if name in self.rule_names:
            raise ValueError(f"rule {name!r} already exists")
        ruleno = self.add_rule(steps)
        self.rule_names[name] = ruleno
        return ruleno

    # -- (de)serialisation --------------------------------------------------

    @classmethod
    def from_dict(cls, d: dict) -> "CrushMap":
        m = cls(tunables=d.get("tunables"))
        for bd in d.get("buckets", []):
            b = Bucket(
                id=bd["id"], alg=bd["alg"], type=bd["type"],
                items=list(bd["items"]), weight=bd.get("weight", 0),
                item_weights=bd.get("item_weights"),
                sum_weights=bd.get("sum_weights"),
                item_weight=bd.get("item_weight"),
                num_nodes=bd.get("num_nodes"),
                node_weights=bd.get("node_weights"),
                straws=bd.get("straws"),
            )
            m.buckets[b.id] = b
        for rd in d.get("rules", []):
            m.rules[rd["ruleno"]] = Rule(
                steps=[tuple(s) for s in rd["steps"]], ruleno=rd["ruleno"],
                type=rd.get("type", 1), min_size=rd.get("min_size", 1),
                max_size=rd.get("max_size", 10))
        if "type_names" in d:
            m.type_names = {int(t): n for t, n in d["type_names"].items()}
        m.item_names = {int(i): n
                        for i, n in d.get("item_names", {}).items()}
        m.rule_names = dict(d.get("rule_names", {}))
        if d.get("device_classes"):
            m.device_classes = {int(i): c
                                for i, c in d["device_classes"].items()}
        if d.get("class_bucket"):
            m.class_bucket = {int(i): dict(cb)
                              for i, cb in d["class_bucket"].items()}
        for sid, args in d.get("choose_args", {}).items():
            m.choose_args[int(sid)] = {int(bid): arg
                                       for bid, arg in args.items()}
        m.max_devices = d.get("max_devices", 0)
        if not m.max_devices:
            m.finalize()
        return m

    def to_dict(self) -> dict:
        buckets = []
        for b in sorted(self.buckets.values(), key=lambda b: -b.id):
            bd = {"id": b.id, "alg": b.alg, "type": b.type,
                  "weight": b.weight, "size": b.size, "items": list(b.items)}
            for k in ("item_weights", "sum_weights", "item_weight",
                      "num_nodes", "node_weights", "straws"):
                v = getattr(b, k)
                if v is not None:
                    bd[k] = v
            buckets.append(bd)
        d = {
            "tunables": dict(self.tunables),
            "max_devices": self.max_devices,
            "buckets": buckets,
            "rules": [{"ruleno": r.ruleno, "type": r.type,
                       "min_size": r.min_size, "max_size": r.max_size,
                       "steps": [list(s) for s in r.steps]}
                      for r in sorted(self.rules.values(),
                                      key=lambda r: r.ruleno)],
            "type_names": {str(t): n for t, n in self.type_names.items()},
            "item_names": {str(i): n for i, n in self.item_names.items()},
            "rule_names": dict(self.rule_names),
        }
        if self.device_classes:
            d["device_classes"] = {str(i): c
                                   for i, c in self.device_classes.items()}
        if self.class_bucket:
            d["class_bucket"] = {str(i): dict(cb)
                                 for i, cb in self.class_bucket.items()}
        if self.choose_args:
            d["choose_args"] = {
                str(sid): {str(bid): arg for bid, arg in args.items()}
                for sid, args in self.choose_args.items()}
        return d
