"""Exact CRUSH rule interpreter (host reference implementation).

Behaviour-equal Python implementation of the reference placement engine
(reference: src/crush/mapper.c): the five bucket choose algorithms
(:105-384), probabilistic reweight rejection is_out (:424-438), depth-first
crush_choose_firstn with collision/local-retry logic (:460-651), the
breadth-first positionally-stable crush_choose_indep used by EC pools
(:652-847, leaves CRUSH_ITEM_NONE holes), and the crush_do_rule step
machine (:900-1105), including choose_args weight-set overrides for the
mgr balancer (:309-326).

Validated bit-for-bit against golden vectors produced by running the
reference C (tests/golden/crush_golden.json).  This is the oracle for the
vmapped JAX bulk mapper in jax_mapper.py.
"""
from __future__ import annotations

from .hash import crush_hash32_2, crush_hash32_3, crush_hash32_4
from .ln import crush_ln
from .map import (CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
                  CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM, CRUSH_ITEM_NONE,
                  CRUSH_ITEM_UNDEF, CRUSH_RULE_CHOOSELEAF_FIRSTN,
                  CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_CHOOSE_FIRSTN,
                  CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT, CRUSH_RULE_NOOP,
                  CRUSH_RULE_SET_CHOOSELEAF_STABLE,
                  CRUSH_RULE_SET_CHOOSELEAF_TRIES,
                  CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
                  CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
                  CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
                  CRUSH_RULE_SET_CHOOSE_TRIES, CRUSH_RULE_TAKE, CrushMap,
                  Bucket)

S64_MIN = -(1 << 63)


def _div64(a: int, b: int) -> int:
    """C-style signed 64-bit division (truncation toward zero)."""
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


class _Work:
    """Per-bucket permutation state (mapper.c crush_work_bucket)."""
    __slots__ = ("perm_x", "perm_n", "perm")

    def __init__(self, size: int):
        self.perm_x = 0
        self.perm_n = 0
        self.perm = [0] * size


class Workspace:
    def __init__(self, cmap: CrushMap):
        self.work = {bid: _Work(b.size) for bid, b in cmap.buckets.items()}


# -- bucket choose methods --------------------------------------------------

def bucket_perm_choose(b: Bucket, work: _Work, x: int, r: int) -> int:
    """Random-permutation choose (mapper.c:73-131), used by uniform buckets
    and the exhaustive local-fallback search."""
    pr = r % b.size
    if work.perm_x != (x & 0xFFFFFFFF) or work.perm_n == 0:
        work.perm_x = x & 0xFFFFFFFF
        if pr == 0:
            s = crush_hash32_3(x, b.id & 0xFFFFFFFF, 0) % b.size
            work.perm[0] = s
            work.perm_n = 0xFFFF
            return b.items[s]
        work.perm = list(range(b.size))
        work.perm_n = 0
    elif work.perm_n == 0xFFFF:
        work.perm[1:] = [i for i in range(1, b.size)]
        work.perm[work.perm[0]] = 0
        work.perm_n = 1
    while work.perm_n <= pr:
        p = work.perm_n
        if p < b.size - 1:
            i = crush_hash32_3(x, b.id & 0xFFFFFFFF, p) % (b.size - p)
            if i:
                work.perm[p + i], work.perm[p] = work.perm[p], work.perm[p + i]
        work.perm_n += 1
    return b.items[work.perm[pr]]


def bucket_list_choose(b: Bucket, x: int, r: int) -> int:
    """(mapper.c:139-163): walk tail to head, hash-scaled cumulative weight."""
    for i in range(b.size - 1, -1, -1):
        w = crush_hash32_4(x, b.items[i] & 0xFFFFFFFF, r, b.id & 0xFFFFFFFF)
        w &= 0xFFFF
        w = (w * b.sum_weights[i]) >> 16
        if w < b.item_weights[i]:
            return b.items[i]
    return b.items[0]


def bucket_tree_choose(b: Bucket, x: int, r: int) -> int:
    """(mapper.c:166-226): descend the implicit binary tree by hashed weight."""

    def height(n: int) -> int:
        h = 0
        while (n & 1) == 0:
            h += 1
            n >>= 1
        return h

    n = b.num_nodes >> 1
    while not (n & 1):
        w = b.node_weights[n]
        t = (crush_hash32_4(x, n, r, b.id & 0xFFFFFFFF) * w) >> 32
        left = n - (1 << (height(n) - 1))
        if t < b.node_weights[left]:
            n = left
        else:
            n = n + (1 << (height(n) - 1))
    return b.items[n >> 1]


def bucket_straw_choose(b: Bucket, x: int, r: int) -> int:
    """straw v1 (mapper.c:231-245): scaled-straw argmax."""
    high, high_draw = 0, 0
    for i in range(b.size):
        draw = crush_hash32_3(x, b.items[i] & 0xFFFFFFFF, r) & 0xFFFF
        draw *= b.straws[i]
        if i == 0 or draw > high_draw:
            high, high_draw = i, draw
    return b.items[high]


def _straw2_weights_ids(b: Bucket, arg, position: int):
    """choose_args overrides (mapper.c:309-326)."""
    weights = b.item_weights
    ids = b.items
    if arg is not None:
        ws = arg.get("weight_set")
        if ws:
            pos = min(position, len(ws) - 1)
            weights = ws[pos]
        if arg.get("ids"):
            ids = arg["ids"]
    return weights, ids


def bucket_straw2_choose(b: Bucket, x: int, r: int, arg=None,
                         position: int = 0) -> int:
    """straw2 (mapper.c:334-384): exponential-draw argmax; draws are
    crush_ln(hash16) - 2^48 divided by the 16.16 weight."""
    weights, ids = _straw2_weights_ids(b, arg, position)
    high, high_draw = 0, 0
    for i in range(b.size):
        if weights[i]:
            u = crush_hash32_3(x, ids[i] & 0xFFFFFFFF, r) & 0xFFFF
            ln = crush_ln(u) - 0x1000000000000
            draw = _div64(ln, weights[i])
        else:
            draw = S64_MIN
        if i == 0 or draw > high_draw:
            high, high_draw = i, draw
    return b.items[high]


def crush_bucket_choose(b: Bucket, work: _Work, x: int, r: int,
                        arg=None, position: int = 0) -> int:
    """(mapper.c:387-418)"""
    assert b.size > 0
    if b.alg == CRUSH_BUCKET_UNIFORM:
        return bucket_perm_choose(b, work, x, r)
    if b.alg == CRUSH_BUCKET_LIST:
        return bucket_list_choose(b, x, r)
    if b.alg == CRUSH_BUCKET_TREE:
        return bucket_tree_choose(b, x, r)
    if b.alg == CRUSH_BUCKET_STRAW:
        return bucket_straw_choose(b, x, r)
    if b.alg == CRUSH_BUCKET_STRAW2:
        return bucket_straw2_choose(b, x, r, arg, position)
    return b.items[0]


def is_out(weights: list[int], weight_max: int, item: int, x: int) -> bool:
    """Probabilistic reweight rejection (mapper.c:424-438)."""
    if item >= weight_max:
        return True
    w = weights[item]
    if w >= 0x10000:
        return False
    if w == 0:
        return True
    return (crush_hash32_2(x, item) & 0xFFFF) >= w


# -- choose_firstn / choose_indep -------------------------------------------

def crush_choose_firstn(cmap: CrushMap, ws: Workspace, bucket: Bucket,
                        weights, weight_max, x, numrep, type, out, outpos,
                        out_size, tries, recurse_tries, local_retries,
                        local_fallback_retries, recurse_to_leaf, vary_r,
                        stable, out2, parent_r, choose_args) -> int:
    """Depth-first replica selection (mapper.c:460-651)."""
    count = out_size
    rep = 0 if stable else outpos
    while rep < numrep and count > 0:
        ftotal = 0
        skip_rep = False
        retry_descent = True
        while retry_descent:
            retry_descent = False
            in_b = bucket
            flocal = 0
            retry_bucket = True
            while retry_bucket:
                retry_bucket = False
                r = rep + parent_r + ftotal
                if in_b.size == 0:
                    reject = True
                    collide = False
                    item = 0
                else:
                    if (local_fallback_retries > 0 and
                            flocal >= (in_b.size >> 1) and
                            flocal > local_fallback_retries):
                        item = bucket_perm_choose(in_b, ws.work[in_b.id], x, r)
                    else:
                        arg = choose_args.get(in_b.id) if choose_args else None
                        item = crush_bucket_choose(in_b, ws.work[in_b.id], x, r,
                                                   arg, outpos)
                    if item >= cmap.max_devices:
                        skip_rep = True
                        break
                    if item < 0 and item not in cmap.buckets:
                        # dangling bucket reference (mapper.c bad-id guard)
                        skip_rep = True
                        break
                    itemtype = cmap.buckets[item].type if item < 0 else 0
                    if itemtype != type:
                        if item >= 0:
                            skip_rep = True
                            break
                        in_b = cmap.buckets[item]
                        retry_bucket = True
                        continue
                    collide = any(out[i] == item for i in range(outpos))
                    reject = False
                    if not collide and recurse_to_leaf:
                        if item < 0:
                            sub_r = (r >> (vary_r - 1)) if vary_r else 0
                            got = crush_choose_firstn(
                                cmap, ws, cmap.buckets[item], weights,
                                weight_max, x, 1 if stable else outpos + 1, 0,
                                out2, outpos, count, recurse_tries, 0,
                                local_retries, local_fallback_retries, False,
                                vary_r, stable, None, sub_r, choose_args)
                            if got <= outpos:
                                reject = True
                        else:
                            out2[outpos] = item
                    if not reject and not collide and itemtype == 0:
                        reject = is_out(weights, weight_max, item, x)
                if reject or collide:
                    ftotal += 1
                    flocal += 1
                    if collide and flocal <= local_retries:
                        retry_bucket = True
                    elif (local_fallback_retries > 0 and
                          flocal <= in_b.size + local_fallback_retries):
                        retry_bucket = True
                    elif ftotal < tries:
                        retry_descent = True
                    else:
                        skip_rep = True
                    if not retry_bucket:
                        break
        if not skip_rep:
            out[outpos] = item
            outpos += 1
            count -= 1
        rep += 1
    return outpos


def crush_choose_indep(cmap: CrushMap, ws: Workspace, bucket: Bucket,
                       weights, weight_max, x, left, numrep, type, out,
                       outpos, tries, recurse_tries, recurse_to_leaf, out2,
                       parent_r, choose_args) -> None:
    """Breadth-first positionally-stable selection (mapper.c:658-847)."""
    endpos = outpos + left
    for rep in range(outpos, endpos):
        out[rep] = CRUSH_ITEM_UNDEF
        if out2 is not None:
            out2[rep] = CRUSH_ITEM_UNDEF
    ftotal = 0
    while left > 0 and ftotal < tries:
        for rep in range(outpos, endpos):
            if out[rep] != CRUSH_ITEM_UNDEF:
                continue
            in_b = bucket
            while True:
                r = rep + parent_r
                if (in_b.alg == CRUSH_BUCKET_UNIFORM and
                        in_b.size % numrep == 0):
                    r += (numrep + 1) * ftotal
                else:
                    r += numrep * ftotal
                if in_b.size == 0:
                    break
                arg = choose_args.get(in_b.id) if choose_args else None
                item = crush_bucket_choose(in_b, ws.work[in_b.id], x, r,
                                           arg, outpos)
                if item >= cmap.max_devices:
                    out[rep] = CRUSH_ITEM_NONE
                    if out2 is not None:
                        out2[rep] = CRUSH_ITEM_NONE
                    left -= 1
                    break
                if item < 0 and item not in cmap.buckets:
                    # dangling bucket reference (mapper.c bad-id guard)
                    out[rep] = CRUSH_ITEM_NONE
                    if out2 is not None:
                        out2[rep] = CRUSH_ITEM_NONE
                    left -= 1
                    break
                itemtype = cmap.buckets[item].type if item < 0 else 0
                if itemtype != type:
                    if item >= 0:
                        out[rep] = CRUSH_ITEM_NONE
                        if out2 is not None:
                            out2[rep] = CRUSH_ITEM_NONE
                        left -= 1
                        break
                    in_b = cmap.buckets[item]
                    continue
                collide = any(out[i] == item for i in range(outpos, endpos))
                if collide:
                    break
                if recurse_to_leaf:
                    if item < 0:
                        crush_choose_indep(
                            cmap, ws, cmap.buckets[item], weights, weight_max,
                            x, 1, numrep, 0, out2, rep, recurse_tries, 0,
                            False, None, r, choose_args)
                        if out2 is not None and out2[rep] == CRUSH_ITEM_NONE:
                            break
                    elif out2 is not None:
                        out2[rep] = item
                if itemtype == 0 and is_out(weights, weight_max, item, x):
                    break
                out[rep] = item
                left -= 1
                break
        ftotal += 1
    for rep in range(outpos, endpos):
        if out[rep] == CRUSH_ITEM_UNDEF:
            out[rep] = CRUSH_ITEM_NONE
        if out2 is not None and out2[rep] == CRUSH_ITEM_UNDEF:
            out2[rep] = CRUSH_ITEM_NONE


# -- do_rule ---------------------------------------------------------------

def crush_do_rule(cmap: CrushMap, ruleno: int, x: int, result_max: int,
                  weights: list[int] | None = None,
                  choose_args: dict | None = None) -> list[int]:
    """The rule step machine (mapper.c:900-1105). Returns the result vector
    (EC rules contain CRUSH_ITEM_NONE holes)."""
    if ruleno not in cmap.rules:
        return []
    rule = cmap.rules[ruleno]
    if weights is None:
        weights = [0x10000] * cmap.max_devices
    weight_max = len(weights)
    ws = Workspace(cmap)

    t = cmap.tunables
    choose_tries = t["choose_total_tries"] + 1
    choose_leaf_tries = 0
    choose_local_retries = t["choose_local_tries"]
    choose_local_fallback_retries = t["choose_local_fallback_tries"]
    vary_r = t["chooseleaf_vary_r"]
    stable = t["chooseleaf_stable"]

    result: list[int] = []
    w: list[int] = []
    for op, arg1, arg2 in rule.steps:
        if op == CRUSH_RULE_TAKE:
            if (0 <= arg1 < cmap.max_devices) or arg1 in cmap.buckets:
                w = [arg1]
        elif op == CRUSH_RULE_SET_CHOOSE_TRIES:
            if arg1 > 0:
                choose_tries = arg1
        elif op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
            if arg1 > 0:
                choose_leaf_tries = arg1
        elif op == CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES:
            if arg1 >= 0:
                choose_local_retries = arg1
        elif op == CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
            if arg1 >= 0:
                choose_local_fallback_retries = arg1
        elif op == CRUSH_RULE_SET_CHOOSELEAF_VARY_R:
            if arg1 >= 0:
                vary_r = arg1
        elif op == CRUSH_RULE_SET_CHOOSELEAF_STABLE:
            if arg1 >= 0:
                stable = arg1
        elif op in (CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSELEAF_FIRSTN,
                    CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_CHOOSELEAF_INDEP):
            if not w:
                continue
            firstn = op in (CRUSH_RULE_CHOOSE_FIRSTN,
                            CRUSH_RULE_CHOOSELEAF_FIRSTN)
            recurse_to_leaf = op in (CRUSH_RULE_CHOOSELEAF_FIRSTN,
                                     CRUSH_RULE_CHOOSELEAF_INDEP)
            # the reference passes o+osize / c+osize as the per-take-item
            # output base (mapper.c:1040-1075), so collision scans stay
            # local to each take item; fresh sub-arrays mirror that.
            o: list[int] = []
            c: list[int] = []
            for wi in w:
                numrep = arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                if wi >= 0 or wi not in cmap.buckets:
                    continue
                bucket = cmap.buckets[wi]
                osize = len(o)
                sub_o = [0] * (result_max - osize)
                sub_c = [0] * (result_max - osize)
                if firstn:
                    if choose_leaf_tries:
                        recurse_tries = choose_leaf_tries
                    elif t["chooseleaf_descend_once"]:
                        recurse_tries = 1
                    else:
                        recurse_tries = choose_tries
                    got = crush_choose_firstn(
                        cmap, ws, bucket, weights, weight_max, x, numrep,
                        arg2, sub_o, 0, result_max - osize, choose_tries,
                        recurse_tries, choose_local_retries,
                        choose_local_fallback_retries, recurse_to_leaf,
                        vary_r, stable, sub_c, 0, choose_args)
                else:
                    got = min(numrep, result_max - osize)
                    crush_choose_indep(
                        cmap, ws, bucket, weights, weight_max, x, got,
                        numrep, arg2, sub_o, 0, choose_tries,
                        choose_leaf_tries if choose_leaf_tries else 1,
                        recurse_to_leaf, sub_c, 0, choose_args)
                o.extend(sub_o[:got])
                c.extend(sub_c[:got])
            w = c if recurse_to_leaf else o
        elif op == CRUSH_RULE_EMIT:
            for item in w:
                if len(result) < result_max:
                    result.append(item)
            w = []
        elif op == CRUSH_RULE_NOOP:
            pass
    return result
