"""Crushmap text-format compiler/decompiler.

Analog of the reference's CrushCompiler (reference:
src/crush/CrushCompiler.{h,cc} — the ``crushtool -d``/``-c`` text format),
re-expressed as a tokenizer + recursive-descent parser over this
framework's :class:`~ceph_tpu.crush.map.CrushMap`.  Format mirrored
line-for-line from the reference's decompile output
(CrushCompiler.cc:299-470):

- ``tunable <name> <value>`` lines;
- ``device <id> <name> [class <c>]``;
- ``type <id> <name>``;
- bucket blocks ``<typename> <name> { id -N; alg straw2; hash 0;
  item <name> weight <w> [pos <p>]; ... }`` with 16.16 weights printed as
  3-decimal floats (CrushCompiler.cc:85-90 print_fixedpoint — the text
  format is deliberately lossy below 0.001, exactly like the reference);
- rule blocks ``rule <name> { id N; type replicated|erasure; min_size;
  max_size; step take <name>; step choose[leaf] firstn|indep N type <t>;
  step set_*; step emit }``;
- ``choose_args <id> { { bucket_id -N  weight_set [ [ ... ] ]
  ids [ ... ] } }`` blocks (CrushCompiler.cc:214-296).

``decompile(compile_crushmap(text))`` is idempotent on normalized text;
``compile_crushmap(decompile(m))`` reproduces ``m``'s placements exactly
for weights representable at 3 decimals.
"""
from __future__ import annotations

import re

from .map import (CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
                  CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM,
                  CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSELEAF_INDEP,
                  CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP,
                  CRUSH_RULE_EMIT, CRUSH_RULE_SET_CHOOSELEAF_STABLE,
                  CRUSH_RULE_SET_CHOOSELEAF_TRIES,
                  CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
                  CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
                  CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
                  CRUSH_RULE_SET_CHOOSE_TRIES, CRUSH_RULE_TAKE, CrushMap)

ALG_NAMES = {CRUSH_BUCKET_UNIFORM: "uniform", CRUSH_BUCKET_LIST: "list",
             CRUSH_BUCKET_TREE: "tree", CRUSH_BUCKET_STRAW: "straw",
             CRUSH_BUCKET_STRAW2: "straw2"}
ALG_IDS = {v: k for k, v in ALG_NAMES.items()}

RULE_TYPE_NAMES = {1: "replicated", 3: "erasure"}
RULE_TYPE_IDS = {v: k for k, v in RULE_TYPE_NAMES.items()}

SET_STEPS = {
    "set_choose_tries": CRUSH_RULE_SET_CHOOSE_TRIES,
    "set_choose_local_tries": CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
    "set_choose_local_fallback_tries":
        CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    "set_chooseleaf_tries": CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    "set_chooseleaf_vary_r": CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
    "set_chooseleaf_stable": CRUSH_RULE_SET_CHOOSELEAF_STABLE,
}
SET_STEP_NAMES = {v: k for k, v in SET_STEPS.items()}

CHOOSE_STEPS = {
    ("choose", "firstn"): CRUSH_RULE_CHOOSE_FIRSTN,
    ("choose", "indep"): CRUSH_RULE_CHOOSE_INDEP,
    ("chooseleaf", "firstn"): CRUSH_RULE_CHOOSELEAF_FIRSTN,
    ("chooseleaf", "indep"): CRUSH_RULE_CHOOSELEAF_INDEP,
}
CHOOSE_STEP_NAMES = {v: k for k, v in CHOOSE_STEPS.items()}

TUNABLE_ORDER = ["choose_local_tries", "choose_local_fallback_tries",
                 "choose_total_tries", "chooseleaf_descend_once",
                 "chooseleaf_vary_r", "chooseleaf_stable"]


def _fixed(w: int) -> str:
    """16.16 -> text (print_fixedpoint, CrushCompiler.cc:85-90)."""
    return f"{w / 0x10000:.3f}"


def _unfixed(s: str) -> int:
    return int(round(float(s) * 0x10000))


# -- decompile (CrushCompiler.cc:299-470) -------------------------------------

def _item_name(m: CrushMap, item: int) -> str:
    name = m.item_names.get(item)
    if name:
        return name
    return f"osd.{item}" if item >= 0 else f"bucket{-1 - item}"


def decompile(m: CrushMap) -> str:
    # straw(v1) buckets round-trip because compile rebuilds their straw
    # lengths via crush_calc_straw parity — but ONLY under the same
    # straw_calc_version.  Loaded reference dumps carry straws as data
    # without the tunable (crush_create defaults to v0, builder.c:1506),
    # so detect which version reproduces the stored straws and pin it in
    # the emitted tunables; refuse if neither does (silent placement
    # divergence otherwise — the v0/v1 split shows on repeated weights).
    tunables = dict(m.tunables)
    straw_buckets = [b for b in m.buckets.values()
                     if b.alg == CRUSH_BUCKET_STRAW and b.straws]
    if straw_buckets:
        from .map import calc_straw_lengths
        declared = tunables.get("straw_calc_version")
        candidates = [int(declared)] if declared is not None else [1, 0]
        scv = next(
            (v for v in candidates
             if all(b.item_weights is not None and
                    b.straws == calc_straw_lengths(b.item_weights, v)
                    for b in straw_buckets)), None)
        if scv is None:
            raise ValueError(
                "straw(v1) straw lengths match no straw_calc_version; "
                "the text form cannot reproduce them — convert to straw2")
        tunables["straw_calc_version"] = scv
    out = ["# begin crush map"]
    for t in TUNABLE_ORDER:
        out.append(f"tunable {t} {int(tunables[t])}")
    for t in sorted(set(tunables) - set(TUNABLE_ORDER)):
        out.append(f"tunable {t} {int(tunables[t])}")

    out.append("")
    out.append("# devices")
    classes = m.device_classes
    devices = {i for b in m.buckets.values() for i in b.items if i >= 0}
    devices |= {d for d in m.item_names if d >= 0}
    # placeholder names keep max_devices stable across the round trip
    # (unreferenced slots would otherwise vanish and renumber weights)
    devices |= set(range(m.max_devices))
    for i in sorted(devices):
        line = f"device {i} {_item_name(m, i)}" if i in m.item_names or \
            any(i in b.items for b in m.buckets.values()) else \
            f"device {i} device{i}"
        if i in classes:
            line += f" class {classes[i]}"
        out.append(line)

    out.append("")
    out.append("# types")
    used_types = {b.type for b in m.buckets.values()}
    type_names = dict(m.type_names)
    for t in used_types - set(type_names):
        type_names[t] = f"type{t}"       # unnamed type: synthesize so the
    for t in sorted(type_names):         # text recompiles
        out.append(f"type {t} {type_names[t]}")

    out.append("")
    out.append("# buckets")
    # the reference walks ids from -1 downward (CrushCompiler.cc:345);
    # emit children before parents so the text compiles in one pass
    emitted: set[int] = set()

    def emit_bucket(bid: int) -> None:
        if bid in emitted:
            return
        b = m.buckets[bid]
        for item in b.items:
            if item < 0 and item in m.buckets:
                emit_bucket(item)
        emitted.add(bid)
        tname = m.type_names.get(b.type, f"type{b.type}")
        out.append(f"{tname} {_item_name(m, bid)} {{")
        out.append(f"\tid {bid}\t\t# do not change unnecessarily")
        # per-class shadow ids (CrushCompiler.cc decompile_bucket: the
        # clones themselves are not dumped; their ids are recorded here
        # so a recompile reuses them)
        for c, sid in sorted(m.class_bucket.get(bid, {}).items()):
            out.append(f"\tid {sid} class {c}\t\t# do not change "
                       f"unnecessarily")
        out.append(f"\t# weight {_fixed(b.weight)}")
        out.append(f"\talg {ALG_NAMES[b.alg]}")
        out.append(f"\thash {b.hash}\t# rjenkins1")
        for j, item in enumerate(b.items):
            if b.alg == CRUSH_BUCKET_UNIFORM:
                w = b.item_weight or 0
            else:
                w = (b.item_weights or [0] * b.size)[j]
            out.append(f"\titem {_item_name(m, item)} weight {_fixed(w)}")
        out.append("}")

    for bid in sorted(m.buckets, reverse=True):     # -1, -2, ...
        if not m.is_shadow(bid):      # shadow trees rebuild on compile
            emit_bucket(bid)

    out.append("")
    out.append("# rules")
    name_of_rule = {v: k for k, v in m.rule_names.items()}
    shadow_of = {sid: (orig, c)
                 for orig, cb in m.class_bucket.items()
                 for c, sid in cb.items()}
    for ruleno in sorted(m.rules):
        rule = m.rules[ruleno]
        rname = name_of_rule.get(ruleno, f"rule{ruleno}")
        out.append(f"rule {rname} {{")
        out.append(f"\tid {ruleno}")
        rtype = getattr(rule, "type", 1)
        out.append(f"\ttype {RULE_TYPE_NAMES.get(rtype, str(rtype))}")
        out.append(f"\tmin_size {getattr(rule, 'min_size', 1)}")
        out.append(f"\tmax_size {getattr(rule, 'max_size', 10)}")
        for op, arg1, arg2 in rule.steps:
            if op == CRUSH_RULE_TAKE:
                if arg1 in shadow_of:
                    orig, c = shadow_of[arg1]
                    out.append(f"\tstep take {_item_name(m, orig)} "
                               f"class {c}")
                else:
                    out.append(f"\tstep take {_item_name(m, arg1)}")
            elif op == CRUSH_RULE_EMIT:
                out.append("\tstep emit")
            elif op in SET_STEP_NAMES:
                out.append(f"\tstep {SET_STEP_NAMES[op]} {arg1}")
            elif op in CHOOSE_STEP_NAMES:
                verb, mode = CHOOSE_STEP_NAMES[op]
                tname = m.type_names.get(arg2, str(arg2))
                out.append(f"\tstep {verb} {mode} {arg1} type {tname}")
            else:
                raise ValueError(f"cannot decompile step op {op}")
        out.append("}")

    if m.choose_args:
        out.append("")
        out.append("# choose_args")
        for set_id in sorted(m.choose_args):
            out.append(f"choose_args {set_id} {{")
            args = m.choose_args[set_id]
            for bid in sorted(args, reverse=True):
                arg = args[bid]
                out.append("  {")
                out.append(f"    bucket_id {bid}")
                wset = arg.get("weight_set")
                if wset:
                    out.append("    weight_set [")
                    for row in wset:
                        out.append("      [ " +
                                   " ".join(_fixed(w) for w in row) + " ]")
                    out.append("    ]")
                if arg.get("ids"):
                    out.append("    ids [ " +
                               " ".join(str(i) for i in arg["ids"]) + " ]")
                out.append("  }")
            out.append("}")

    out.append("")
    out.append("# end crush map")
    return "\n".join(out) + "\n"


# -- compile ------------------------------------------------------------------

_TOKEN = re.compile(r"[{}\[\]]|[^\s{}\[\]]+")


def _tokenize(text: str) -> list[str]:
    toks = []
    for line in text.splitlines():
        line = line.split("#", 1)[0]
        toks.extend(_TOKEN.findall(line))
    return toks


class _Parser:
    def __init__(self, toks: list[str]):
        self.toks = toks
        self.i = 0

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> str:
        if self.i >= len(self.toks):
            raise ValueError("unexpected end of crushmap text")
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, tok: str) -> None:
        got = self.next()
        if got != tok:
            raise ValueError(f"expected {tok!r}, got {got!r} "
                             f"(token {self.i - 1})")


def compile_crushmap(text: str) -> CrushMap:
    """Parse crushmap text into a CrushMap (CrushCompiler parse_* shape)."""
    p = _Parser(_tokenize(text))
    m = CrushMap()
    m.type_names = {}
    m.device_classes = {}
    name_to_id: dict[str, int] = {}
    next_auto_id = -1
    max_device_line = 0       # device lines pin max_devices (holes incl.)
    while p.peek() is not None:
        tok = p.next()
        if tok == "tunable":
            name, val = p.next(), int(p.next())
            m.tunables[name] = val
        elif tok == "device":
            dev_id = int(p.next())
            name = p.next()
            name_to_id[name] = dev_id
            max_device_line = max(max_device_line, dev_id + 1)
            if not re.fullmatch(r"device\d+", name):
                m.item_names[dev_id] = name
            if p.peek() == "class":
                p.next()
                m.device_classes[dev_id] = p.next()
        elif tok == "type":
            tid = int(p.next())
            m.type_names[tid] = p.next()
        elif tok == "rule":
            _parse_rule(p, m, name_to_id)
        elif tok == "choose_args":
            _parse_choose_args(p, m, name_to_id)
        elif tok in m.type_names.values():
            next_auto_id = _parse_bucket(p, m, tok, name_to_id, next_auto_id)
        else:
            raise ValueError(f"unexpected token {tok!r}")
    # materialize any reserved shadow trees no rule referenced, so the
    # class_bucket table (and its ids) survives the round trip
    for (bid, cls) in list(m._shadow_id_hints):
        if bid in m.buckets:
            m.device_class_clone(bid, cls)
    m.finalize()
    m.max_devices = max(m.max_devices, max_device_line)
    return m


def _parse_bucket(p: _Parser, m: CrushMap, tname: str, name_to_id,
                  next_auto_id: int) -> int:
    bname = p.next()
    p.expect("{")
    bid = None
    alg = CRUSH_BUCKET_STRAW2
    hash_ = 0
    items: list[int] = []
    weights: list[int] = []
    class_ids: list[tuple[int, str]] = []   # (shadow id, class) lines
    while True:
        tok = p.next()
        if tok == "}":
            break
        if tok == "id":
            val = int(p.next())
            if p.peek() == "class":       # per-class shadow id
                p.next()
                class_ids.append((val, p.next()))
            else:
                bid = val
        elif tok == "alg":
            alg = ALG_IDS[p.next()]
        elif tok == "hash":
            hash_ = int(p.next())
        elif tok == "item":
            iname = p.next()
            w = 0
            pos = len(items)
            while p.peek() in ("weight", "pos"):
                what = p.next()
                if what == "weight":
                    w = _unfixed(p.next())
                else:
                    pos = int(p.next())
            while len(items) <= pos:
                items.append(None)
                weights.append(0)
            items[pos] = item_by_name_or_fail(iname, name_to_id)
            weights[pos] = w
        else:
            raise ValueError(f"unexpected token {tok!r} in bucket {bname!r}")
    if any(i is None for i in items):
        raise ValueError(f"bucket {bname!r} has item position holes")
    if bid is None:
        while next_auto_id in m.buckets:
            next_auto_id -= 1
        bid = next_auto_id
        next_auto_id -= 1
    type_id = {v: k for k, v in m.type_names.items()}[tname]
    if alg == CRUSH_BUCKET_UNIFORM:
        uw = weights[0] if weights else 0
        m.add_bucket(alg, type_id, items, id=bid, uniform_weight=uw)
    else:
        m.add_bucket(alg, type_id, items, weights, id=bid)
    m.buckets[bid].hash = hash_
    m.set_item_name(bid, bname)
    name_to_id[bname] = bid
    for sid, cls in class_ids:
        # reserve the dumped shadow id; the clone itself is rebuilt once
        # every bucket is parsed (CrushWrapper::populate_classes with
        # old_class_bucket id reuse)
        m._shadow_id_hints[(bid, cls)] = sid
    return next_auto_id


def item_by_name_or_fail(name: str, name_to_id: dict) -> int:
    if name in name_to_id:
        return name_to_id[name]
    if re.fullmatch(r"osd\.\d+", name):
        return int(name.split(".")[1])
    raise ValueError(f"unknown item {name!r} (define it first)")


def _parse_rule(p: _Parser, m: CrushMap, name_to_id) -> None:
    rname = p.next()
    p.expect("{")
    ruleno = None
    rtype = 1
    min_size, max_size = 1, 10
    steps: list[tuple[int, int, int]] = []
    type_ids = {v: k for k, v in m.type_names.items()}
    while True:
        tok = p.next()
        if tok == "}":
            break
        if tok == "id" or tok == "ruleset":
            ruleno = int(p.next())
        elif tok == "type":
            t = p.next()
            rtype = RULE_TYPE_IDS.get(t, None)
            if rtype is None:
                rtype = int(t)
        elif tok == "min_size":
            min_size = int(p.next())
        elif tok == "max_size":
            max_size = int(p.next())
        elif tok == "step":
            verb = p.next()
            if verb == "take":
                name = p.next()
                item = item_by_name_or_fail(name, name_to_id)
                if p.peek() == "class":
                    p.next()
                    cls = p.next()
                    if item >= 0:
                        raise ValueError(
                            f"step take {name} class {cls}: class takes "
                            f"need a bucket, not a device")
                    if cls not in set(m.device_classes.values()):
                        # the reference compiler rejects unknown classes
                        # at compile time (a typo would otherwise build
                        # an empty shadow tree that maps only holes)
                        raise ValueError(
                            f"step take {name} class {cls}: device class "
                            f"{cls!r} is not assigned to any device")
                    item = m.device_class_clone(item, cls)
                steps.append((CRUSH_RULE_TAKE, item, 0))
            elif verb == "emit":
                steps.append((CRUSH_RULE_EMIT, 0, 0))
            elif verb in ("choose", "chooseleaf"):
                mode = p.next()
                n = int(p.next())
                p.expect("type")
                t = p.next()
                ttype = type_ids[t] if t in type_ids else int(t)
                steps.append((CHOOSE_STEPS[(verb, mode)], n, ttype))
            elif verb in SET_STEPS:
                steps.append((SET_STEPS[verb], int(p.next()), 0))
            else:
                raise ValueError(f"unknown rule step {verb!r}")
        else:
            raise ValueError(f"unexpected token {tok!r} in rule {rname!r}")
    ruleno = m.add_rule(steps, ruleno=ruleno)
    rule = m.rules[ruleno]
    rule.type = rtype
    rule.min_size = min_size
    rule.max_size = max_size
    m.rule_names[rname] = ruleno


def _parse_choose_args(p: _Parser, m: CrushMap, name_to_id) -> None:
    set_id = int(p.next())
    p.expect("{")
    args: dict[int, dict] = {}
    while True:
        tok = p.next()
        if tok == "}":
            break
        if tok != "{":
            raise ValueError(f"expected {{ in choose_args, got {tok!r}")
        arg: dict = {}
        bid = None
        while True:
            t2 = p.next()
            if t2 == "}":
                break
            if t2 == "bucket_id":
                bid = int(p.next())
            elif t2 == "weight_set":
                p.expect("[")
                wset = []
                while p.peek() == "[":
                    p.next()
                    row = []
                    while p.peek() != "]":
                        row.append(_unfixed(p.next()))
                    p.next()
                    wset.append(row)
                p.expect("]")
                arg["weight_set"] = wset
            elif t2 == "ids":
                p.expect("[")
                ids = []
                while p.peek() != "]":
                    ids.append(int(p.next()))
                p.next()
                arg["ids"] = ids
            else:
                raise ValueError(f"unexpected {t2!r} in choose_args")
        if bid is None:
            raise ValueError("choose_args entry missing bucket_id")
        args[bid] = arg
    m.choose_args[set_id] = args
