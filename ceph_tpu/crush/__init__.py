from .hash import (crush_hash32, crush_hash32_2, crush_hash32_3,
                   crush_hash32_4, crush_hash32_5, crush_hash32_2_np,
                   crush_hash32_3_np, crush_hash32_2_jax, crush_hash32_3_jax)
from .ln import crush_ln, crush_ln_np, LN_TABLE
from .map import (CrushMap, Bucket, Rule, CRUSH_BUCKET_UNIFORM,
                  CRUSH_BUCKET_LIST, CRUSH_BUCKET_TREE, CRUSH_BUCKET_STRAW,
                  CRUSH_BUCKET_STRAW2, CRUSH_ITEM_NONE, CRUSH_ITEM_UNDEF,
                  CRUSH_RULE_TAKE, CRUSH_RULE_CHOOSE_FIRSTN,
                  CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_CHOOSELEAF_FIRSTN,
                  CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_EMIT,
                  OPTIMAL_TUNABLES, LEGACY_TUNABLES)
from .mapper import crush_do_rule, Workspace, is_out
from .compiler import compile_crushmap, decompile

__all__ = [
    "crush_hash32", "crush_hash32_2", "crush_hash32_3", "crush_hash32_4",
    "crush_hash32_5", "crush_hash32_2_np", "crush_hash32_3_np",
    "crush_hash32_2_jax", "crush_hash32_3_jax",
    "crush_ln", "crush_ln_np", "LN_TABLE",
    "CrushMap", "Bucket", "Rule", "CRUSH_BUCKET_UNIFORM", "CRUSH_BUCKET_LIST",
    "CRUSH_BUCKET_TREE", "CRUSH_BUCKET_STRAW", "CRUSH_BUCKET_STRAW2",
    "CRUSH_ITEM_NONE", "CRUSH_ITEM_UNDEF", "CRUSH_RULE_TAKE",
    "CRUSH_RULE_CHOOSE_FIRSTN", "CRUSH_RULE_CHOOSE_INDEP",
    "CRUSH_RULE_CHOOSELEAF_FIRSTN", "CRUSH_RULE_CHOOSELEAF_INDEP",
    "CRUSH_RULE_EMIT", "OPTIMAL_TUNABLES", "LEGACY_TUNABLES",
    "crush_do_rule", "Workspace", "is_out",
    "compile_crushmap", "decompile",
]
