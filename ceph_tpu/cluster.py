"""MiniCluster: an in-process cluster harness (the vstart.sh analog).

Mirror of the reference's dev-cluster workflow (reference: src/vstart.sh +
qa/standalone/ceph-helpers.sh run_osd/wait_for_clean;
qa/standalone/erasure-code/test-erasure-code.sh:21-66 creates an EC pool
over 11 OSDs and does put/get): builds a CRUSH tree + OSDMap, creates EC
pools from profiles (plugin factory + create_rule, the mon's pool-creation
path), places every PG via the OSDMap mapping chain, and instantiates one
EC group (primary ECBackend + shard OSDs on a message bus) per PG with the
acting set CRUSH chose.  Objects route to PGs with the librados placement
(ceph_str_hash_rjenkins + ceph_stable_mod).

Scope note: each PG gets its own MessageBus and per-PG shard stores (the
reference's OSD runs many PGs against one ObjectStore; here stores are
per-(PG, shard), which preserves all placement/EC semantics while keeping
PG pipelines independent — the same simplification MemStore-backed unit
tests make).
"""
from __future__ import annotations

import numpy as np

from .backend import (ECBackend, MessageBus, PGTransaction, ReplicatedBackend,
                      StripeInfo)
from .backend.ec_backend import OSDShard
from .common import Context, default_context
from .crush import (CRUSH_BUCKET_STRAW2, CRUSH_RULE_CHOOSELEAF_FIRSTN,
                    CRUSH_RULE_CHOOSELEAF_INDEP,
                    CRUSH_RULE_EMIT, CRUSH_RULE_TAKE, CrushMap)
from .osdmap import (OSDMap, PG, Pool, POOL_TYPE_ERASURE,
                     POOL_TYPE_REPLICATED, ceph_stable_mod)
from .osdmap.str_hash import ceph_str_hash_rjenkins
from .plugins.registry import ErasureCodePluginRegistry


import itertools

NONE_ID = 0x7FFFFFFF          # CRUSH_ITEM_NONE

_cluster_ids = itertools.count(1)


class BlockedWriteError(IOError):
    """A write parked on an inactive PG (< min_size current shards): it is
    queued — neither acked nor lost — and commits when shards return."""


class PGGroup:
    """One placement group: primary backend + shard OSDs.

    With ``bus`` (a cluster-wide MessageBus), the PG talks through a
    :class:`~ceph_tpu.backend.messages.PGChannel` — one endpoint per OSD
    on ONE shared bus, the reference's messenger topology.  Without it
    (standalone/unit use) the PG gets a private bus as before."""

    def __init__(self, pgid: PG, acting: list[int], ec_impl,
                 chunk_size: int, cct, name_prefix: str,
                 min_size: int = 0, store_factory=None, epoch: int = 0,
                 bus: MessageBus | None = None):
        self.pgid = pgid
        self.acting = acting
        # map epoch this acting set was established at: ops stamped with
        # an older epoch by a stale client get rejected (the OSD's
        # require_same_or_newer_map check, src/osd/OSD.cc)
        self.epoch = epoch
        if bus is None:
            self.bus = MessageBus()
        else:
            from .backend.messages import PGChannel
            self.bus = PGChannel(bus, f"{name_prefix}.{pgid}")
        primary = acting[0]
        mk = store_factory if store_factory is not None else lambda osd: None
        # name is unique across PGs sharing a primary AND across clusters
        # sharing a Context (salted with the cluster id)
        if ec_impl is None:       # replicated pool: full copies, no codec
            self.backend = ReplicatedBackend(
                len(acting), self.bus, acting=list(acting), whoami=primary,
                cct=cct, name=f"{name_prefix}.pg{pgid}", min_size=min_size,
                store=mk(primary))
        else:
            k = ec_impl.get_data_chunk_count()
            self.backend = ECBackend(
                ec_impl, StripeInfo(k, chunk_size), self.bus,
                acting=list(acting), whoami=primary, cct=cct,
                name=f"{name_prefix}.pg{pgid}", min_size=min_size,
                store=mk(primary))
        for osd in acting:
            if osd != primary:
                OSDShard(osd, self.bus, store=mk(osd))
        # the primary's object-op engine (PrimaryLogPG analog): executes
        # client op vectors atomically on top of the backend pipeline
        from .osd.primary_log_pg import PrimaryLogPG
        self.engine = PrimaryLogPG(
            self.backend, pool_type="replicated" if ec_impl is None else "ec")
        # the peering statechart (acting-set negotiation on map changes)
        from .osd.peering import PeeringCoordinator
        self.peering = PeeringCoordinator(self.backend)
        # admin-socket observability for the PG-level subsystems
        # (the reference's 'dump_watchers' and pg-state query commands)
        name = self.backend.instance_name
        for cmd, fn in (
                (f"dump_watchers.{name}",
                 lambda **kw: {oid: sorted(ws) for oid, ws in
                               self.engine.watchers.items() if ws}),
                (f"peering_history.{name}",
                 lambda **kw: {"state": self.peering.state.value,
                               "last_epoch_started":
                                   self.peering.last_epoch_started,
                               "history": list(self.peering.history)})):
            # names are unique (cluster-id + epoch salted), so a duplicate
            # registration is a LIFECYCLE BUG — let the guard raise
            cct.admin_socket.register(cmd, fn)

    def shutdown(self, discard_stores: bool = False) -> None:
        # closes the primary's store too; discard skips the final
        # checkpoint when the directories are about to be deleted.
        # (Collections over a shared per-OSD store close as no-ops — the
        # daemon owns that store's lifecycle.)
        name = self.backend.instance_name
        for cmd in (f"dump_watchers.{name}", f"peering_history.{name}"):
            self.backend.cct.admin_socket.unregister(cmd)
        self.backend.shutdown(checkpoint_store=not discard_stores)
        for h in self.bus.handlers.values():
            if isinstance(h, OSDShard) and h is not self.backend.local_shard \
                    and hasattr(h.store, "close"):
                h.store.close(checkpoint=not discard_stores)
        if hasattr(self.bus, "unregister_all"):
            self.bus.unregister_all()


class MiniCluster:
    def __init__(self, n_osds: int = 12, osds_per_host: int = 3,
                 chunk_size: int = 4096, cct: Context | None = None,
                 data_dir=None, store_backend: str = "file"):
        self.cct = cct if cct is not None else default_context()
        self.chunk_size = chunk_size
        self.n_osds = n_osds
        self.osds_per_host = osds_per_host
        # durable-store flavour: "file" (FileStore WAL+snapshot) or
        # "bluestore" (extent allocator, checksums at rest, compression)
        if store_backend not in ("file", "bluestore"):
            raise ValueError(f"unknown store_backend {store_backend!r} "
                             f"(choose 'file' or 'bluestore')")
        self.store_backend = store_backend
        # durable mode: every shard store is a FileStore under
        # data_dir/osd.<id>/pg.<pool>.<ps>/ and cluster metadata persists
        # to cluster_meta.pkl — MiniCluster.load() reopens the whole thing
        from pathlib import Path
        self.data_dir = Path(data_dir) if data_dir is not None else None
        self.cluster_id = next(_cluster_ids)
        cmap = CrushMap()
        cmap.set_type_name(1, "host")
        cmap.set_type_name(2, "root")
        hosts = []
        for h0 in range(0, n_osds, osds_per_host):
            items = list(range(h0, min(h0 + osds_per_host, n_osds)))
            hb = cmap.add_bucket(
                CRUSH_BUCKET_STRAW2, 1, items, [0x10000] * len(items))
            cmap.set_item_name(hb, f"host{len(hosts)}")
            hosts.append(hb)
        root = cmap.add_bucket(
            CRUSH_BUCKET_STRAW2, 2, hosts,
            [sum(cmap.buckets[h].item_weights) for h in hosts])
        cmap.set_item_name(root, "default")
        cmap.finalize()
        self.osdmap = OSDMap(crush=cmap)
        for o in range(n_osds):
            self.osdmap.create_osd(o)
        self._next_pool = 1
        self.pools: dict[int, dict] = {}       # pool_id -> {pgs, pool, ec}
        self.pool_ids: dict[str, int] = {}
        self.objects: dict[int, set[str]] = {}  # pool_id -> written oids
        # (oid, result, msg) from batched (deliver=False) op replies that
        # completed with an error AFTER their submit call returned — the
        # next deliver_all() surfaces them (raising from inside the
        # daemon drain would strand the rest of the queue)
        self._deferred_errors: list[tuple[str, int, str]] = []
        # ONE cluster-wide message bus: each OSD registers a single
        # endpoint that demuxes PG-enveloped traffic to its hosted PGs —
        # the reference's one-messenger-per-OSD topology
        self.bus = MessageBus()
        self.bus.pre_deliver_hooks.append(self._drain_live_daemons)
        # wire accounting (common/wire_accounting.py): every bus send
        # charges byte/op counters per message type and owner op class —
        # the source of recovery.wire_bytes_per_byte_repaired and
        # serving.wire_bytes_per_op in the stats digest
        from .common.wire_accounting import WireAccounting
        self.wire = WireAccounting(cct=self.cct,
                                   name=f"c{self.cluster_id}")
        self.bus.wire_stats = self.wire
        # one daemon shell per OSD: sharded mClock op queue + superblock,
        # and ONE ObjectStore hosting every PG shard on that OSD as
        # collections (OSD.cc:3971 load_pgs iterates one store)
        from .osd.osd_daemon import OSDDaemon
        self.osds = {}
        # osd_queue_throttle_ops > 0 bounds every daemon's op queue: past
        # it, ms_dispatch answers ('throttled', epoch) instead of queueing
        qcap = self.cct.conf.get("osd_queue_throttle_ops")
        for o in range(n_osds):
            st = self._osd_store(o)
            throttle = None
            if qcap:
                from .exec import Throttle
                throttle = Throttle(f"osd.{o}.q", qcap, cct=self.cct)
            d = OSDDaemon(o, meta_store=st, op_throttle=throttle)
            d.store = st
            self.osds[o] = d
        # optional serving engine (enable_serving): cross-PG encode/decode
        # coalescing + admission throttles for every EC backend
        self.serving = None
        # optional recovery scheduler (enable_recovery_scheduler):
        # reservation-gated, prioritized, batch-fused background repair
        self.recovery = None
        # optional fault injection campaign (inject_faults): one seeded
        # FaultInjector spanning bus/store/device planes
        self.fault_injector = None
        # cache tiers (create_tier): cache pool id -> (TierService,
        # TierAgent); the TIER_* health checks register lazily with the
        # first tier (the enable_recovery_scheduler discipline)
        self.tiers: dict[int, tuple] = {}
        # telemetry spine (mgr/stats + mgr/health + flight recorder):
        # status() renders the stats digest, health() is a thin view over
        # the check engine, and any check entering WARN/ERR snapshots a
        # flight bundle (to data_dir/flight in durable mode)
        self._init_telemetry()

    def _init_telemetry(self) -> None:
        from .common.clusterlog import ClusterLog
        from .common.flight_recorder import FlightRecorder
        from .mgr.health import HealthCheckEngine
        from .mgr.heat import HeatTracker
        from .mgr.stats import StatsAggregator
        from .mgr.timeseries import TimeSeriesRing
        self.stats = StatsAggregator(cct=self.cct,
                                     name=f"c{self.cluster_id}")
        self.flight = FlightRecorder(
            cct=self.cct,
            out_dir=(self.data_dir / "flight")
            if self.data_dir is not None else None,
            capacity=self.cct.conf.get("mgr_flight_capacity"))
        self.health_engine = HealthCheckEngine(
            name=f"c{self.cluster_id}", cct=self.cct,
            on_transition=self._on_health_transition,
            on_clear=self._on_health_clear)
        # the cluster log (clog analog): the dozen human-readable lines
        # an incident reads first, persisted under <data_dir>/clusterlog
        # so `ceph -w` can follow from another process
        self.clusterlog = ClusterLog(
            cct=self.cct,
            path=(self.data_dir / "clusterlog")
            if self.data_dir is not None else None)
        # workload heat maps over the stats window, scoped to this
        # cluster's PG collections by the c<id> tag
        self.heat = HeatTracker(self.stats, self._heat_topology,
                                name=f"c{self.cluster_id}",
                                tag=f"c{self.cluster_id}")
        # the embedded time-series ring: status() ticks it; flight
        # bundles carry it; ts_report reads it post-hoc
        self.ts = TimeSeriesRing(cct=self.cct)
        self.ts.add_source("stats", self.stats.digest_flat)
        self.ts.add_source("heat", self.heat.flat_series)
        from .common import roofline
        self.ts.add_source("efficiency", roofline.flat_series)
        # critical-path latency decomposition + SLO burn engine
        # (common/critpath.py + mgr/slo.py): status() folds completed
        # traces into per-class phase attribution; the SLO tracker
        # judges them against slo_<class>_p99_ms objectives
        from .common.critpath import CritPathLedger
        from .mgr.slo import SLOTracker
        self.critpath = CritPathLedger(cct=self.cct,
                                       name=f"c{self.cluster_id}")
        self.slo = SLOTracker(self.critpath, cct=self.cct,
                              name=f"c{self.cluster_id}")
        self.ts.add_source("slo", self.slo.flat_series)
        # XLA profiler capture windows (common/profiler_capture.py):
        # `device profile start|stop|status` plus a rate-limited one-shot
        # auto-capture on any WARN/ERR health transition.  Durable mode
        # only (captures need a disk home under <data_dir>/profiles).
        from .common.profiler_capture import ProfilerCapture
        self.profiler = ProfilerCapture(
            cct=self.cct,
            out_dir=(self.data_dir / "profiles")
            if self.data_dir is not None else None)
        self.profiler.register_admin()
        self._register_health_checks()
        # OSD up/down land in the cluster log the moment the bus flips
        # (the mon's "osd.3 down" clog lines)
        self.bus.down_listeners.append(
            lambda osd: self.clusterlog.warn(f"osd.{osd} down",
                                             channel="osd"))
        self.bus.up_listeners.append(
            lambda osd: self.clusterlog.info(f"osd.{osd} up",
                                             channel="osd"))
        # transition-triggered dumps see the evaluation already cached;
        # MANUAL dumps (admin/CLI) on a process that never ran health()
        # fall back to a read-only evaluation (no hooks — evaluating
        # inside a dump must not recurse into another dump)
        self.flight.add_source(
            "health", lambda: self.health_engine.last_evaluation
            or self.health_engine.evaluate(fire_transitions=False))
        self.flight.add_source("stats", lambda: self.stats.digest())
        self.flight.add_source("wire", self.wire.dump)
        self.flight.add_source("heat", self.heat.dump)
        self.flight.add_source("clusterlog", self.clusterlog.dump)
        self.flight.add_source("timeseries", self.ts.dump)
        self.flight.add_source("efficiency", roofline.snapshot)
        # a WARN/ERR bundle must answer "which phase blew the budget"
        # from the artifact alone: both the SLO state and the raw
        # per-class attribution ride every capture (the fold runs first
        # so the bundle carries traces completed right up to the dump)
        self.flight.add_source("slo", self._slo_flight_source)
        self.flight.register_admin()
        # slo status/dump admin commands (takeover-register, the flight
        # recorder's idiom: newest owner of the shared name wins)
        def _slo_status(**kw):
            self.critpath.refresh()
            return self.slo.status()

        def _slo_dump(**kw):
            return self._slo_flight_source()
        self._slo_admin_fns = {"slo status": _slo_status,
                               "slo dump": _slo_dump}
        for cmd, desc in (
                ("slo status",
                 "per-class latency objectives, burn rates, and "
                 "critical-path phase attribution"),
                ("slo dump",
                 "full SLO + critical-path ledger snapshot (JSON)")):
            self.cct.admin_socket.unregister(cmd)
            self.cct.admin_socket.register(cmd, self._slo_admin_fns[cmd],
                                           desc)

        # object-granularity heat (the tier agent's promotion surface):
        # `heat top [n]` folds the per-PG hit sets into a bounded top-N
        # hot-object digest (mgr/heat.py:top_objects)
        def _heat_top(n=20, **kw):
            from .mgr.heat import top_objects
            return {"top": top_objects(self, int(n))}
        self._slo_admin_fns["heat top"] = _heat_top
        self.cct.admin_socket.unregister("heat top")
        self.cct.admin_socket.register(
            "heat top", _heat_top,
            "top-N hottest objects by hit-set membership "
            "(object-granularity heat under the PG/OSD maps)")

    def _slo_flight_source(self) -> dict:
        self.critpath.refresh()
        return self.slo.dump()

    def _heat_topology(self) -> dict:
        """The heat tracker's placement view: pg -> primary + acting."""
        return {str(g.pgid): {"primary": g.backend.whoami,
                              "acting": list(g.acting)}
                for p in self.pools.values()
                for g in p["pgs"].values()}

    def _on_health_transition(self, key, info, evaluation) -> None:
        """A check newly raised or escalated: capture the run-up NOW
        (tracer ring + perf + health + stats), while the state that
        tripped it is still live — and log the transition where a human
        will read it."""
        msg = f"health check {key} raised: {info['summary']}"
        sev = "ERR" if info["severity"] == "HEALTH_ERR" else "WRN"
        # a fresh process's engine re-fires STANDING checks as new
        # transitions (its prior state is empty), and the clusterlog ring
        # persists across reopens: only log when this key's latest
        # persisted line differs (message OR severity — an escalation
        # with an unchanged summary still logs), so `ceph -s` in a loop
        # against an unhealthy cluster doesn't bury the history in
        # duplicates.  Genuine raise/clear/raise cycles log every time:
        # the "cleared" line (on_clear below) breaks the dedup chain.
        prior = self._last_health_line(key)
        if prior is None or prior["message"] != msg \
                or prior.get("severity") != sev:
            self.clusterlog.log(sev, msg, channel="health")
        self.flight.dump(reason=f"health-{key}-{info['severity']}")
        # one bounded profiler capture per anomaly (cooldown-gated inside:
        # a flapping check must not churn the process-global profiler)
        self.profiler.auto_capture(reason=f"{key}-{info['severity']}")

    def _last_health_line(self, key: str) -> dict | None:
        return next((e for e in reversed(self.clusterlog.dump())
                     if e.get("channel") == "health"
                     and e["message"].startswith(f"health check {key} ")),
                    None)

    def _on_health_clear(self, key, evaluation) -> None:
        """A raised check stopped reporting: one INF line — but only if
        the raise itself was logged (muted checks never were), and only
        once (the dedup mirror of _on_health_transition)."""
        msg = f"health check {key} cleared"
        prior = self._last_health_line(key)
        if prior is not None and prior["message"] != msg:
            self.clusterlog.info(msg, channel="health")

    def _register_health_checks(self) -> None:
        """The named check set (mon/health_check.h keys where the concept
        matches).  Cluster-shape checks close over self; the generic
        perf-surface checks come from mgr.health factories."""
        from .mgr.health import (CheckResult, HEALTH_ERR,
                                 recompile_storm_check, slow_ops_check,
                                 throttle_saturated_check)
        eng = self.health_engine

        def osd_down():
            down = [o for o in range(self.osdmap.max_osd)
                    if not self.osdmap.is_up(o)]
            if down:
                return CheckResult(
                    f"{len(down)} osds down",
                    detail=[f"osd.{o} is down" for o in down],
                    count=len(down))
            return None

        # ONE per-PG state walk per evaluation, shared by the two state
        # checks (keyed on the engine's eval_seq — without the memo every
        # health()/scrape would re-classify every PG once per check)
        walk = {"seq": -1, "states": {}}

        def _pgs_in_state(state: str) -> list[str]:
            if walk["seq"] != eng.eval_seq:
                states: dict[str, list[str]] = {}
                for p in self.pools.values():
                    for g in p["pgs"].values():
                        states.setdefault(self.pg_state(g),
                                          []).append(repr(g.pgid))
                walk["seq"] = eng.eval_seq
                walk["states"] = states
            return walk["states"].get(state, [])

        def pg_degraded():
            pgs = _pgs_in_state("active+degraded")
            if pgs:
                return CheckResult(
                    f"{len(pgs)} pgs degraded",
                    detail=[f"pg {pgid} is active+degraded"
                            for pgid in pgs], count=len(pgs))
            return None

        def pg_availability():
            pgs = _pgs_in_state("inactive")
            if pgs:
                return CheckResult(
                    f"{len(pgs)} pgs inactive",
                    detail=[f"pg {pgid} is inactive (< min_size current "
                            f"shards)" for pgid in pgs], count=len(pgs))
            return None

        def object_damaged():
            oids = [f"{pid}/{oid}" for pid, p in self.pools.items()
                    for g in p["pgs"].values()
                    for oid in sorted(getattr(g.backend,
                                              "inconsistent_objects", ()))]
            if oids:
                return CheckResult(
                    f"{len(oids)} objects with unlocatable inconsistency",
                    detail=oids, count=len(oids))
            return None

        eng.register("OSD_DOWN", osd_down,
                     description="one or more OSDs are marked down")
        eng.register("PG_DEGRADED", pg_degraded,
                     description="PGs serving with fewer than size "
                                 "current shards")
        eng.register("PG_AVAILABILITY", pg_availability,
                     severity=HEALTH_ERR,
                     description="PGs below min_size: writes blocked")
        eng.register("OBJECT_DAMAGED", object_damaged,
                     description="objects flagged inconsistent with no "
                                 "locatable bad shard")
        eng.register("SLOW_OPS", slow_ops_check(self.stats),
                     description="ops exceeded osd_op_complaint_time "
                                 "within the stats window")
        eng.register("THROTTLE_SATURATED",
                     throttle_saturated_check(self.cct),
                     description="an admission throttle is pinned near "
                                 "its limit (sustained backpressure)")
        eng.register("RECOMPILE_STORM",
                     recompile_storm_check(self.cct, self.stats),
                     description="jit compilations within the stats "
                                 "window exceeded the storm threshold")
        from .mgr.heat import hot_shard_check
        eng.register("HOT_SHARD", hot_shard_check(self.heat, self.cct),
                     description="one OSD's primary-op load is a "
                                 "sustained multiple of the median "
                                 "(hot-shard workload skew)")
        from .mgr.health import hbm_pressure_check
        eng.register("HBM_PRESSURE",
                     hbm_pressure_check(self.cct),
                     description="a device's high-water memory mark is "
                                 "pinned near its capacity (guarded "
                                 "watermark sampler: silent on backends "
                                 "without memory stats)")
        from .mgr.health import device_degraded_check, osd_flapping_check
        eng.register("DEVICE_DEGRADED", device_degraded_check(),
                     description="a codec pipeline circuit-broke its "
                                 "device path: batches run the sync "
                                 "host codec until half-open probes "
                                 "re-close the breaker")
        eng.register("OSD_FLAPPING",
                     osd_flapping_check(
                         lambda: getattr(getattr(self, "monitor", None),
                                         "markdown", None)),
                     description="an OSD was marked down too often "
                                 "within osd_markdown_window: boots are "
                                 "damped until the operator clears the "
                                 "markdown record")
        from .mgr.slo import slo_burn_check, slo_exhausted_check
        eng.register("SLO_BURN", slo_burn_check(self.slo),
                     description="a class's latency error budget is "
                                 "burning past slo_burn_rate_threshold "
                                 "in BOTH burn windows (fast+slow "
                                 "agreement: a blip does not page, a "
                                 "sustained burn does)")
        eng.register("SLO_EXHAUSTED", slo_exhausted_check(self.slo),
                     severity=HEALTH_ERR,
                     description="a class's slow-window burn rate says "
                                 "the latency error budget is gone "
                                 "(slo_exhausted_burn_rate)")

    def enable_serving(self, start: bool = False, **kw):
        """Attach a :class:`~ceph_tpu.exec.ServingEngine` to every EC
        backend (current and future pools): their encode/decode
        dispatches then flow through throttled admission and the op
        coalescer.  ``start=True`` runs it threaded (deadline batching
        across concurrent submitters); the default single-thread mode
        keeps the cluster deterministic — ops coalesce when submitted in
        bursts and flush inline otherwise."""
        from .exec import ServingEngine
        kw.setdefault("name", f"serving.c{self.cluster_id}")
        self.serving = ServingEngine(cct=self.cct, **kw)
        if start:
            self.serving.start()
        for pool in self.pools.values():
            if pool["ec"] is not None:
                for g in pool["pgs"].values():
                    g.backend.attach_serving(self.serving)
        return self.serving

    def enable_recovery_scheduler(self, **kw):
        """Attach a :class:`~ceph_tpu.recovery.RecoveryScheduler` to
        every PG backend (current and future pools): shard revival,
        peering activation, and stalled-recovery re-drives then route
        through per-OSD local+remote reservations (``osd_max_backfills``),
        Ceph-style priorities, and byte-rate-capped waves whose degraded
        objects reconstruct through one batched decode dispatch."""
        from .recovery import RecoveryScheduler
        if self.recovery is None:
            kw.setdefault("name", f"c{self.cluster_id}")
            self.recovery = RecoveryScheduler(cct=self.cct, **kw)
            # recovery start/finish lines land in the cluster log
            self.recovery.clog = self.clusterlog
            from .mgr.health import pg_recovery_stalled_check
            self.health_engine.register(
                "PG_RECOVERY_STALLED",
                pg_recovery_stalled_check(self.stats,
                                          lambda: self.recovery),
                description="degraded PGs queued for recovery but no "
                            "reservation is progressing")
        for pool in self.pools.values():
            for g in pool["pgs"].values():
                self._attach_recovery(g, pool["pool"])
        return self.recovery

    def _attach_recovery(self, g: PGGroup, pool: Pool) -> None:
        # chain planning is topology-aware: osd -> host bucket, the same
        # layout the crush map above was built with
        g.backend.osd_locations = {o: o // self.osds_per_host
                                   for o in range(self.n_osds)}
        self.recovery.attach_backend(
            g.backend, pgid=g.pgid, daemon=self.osds[g.backend.whoami],
            pool_params=pool.params)

    # -- cache tiering (tier/) ---------------------------------------------

    def create_tier(self, cache_pool: int, base_pool: int, *,
                    mode: str = "writeback", frontend=None):
        """Bind a replicated cache pool over an EC base pool (the mon's
        ``osd tier add`` + ``cache-mode``): returns the
        :class:`~ceph_tpu.tier.TierService` with its flush/evict agent
        attached as ``.agent``.  The ``TIER_FULL`` /
        ``TIER_FLUSH_BACKLOG`` health checks and the ``tier status``
        admin command register with the FIRST tier (lazily, the
        enable_recovery_scheduler discipline: clusters without tiering
        never evaluate them)."""
        from .tier import TierAgent, TierService
        if cache_pool in self.tiers:
            raise ValueError(f"pool {cache_pool} is already a cache tier")
        svc = TierService(self, cache_pool, base_pool, mode=mode,
                          frontend=frontend,
                          name=f"c{self.cluster_id}.p{cache_pool}")
        svc.agent = TierAgent(svc)
        first = not self.tiers
        self.tiers[cache_pool] = (svc, svc.agent)
        if first:
            from .mgr.health import (tier_flush_backlog_check,
                                     tier_full_check)
            self.health_engine.register(
                "TIER_FULL", tier_full_check(lambda: self.tiers),
                description="a cache tier's residency is at/over its "
                            "tier_full_ratio watermark")
            self.health_engine.register(
                "TIER_FLUSH_BACKLOG",
                tier_flush_backlog_check(lambda: self.tiers),
                description="a tier agent keeps ending its passes over "
                            "tier_dirty_ratio_high: the base pool is "
                            "not absorbing flushes fast enough")

            def _tier_status(**kw):
                return {str(pid): s.stats()
                        for pid, (s, _a) in sorted(self.tiers.items())}
            self._slo_admin_fns["tier status"] = _tier_status
            self.cct.admin_socket.unregister("tier status")
            self.cct.admin_socket.register(
                "tier status", _tier_status,
                "per-tier cache mode, residency, hit rate, and "
                "promotion/flush/evict counters")
        self.clusterlog.info(
            f"pool {cache_pool} is now a {mode} cache tier over pool "
            f"{base_pool}", channel="mon")
        return svc

    # -- pool parameter updates (the mon's 'osd pool set') ------------------

    def pool_set(self, pool_id: int, key: str, value) -> None:
        """``ceph osd pool set <pool> <key> <value>``: update one pool
        param LIVE and persist it.  The ``hit_set_*`` family re-arms
        per-PG hit-set accumulation in place (the observer hook pool
        params get in lieu of ConfigProxy observers): the accumulating
        set restarts under the new geometry, the persisted archive ring
        is resumed, and ``hit_set_count 0`` disarms tracking."""
        if pool_id not in self.pools:
            raise KeyError(f"no pool {pool_id}")
        pool = self.pools[pool_id]["pool"]
        pool.params[key] = str(value)
        if key in ("hit_set_count", "hit_set_period",
                   "hit_set_target_size", "hit_set_fpp"):
            for g in self.pools[pool_id]["pgs"].values():
                if int(pool.params.get("hit_set_count", 0)) > 0:
                    self._arm_hit_sets(g, pool)
                else:
                    g.engine.hit_set = None
                    g.engine.hit_set_params = None
        self.clusterlog.info(
            f"pool '{pool.name}' set {key} = {value}", channel="mon")
        self._save_meta()

    # -- fault injection (failure/) ----------------------------------------

    def inject_faults(self, plan=None):
        """Arm (or, with ``None``, disarm) cluster-wide fault injection
        from ONE seeded :class:`~ceph_tpu.failure.config.FaultPlan`:

        - the bus plane drives the shared MessageBus (reorder/dup/drop,
          stamping its events into the campaign log);
        - the store plane wraps every PG shard store in a
          :class:`~ceph_tpu.failure.store.FaultyStore` (EIO / torn
          writes / slow reads);
        - the device plane rides the serving/recovery pipelines when
          those subsystems are enabled.

        The TRANSPORT plane lives on the :class:`~ceph_tpu.net.
        ClusterServer` (``server.inject_faults(cluster.fault_injector)``)
        — the sockets are its, not ours.  Returns the
        :class:`~ceph_tpu.failure.injector.FaultInjector` (or None)."""
        from .failure import FaultInjector
        from .failure.store import FaultyStore, unwrap
        if plan is None:
            self.bus.inject_faults(None)
            self.bus.fault_log = None
            for g in (g for p in self.pools.values()
                      for g in p["pgs"].values()):
                for h in g.bus.handlers.values():
                    st = getattr(h, "store", None)
                    if isinstance(st, FaultyStore):
                        h.store = unwrap(st)
            if self.serving is not None:
                self.serving.inject_device_faults(None)
            if self.recovery is not None:
                self.recovery.inject_device_faults(None)
            old, self.fault_injector = getattr(self, "fault_injector",
                                               None), None
            if old is not None:
                old.close()
            return None
        if self.fault_injector is not None:
            # re-arming with a new plan: disarm first, so store wrappers
            # rebind to the NEW injector (stale wrappers would keep
            # rolling the old plan's faults into the old event log) and
            # the old perf collection is released before its replacement
            # registers under the same name
            self.inject_faults(None)
        inj = FaultInjector(plan, clusterlog=self.clusterlog,
                            cct=self.cct, name=f"c{self.cluster_id}")
        self.fault_injector = inj
        self.bus.inject_faults(plan)
        self.bus.fault_log = inj.record
        for g in (g for p in self.pools.values()
                  for g in p["pgs"].values()):
            self._wrap_stores(g, inj)
        if self.serving is not None:
            self.serving.inject_device_faults(inj)
        if self.recovery is not None:
            self.recovery.inject_device_faults(inj)
        self.clusterlog.info(
            f"fault injection armed (seed {plan.seed})", channel="faults")
        return inj

    @staticmethod
    def _wrap_stores(g: PGGroup, injector) -> None:
        """Every shard store of one PG behind a FaultyStore (idempotent:
        an already-wrapped store is left alone)."""
        from .failure.store import FaultyStore
        for shard, h in g.bus.handlers.items():
            st = getattr(h, "store", None)
            if st is not None and not isinstance(st, FaultyStore):
                h.store = FaultyStore(st, injector,
                                      target=f"osd.{shard}/{g.pgid}")

    # -- pool creation (the mon's osd pool create path) --------------------

    def create_ec_pool(self, name: str, profile: dict | None = None,
                      pg_num: int = 8) -> int:
        profile = dict(profile or {})
        profile.setdefault("plugin", "jax_rs")
        profile.setdefault("k", "4")
        profile.setdefault("m", "2")
        plugin = profile["plugin"]
        ec = ErasureCodePluginRegistry.instance().factory(
            plugin, "", dict(profile), cct=self.cct)
        n = ec.get_chunk_count()
        # ErasureCode::create_rule semantics: chooseleaf indep over hosts
        # when enough hosts exist, else osds (ErasureCode.cc:64-83); a
        # crush-device-class profile key routes the take through the
        # per-class shadow tree (ErasureCode.cc:44-62 parses it)
        root = self.osdmap.crush.take_with_class(
            "default", profile.get("crush-device-class", ""))
        n_hosts = sum(1 for bid, b in self.osdmap.crush.buckets.items()
                      if b.type == 1 and not self.osdmap.crush.is_shadow(bid))
        ftype = 1 if n_hosts >= n else 0
        ruleno = self.osdmap.crush.add_rule(
            [(CRUSH_RULE_TAKE, root, 0),
             (CRUSH_RULE_CHOOSELEAF_INDEP, n, ftype),
             (CRUSH_RULE_EMIT, 0, 0)])
        pool_id = self._next_pool
        self._next_pool += 1
        pool = Pool(pool_id=pool_id, type=POOL_TYPE_ERASURE, size=n,
                    min_size=ec.get_data_chunk_count() + 1, pg_num=pg_num,
                    crush_rule=ruleno, name=name,
                    erasure_code_profile=" ".join(
                        f"{k}={v}" for k, v in sorted(profile.items())),
                    params=dict(profile))
        return self._instantiate_pool(pool, name, ec)

    def create_replicated_pool(self, name: str, size: int = 3,
                               pg_num: int = 8,
                               params: dict | None = None) -> int:
        """Replicated pool: ``size`` full copies, min_size = size//2 + 1
        (the mon's defaults for ``osd pool create ... replicated``);
        CRUSH chooses hosts firstn the way replicated rules do.
        ``params`` carries pool options (hit_set_count/hit_set_period
        arm cache-tier hit sets)."""
        root = self.osdmap.crush.item_id("default")
        n_hosts = sum(1 for bid, b in self.osdmap.crush.buckets.items()
                      if b.type == 1 and not self.osdmap.crush.is_shadow(bid))
        ftype = 1 if n_hosts >= size else 0
        ruleno = self.osdmap.crush.add_rule(
            [(CRUSH_RULE_TAKE, root, 0),
             (CRUSH_RULE_CHOOSELEAF_FIRSTN, size, ftype),
             (CRUSH_RULE_EMIT, 0, 0)])
        pool_id = self._next_pool
        self._next_pool += 1
        pool = Pool(pool_id=pool_id, type=POOL_TYPE_REPLICATED, size=size,
                    min_size=size // 2 + 1, pg_num=pg_num,
                    crush_rule=ruleno, name=name,
                    params={"size": str(size), **(params or {})})
        return self._instantiate_pool(pool, name, None)

    def _instantiate_pool(self, pool: Pool, name: str, ec) -> int:
        self.osdmap.add_pool(pool)
        pgs = {}
        for ps in range(pool.pg_num):
            pgid = PG(pool.pool_id, ps)
            up, up_primary, acting, _ = self.osdmap.pg_to_up_acting_osds(pgid)
            if not acting or any(a == 0x7FFFFFFF for a in acting):
                raise RuntimeError(
                    f"pg {pgid} not fully mapped (acting={acting}); "
                    f"add OSDs or shrink the pool size")
            pgs[ps] = PGGroup(pgid, acting, ec, self.chunk_size, self.cct,
                              name_prefix=f"c{self.cluster_id}",
                              min_size=pool.min_size,
                              store_factory=self._store_factory(
                                  pool.pool_id, ps),
                              epoch=self.osdmap.epoch,
                              bus=self.bus)
            self.osds[acting[0]].register_pg(pgid, pgs[ps])
            self._arm_hit_sets(pgs[ps], pool)
            if self.serving is not None and ec is not None:
                pgs[ps].backend.attach_serving(self.serving)
            if self.recovery is not None:
                self._attach_recovery(pgs[ps], pool)
            if getattr(self, "fault_injector", None) is not None:
                # the store plane covers pools created mid-campaign too
                self._wrap_stores(pgs[ps], self.fault_injector)
        self.pools[pool.pool_id] = {"pool": pool, "pgs": pgs, "ec": ec}
        self.pool_ids[name] = pool.pool_id
        if not getattr(self, "_restoring", False):
            # reopens restore pools through this same path: only a
            # GENUINELY new pool is a cluster-log event (a "created"
            # line per CLI invocation would bury the real history)
            self.clusterlog.info(
                f"pool '{name}' created (id {pool.pool_id}, "
                f"{'ec' if ec is not None else 'replicated'}, "
                f"{pool.pg_num} pgs)", channel="mon")
        self._save_meta()
        return pool.pool_id

    @staticmethod
    def _arm_hit_sets(g: PGGroup, pool: Pool) -> None:
        """hit_set_count/hit_set_period pool params arm per-PG hit-set
        accumulation (PrimaryLogPG::hit_set_setup; the tiering agent's
        temperature source).  Called at pool creation AND after a remap
        rebuilds the PGGroup — the new engine would otherwise silently
        stop tracking and the agent would evict its whole working set."""
        hs_count = int(pool.params.get("hit_set_count", 0))
        if hs_count > 0:
            g.engine.configure_hit_sets(
                hs_count, int(pool.params.get("hit_set_period", 100)),
                int(pool.params.get("hit_set_target_size", 1000)),
                float(pool.params.get("hit_set_fpp", 0.05)))

    # -- durability (data_dir mode) ----------------------------------------

    def _drain_live_daemons(self) -> None:
        """Run every live OSD's queued client ops (dead OSDs stay
        parked); hooked into the shared bus's deliver_all so 'deliver
        everything' includes daemon queues."""
        for osd, daemon in self.osds.items():
            if osd not in self.bus.down:
                daemon.drain()

    def _store_factory(self, pool_id: int, ps: int):
        """Every (PG, shard) store is a Collection inside the hosting
        OSD's ONE shared store — shared WAL ordering, one checkpoint, one
        restart recovering every hosted PG (reference: OSD.cc:3971
        load_pgs over a single ObjectStore)."""
        from .backend.collection import Collection

        def factory(osd, _pid=pool_id, _ps=ps):
            return Collection(self.osds[osd].store, f"pg.{_pid}.{_ps}")
        return factory

    def _osd_store(self, osd: int):
        """The OSD's single ObjectStore: superblock at the root namespace,
        PG shards as collections (FileStore or BlueStore-lite in durable
        mode, per ``store_backend``)."""
        if self.data_dir is None:
            from .backend.memstore import MemStore
            return MemStore()
        if self.store_backend == "bluestore":
            from .backend.bluestore import BlueStoreLite
            return BlueStoreLite(self.data_dir / f"osd.{osd}" / "store")
        from .backend.filestore import FileStore
        return FileStore(self.data_dir / f"osd.{osd}" / "store")

    def _save_meta(self) -> None:
        """Persist what cannot be rebuilt from the shard stores: the pool
        definitions (the mon's role; object bookkeeping is rediscovered
        from the primaries' stores at load)."""
        if self.data_dir is None:
            return
        import os
        import pickle
        self.data_dir.mkdir(parents=True, exist_ok=True)
        meta = {
            "n_osds": self.n_osds,
            "osds_per_host": self.osds_per_host,
            "chunk_size": self.chunk_size,
            "store_backend": self.store_backend,
            # operator state the data path cannot rebuild: muted health
            # checks survive a reopen (the mon persists mutes the same way)
            "health_mutes": sorted(self.health_engine.muted),
            "pools": [{"name": p["pool"].name,
                       "type": p["pool"].type,
                       "size": p["pool"].size,
                       "params": dict(p["pool"].params),
                       "pg_num": p["pool"].pg_num,
                       "snap_seq": p["pool"].snap_seq,
                       "snaps": dict(p["pool"].snaps),
                       "removed_snaps": set(p["pool"].removed_snaps)}
                      for _, p in sorted(self.pools.items())],
        }
        tmp = self.data_dir / "cluster_meta.pkl.tmp"
        with open(tmp, "wb") as f:
            pickle.dump(meta, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, self.data_dir / "cluster_meta.pkl")

    @classmethod
    def load(cls, data_dir, cct: Context | None = None) -> "MiniCluster":
        """Reopen a durable cluster: rebuild the maps from the persisted
        pool definitions (deterministic CRUSH -> identical placements),
        reopen every shard's FileStore, replay PG logs (OSDShard boot),
        and run a boot-time repair pass so any shard that restarted stale
        catches up through the ordinary log path before serving."""
        import pickle
        from pathlib import Path
        with open(Path(data_dir) / "cluster_meta.pkl", "rb") as f:
            meta = pickle.load(f)
        c = cls(n_osds=meta["n_osds"], osds_per_host=meta["osds_per_host"],
                chunk_size=meta["chunk_size"], cct=cct, data_dir=data_dir,
                store_backend=meta.get("store_backend", "file"))
        for key in meta.get("health_mutes", ()):
            c.health_engine.mute(key)
        c._restoring = True
        try:
            for p in meta["pools"]:
                if p["type"] == POOL_TYPE_REPLICATED:
                    pid = c.create_replicated_pool(p["name"], p["size"],
                                                   p["pg_num"],
                                                   params=p.get("params"))
                else:
                    pid = c.create_ec_pool(p["name"], p["params"],
                                           p["pg_num"])
                pool = c.pools[pid]["pool"]
                pool.snap_seq = p.get("snap_seq", 0)
                pool.snaps = dict(p.get("snaps", {}))
                pool.removed_snaps = set(p.get("removed_snaps", ()))
        finally:
            c._restoring = False
        # re-persist: pool creation above rewrote the meta file BEFORE the
        # snap fields were restored; without this, the next process would
        # load a cluster whose pool snaps were silently wiped
        c._save_meta()
        for pid, pool in c.pools.items():
            for g in pool["pgs"].values():
                # crash recovery first: elect the authoritative log and
                # roll back any write persisted on < min_size shards (it
                # was never acked); only then repair stale shards
                g.backend.start_boot_peering()
                g.bus.deliver_all()
                from .osd.hit_set import is_hit_set_oid
                from .osd.primary_log_pg import is_clone_oid
                c.objects.setdefault(pid, set()).update(
                    o for o in g.backend._local_oids()
                    if not is_clone_oid(o) and not is_hit_set_oid(o))
                for osd in g.acting:
                    if osd != g.backend.whoami:
                        g.backend.start_shard_repair(osd)
                # the primary itself may have restarted stale (peering
                # adopted a peer's log): repair its own shard too
                if g.backend.local_shard.pg_log.head < g.backend.pg_log.head:
                    g.backend.start_shard_repair(g.backend.whoami)
                g.bus.deliver_all()
        return c

    # -- object placement (librados object_locator -> pg) ------------------

    def object_pg(self, pool_id: int, oid: str) -> int:
        pool = self.pools[pool_id]["pool"]
        ps = ceph_str_hash_rjenkins(oid)
        return ceph_stable_mod(ps, pool.pg_num, pool.pg_num_mask)

    def pg_group(self, pool_id: int, oid: str) -> PGGroup:
        return self.pools[pool_id]["pgs"][self.object_pg(pool_id, oid)]

    # -- client I/O --------------------------------------------------------

    def put(self, pool_id: int, oid: str, data: bytes,
            deliver: bool = True, wait: bool = True,
            on_commit=None) -> PGGroup:
        """Write ``oid``.  With ``wait`` (default), raises BlockedWriteError
        if the PG is inactive (< min_size current shards) — the op stays
        queued and commits when shards return, exactly like a blocked
        client op on an inactive reference PG.  ``on_commit`` fires when
        (possibly much later) the write is durable on min_size shards."""
        g = self.pg_group(pool_id, oid)
        sinfo = getattr(g.backend, "sinfo", None)
        pad = (-len(data)) % sinfo.stripe_width if sinfo is not None else 0
        done: list[int] = []

        def _committed(tid):
            done.append(tid)
            if on_commit:
                on_commit(tid)
        if self.pools[pool_id]["pool"].snap_seq:
            # pool snapshots exist: the write MUST run through the op
            # engine so make_writable clones the head at snap boundaries
            # (bypassing it would silently break snapshot isolation)
            from .osd.osd_ops import ObjectOperation
            failed: list[int] = []
            sync_phase = [True]      # until put() has checked `failed`

            def _snap_done(reply):
                # an error reply is NOT a committed write: surface it like
                # operate() does instead of silently acking the put
                if reply.result < 0:
                    if sync_phase[0] and deliver:
                        failed.append(reply.result)
                    else:
                        # the reply arrived AFTER put() returned (batched
                        # deliver=False op, or a blocked write completing
                        # once shards came back).  Raising here would
                        # unwind through the op engine's _finish and
                        # strand the daemon queue, so park the error for
                        # deliver_all() to surface instead.
                        self._deferred_errors.append(
                            (oid, reply.result,
                             f"put of {oid} failed: result {reply.result}"))
                else:
                    _committed(reply.version)
            res = self._dispatch_op_vector(
                g, pool_id, oid,
                ObjectOperation().write(0, bytes(data) + b"\0" * pad).ops,
                self.osdmap.epoch, _snap_done, drain=deliver)
            sync_phase[0] = False
            if res is not None:
                raise IOError(f"put of {oid} rejected ({res[0]}): {res}")
            if failed:
                err = IOError(f"put of {oid} failed: result {failed[0]}")
                err.errno = failed[0]
                raise err
            if deliver and wait and not done:
                raise BlockedWriteError(
                    f"write of {oid} blocked: PG {g.pgid} inactive")
            return g
        # the fast-path put is still a CLIENT op: root a trace here (the
        # MOSDOp dispatch edge does the same) so the sub-writes it fans
        # out attribute their wire bytes to the client class
        from .common.tracer import root_or_ambient
        with root_or_ambient("client"):
            g.backend.submit_transaction(
                PGTransaction().write(oid, 0, bytes(data) + b"\0" * pad),
                on_commit=_committed)
        self.objects.setdefault(pool_id, set()).add(oid)
        if deliver:
            g.bus.deliver_all()
            if wait and not done:
                raise BlockedWriteError(
                    f"write of {oid} blocked: PG {g.pgid} inactive "
                    f"({len(g.backend.current_shards())} current shards < "
                    f"min_size {g.backend.min_size})")
        return g

    def put_many(self, pool_id: int, objects: dict[str, bytes],
                 wait: bool = True) -> None:
        """Write a batch of objects with ONE device encode dispatch for
        the whole batch, across PGs (ecutil.encode_many — the cross-op
        coalescing SURVEY §3.2 calls the main TPU restructuring; the
        reference encodes per stripe per op, ECUtil.cc:136-148).
        Replicated pools have nothing to encode and just loop."""
        if not objects:
            return
        pool = self.pools[pool_id]
        if pool["ec"] is None or pool["pool"].snap_seq:
            # replicated: nothing to batch-encode.  Snapped pools: every
            # write must run the op engine's COW (put handles both).
            for oid, data in objects.items():
                self.put(pool_id, oid, data, wait=wait)
            return
        from .backend import ecutil
        order = sorted(objects)
        groups = {oid: self.pg_group(pool_id, oid) for oid in order}
        sinfo = groups[order[0]].backend.sinfo
        padded = {}
        for oid in order:
            data = bytes(objects[oid])
            padded[oid] = data + b"\0" * ((-len(data)) % sinfo.stripe_width)
        encoded = ecutil.encode_many(sinfo, pool["ec"],
                                     [padded[oid] for oid in order])
        done: list[str] = []
        from .common.tracer import root_or_ambient
        with root_or_ambient("client"):
            for oid, enc in zip(order, encoded):
                t = PGTransaction().write(oid, 0, padded[oid])
                objop = t.ops[oid]
                objop.precomputed_chunks = enc
                objop.precomputed_for = padded[oid]
                groups[oid].backend.submit_transaction(
                    t, on_commit=lambda tid, _oid=oid: done.append(_oid))
                self.objects.setdefault(pool_id, set()).add(oid)
        for g in {id(g): g for g in groups.values()}.values():
            g.bus.deliver_all()
        if wait and len(done) != len(order):
            missing = sorted(set(order) - set(done))
            raise BlockedWriteError(
                f"batch writes blocked on inactive PGs: {missing}")

    def _snap_context(self, pool_id: int):
        """The pool's live SnapContext (what librados attaches to every
        write once pool snaps exist)."""
        from .osd.osd_ops import SnapContext
        pool = self.pools[pool_id]["pool"]
        if not pool.snap_seq:
            return None
        return SnapContext(pool.snap_seq,
                           tuple(sorted(pool.snaps, reverse=True)))

    def _dispatch_op_vector(self, g, pool_id: int, oid: str, ops,
                            epoch: int, on_done, drain: bool = True,
                            snapid: int | None = None,
                            internal: bool = False):
        """ONE copy of the MOSDOp dispatch path (used by operate() and
        the Objecter-facing osd_submit): daemon queue -> op engine, with
        object bookkeeping in the COMPLETION callback — a write parked on
        an inactive PG has not hit the store yet, so bookkeeping at
        dispatch time would let a later backfill drop the acked object.
        Returns None when accepted, or ("stale", current_map)."""
        from .backend.memstore import GObject
        from .osd.osd_ops import MOSDOp, MOSDOpReply
        if snapid is not None and \
                snapid not in self.pools[pool_id]["pool"].snaps:
            # reads at a removed (or never-issued) pool snap are ENOENT
            # even while a shared clone still covers the id for an older
            # live snap (the reference validates against the pool first)
            if on_done:
                on_done(MOSDOpReply(-2, list(ops)))
            return None
        daemon = self.osds[g.backend.whoami]
        primary_dead = g.backend.whoami in g.bus.down
        # every client op gets a trace context here, the MOSDOp dispatch
        # edge: an ambient one (Objecter / net.py RPC / an operate() call
        # inside a traced scope) is adopted, otherwise a fresh client
        # root — so the daemon's spans and every sub-op fanned out below
        # stitch into one cross-daemon trace
        from .common.tracer import default_tracer
        tr = default_tracer()
        trace_ctx = tr.current_ctx() or tr.new_trace("client")

        def _done(reply):
            if g.backend.local_shard.store.exists(
                    GObject(oid, g.backend.whoami)):
                self.objects.setdefault(pool_id, set()).add(oid)
            else:
                self.objects.get(pool_id, set()).discard(oid)
            if on_done:
                on_done(reply)
        m = MOSDOp(oid=oid, ops=ops, epoch=epoch, snapid=snapid,
                   snapc=self._snap_context(pool_id), internal=internal,
                   trace=trace_ctx)
        res = daemon.ms_dispatch(g.pgid, m, _done)
        if res is not None and res[0] == "throttled" and not primary_dead:
            # bounded daemon queue hit (osd_queue_throttle_ops): the
            # cooperative analog of client backoff-and-resend is draining
            # the queue — running the backlog releases its throttle units
            # — then resending once.  Only a DEAD primary's parked queue
            # can stay full past a drain.  Deliberate trade-off: with
            # deliver=False batching, this runs the parked ops early and
            # fragments the batch — when demand overruns the bound,
            # bounded memory wins over maximal coalescing.
            import time as _time
            t0 = _time.monotonic()
            daemon.drain()
            backoff = _time.monotonic() - t0
            # the bounce + drain is this op's backoff-and-resend time:
            # stamped as `retry` phase in its trace
            tr.complete("client.backoff_resend", _time.time() - backoff,
                        backoff, ctx=trace_ctx, oid=oid)
            res = daemon.ms_dispatch(g.pgid, m, _done)
        if res is not None:
            return res
        if drain:
            if primary_dead:
                # a dead OSD executes nothing: the op stays queued on the
                # daemon (BlockedWriteError surface) and runs at the next
                # deliver_all() after revival.  Draining now would let the
                # engine fan out an op whose replies a bus-down primary
                # can never receive — leaking its per-object write slot.
                return None
            daemon.drain()
            g.bus.deliver_all()
        return None

    def operate(self, pool_id: int, oid: str, op,
                deliver: bool = True, snapid: int | None = None,
                internal: bool = False):
        """Execute a librados-style op vector atomically on ``oid``
        through the primary's op engine (IoCtx::operate →
        PrimaryLogPG::do_osd_ops).  Returns the MOSDOpReply; raises
        IOError on a negative overall result.  With ``deliver=False`` the
        op is only queued on the primary's daemon (returns None); the
        caller drains the daemon and delivers the bus itself — batch
        submission, like put(deliver=False)."""
        g = self.pg_group(pool_id, oid)
        out: list = []
        abandoned = [False]

        def _cb(reply):
            if abandoned[0]:
                # the caller got BlockedWriteError and stopped listening:
                # a LATE error reply must not vanish (mirror put()'s
                # _snap_done) — deliver_all() surfaces it
                if reply.result < 0:
                    self._deferred_errors.append(
                        (oid, reply.result,
                         f"op on {oid} failed after revival: "
                         f"result {reply.result}"))
                return
            out.append(reply)
        res = self._dispatch_op_vector(g, pool_id, oid, op.ops,
                                       self.osdmap.epoch, _cb,
                                       drain=deliver, snapid=snapid,
                                       internal=internal)
        if res is not None:
            raise IOError(f"op on {oid} rejected ({res[0]}): {res}")
        if not deliver:
            return None
        if not out:
            abandoned[0] = True
            raise BlockedWriteError(
                f"op on {oid} blocked: PG {g.pgid} inactive")
        reply = out[0]
        if reply.result < 0:
            err = IOError(f"op on {oid} failed: result {reply.result}")
            err.errno = reply.result
            err.reply = reply
            raise err
        return reply

    def get(self, pool_id: int, oid: str, length: int) -> bytes:
        g = self.pg_group(pool_id, oid)
        out = {}
        from .common.tracer import root_or_ambient
        # client-class root (see put): degraded-read sub-reads account
        # their wire bytes to the client that asked for them
        with root_or_ambient("client"):
            g.backend.objects_read_and_reconstruct(
                {oid: [(0, length)]},
                lambda result, errors: out.update(result=result,
                                                  errors=errors))
        g.bus.deliver_all()
        if out.get("errors"):
            raise IOError(out["errors"])
        return out["result"][oid][0][2][:length]

    def deliver_all(self) -> None:
        """Run everything queued: daemon op queues FIRST (batched
        deliver=False ops park there — bus delivery alone would never
        execute them), then every PG bus.  Errors parked by batched op
        replies surface here, where the caller expects completion.
        Daemons of bus-down OSDs stay parked: a dead OSD executes
        nothing until revived."""
        # every PG channel shares ONE cluster bus whose pre-deliver hook
        # drains the live daemons: one call quiesces everything (a per-PG
        # loop would redo the full drain once per PG)
        self.bus.deliver_all()
        if self._deferred_errors:
            oid, result, msg = self._deferred_errors[0]
            rest = len(self._deferred_errors) - 1
            self._deferred_errors.clear()
            err = IOError(msg + (f" (+{rest} more batched errors)"
                                 if rest else ""))
            err.errno = result
            raise err

    @staticmethod
    def pg_state(g: PGGroup) -> str:
        """ONE classification of a PG's serving state, shared by
        status(), health(), and 'ceph pg dump'."""
        current = len(g.backend.current_shards())
        if current < g.backend.min_size:
            return "inactive"
        if current < len(g.acting):
            return "active+degraded"
        return "active+clean"

    def health(self) -> dict:
        """'ceph health' shape: a THIN view over the HealthCheckEngine —
        {"status", "checks": {key: summary}}, muted checks split out
        under "muted" (only when any exist, so the healthy shape stays
        exactly {"status", "checks"})."""
        from .mgr.health import thin_view
        return thin_view(self.health_engine.evaluate())

    def health_detail(self) -> dict:
        """The full engine evaluation (per-check severity + detail lines
        + mute state) — 'ceph health detail' / the flight-recorder
        source."""
        return self.health_engine.evaluate()

    def mute_health(self, key: str) -> None:
        """'ceph health mute <KEY>': mute AND persist in one step — any
        surface that mutes through the engine alone would lose the mute
        at the next reopen."""
        self.health_engine.mute(key)
        self._save_meta()

    def unmute_health(self, key: str) -> None:
        self.health_engine.unmute(key)
        self._save_meta()

    # -- scrub (PG::scrub scheduling through the daemons' op queues) --------

    def scrub_pool(self, pool_id: int, repair: bool = True) -> dict:
        """Deep-scrub every PG of the pool as BG_SCRUB work on the
        primaries' mClock queues (scrubs cannot starve clients), compare
        every shard against the authority, and (with ``repair``) queue
        shard repairs for inconsistencies — the reference's
        'ceph pg deep-scrub' + repair flow.  Returns
        {pgid: {oid: [bad shards]}} with only the inconsistencies."""
        from .osd.mclock import BG_SCRUB
        report: dict = {}
        for g in self.pools[pool_id]["pgs"].values():
            daemon = self.osds[g.backend.whoami]

            def scrub(g=g):
                from .backend.memstore import GObject
                from .backend.pg_backend import PG_META, shard_store
                # the scrub object list is the UNION over every up
                # shard's store: an object whose primary copy is missing
                # must still be scrubbed (the reference compares scrub
                # maps from all shards)
                oids: set[str] = set()
                for shard in g.acting:
                    if shard in g.bus.down:
                        continue
                    store = shard_store(g.bus, shard)
                    oids.update(gobj.oid for gobj in store.list_objects()
                                if gobj.shard == shard
                                and gobj.oid != PG_META)
                bad: dict[str, list[int]] = {}
                scanned: dict[str, int] = {}
                # damaged objects (inconsistent recovery sources) stay in
                # the report until an operator-grade overwrite clears
                # them — a laundered object can scrub "clean" wrongly
                for oid in sorted(getattr(g.backend,
                                          "inconsistent_objects", ())):
                    bad[oid] = sorted(
                        ci for ci, s in enumerate(g.acting)
                        if s not in g.bus.down)
                    scanned[oid] = len(bad[oid])
                for oid in sorted(oids):
                    try:
                        per_shard = g.backend.be_deep_scrub(oid)
                    except (KeyError, FileNotFoundError):
                        # authority state unreadable (e.g. the primary's
                        # copy is gone): fall back to per-shard existence
                        # so recovery still has its healthy sources
                        per_shard = {}
                        for ci, s in enumerate(g.acting):
                            if s in g.bus.down:
                                continue
                            per_shard[ci] = shard_store(g.bus, s).exists(
                                GObject(oid, s))
                    bads = sorted(s for s, ok in per_shard.items() if not ok)
                    if bads and oid not in bad:
                        bad[oid] = bads
                        scanned[oid] = len(per_shard)
                if bad:
                    report[repr(g.pgid)] = bad
                    if repair:
                        # object-level recovery, not log repair: scrub
                        # finds BITROT, which the logs cannot see — the
                        # bad chunks reconstruct from healthy shards and
                        # re-push (be_deep_scrub keys by chunk index).
                        # An UNRECOVERABLE set (every scanned chunk
                        # flagged: ambiguous/multi-chunk rot) stays in
                        # the report — recovery with zero healthy
                        # sources would just park a dead op forever.
                        for oid, chunks in sorted(bad.items()):
                            if len(chunks) >= scanned[oid]:
                                continue
                            g.backend.recover_object(oid, set(chunks))
                        g.bus.deliver_all()
            daemon.queue_background(g.pgid, scrub, op_class=BG_SCRUB)
            daemon.drain()
            g.bus.deliver_all()
        if report:
            self.clusterlog.warn(
                f"deep scrub of pool {pool_id} found inconsistencies in "
                f"{len(report)} pg(s): "
                f"{sum(len(b) for b in report.values())} object(s)",
                channel="scrub")
        return report

    # -- pool snapshots (the mon's 'osd pool mksnap/rmsnap') ----------------

    def create_pool_snap(self, pool_id: int, name: str) -> int:
        """Issue a pool snapshot: bumps snap_seq; subsequent writes carry
        the new SnapContext and COW-clone heads at first touch
        (pg_pool_t::add_snap)."""
        pool = self.pools[pool_id]["pool"]
        if name in pool.snaps.values():
            raise ValueError(f"pool snap {name!r} already exists")
        pool.snap_seq += 1
        pool.snaps[pool.snap_seq] = name
        self._save_meta()
        return pool.snap_seq

    def remove_pool_snap(self, pool_id: int, name: str) -> None:
        """Delete a pool snapshot and queue snaptrim: clone objects of the
        removed snap are deleted by BACKGROUND work riding the daemons'
        mClock queues under BG_SNAPTRIM — trimming cannot starve client
        ops (pg_pool_t::remove_snap + the SnapTrimmer)."""
        from .osd.mclock import BG_SNAPTRIM
        from .osd.primary_log_pg import (SNAP_SEP, SS_ATTR, empty_snapset,
                                         split_clone_oid)
        from .backend.memstore import GObject
        pool = self.pools[pool_id]["pool"]
        snapid = next((s for s, n in pool.snaps.items() if n == name), None)
        if snapid is None:
            raise ValueError(f"no pool snap named {name!r}")
        del pool.snaps[snapid]
        pool.removed_snaps.add(snapid)
        self._save_meta()
        live = set(pool.snaps)
        for g in self.pools[pool_id]["pgs"].values():
            daemon = self.osds[g.backend.whoami]

            def trim(g=g, live=live):
                # A clone with id c covers the snaps in (previous clone,
                # c]; it is removable only when NO live snap remains in
                # that interval (the reference deletes a clone when its
                # per-clone snaps list empties, SnapTrimmer).
                store = g.backend.local_shard.store
                whoami = g.backend.whoami
                t = PGTransaction()
                clones_by_head: dict[str, list[int]] = {}
                for gobj in store.list_objects():
                    if gobj.shard != whoami:
                        continue
                    parsed = split_clone_oid(gobj.oid)
                    if parsed is None:
                        continue
                    head, cid = parsed
                    clones_by_head.setdefault(head, []).append(cid)
                for head, clones in sorted(clones_by_head.items()):
                    clones.sort()
                    keep = []
                    for i, c in enumerate(clones):
                        prev = clones[i - 1] if i else 0
                        if any(prev < s <= c for s in live):
                            keep.append(c)
                            continue
                        t.delete(f"{head}{SNAP_SEP}{c}")
                        # (the delete's wholesale exoneration in the
                        # backend drops any damage flag with the clone)
                    if keep != clones:
                        hobj = GObject(head, whoami)
                        if store.exists(hobj):
                            try:
                                ss = dict(store.getattr(hobj, SS_ATTR))
                            except KeyError:
                                ss = empty_snapset()
                            ss["clones"] = keep
                            ss["sizes"] = {k: v
                                           for k, v in ss["sizes"].items()
                                           if int(k) in keep}
                            t.touch(head).setattr(SS_ATTR, ss)
                if t.ops:
                    g.backend.submit_transaction(t)
                    g.bus.deliver_all()
            daemon.queue_background(g.pgid, trim, op_class=BG_SNAPTRIM)
            daemon.drain()
            g.bus.deliver_all()

    # -- RADOS protocol surface (what an Objecter talks to) ----------------

    def osd_submit(self, pool_id: int, ps: int, target_osd: int,
                   client_epoch: int, oid: str, data: bytes | None,
                   read_len: int = 0, on_done=None, ops=None,
                   snapid: int | None = None, drain: bool = True):
        """One client op arriving at an OSD.  Returns None when accepted
        (completion via ``on_done``), or ``("stale", current_map)`` when
        the client's map is too old for this PG — wrong primary, or an
        epoch predating the PG's current acting set — mirroring the OSD's
        require_same_or_newer_map + "client has old map" resend dance.
        ``ops`` carries an op VECTOR through the daemon queue into the
        primary's op engine (the MOSDOp path); data/read_len are the
        legacy whole-object put/get shape."""
        g = self.pools[pool_id]["pgs"][ps]
        if target_osd != g.backend.whoami or client_epoch < g.epoch:
            return ("stale", self.osdmap)
        if ops is not None:
            res = self._dispatch_op_vector(g, pool_id, oid, ops,
                                           client_epoch, on_done,
                                           snapid=snapid, drain=drain)
            if res is not None:
                return ("stale", self.osdmap)
            return None
        if data is not None:
            # wait=False: an inactive PG parks the op, which stays in the
            # objecter's inflight list until it commits — the reference's
            # blocked-op behavior, not an error
            self.put(pool_id, oid, data, wait=False,
                     on_commit=lambda tid: on_done(len(data))
                     if on_done else None)
        else:
            try:
                on_done(self.get(pool_id, oid, read_len))
            except (IOError, KeyError) as e:
                on_done(e if isinstance(e, IOError) else IOError(str(e)))
        return None

    def shutdown(self) -> None:
        """Unhook every PG backend from the (possibly shared) Context so a
        discarded cluster is collectable and does not shadow later ones;
        durable stores checkpoint and close."""
        if self.serving is not None:
            self.serving.stop()
        if self.recovery is not None:
            self.recovery.close()
        if self.fault_injector is not None:
            self.fault_injector.close()
            self.fault_injector = None
        for svc, _agent in self.tiers.values():
            svc.close()
        self.tiers.clear()
        # telemetry spine down FIRST: a prometheus scrape racing the
        # teardown must not evaluate checks over half-closed PGs
        self.stats.close()
        self.health_engine.close()
        self.heat.close()
        self.clusterlog.close()
        self.flight.close()
        self.profiler.close()
        self.wire.close()
        self.slo.close()
        self.critpath.close()
        for cmd, fn in self._slo_admin_fns.items():
            if self.cct.admin_socket.get(cmd) is fn:
                self.cct.admin_socket.unregister(cmd)
        for p in self.pools.values():
            for g in p["pgs"].values():
                g.shutdown()
        for d in self.osds.values():
            if hasattr(d.store, "close"):
                d.store.close()     # meta_store IS the same store

    # -- control plane -----------------------------------------------------

    def _pg_objects(self, pool_id: int, g: PGGroup) -> list[str]:
        return [oid for oid in sorted(self.objects.get(pool_id, ()))
                if self.pools[pool_id]["pgs"][self.object_pg(pool_id, oid)]
                is g]

    def _repair_after_boot(self, pool_id: int, g: PGGroup,
                           shard: int) -> None:
        """Bring a rebooted shard current BEFORE it serves reads, via the
        PG log: equality is free, missed writes replay in O(missed
        entries), and only a shard past the log horizon pays a full
        backfill (PGLog.cc semantics — replaces the old O(all objects)
        deep scrub on every boot).  A revived primary repairs its own
        store the same way: its local shard log lags the authority log
        by exactly the writes that committed without it."""
        from .backend.ec_backend import RepairState
        rop = g.backend.start_shard_repair(shard)
        g.bus.deliver_all()
        if rop.state != RepairState.COMPLETE:
            raise IOError(
                f"repair of shard {shard} after boot failed: {rop.state}")

    def _backfill_pg(self, pool_id: int, ps: int, new_acting: list[int],
                     ec) -> None:
        """Acting set changed (auto-out remapping): move the PG's data to
        the new layout — read every object through the old group (degraded
        reads reconstruct), re-encode into a fresh group (the reference's
        backfill)."""
        from .common.tracer import default_tracer
        tr = default_tracer()
        self.clusterlog.info(
            f"backfill of pg {pool_id}.{ps:x} -> {new_acting}",
            channel="osd")
        with tr.activate(tr.new_trace("rebalance")), \
                tr.span("backfill.pg", owner="rebalance",
                        pg=f"{pool_id}.{ps}"):
            self._backfill_pg_traced(pool_id, ps, new_acting, ec)

    def _backfill_pg_traced(self, pool_id: int, ps: int,
                            new_acting: list[int], ec) -> None:
        old = self.pools[pool_id]["pgs"][ps]
        damaged = set(getattr(old.backend, "inconsistent_objects", ()))
        # read everything out of the old layout FIRST: in durable mode the
        # new group reopens the same per-(osd, pg) directories, so the old
        # stores must be drained and closed before the new ones open
        from .backend.ecutil import HINFO_KEY
        from .backend.memstore import GObject
        from .backend.replicated import VERSION_KEY
        contents: dict[str, bytes] = {}
        metadata: dict[str, tuple] = {}       # oid -> (attrs, omap, header)
        store = old.backend.local_shard.store
        # ground truth from the primary's own store, not just client
        # bookkeeping: snapshot CLONES are real objects the engine
        # created internally and must move with their heads
        moving = sorted(set(self._pg_objects(pool_id, old)) |
                        set(old.backend._local_oids()))
        for oid in moving:
            size = old.backend.object_size(oid)
            out = {}
            old.backend.objects_read_and_reconstruct(
                {oid: [(0, size)]},
                lambda result, errors: out.update(result=result,
                                                  errors=errors))
            old.bus.deliver_all()
            if out.get("errors"):
                raise IOError(f"backfill read of {oid}: {out['errors']}")
            contents[oid] = out["result"][oid][0][2]
            # object metadata moves with the data: attrs (minus per-layout
            # internals — hinfo is chunk-layout-specific, @version is
            # re-stamped by the new group's log) plus omap on replicated
            gobj = GObject(oid, old.backend.whoami)
            attrs = {k: v for k, v in store.getattrs(gobj).items()
                     if k not in (HINFO_KEY, VERSION_KEY)} \
                if store.exists(gobj) else {}
            omap = store.get_omap(gobj) if ec is None and \
                store.exists(gobj) else {}
            header = store.get_omap_header(gobj) if ec is None and \
                store.exists(gobj) else b""
            metadata[oid] = (attrs, omap, header)
        old.shutdown(discard_stores=self.data_dir is not None)
        # destroy the outgoing incarnation's collections: the new group
        # reuses the same collection name, and OSDs present in BOTH
        # acting sets (or rejoining later) would otherwise boot their
        # shard from the stale incarnation's persisted pg log
        from .backend.collection import Collection
        for osd in old.acting:
            if osd != NONE_ID:
                Collection(self.osds[osd].store,
                           f"pg.{pool_id}.{ps}").destroy()
        new = PGGroup(PG(pool_id, ps), new_acting, ec, self.chunk_size,
                      self.cct, name_prefix=f"c{self.cluster_id}e"
                                            f"{self.osdmap.epoch}",
                      min_size=self.pools[pool_id]["pool"].min_size,
                      store_factory=self._store_factory(pool_id, ps),
                      epoch=self.osdmap.epoch,
                      bus=self.bus)
        for oid, data in contents.items():
            t = PGTransaction().write(oid, 0, data)
            attrs, omap, header = metadata[oid]
            objop = t.ops[oid]
            objop.attr_updates.update(attrs)
            if omap:
                objop.omap_ops.append(("set", omap))
            if header:
                objop.omap_ops.append(("header", header))
            new.backend.submit_transaction(t)
            new.bus.deliver_all()
        # damaged-object state survives the move: the copied bytes may
        # BE the laundered rot, and dropping the flag would let it scrub
        # clean forever without an operator restore
        new.backend.inconsistent_objects |= damaged
        if self.serving is not None and ec is not None:
            new.backend.attach_serving(self.serving)
        if self.recovery is not None:
            self.recovery.cancel_pg(old.backend, reason="backfill remap")
            self._attach_recovery(new, self.pools[pool_id]["pool"])
        self._arm_hit_sets(new, self.pools[pool_id]["pool"])
        self.pools[pool_id]["pgs"][ps] = new
        # re-home the PG on its (possibly new) primary's daemon
        if old.backend.whoami != new.backend.whoami:
            self.osds[old.backend.whoami].pgs.pop(new.pgid, None)
            self.osds[old.backend.whoami].write_superblock()
        self.osds[new.backend.whoami].register_pg(new.pgid, new)

    def attach_monitor(self, n_mons: int = 1):
        """Wire the control plane over this cluster's OSDMap: committed
        epochs propagate to the data path the way daemons react to osdmap
        epoch bumps in the reference — down-marks route around the shard,
        boot-marks repair it before it serves, and weight changes
        (auto-out) backfill PGs onto their new acting sets.

        ``n_mons > 1`` runs a real Paxos quorum (MonCluster): map commits
        then require a monitor majority and survive monitor deaths."""
        from .mon import MonCluster, Monitor
        from .osdmap import OSD_UP
        if n_mons > 1:
            mon = MonCluster(self.osdmap, n_mons=n_mons, cct=self.cct)
        else:
            mon = Monitor(self.osdmap, cct=self.cct)

        def on_map(new_map, inc):
            self.osdmap = new_map
            affected: dict[int, PGGroup] = {}
            for o, st in inc.new_state.items():
                if not (st & OSD_UP):
                    continue
                down_now = new_map.is_down(o)
                for pid, pool in self.pools.items():
                    for g in pool["pgs"].values():
                        if o not in g.acting:
                            continue
                        if down_now:
                            g.bus.mark_down(o)
                        else:
                            g.bus.mark_up(o)
                        if new_map.is_down(g.backend.whoami):
                            # the PRIMARY is dead (this flip or an earlier
                            # one): its coordinator cannot peer and its
                            # repairs cannot complete (replies to a down
                            # shard drop) — the group is moribund until
                            # the weight/backfill path re-homes it or the
                            # primary itself boots back
                            continue
                        if not down_now:
                            self._repair_after_boot(pid, g, o)
                        affected[id(g)] = g
            # AdvMap: ONE statechart round per affected PG per committed
            # incremental, however many OSDs it flipped (GetInfo -> ... ->
            # Active); explicit repairs above just join the repair queues
            for g in affected.values():
                g.peering.advance_map(new_map.epoch)
                g.bus.deliver_all()
            if inc.new_weight:
                # CRUSH remapping: re-place every PG, backfill the changed
                for pid, pool in self.pools.items():
                    ec = pool["ec"]
                    for ps, g in list(pool["pgs"].items()):
                        _, _, acting, _ = new_map.pg_to_up_acting_osds(
                            PG(pid, ps))
                        if (acting and NONE_ID not in acting and
                                list(acting) != list(g.acting)):
                            self._backfill_pg(pid, ps, list(acting), ec)
        mon.subscribers.append(on_map)
        # monitor transitions (up/down/flap damping) land in the cluster
        # log next to the bus-level lines.  In a quorum, apply_committed
        # runs on EVERY replica: the clog_gate keeps only the current
        # leader speaking, so one commit logs once, not n_mons times.
        if hasattr(mon, "mons"):
            for pm in mon.mons:
                pm.service.clog = self.clusterlog
                pm.service.clog_gate = \
                    (lambda _pm=pm, _mc=mon: _mc.leader() is _pm)
        else:
            mon.clog = self.clusterlog
        self.monitor = mon
        return mon

    # -- cluster-wide status (ceph -s shape) -------------------------------

    def status(self) -> dict:
        """ceph -s shape: osdmap summary + pgmap with per-state counts
        (the PGMap the mon's stats service aggregates — active+clean /
        active+degraded / inactive from each PG's shard availability)
        plus the rate digest (client IO B/s and op/s, recovery B/s,
        serving batch throughput).  Each call ticks the StatsAggregator,
        so consecutive status calls bracket the rate window the way the
        mgr's periodic reports do."""
        n_pgs = 0
        states = {"active+clean": 0, "active+degraded": 0, "inactive": 0}
        for p in self.pools.values():
            for g in p["pgs"].values():
                n_pgs += 1
                states[self.pg_state(g)] += 1
        self.stats.sample()
        # status IS the mgr tick: the time-series ring records a point
        # (interval-gated, so a tight status loop stays bounded), and
        # every objecter attached to this cluster sweeps its op
        # timeouts — a parked/black-holed client op ages onto slow_ops
        # and the SLOW_OPS window delta without anyone polling by hand
        from .client.objecter import live_objecters
        for ob in live_objecters():
            if ob.cluster is self:
                ob.check_op_timeouts()
        # fold completed traces into the critical-path ledger BEFORE the
        # ts point records: the `slo` series reads the ledger
        self.critpath.refresh()
        self.ts.record()
        st = {
            "osdmap": {"epoch": self.osdmap.epoch,
                       "num_osds": self.osdmap.max_osd,
                       "num_up_osds": sum(
                           1 for o in range(self.osdmap.max_osd)
                           if self.osdmap.is_up(o))},
            "pgmap": {"num_pgs": n_pgs,
                      "num_pools": len(self.pools),
                      "pgs_by_state": {k: v for k, v in states.items()
                                       if v},
                      "io_rates": self.stats.digest()},
        }
        if self.recovery is not None:
            # recovering/queued PG counts + reservation occupancy (the
            # 'recovery:' block ceph -s renders next to the IO rates)
            st["pgmap"]["recovery"] = self.recovery.summary()
        return st
