"""Client library: the librados/Objecter layer (SURVEY.md §1 layer 8).

The Objecter computes op targets from the CLIENT's own (possibly stale)
OSDMap, stamps every op with its epoch, and resends when the map moves —
mirroring src/osdc/Objecter.cc op_submit :2257 / _calc_target :2786."""
from .objecter import Objecter

__all__ = ["Objecter"]
