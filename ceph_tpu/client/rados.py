"""librados facade: the Rados/IoCtx API surface.

Analog of the reference's librados C++/Python bindings (reference:
src/librados/librados_cxx.cc — IoCtx::operate :1482, rados_write in
librados_c.cc:1111; src/pybind/rados/rados.pyx shapes the method names):
a ``Rados`` handle opens ``IoCtx``s bound to pools; each IoCtx exposes
whole-object I/O, op vectors, xattrs, omap, snapshots, and watch/notify,
all routed through the Objecter's full client lifecycle (epoch-stamped
targets, stale rejects, resend on map change).
"""
from __future__ import annotations

from ..osd.osd_ops import ObjectOperation
from .objecter import Objecter


class ObjectNotFound(IOError):
    pass


def _raise(e: IOError):
    if getattr(e, "errno", None) == -2:
        err = ObjectNotFound(str(e))
        err.errno = -2
        raise err from None
    raise e


class Rados:
    """The cluster handle (librados::Rados)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.objecter = Objecter(cluster)
        if getattr(cluster, "monitor", None) is not None:
            self.objecter.attach(cluster.monitor)

    def open_ioctx(self, pool_name: str) -> "IoCtx":
        pool_id = self.cluster.pool_ids.get(pool_name)
        if pool_id is None:
            raise ValueError(f"no pool named {pool_name!r}")
        return IoCtx(self, pool_id)

    def pool_list(self) -> list[str]:
        return sorted(self.cluster.pool_ids)

    def cluster_stat(self) -> dict:
        return self.cluster.status()


class IoCtx:
    """One pool's I/O context (librados::IoCtx)."""

    def __init__(self, rados: Rados, pool_id: int):
        self.rados = rados
        self.pool_id = pool_id
        self.snap_read: int | None = None     # set_read at a snap
        self._next_cookie = 0

    # -- op vectors (IoCtx::operate) ----------------------------------------

    def operate(self, oid: str, op: ObjectOperation):
        """Synchronous operate; returns the MOSDOpReply."""
        try:
            return self.rados.cluster.operate(
                self.pool_id, oid, op, snapid=self.snap_read)
        except IOError as e:
            _raise(e)

    # -- whole-object convenience -------------------------------------------

    def write_full(self, oid: str, data: bytes) -> None:
        self.operate(oid, ObjectOperation().write_full(data))

    def write(self, oid: str, data: bytes, offset: int = 0) -> None:
        self.operate(oid, ObjectOperation().write(offset, data))

    def append(self, oid: str, data: bytes) -> None:
        self.operate(oid, ObjectOperation().append(data))

    def read(self, oid: str, length: int = 0, offset: int = 0) -> bytes:
        r = self.operate(oid, ObjectOperation().read(offset, length))
        return r.outdata(0)

    def stat(self, oid: str) -> tuple[int, float]:
        return self.operate(oid, ObjectOperation().stat()).outdata(0)

    def remove_object(self, oid: str) -> None:
        self.operate(oid, ObjectOperation().remove())

    def list_objects(self) -> list[str]:
        from ..osd.primary_log_pg import is_clone_oid
        return sorted(o for o in
                      self.rados.cluster.objects.get(self.pool_id, ())
                      if not is_clone_oid(o))

    # -- xattrs --------------------------------------------------------------

    def get_xattr(self, oid: str, name: str):
        return self.operate(oid, ObjectOperation().getxattr(name)).outdata(0)

    def set_xattr(self, oid: str, name: str, value) -> None:
        self.operate(oid, ObjectOperation().setxattr(name, value))

    def rm_xattr(self, oid: str, name: str) -> None:
        self.operate(oid, ObjectOperation().rmxattr(name))

    def get_xattrs(self, oid: str) -> dict:
        return self.operate(oid, ObjectOperation().getxattrs()).outdata(0)

    # -- omap ---------------------------------------------------------------

    def omap_set(self, oid: str, kvs: dict) -> None:
        self.operate(oid, ObjectOperation().omap_set(kvs))

    def omap_get_vals(self, oid: str, **kw) -> dict:
        return self.operate(oid,
                            ObjectOperation().omap_get_vals(**kw)).outdata(0)

    # -- snapshots (IoCtx snap_create/remove/rollback/set_read) -------------

    def snap_create(self, name: str) -> int:
        return self.rados.cluster.create_pool_snap(self.pool_id, name)

    def snap_remove(self, name: str) -> None:
        self.rados.cluster.remove_pool_snap(self.pool_id, name)

    def snap_list(self) -> dict[int, str]:
        return dict(self.rados.cluster.pools[self.pool_id]["pool"].snaps)

    def snap_lookup(self, name: str) -> int:
        for sid, n in self.snap_list().items():
            if n == name:
                return sid
        raise KeyError(name)

    def snap_rollback(self, oid: str, name: str) -> None:
        self.operate(oid, ObjectOperation().rollback(self.snap_lookup(name)))

    def set_read(self, snapid: int | None) -> None:
        """Subsequent reads resolve at this snap (SNAP_HEAD = None)."""
        self.snap_read = snapid

    # -- watch/notify --------------------------------------------------------

    def watch(self, oid: str, on_notify, cookie: int | None = None) -> int:
        if cookie is None:
            # unique per IoCtx: the same callback watched twice must get
            # two registrations, not silently overwrite one
            self._next_cookie += 1
            cookie = self._next_cookie
        self.operate(oid, ObjectOperation().watch(cookie, on_notify))
        return cookie

    def unwatch(self, oid: str, cookie: int) -> None:
        self.operate(oid, ObjectOperation().unwatch(cookie))

    def notify(self, oid: str, payload: bytes = b"") -> dict:
        return self.operate(oid,
                            ObjectOperation().notify(payload)).outdata(0)
