"""librados facade: the Rados/IoCtx API surface.

Analog of the reference's librados C++/Python bindings (reference:
src/librados/librados_cxx.cc — IoCtx::operate :1482, rados_write in
librados_c.cc:1111; src/pybind/rados/rados.pyx shapes the method names):
a ``Rados`` handle opens ``IoCtx``s bound to pools; each IoCtx exposes
whole-object I/O, op vectors, xattrs, omap, snapshots, and watch/notify,
all routed through the Objecter's full client lifecycle (epoch-stamped
targets, stale rejects, resend on map change).
"""
from __future__ import annotations

import itertools

from ..osd.osd_ops import (OP_CALL, OP_LIST_WATCHERS, OP_NOTIFY,
                           OP_UNWATCH, OP_WATCH, ObjectOperation,
                           WRITE_OPS)
from .objecter import Objecter

# ops that must always target the HEAD regardless of set_read (librados
# snap_set_read affects READS only; watches live on the head)
_HEAD_ONLY = WRITE_OPS | {OP_CALL, OP_WATCH, OP_UNWATCH, OP_NOTIFY,
                          OP_LIST_WATCHERS}

# watch cookies must be unique across ALL handles: the PG keys watchers
# by cookie alone, so per-IoCtx counters would collide between clients
_cookies = itertools.count(1)


class ObjectNotFound(IOError):
    pass


def _raise(e: IOError):
    if getattr(e, "errno", None) == -2:
        err = ObjectNotFound(str(e))
        err.errno = -2
        raise err from None
    raise e


class Completion:
    """An in-flight async op (librados::AioCompletion): poll
    ``is_complete`` or ``wait_for_complete`` (pumping the cluster's
    queues), then read ``result``/``reply``."""

    def __init__(self, cluster, pg_group):
        self._cluster = cluster
        self._g = pg_group
        self.reply = None
        self._callbacks: list = []

    @property
    def is_complete(self) -> bool:
        return self.reply is not None

    @property
    def result(self) -> int:
        """The op's result; raises while incomplete — defaulting to 0
        here would report success for a write that never applied."""
        if self.reply is None:
            raise ValueError("op not complete; poll is_complete")
        return self.reply.result

    def set_complete_callback(self, fn) -> None:
        if self.reply is not None:
            fn(self)
        else:
            self._callbacks.append(fn)

    def _done(self, reply) -> None:
        self.reply = reply
        for fn in self._callbacks:
            fn(self)
        self._callbacks.clear()

    def wait_for_complete(self) -> int:
        """Drive the daemon + bus until the op completes.  An op parked
        on an inactive PG cannot complete yet: raises BlockedWriteError
        (queued, not lost — it commits when the PG reactivates) instead
        of faking a success code."""
        daemon = self._cluster.osds[self._g.backend.whoami]
        daemon.drain()
        self._g.bus.deliver_all()
        if not self.is_complete:
            from ..cluster import BlockedWriteError
            raise BlockedWriteError("op parked on an inactive PG")
        return self.result


class Rados:
    """The cluster handle (librados::Rados)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.objecter = Objecter(cluster)
        if getattr(cluster, "monitor", None) is not None:
            self.objecter.attach(cluster.monitor)

    def open_ioctx(self, pool_name: str) -> "IoCtx":
        pool_id = self.cluster.pool_ids.get(pool_name)
        if pool_id is None:
            raise ValueError(f"no pool named {pool_name!r}")
        return IoCtx(self, pool_id)

    def pool_list(self) -> list[str]:
        return sorted(self.cluster.pool_ids)

    def cluster_stat(self) -> dict:
        return self.cluster.status()

    def health(self) -> dict:
        return self.cluster.health()

    def shutdown(self) -> None:
        """librados rados_shutdown: release the objecter's perf
        collection and live registration (a discarded handle must not
        keep exporting a frozen inflight gauge)."""
        self.objecter.close()

    # context-manager sugar: `with Rados(c) as r: ...` shuts down
    def __enter__(self) -> "Rados":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


class IoCtx:
    """One pool's I/O context (librados::IoCtx)."""

    def __init__(self, rados: Rados, pool_id: int):
        self.rados = rados
        self.pool_id = pool_id
        self.snap_read: int | None = None     # set_read at a snap

    def _effective_snapid(self, op: ObjectOperation) -> int | None:
        """set_read's snapid for pure-read vectors; head otherwise
        (ONE copy of the librados snap_set_read rule)."""
        return (None if any(o.op in _HEAD_ONLY for o in op.ops)
                else self.snap_read)

    # -- op vectors (IoCtx::operate) ----------------------------------------

    def operate(self, oid: str, op: ObjectOperation):
        """Synchronous operate through the Objecter's full client
        lifecycle (epoch/resend); returns the MOSDOpReply.  set_read's
        snapid applies to pure-read vectors only — writes, cls calls,
        and watch ops always target the head (librados snap_set_read
        semantics)."""
        out: list = []
        tid = self.rados.objecter.operate(self.pool_id, oid, op,
                                          on_complete=out.append,
                                          snapid=self._effective_snapid(op))
        if not out:
            # parked on an inactive PG: it stays queued at the OSD and
            # commits when shards return (put()'s semantics) — but it
            # must leave the objecter's inflight list NOW, or a map
            # change would RESEND it and a non-idempotent op (append,
            # omap mutation) could apply twice
            self.rados.objecter.inflight.pop(tid, None)
            from ..cluster import BlockedWriteError
            raise BlockedWriteError(
                f"op on {oid} blocked: PG inactive (queued, not lost)")
        reply = out[0]
        if isinstance(reply, Exception):
            _raise(reply if isinstance(reply, IOError)
                   else IOError(str(reply)))
        if reply.result < 0:
            err = IOError(f"op on {oid} failed: result {reply.result}")
            err.errno = reply.result
            err.reply = reply
            _raise(err)
        return reply

    def aio_operate(self, oid: str, op: ObjectOperation) -> Completion:
        """Async operate (librados aio_operate): the op is QUEUED on the
        primary's daemon without draining; the returned Completion fires
        when the reply lands (wait_for_complete pumps the queues).
        Shares operate()'s snap_read/head-only logic and the Objecter's
        epoch-stamped lifecycle."""
        cluster = self.rados.cluster
        g = cluster.pg_group(self.pool_id, oid)
        comp = Completion(cluster, g)
        tid = self.rados.objecter.operate(
            self.pool_id, oid, op, on_complete=comp._done,
            snapid=self._effective_snapid(op), drain=False)
        # A queued op must NOT stay resendable: with no OSD-side reqid
        # dedup, a map change while it sits undrained would double-apply
        # a non-idempotent vector (same queued-not-lost choice the sync
        # path makes for parked ops).  It also pins the op to the PG
        # group captured in the Completion — the one wait_for_complete
        # pumps.
        self.rados.objecter.inflight.pop(tid, None)
        return comp

    # -- whole-object convenience -------------------------------------------

    def write_full(self, oid: str, data: bytes) -> None:
        self.operate(oid, ObjectOperation().write_full(data))

    def write(self, oid: str, data: bytes, offset: int = 0) -> None:
        self.operate(oid, ObjectOperation().write(offset, data))

    def append(self, oid: str, data: bytes) -> None:
        self.operate(oid, ObjectOperation().append(data))

    def read(self, oid: str, length: int = 0, offset: int = 0) -> bytes:
        r = self.operate(oid, ObjectOperation().read(offset, length))
        return r.outdata(0)

    def stat(self, oid: str) -> tuple[int, float]:
        return self.operate(oid, ObjectOperation().stat()).outdata(0)

    def remove_object(self, oid: str) -> None:
        self.operate(oid, ObjectOperation().remove())

    def list_objects(self) -> list[str]:
        from ..osd.hit_set import is_hit_set_oid
        from ..osd.primary_log_pg import is_clone_oid
        return sorted(o for o in
                      self.rados.cluster.objects.get(self.pool_id, ())
                      if not is_clone_oid(o) and not is_hit_set_oid(o))

    # -- xattrs --------------------------------------------------------------

    def get_xattr(self, oid: str, name: str):
        return self.operate(oid, ObjectOperation().getxattr(name)).outdata(0)

    def set_xattr(self, oid: str, name: str, value) -> None:
        self.operate(oid, ObjectOperation().setxattr(name, value))

    def rm_xattr(self, oid: str, name: str) -> None:
        self.operate(oid, ObjectOperation().rmxattr(name))

    def get_xattrs(self, oid: str) -> dict:
        return self.operate(oid, ObjectOperation().getxattrs()).outdata(0)

    # -- omap ---------------------------------------------------------------

    def omap_set(self, oid: str, kvs: dict) -> None:
        self.operate(oid, ObjectOperation().omap_set(kvs))

    def omap_get_vals(self, oid: str, **kw) -> dict:
        return self.operate(oid,
                            ObjectOperation().omap_get_vals(**kw)).outdata(0)

    # -- snapshots (IoCtx snap_create/remove/rollback/set_read) -------------

    def snap_create(self, name: str) -> int:
        return self.rados.cluster.create_pool_snap(self.pool_id, name)

    def snap_remove(self, name: str) -> None:
        self.rados.cluster.remove_pool_snap(self.pool_id, name)

    def snap_list(self) -> dict[int, str]:
        return dict(self.rados.cluster.pools[self.pool_id]["pool"].snaps)

    def snap_lookup(self, name: str) -> int:
        for sid, n in self.snap_list().items():
            if n == name:
                return sid
        raise KeyError(name)

    def snap_rollback(self, oid: str, name: str) -> None:
        self.operate(oid, ObjectOperation().rollback(self.snap_lookup(name)))

    def set_read(self, snapid: int | None) -> None:
        """Subsequent reads resolve at this snap (SNAP_HEAD = None)."""
        self.snap_read = snapid

    # -- watch/notify --------------------------------------------------------

    def watch(self, oid: str, on_notify, cookie: int | None = None) -> int:
        if cookie is None:
            cookie = next(_cookies)       # unique across ALL handles
        self.operate(oid, ObjectOperation().watch(cookie, on_notify))
        return cookie

    def unwatch(self, oid: str, cookie: int) -> None:
        self.operate(oid, ObjectOperation().unwatch(cookie))

    def notify(self, oid: str, payload: bytes = b"") -> dict:
        return self.operate(oid,
                            ObjectOperation().notify(payload)).outdata(0)
